"""Retrieval metric tests: segment engine vs a per-query numpy loop reference."""

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.retrieval import (
    RetrievalAUROC,
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRecall,
    RetrievalRPrecision,
)

_rng = np.random.RandomState(21)
N_QUERIES = 12
sizes = _rng.randint(3, 12, N_QUERIES)
indexes = np.concatenate([np.full(s, i) for i, s in enumerate(sizes)])
preds = _rng.rand(indexes.shape[0]).astype(np.float32)
target = _rng.randint(0, 2, indexes.shape[0])
graded = _rng.randint(0, 4, indexes.shape[0])


def _per_query(metric_fn, tgt=target, skip_empty=False, empty_val=0.0):
    scores = []
    for q in np.unique(indexes):
        sel = indexes == q
        p, t = preds[sel], tgt[sel]
        if t.sum() == 0:
            if skip_empty:
                continue
            scores.append(empty_val)
            continue
        scores.append(metric_fn(p, t))
    return float(np.mean(scores))


def _np_ap(p, t):
    order = np.argsort(-p, kind="stable")
    t = t[order] > 0
    prec = np.cumsum(t) / (np.arange(len(t)) + 1)
    return (prec * t).sum() / t.sum()


def _np_mrr(p, t):
    order = np.argsort(-p, kind="stable")
    t = t[order] > 0
    return 1.0 / (np.argmax(t) + 1)


def _np_ndcg(p, t, k=None):
    order = np.argsort(-p, kind="stable")
    t_sorted = t[order].astype(float)
    k = k or len(t)
    disc = 1.0 / np.log2(np.arange(len(t)) + 2)
    dcg = (t_sorted * disc)[:k].sum()
    ideal = -np.sort(-t.astype(float))
    idcg = (ideal * disc)[:k].sum()
    return dcg / idcg if idcg > 0 else 0.0


def _run(metric, tgt=target):
    metric.update(jnp.asarray(preds), jnp.asarray(tgt), indexes=jnp.asarray(indexes))
    return float(metric.compute())


def test_retrieval_map():
    np.testing.assert_allclose(_run(RetrievalMAP()), _per_query(_np_ap), rtol=1e-5)


def test_retrieval_mrr():
    np.testing.assert_allclose(_run(RetrievalMRR()), _per_query(_np_mrr), rtol=1e-5)


@pytest.mark.parametrize("k", [1, 3, None])
def test_retrieval_precision(k):
    def np_prec(p, t):
        kk = k or len(p)
        order = np.argsort(-p, kind="stable")
        return (t[order] > 0)[:kk].sum() / kk

    np.testing.assert_allclose(_run(RetrievalPrecision(top_k=k)), _per_query(np_prec), rtol=1e-5)


@pytest.mark.parametrize("k", [1, 3, None])
def test_retrieval_recall(k):
    def np_rec(p, t):
        kk = k or len(p)
        order = np.argsort(-p, kind="stable")
        return (t[order] > 0)[:kk].sum() / (t > 0).sum()

    np.testing.assert_allclose(_run(RetrievalRecall(top_k=k)), _per_query(np_rec), rtol=1e-5)


def test_retrieval_hit_rate():
    def np_hr(p, t):
        order = np.argsort(-p, kind="stable")
        return float((t[order] > 0)[:2].any())

    np.testing.assert_allclose(_run(RetrievalHitRate(top_k=2)), _per_query(np_hr), rtol=1e-5)


def test_retrieval_fall_out():
    def np_fo_scores():
        scores = []
        for q in np.unique(indexes):
            sel = indexes == q
            p, t = preds[sel], 1 - target[sel]
            if t.sum() == 0:
                scores.append(0.0)
                continue
            order = np.argsort(-p, kind="stable")
            scores.append((t[order] > 0)[:2].sum() / t.sum())
        return float(np.mean(scores))

    np.testing.assert_allclose(_run(RetrievalFallOut(top_k=2)), np_fo_scores(), rtol=1e-5)


def test_retrieval_r_precision():
    def np_rp(p, t):
        order = np.argsort(-p, kind="stable")
        r = int((t > 0).sum())
        return (t[order] > 0)[:r].sum() / r

    np.testing.assert_allclose(_run(RetrievalRPrecision()), _per_query(np_rp), rtol=1e-5)


def test_retrieval_ndcg_graded():
    np.testing.assert_allclose(
        _run(RetrievalNormalizedDCG(), tgt=graded),
        np.mean([
            _np_ndcg(preds[indexes == q], graded[indexes == q]) for q in np.unique(indexes)
        ]),
        rtol=1e-5,
    )


def test_retrieval_auroc_vs_sklearn():
    from sklearn.metrics import roc_auc_score

    def np_auroc_scores():
        scores = []
        for q in np.unique(indexes):
            sel = indexes == q
            p, t = preds[sel], target[sel]
            if t.sum() == 0 or (1 - t).sum() == 0:
                scores.append(0.0 if t.sum() == 0 else 0.0)
                continue
            scores.append(roc_auc_score(t, p))
        return float(np.mean(scores))

    # queries with only positives: our U-statistic gives 0/0 -> 0; emulate in ref above
    np.testing.assert_allclose(_run(RetrievalAUROC()), np_auroc_scores(), rtol=1e-5)


def test_retrieval_prc_shapes_and_skip():
    m = RetrievalPrecisionRecallCurve(max_k=5)
    m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(indexes))
    precision, recall, ks = m.compute()
    assert precision.shape == (5,) and recall.shape == (5,) and list(np.asarray(ks)) == [1, 2, 3, 4, 5]
    assert bool(jnp.all(jnp.diff(recall) >= -1e-6))  # recall non-decreasing in k


def test_empty_target_actions():
    idx = np.array([0, 0, 1, 1])
    p = np.array([0.3, 0.7, 0.2, 0.9], dtype=np.float32)
    t = np.array([1, 0, 0, 0])  # query 1 has no positives
    for action, expected in [("neg", 0.25), ("pos", 0.75), ("skip", 0.5)]:
        m = RetrievalMAP(empty_target_action=action)
        m.update(jnp.asarray(p), jnp.asarray(t), indexes=jnp.asarray(idx))
        np.testing.assert_allclose(float(m.compute()), expected, rtol=1e-6)
    m = RetrievalMAP(empty_target_action="error")
    m.update(jnp.asarray(p), jnp.asarray(t), indexes=jnp.asarray(idx))
    with pytest.raises(ValueError, match="no positive"):
        m.compute()


def test_aggregation_modes():
    for agg in ("mean", "median", "min", "max"):
        m = RetrievalMAP(aggregation=agg)
        m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(indexes))
        v = float(m.compute())
        assert 0.0 <= v <= 1.0


def test_ignore_index():
    t = target.copy()
    t[::5] = -1
    m = RetrievalMAP(ignore_index=-1)
    m.update(jnp.asarray(preds), jnp.asarray(t), indexes=jnp.asarray(indexes))
    v = float(m.compute())
    assert 0.0 <= v <= 1.0


def test_retrieval_update_and_compute_jit_one_program():
    """The whole retrieval evaluation — grouping, scoring, aggregation — traces as ONE jitted program."""
    import jax

    rng = np.random.RandomState(0)
    n_q, n_d = 16, 20
    indexes = jnp.asarray(np.repeat(np.arange(n_q), n_d))
    preds = jnp.asarray(rng.rand(n_q * n_d).astype(np.float32))
    target = jnp.asarray((rng.rand(n_q * n_d) < 0.2).astype(np.int64))

    for cls in (RetrievalMAP, RetrievalMRR, RetrievalNormalizedDCG, RetrievalAUROC):
        m = cls()

        @jax.jit
        def program(p, t, i, m=m):
            return m.compute_flat(p, t, i)

        jitted = float(program(preds, target, indexes))
        m.update(preds, target, indexes=indexes)
        eager = float(m.compute())
        np.testing.assert_allclose(jitted, eager, rtol=1e-6), cls.__name__


def test_retrieval_skip_action_masked_aggregation():
    indexes = jnp.asarray([0, 0, 1, 1, 2, 2])
    preds = jnp.asarray([0.9, 0.1, 0.8, 0.2, 0.7, 0.3])
    target = jnp.asarray([1, 0, 0, 0, 1, 0])  # query 1 has no positives
    m = RetrievalMAP(empty_target_action="skip")
    m.update(preds, target, indexes=indexes)
    # queries 0 and 2 both have AP=1; query 1 skipped
    assert float(m.compute()) == pytest.approx(1.0)


def test_host_sort_matches_device_lexsort_edge_values():
    """The cpu-backend host argsort agrees with jnp.lexsort on NaN and ±0.0 keys."""
    from metrics_tpu.retrieval.base import _order_by_query_desc

    preds = jnp.asarray([0.5, np.nan, -0.0, 0.0, np.inf, -np.inf, 0.5], dtype=jnp.float32)
    indexes = jnp.asarray([0, 0, 0, 0, 1, 1, 1])
    got = np.asarray(_order_by_query_desc(indexes, preds))
    want = np.asarray(jnp.lexsort((-preds, indexes)))
    assert np.array_equal(got, want), (got, want)


def test_shared_view_reused_across_group_mates_and_released_on_reset():
    from metrics_tpu.retrieval.base import _VIEW_CACHE, shared_grouped_view

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(50).astype(np.float32))
    target = jnp.asarray((rng.rand(50) < 0.3).astype(np.int64))
    indexes = jnp.asarray(np.repeat(np.arange(5), 10))

    m1, m2 = RetrievalMAP(), RetrievalMRR()
    for m in (m1, m2):
        m.update(preds, target, indexes=indexes)
        m.compute()
    # group-mate sharing: both metrics store the identical array objects, so one view
    anchors = m1._state_anchors()
    gq1 = shared_grouped_view(None, None, None, anchors)  # cache hit: inputs unused
    assert gq1 is shared_grouped_view(None, None, None, m2._state_anchors())

    # releasing the states kills the weakref anchors: nothing stays pinned
    m1.reset(), m2.reset()
    del preds, target, indexes, anchors, gq1
    import gc

    gc.collect()
    assert all(any(r() is None for r in refs) for refs, _ in _VIEW_CACHE.values())
    # the next call purges dead entries
    p2 = jnp.asarray([0.5, 0.2]); t2 = jnp.asarray([1, 0]); i2 = jnp.asarray([0, 0])
    shared_grouped_view(i2, p2, t2, (i2, p2, t2))
    assert len(_VIEW_CACHE) == 1
