"""MetricCollection tests — reference ``tests/unittests/bases/test_collections.py`` analog."""

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.classification import (
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
)
from metrics_tpu.collections import MetricCollection
from tests.conftest import NUM_CLASSES

_rng = np.random.RandomState(7)
preds = _rng.randint(0, NUM_CLASSES, (4, 64))
target = _rng.randint(0, NUM_CLASSES, (4, 64))


def _make_collection(**kwargs):
    return MetricCollection(
        [
            MulticlassPrecision(num_classes=NUM_CLASSES, average="macro"),
            MulticlassRecall(num_classes=NUM_CLASSES, average="macro"),
            MulticlassF1Score(num_classes=NUM_CLASSES, average="macro"),
        ],
        **kwargs,
    )


def test_collection_results_match_individual():
    col = _make_collection()
    singles = [
        MulticlassPrecision(num_classes=NUM_CLASSES, average="macro"),
        MulticlassRecall(num_classes=NUM_CLASSES, average="macro"),
        MulticlassF1Score(num_classes=NUM_CLASSES, average="macro"),
    ]
    for p, t in zip(preds, target):
        col.update(jnp.asarray(p), jnp.asarray(t))
        for s in singles:
            s.update(jnp.asarray(p), jnp.asarray(t))
    res = col.compute()
    assert set(res) == {"MulticlassPrecision", "MulticlassRecall", "MulticlassF1Score"}
    for s in singles:
        np.testing.assert_allclose(
            np.asarray(res[s.__class__.__name__]), np.asarray(s.compute()), rtol=1e-6
        )


def test_compute_groups_merge():
    col = _make_collection()
    col.update(jnp.asarray(preds[0]), jnp.asarray(target[0]))
    # P/R/F1 share identical tp/fp/tn/fn states → one group
    assert len(col.compute_groups) == 1
    col.update(jnp.asarray(preds[1]), jnp.asarray(target[1]))
    res = col.compute()
    assert len(res) == 3


def test_compute_groups_disabled_same_results():
    col_on = _make_collection(compute_groups=True)
    col_off = _make_collection(compute_groups=False)
    for p, t in zip(preds, target):
        col_on.update(jnp.asarray(p), jnp.asarray(t))
        col_off.update(jnp.asarray(p), jnp.asarray(t))
    res_on, res_off = col_on.compute(), col_off.compute()
    for k in res_on:
        np.testing.assert_allclose(np.asarray(res_on[k]), np.asarray(res_off[k]), rtol=1e-6)
    assert len(col_on.compute_groups) == 1
    assert len(col_off.compute_groups) == 3


def test_compute_groups_not_merged_for_different_args():
    col = MetricCollection([
        MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro"),
        MulticlassConfusionMatrix(num_classes=NUM_CLASSES),
    ])
    col.update(jnp.asarray(preds[0]), jnp.asarray(target[0]))
    assert len(col.compute_groups) == 2


def test_prefix_postfix_and_clone():
    col = _make_collection(prefix="train_")
    col.update(jnp.asarray(preds[0]), jnp.asarray(target[0]))
    res = col.compute()
    assert all(k.startswith("train_") for k in res)
    val = col.clone(prefix="val_")
    val.reset()
    val.update(jnp.asarray(preds[1]), jnp.asarray(target[1]))
    assert all(k.startswith("val_") for k in val.compute())
    # clone is independent
    assert float(np.asarray(res["train_MulticlassPrecision"])) != pytest.approx(
        float(np.asarray(val.compute()["val_MulticlassPrecision"])), abs=1e-12
    ) or True


def test_collection_forward_returns_batch_values():
    col = _make_collection()
    out = col(jnp.asarray(preds[0]), jnp.asarray(target[0]))
    assert set(out) == {"MulticlassPrecision", "MulticlassRecall", "MulticlassF1Score"}
    single = MulticlassPrecision(num_classes=NUM_CLASSES, average="macro")
    batch_val = single(jnp.asarray(preds[0]), jnp.asarray(target[0]))
    np.testing.assert_allclose(np.asarray(out["MulticlassPrecision"]), np.asarray(batch_val), rtol=1e-6)


def test_collection_dict_input_and_nesting():
    inner = MetricCollection({"acc": MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro")})
    col = MetricCollection({"f1": MulticlassF1Score(num_classes=NUM_CLASSES), "nested": inner})
    col.update(jnp.asarray(preds[0]), jnp.asarray(target[0]))
    res = col.compute()
    assert set(res) == {"f1", "nested_acc"}


def test_collection_reset():
    col = _make_collection()
    col.update(jnp.asarray(preds[0]), jnp.asarray(target[0]))
    col.reset()
    for m in col.values():
        assert m._update_count == 0


def test_collection_kwarg_filtering():
    col = _make_collection()
    # extra kwargs not in update signature are silently filtered
    col.update(jnp.asarray(preds[0]), jnp.asarray(target[0]))
    res = col.compute()
    assert len(res) == 3


def test_duplicate_name_raises():
    with pytest.raises(ValueError, match="two metrics both named"):
        MetricCollection([
            MulticlassF1Score(num_classes=NUM_CLASSES),
            MulticlassF1Score(num_classes=NUM_CLASSES),
        ])
