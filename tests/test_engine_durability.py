"""Fleet durability (``engine/durability.py``, DESIGN §17): the CRC-framed
ingest WAL, incremental fleet checkpoints, validated restore + journal replay,
and the blast-radius contracts — recovery is bit-exact versus a never-crashed
oracle, and a quarantined session never demotes its bucket (the full per-class
sweep runs as the ``chaos`` pass's fleet scenarios, not here)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import StreamEngine, observe
from metrics_tpu.classification import BinaryAUROC, MulticlassAccuracy
from metrics_tpu.engine import durability as dur_mod
from metrics_tpu.engine.durability import IngestWAL
from metrics_tpu.metric import Metric, clear_jit_cache, jit_update_enabled
from metrics_tpu.resilience import CorruptCheckpointError, IncompatibleCheckpointError


@pytest.fixture(autouse=True)
def _pristine():
    clear_jit_cache()
    jit_update_enabled(True)
    with observe.scope(reset=True):
        yield
    clear_jit_cache()
    jit_update_enabled(True)


def _acc():
    return MulticlassAccuracy(num_classes=4)


def _acc_batch(rng, n=8):
    return jnp.asarray(rng.randint(4, size=n)), jnp.asarray(rng.randint(4, size=n))


def _auroc():
    return BinaryAUROC(thresholds=8)


def _auroc_batch(rng, n=8):
    return jnp.asarray(rng.rand(n).astype(np.float32)), jnp.asarray(rng.randint(2, size=n))


def _state_rows(engine, sid):
    sess = engine._sessions[sid]
    if sess.bucket is None:
        return dict(sess.metric._state)
    return {k: v[sess.slot] for k, v in sess.bucket.stacked.items()}


def _assert_engines_equal(got, want, sids):
    assert set(got.session_ids()) == set(want.session_ids())
    for sid in sids:
        a, b = _state_rows(got, sid), _state_rows(want, sid)
        for k in b:
            np.testing.assert_array_equal(
                np.asarray(a[k]), np.asarray(b[k]), err_msg=f"session {sid!r} state {k!r}"
            )


def _counters(name):
    return sum(observe.snapshot()["counters"].get(name, {}).values())


# ------------------------------------------------------------------ WAL frames
def test_wal_append_sync_read_roundtrip(tmp_path):
    path = str(tmp_path / "ingest.wal")
    wal = IngestWAL(path)
    wal.append("submit", 1, "a", ((1, 2), {}))
    wal.append("expire", 2, "a")
    wal.append("add", 3, "b", _acc())  # Metric payloads ride as tagged pickles
    wal.sync()
    records, torn = IngestWAL.read_records(path)
    assert not torn
    assert [(r[0], r[1], r[2]) for r in records] == [("submit", 1, "a"), ("expire", 2, "a"), ("add", 3, "b")]
    assert records[0][3] == ((1, 2), {})
    tag, blob = records[2][3]
    assert tag == "__metric__" and isinstance(blob, bytes)
    wal.close()


def test_wal_append_is_buffered_until_sync(tmp_path):
    path = str(tmp_path / "ingest.wal")
    wal = IngestWAL(path)
    wal.append("submit", 1, "a", ((), {}))
    # not yet durable: the reader sees an empty journal until sync()
    assert IngestWAL.read_records(path) == ([], False) or os.path.getsize(path) == len(dur_mod.WAL_MAGIC)
    wal.sync()
    records, torn = IngestWAL.read_records(path)
    assert len(records) == 1 and not torn
    wal.close()


def test_wal_truncate_keeps_predicate_and_stays_appendable(tmp_path):
    path = str(tmp_path / "ingest.wal")
    wal = IngestWAL(path)
    for seq in range(1, 6):
        wal.append("submit", seq, "a", ((seq,), {}))
    wal.sync()
    assert wal.truncate(lambda seq: seq > 3) == 2
    records, torn = IngestWAL.read_records(path)
    assert [r[1] for r in records] == [4, 5] and not torn
    wal.append("submit", 6, "a", ((6,), {}))  # the reopened handle keeps working
    wal.sync()
    assert [r[1] for r in IngestWAL.read_records(path)[0]] == [4, 5, 6]
    wal.close()


def test_wal_torn_and_bitflipped_tail_stop_replay_cleanly(tmp_path):
    path = str(tmp_path / "ingest.wal")
    wal = IngestWAL(path)
    for seq in range(1, 4):
        wal.append("submit", seq, "a", ((seq,), {}))
    wal.close()
    blob = open(path, "rb").read()
    torn_path = str(tmp_path / "torn.wal")
    with open(torn_path, "wb") as fh:
        fh.write(blob[:-5])  # a crash mid-append tears a suffix
    records, torn = IngestWAL.read_records(torn_path)
    assert [r[1] for r in records] == [1, 2] and torn
    flip_path = str(tmp_path / "flip.wal")
    flipped = bytearray(blob)
    flipped[-1] ^= 0xFF
    with open(flip_path, "wb") as fh:
        fh.write(bytes(flipped))
    records, torn = IngestWAL.read_records(flip_path)
    assert [r[1] for r in records] == [1, 2] and torn
    assert IngestWAL.read_records(str(tmp_path / "missing.wal")) == ([], False)


# --------------------------------------------------------- checkpoint + replay
def test_crash_recovery_is_bit_exact_vs_never_crashed_oracle(tmp_path):
    rng = np.random.RandomState(0)
    wal = str(tmp_path / "ingest.wal")
    ckpt = str(tmp_path / "fleet.mtckpt")
    engine = StreamEngine(initial_capacity=4, wal_path=wal)
    sids = [engine.add_session(_acc()) for _ in range(3)]
    sids += [engine.add_session(_auroc()) for _ in range(3)]
    batches = {sid: [] for sid in sids}
    for _ in range(2):
        for sid in sids:
            args = _acc_batch(rng) if sid < 3 else _auroc_batch(rng)
            batches[sid].append(args)
            engine.submit(sid, *args)
        engine.tick()
    engine.checkpoint(ckpt)
    # the pending tail: journaled + fsynced, never ticked — the crash state
    for sid in sids:
        args = _acc_batch(rng) if sid < 3 else _auroc_batch(rng)
        batches[sid].append(args)
        engine.submit(sid, *args)
    engine._wal.sync()
    recovered = StreamEngine.restore(ckpt, wal_path=wal)
    engine.tick()  # the oracle never crashed: it just applies the same tail
    recovered.tick()
    _assert_engines_equal(recovered, engine, sids)
    for sid in (sids[0], sids[-1]):
        np.testing.assert_array_equal(
            np.asarray(recovered.compute(sid)), np.asarray(engine.compute(sid))
        )
    assert _counters("wal_replay") == len(sids)  # exactly the unticked wave
    assert _counters("ckpt_restore") == 1
    assert _counters("fleet_restore") == 1


def test_restored_engine_keeps_one_dispatch_per_bucket_tick(tmp_path):
    rng = np.random.RandomState(1)
    wal = str(tmp_path / "ingest.wal")
    ckpt = str(tmp_path / "fleet.mtckpt")
    engine = StreamEngine(wal_path=wal)
    sids = [engine.add_session(_acc()) for _ in range(3)]
    for sid in sids:
        engine.submit(sid, *_acc_batch(rng))
    engine.tick()
    engine.checkpoint(ckpt)
    for sid in sids:
        engine.submit(sid, *_acc_batch(rng))
    engine._wal.sync()
    recovered = StreamEngine.restore(ckpt, wal_path=wal)
    # the replayed wave coalesces exactly like a never-crashed tick would
    assert recovered.tick() == 1
    # lifecycle keeps journaling on the repaired WAL: another crashless cycle
    recovered.submit(sids[0], *_acc_batch(rng))
    assert recovered.tick() == 1


def test_expire_and_reset_replay_from_journal(tmp_path):
    rng = np.random.RandomState(2)
    wal = str(tmp_path / "ingest.wal")
    ckpt = str(tmp_path / "fleet.mtckpt")
    engine = StreamEngine(wal_path=wal)
    a, b = engine.add_session(_acc()), engine.add_session(_acc())
    for sid in (a, b):
        engine.submit(sid, *_acc_batch(rng))
    engine.tick()
    engine.checkpoint(ckpt)
    engine.submit(a, *_acc_batch(rng))
    engine.expire(b)
    engine.reset(a)  # discards a's queued submission too
    engine._wal.sync()
    recovered = StreamEngine.restore(ckpt, wal_path=wal)
    recovered.tick()
    engine.tick()
    assert set(recovered.session_ids()) == {a}
    _assert_engines_equal(recovered, engine, [a])
    oracle = _acc()  # reset wound a back to defaults in both engines
    for k, v in _state_rows(recovered, a).items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(oracle._state[k]))


def test_restore_resumes_auto_session_ids_past_journal(tmp_path):
    wal = str(tmp_path / "ingest.wal")
    ckpt = str(tmp_path / "fleet.mtckpt")
    engine = StreamEngine(wal_path=wal)
    sids = [engine.add_session(_acc()) for _ in range(3)]
    engine.checkpoint(ckpt)
    post = engine.add_session(_acc())  # journaled, not checkpointed
    engine._wal.sync()
    recovered = StreamEngine.restore(ckpt, wal_path=wal)
    assert set(recovered.session_ids()) == {*sids, post}
    fresh = recovered.add_session(_acc())
    assert fresh not in {*sids, post}  # recovered ids never recycle


def test_checkpoint_truncates_journal_to_uncovered_records(tmp_path):
    rng = np.random.RandomState(3)
    wal = str(tmp_path / "ingest.wal")
    engine = StreamEngine(wal_path=wal)
    sid = engine.add_session(_acc())
    engine.submit(sid, *_acc_batch(rng))
    engine.tick()
    engine.checkpoint(str(tmp_path / "a.mtckpt"))
    assert IngestWAL.read_records(wal)[0] == []  # snapshot covers everything
    engine.submit(sid, *_acc_batch(rng))  # pending again
    engine._wal.sync()
    engine.checkpoint(str(tmp_path / "b.mtckpt"))
    kinds = [r[0] for r in IngestWAL.read_records(wal)[0]]
    assert kinds == ["submit"]  # pending records survive truncation
    assert _counters("wal_truncate") == 2


def test_clean_buckets_reuse_cached_checkpoint_bytes(tmp_path, monkeypatch):
    rng = np.random.RandomState(4)
    engine = StreamEngine()
    acc_sid = engine.add_session(_acc())
    auroc_sid = engine.add_session(_auroc())
    engine.submit(acc_sid, *_acc_batch(rng))
    engine.submit(auroc_sid, *_auroc_batch(rng))
    engine.tick()
    calls = []
    real = dur_mod._bucket_node
    monkeypatch.setattr(dur_mod, "_bucket_node", lambda b: calls.append(b.label) or real(b))
    engine.checkpoint(str(tmp_path / "one.mtckpt"))
    assert len(calls) == 2  # both buckets dirty on first snapshot
    del calls[:]
    engine.checkpoint(str(tmp_path / "two.mtckpt"))
    assert calls == []  # nothing moved: both re-emitted from cache
    engine.submit(acc_sid, *_acc_batch(rng))
    engine.tick()
    engine.checkpoint(str(tmp_path / "three.mtckpt"))
    assert len(calls) == 1  # only the bucket whose version moved re-pickles


def test_corrupt_fleet_checkpoint_rejected(tmp_path):
    rng = np.random.RandomState(5)
    engine = StreamEngine()
    sid = engine.add_session(_acc())
    engine.submit(sid, *_acc_batch(rng))
    engine.tick()
    ckpt = str(tmp_path / "fleet.mtckpt")
    engine.checkpoint(ckpt)
    blob = open(ckpt, "rb").read()
    torn = str(tmp_path / "torn.mtckpt")
    with open(torn, "wb") as fh:
        fh.write(blob[:-9])
    with pytest.raises(CorruptCheckpointError):
        StreamEngine.restore(torn)
    flipped = bytearray(blob)
    flipped[len(flipped) // 2] ^= 0xFF
    flip = str(tmp_path / "flip.mtckpt")
    with open(flip, "wb") as fh:
        fh.write(bytes(flipped))
    with pytest.raises(CorruptCheckpointError):
        StreamEngine.restore(flip)
    # the intact original still restores after both rejections
    assert set(StreamEngine.restore(ckpt).session_ids()) == {sid}


def test_journal_targeting_unknown_session_rejected(tmp_path):
    rng = np.random.RandomState(6)
    wal = str(tmp_path / "ingest.wal")
    ckpt = str(tmp_path / "fleet.mtckpt")
    engine = StreamEngine(wal_path=wal)
    sid = engine.add_session(_acc())
    engine.checkpoint(ckpt)
    engine.submit(sid, *_acc_batch(rng))
    engine._wal.sync()
    # a journal from a DIFFERENT engine history must not replay onto this snapshot
    records, _ = IngestWAL.read_records(wal)
    alien = IngestWAL(str(tmp_path / "alien.wal"))
    for kind, seq, _sid, payload in records:
        alien.append(kind, seq, "never-added", payload)
    alien.close()
    with pytest.raises(CorruptCheckpointError, match="unknown"):
        StreamEngine.restore(ckpt, wal_path=str(tmp_path / "alien.wal"))


# ------------------------------------------------------------ precision regime
def test_roundtrip_under_x64_and_regime_mismatch_rejected(tmp_path):
    rng = np.random.RandomState(7)
    ckpt32 = str(tmp_path / "f32.mtckpt")
    engine = StreamEngine()
    sid = engine.add_session(_acc())
    engine.submit(sid, *_acc_batch(rng))
    engine.tick()
    engine.checkpoint(ckpt32)
    assert jax.config.jax_enable_x64 is False
    jax.config.update("jax_enable_x64", True)
    try:
        clear_jit_cache()
        # f32-written / f64-read: refused loudly, never silently cast
        with pytest.raises(IncompatibleCheckpointError, match="precision regime"):
            StreamEngine.restore(ckpt32)
        # a full round trip natively under x64 stays bit-exact
        ckpt64 = str(tmp_path / "f64.mtckpt")
        wide = StreamEngine()
        wsid = wide.add_session(_acc())
        wide.submit(wsid, *_acc_batch(rng))
        wide.tick()
        wide.checkpoint(ckpt64)
        recovered = StreamEngine.restore(ckpt64)
        _assert_engines_equal(recovered, wide, [wsid])
    finally:
        jax.config.update("jax_enable_x64", False)
        clear_jit_cache()
    # f64-written / f32-read: the same refusal, other direction
    with pytest.raises(IncompatibleCheckpointError, match="precision regime"):
        StreamEngine.restore(ckpt64)


# --------------------------------------------------------- blast-radius limits
def test_nan_guard_quarantines_one_session_never_the_bucket(tmp_path):
    rng = np.random.RandomState(8)
    engine = StreamEngine(nan_guard=True)
    sids = [engine.add_session(_auroc()) for _ in range(4)]
    oracles = {sid: _auroc() for sid in sids[1:]}
    for sid in sids[1:]:
        args = _auroc_batch(rng)
        engine.submit(sid, *args)
        oracles[sid].update(*args)
    preds, target = _auroc_batch(rng)
    engine.submit(sids[0], preds.at[3].set(jnp.nan), target)
    # the poisoned batch is dropped pre-dispatch; the survivors still coalesce
    # into ONE dispatch — a quarantined session never demotes its bucket
    assert engine.tick() == 1
    assert engine.session_health(sids[0]) == "quarantined"
    assert all(engine.session_health(sid) == "healthy" for sid in sids[1:])
    for sid in sids[1:]:
        assert engine._sessions[sid].bucket is not None
        for k, ref in oracles[sid]._state.items():
            np.testing.assert_array_equal(
                np.asarray(_state_rows(engine, sid)[k]), np.asarray(ref)
            )
    snap = observe.snapshot()["counters"]
    assert sum(snap["fleet_quarantine"].values()) == 1
    assert sum(snap["fleet_dispatch"].values()) == 1
    stats = engine.stats()
    assert stats["quarantined_sessions"] == 1
    (label,) = stats["buckets"]
    assert stats["buckets"][label]["health"] == "degraded"  # faulted, not dissolved
    # the quarantined session lives on loose: clean submissions still land
    clean = _auroc_batch(rng)
    engine.submit(sids[0], *clean)
    engine.tick()
    oracle = _auroc()
    oracle.update(*clean)
    np.testing.assert_array_equal(
        np.asarray(engine.compute(sids[0])), np.asarray(oracle.compute())
    )


def test_runtime_dispatch_death_replays_rows_and_quarantines_the_poison():
    import metrics_tpu.engine.stream as stream_mod

    rng = np.random.RandomState(9)
    engine = StreamEngine()
    sids = [engine.add_session(_auroc()) for _ in range(3)]
    oracles = {sid: _auroc() for sid in sids}
    marked = {}
    for j, sid in enumerate(sids):
        preds, target = _auroc_batch(rng)
        if j == 1:
            preds = preds.at[0].set(7.0)  # the marker the row replay will reject
        marked[sid] = (preds, target)
        engine.submit(sid, *marked[sid])
        oracles[sid].update(*marked[sid])
    bucket = engine._sessions[sids[0]].bucket
    real_update = stream_mod.engine_update
    real_row = bucket.template._functional_update

    def dead_dispatch(*args, **kwargs):
        raise RuntimeError("injected runtime dispatch death")

    def picky_row(row, preds, target):
        if float(np.asarray(preds).max()) > 1.0:
            raise ValueError("poisoned row")
        return real_row(row, preds, target)

    stream_mod.engine_update = dead_dispatch
    bucket.template._functional_update = picky_row
    try:
        engine.tick()  # dispatch dies -> per-row eager replay with intact buffers
    finally:
        stream_mod.engine_update = real_update
        del bucket.template.__dict__["_functional_update"]
    assert engine.session_health(sids[1]) == "quarantined"
    assert engine.session_health(sids[0]) == "healthy"
    assert engine.session_health(sids[2]) == "healthy"
    for sid in (sids[0], sids[2]):  # survivors landed their updates bit-exact
        assert engine._sessions[sid].bucket is not None
        for k, ref in oracles[sid]._state.items():
            np.testing.assert_array_equal(
                np.asarray(_state_rows(engine, sid)[k]), np.asarray(ref)
            )
    # the poisoned session rolled back: its failed batch was consumed, not applied
    for k, ref in _auroc()._state.items():
        np.testing.assert_array_equal(np.asarray(_state_rows(engine, sids[1])[k]), np.asarray(ref))
    snap = observe.snapshot()["counters"]
    assert sum(snap["fleet_quarantine"].values()) == 1
    assert sum(snap["fleet_row_replay"].values()) == 2


def test_quarantined_sessions_checkpoint_and_restore_loose(tmp_path):
    rng = np.random.RandomState(10)
    wal = str(tmp_path / "ingest.wal")
    ckpt = str(tmp_path / "fleet.mtckpt")
    engine = StreamEngine(wal_path=wal, nan_guard=True)
    sids = [engine.add_session(_auroc()) for _ in range(2)]
    preds, target = _auroc_batch(rng)
    engine.submit(sids[0], preds.at[0].set(jnp.inf), target)
    engine.submit(sids[1], *_auroc_batch(rng))
    engine.tick()
    assert engine.session_health(sids[0]) == "quarantined"
    engine.checkpoint(ckpt)
    recovered = StreamEngine.restore(ckpt, wal_path=wal)
    assert recovered.session_health(sids[0]) == "quarantined"
    assert recovered.session_health(sids[1]) == "healthy"
    _assert_engines_equal(recovered, engine, sids)


# ------------------------------------------------- crashpoints + torn tails
def test_wal_truncation_waits_for_a_durable_checkpoint(tmp_path, monkeypatch):
    """Crashpoint between snapshot write and journal truncation: the ordering
    contract is that not one journal byte drops until the checkpoint file is
    durable, so a crash exactly there recovers bit-exact from new-ckpt+full-WAL."""
    rng = np.random.RandomState(37)
    wal = str(tmp_path / "ingest.wal")
    ckpt = str(tmp_path / "fleet.mtckpt")
    engine = StreamEngine(wal_path=wal)
    sid = engine.add_session(_acc())
    oracle = _acc()
    args = _acc_batch(rng)
    engine.submit(sid, *args)
    oracle.update(*args)
    engine.tick()
    seen = {}

    def crashing_truncate(self, keep):
        seen["ckpt_durable"] = os.path.exists(ckpt) and os.path.getsize(ckpt) > 0
        seen["wal_bytes"] = os.path.getsize(wal)
        raise RuntimeError("injected crash before truncate")

    monkeypatch.setattr(IngestWAL, "truncate", crashing_truncate)
    with pytest.raises(RuntimeError, match="injected crash"):
        engine.checkpoint(ckpt)
    monkeypatch.undo()
    # the snapshot was already durable when the crash hit, the journal untouched
    assert seen == {"ckpt_durable": True, "wal_bytes": os.path.getsize(wal)}
    engine._wal.close()
    recovered = StreamEngine.restore(ckpt, wal_path=wal)
    np.testing.assert_array_equal(
        np.asarray(recovered.compute(sid)), np.asarray(oracle.compute())
    )


def test_sharded_truncation_waits_for_a_durable_manifest(tmp_path, monkeypatch):
    """Same ordering contract one level up (engine/sharded.py): every shard's
    journal truncates only AFTER the fleet manifest is on disk, and a crash
    between per-shard truncations still restores bit-exact (the survivors'
    journals carry applied records that replay filters out)."""
    from metrics_tpu.engine import ShardedStreamEngine
    from metrics_tpu.engine.sharded import MANIFEST_NAME, shard_of
    from metrics_tpu.resilience.checkpoint import load_manifest

    rng = np.random.RandomState(39)
    wal_dir, ckpt_dir = str(tmp_path / "w"), str(tmp_path / "c")
    fleet = ShardedStreamEngine(n_shards=2, wal_dir=wal_dir)
    sids, i = [], 0
    while len(sids) < 4:  # two sessions per shard
        sid = f"s{i}"
        i += 1
        if sum(1 for s in sids if shard_of(s, 2) == shard_of(sid, 2)) < 2:
            sids.append(sid)
    oracles = {sid: _acc() for sid in sids}
    for sid in sids:
        fleet.add_session(_acc(), sid)
        args = _acc_batch(rng)
        fleet.submit(sid, *args)
        oracles[sid].update(*args)
    fleet.tick()
    manifest_path = os.path.join(ckpt_dir, MANIFEST_NAME)
    durable_at_truncate = []
    real_truncate = IngestWAL.truncate

    def observing_truncate(self, keep):
        durable_at_truncate.append(
            os.path.exists(manifest_path) and load_manifest(manifest_path)["generation"] == 1
        )
        if len(durable_at_truncate) == 2:
            raise RuntimeError("injected crash between shard truncations")
        return real_truncate(self, keep)

    monkeypatch.setattr(IngestWAL, "truncate", observing_truncate)
    with pytest.raises(RuntimeError, match="injected crash"):
        fleet.checkpoint(ckpt_dir)
    monkeypatch.undo()
    assert durable_at_truncate == [True, True]  # manifest preceded EVERY truncate
    for shard in fleet._shards:
        if shard._wal is not None:
            shard._wal.close()
    rec = ShardedStreamEngine.restore(ckpt_dir, wal_dir=wal_dir)
    assert set(rec.session_ids()) == set(sids)
    for sid in sids:
        np.testing.assert_array_equal(
            np.asarray(rec.compute(sid)), np.asarray(oracles[sid].compute())
        )


def test_torn_tail_location_is_surfaced_in_stats_and_events(tmp_path):
    rng = np.random.RandomState(41)
    wal = str(tmp_path / "ingest.wal")
    ckpt = str(tmp_path / "fleet.mtckpt")
    engine = StreamEngine(wal_path=wal)
    sid = engine.add_session(_acc())
    oracle = _acc()
    args = _acc_batch(rng)
    engine.submit(sid, *args)
    oracle.update(*args)
    engine.tick()
    engine.checkpoint(ckpt)
    args = _acc_batch(rng)  # journaled after the snapshot: survives the tear
    engine.submit(sid, *args)
    oracle.update(*args)
    engine.submit(sid, *_acc_batch(rng))  # the frame the crash tears off
    engine._wal.sync()
    engine._wal.close()
    blob = open(wal, "rb").read()
    open(wal, "wb").write(blob[:-5])
    records, torn = IngestWAL.read_records_detailed(wal)
    assert torn is not None
    assert torn["frame_index"] == len(records) == 1
    assert 0 < torn["byte_offset"] < len(blob)
    recovered = StreamEngine.restore(ckpt, wal_path=wal)
    # the damage location rides the stats surface and the observe event stream
    assert recovered.stats()["wal_torn_tail"] == (torn["frame_index"], torn["byte_offset"])
    assert _counters("wal_torn_tail") == 1
    events = [e for e in observe.snapshot()["events"] if e["kind"] == "wal_torn_tail"]
    assert events[-1]["frame"] == torn["frame_index"]
    assert events[-1]["offset"] == torn["byte_offset"]
    assert observe.snapshot()["derived"]["wal_torn_tails_total"] == 1
    recovered.tick()  # everything before the tear still replays
    np.testing.assert_array_equal(
        np.asarray(recovered.compute(sid)), np.asarray(oracle.compute())
    )


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))
