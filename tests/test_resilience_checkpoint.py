"""Durable checkpoint/restore (DESIGN §14): atomic snapshot files with a
versioned, validated header; corrupt or incompatible checkpoints are rejected
before a single byte of state is installed."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.aggregation import MeanMetric
from metrics_tpu.classification import BinaryAccuracy, BinaryF1Score
from metrics_tpu.collections import MetricCollection
from metrics_tpu.metric import Metric, clear_jit_cache
from metrics_tpu.resilience import (
    CorruptCheckpointError,
    IncompatibleCheckpointError,
    PeriodicCheckpointer,
    SnapshotPolicy,
    restore_checkpoint,
    save_checkpoint,
)


def _host_state(m):
    return {k: np.asarray(jax.device_get(v)) for k, v in m.__dict__["_state"].items()}


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.rand(32)), jnp.asarray(rng.randint(0, 2, 32))


def test_metric_roundtrip_is_bit_exact(tmp_path):
    m = BinaryAccuracy()
    m.update(*_batch(0))
    m.update(*_batch(1))
    path = str(tmp_path / "acc.ckpt")
    save_checkpoint(m, path)

    fresh = BinaryAccuracy()
    restore_checkpoint(fresh, path)
    a, b = _host_state(m), _host_state(fresh)
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])
    assert fresh._update_count == m._update_count
    np.testing.assert_allclose(np.asarray(fresh.compute()), np.asarray(m.compute()))


def test_restored_metric_survives_donated_dispatch(tmp_path):
    clear_jit_cache()
    m = BinaryAccuracy()
    m.update(*_batch(0))
    path = str(tmp_path / "acc.ckpt")
    save_checkpoint(m, path)
    fresh = BinaryAccuracy()
    restore_checkpoint(fresh, path)
    # the restored buffers may be aliased by the checkpoint layer: the next
    # donated dispatch must copy, not consume (escape latch set on install)
    fresh.update(*_batch(1))
    fresh.update(*_batch(2))
    oracle = BinaryAccuracy()
    for s in (0, 1, 2):
        oracle.update(*_batch(s))
    np.testing.assert_allclose(np.asarray(fresh.compute()), np.asarray(oracle.compute()), rtol=1e-6)


def test_collection_roundtrip(tmp_path):
    col = MetricCollection([BinaryAccuracy(), BinaryF1Score()])
    col.update(*_batch(0))
    path = str(tmp_path / "col.ckpt")
    save_checkpoint(col, path)
    fresh = MetricCollection([BinaryAccuracy(), BinaryF1Score()])
    restore_checkpoint(fresh, path)
    got, want = fresh.compute(), col.compute()
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]))


def test_truncated_checkpoint_rejected_and_target_untouched(tmp_path):
    m = BinaryAccuracy()
    m.update(*_batch(0))
    path = str(tmp_path / "acc.ckpt")
    save_checkpoint(m, path)
    blob = open(path, "rb").read()
    broken = str(tmp_path / "trunc.ckpt")
    with open(broken, "wb") as fh:
        fh.write(blob[:-7])

    target = BinaryAccuracy()
    target.update(*_batch(1))
    before = _host_state(target)
    with pytest.raises(CorruptCheckpointError):
        restore_checkpoint(target, broken)
    after = _host_state(target)
    for k in before:
        np.testing.assert_array_equal(before[k], after[k])


def test_bitflipped_checkpoint_rejected(tmp_path):
    m = BinaryAccuracy()
    m.update(*_batch(0))
    path = str(tmp_path / "acc.ckpt")
    save_checkpoint(m, path)
    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0xFF
    broken = str(tmp_path / "flip.ckpt")
    with open(broken, "wb") as fh:
        fh.write(bytes(blob))
    with pytest.raises(CorruptCheckpointError):
        restore_checkpoint(BinaryAccuracy(), broken)


def test_trailing_garbage_rejected(tmp_path):
    m = BinaryAccuracy()
    m.update(*_batch(0))
    path = str(tmp_path / "acc.ckpt")
    save_checkpoint(m, path)
    with open(path, "ab") as fh:
        fh.write(b"garbage")
    with pytest.raises(CorruptCheckpointError):
        restore_checkpoint(BinaryAccuracy(), path)


def test_wrong_class_rejected(tmp_path):
    m = BinaryAccuracy()
    m.update(*_batch(0))
    path = str(tmp_path / "acc.ckpt")
    save_checkpoint(m, path)
    with pytest.raises(IncompatibleCheckpointError):
        restore_checkpoint(MeanMetric(), path)


def test_wrong_config_rejected_by_fingerprint(tmp_path):
    m = BinaryAccuracy()
    m.update(*_batch(0))
    path = str(tmp_path / "acc.ckpt")
    save_checkpoint(m, path)
    with pytest.raises(IncompatibleCheckpointError, match="fingerprint"):
        restore_checkpoint(BinaryAccuracy(threshold=0.7), path)


def test_periodic_checkpointer_fires_on_cadence(tmp_path):
    m = BinaryAccuracy()
    path = str(tmp_path / "periodic.ckpt")
    ck = PeriodicCheckpointer(m, path, SnapshotPolicy(every_n_updates=3))
    fired = []
    for i in range(7):
        m.update(*_batch(i))
        fired.append(ck.step())
    assert fired == [False, False, True, False, False, True, False]
    assert os.path.exists(path)
    fresh = BinaryAccuracy()
    restore_checkpoint(fresh, path)
    assert fresh._update_count == 6  # the snapshot at step 6, not the live state


def test_save_is_atomic_no_partial_files(tmp_path):
    m = BinaryAccuracy()
    m.update(*_batch(0))
    path = str(tmp_path / "acc.ckpt")
    save_checkpoint(m, path)
    save_checkpoint(m, path)  # overwrite goes through rename too
    leftovers = [p for p in os.listdir(tmp_path) if p != "acc.ckpt"]
    assert leftovers == []


def test_chunked_crc_is_bit_identical_to_monolithic():
    """The streaming CRC (O(chunk) memory) must pin the exact zlib value — a
    drift here would reject every checkpoint written by the other code path."""
    import zlib

    from metrics_tpu.resilience.checkpoint import _crc32_chunked

    rng = np.random.RandomState(42)
    parts = [rng.bytes(n) for n in (0, 1, 7, 1 << 10, (1 << 16) + 13)]
    joined = b"".join(parts)
    assert _crc32_chunked(*parts) == zlib.crc32(joined) & 0xFFFFFFFF
    # chunk boundaries must not matter, including chunks smaller than a part
    for chunk in (1, 3, 1 << 8, 1 << 22):
        assert _crc32_chunked(*parts, chunk_size=chunk) == zlib.crc32(joined) & 0xFFFFFFFF
    assert _crc32_chunked() == 0  # empty payload: zlib's identity CRC


def test_save_checkpoint_dispatches_stream_engine_to_fleet_path(tmp_path):
    """``save_checkpoint(engine)`` and ``engine.checkpoint()`` are the same
    fleet container — either save restores through either entry point."""
    from metrics_tpu import StreamEngine

    engine = StreamEngine()
    sid = engine.add_session(BinaryAccuracy())
    engine.submit(sid, *_batch(0))
    engine.tick()
    path = str(tmp_path / "fleet.mtckpt")
    save_checkpoint(engine, path)
    target = StreamEngine()
    restore_checkpoint(target, path)
    np.testing.assert_array_equal(
        np.asarray(target.compute(sid)), np.asarray(engine.compute(sid))
    )


# ------------------------------------------------- load_state_dict satellites
class _PersistentSum(Metric):
    full_state_update = False

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum", persistent=True)
        self.add_state("count", jnp.asarray(0), dist_reduce_fx="sum", persistent=True)

    def update(self, x):
        x = jnp.asarray(x, dtype=jnp.float32)
        self.total = self.total + x.sum()
        self.count = self.count + x.size

    def compute(self):
        return self.total / jnp.maximum(self.count, 1)


def test_load_state_dict_strict_false_tolerates_missing_keys():
    m = _PersistentSum()
    m.update(jnp.arange(4.0))
    sd = m.state_dict()
    partial = {"total": sd["total"], "_update_count": sd["_update_count"]}
    with pytest.raises(RuntimeError, match="Missing key count"):
        _PersistentSum().load_state_dict(partial, strict=True)
    fresh = _PersistentSum()
    fresh.load_state_dict(partial, strict=False)
    np.testing.assert_array_equal(np.asarray(fresh.__dict__["_state"]["total"]), sd["total"])
    assert fresh._update_count == m._update_count
    np.testing.assert_array_equal(np.asarray(fresh.__dict__["_state"]["count"]), 0)


def test_load_state_dict_aval_mismatch_names_the_metric():
    fresh = BinaryAccuracy()
    key = next(iter(fresh.__dict__["_state"]))
    bad = {key: jnp.zeros((3, 3, 3), dtype=jnp.float32)}
    with pytest.raises(RuntimeError, match="BinaryAccuracy"):
        fresh.load_state_dict(bad, strict=False)


def test_replicated_wrapper_roundtrip_bit_exact(tmp_path):
    from metrics_tpu.wrappers import BootStrapper

    np.random.seed(7)
    w = BootStrapper(BinaryAccuracy(), num_bootstraps=4)
    np.random.seed(7)
    w.update(*_batch(0))
    path = str(tmp_path / "boot.ckpt")
    save_checkpoint(w, path)

    fresh = BootStrapper(BinaryAccuracy(), num_bootstraps=4)
    restore_checkpoint(fresh, path)
    got, want = fresh.compute(), w.compute()
    for k in want:
        np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(want[k]))
    # BootStrapper resamples from the global numpy RNG: seed identically so the
    # restored wrapper and the original stay twins through post-restore updates
    np.random.seed(11)
    w.update(*_batch(1))
    np.random.seed(11)
    fresh.update(*_batch(1))
    got, want = fresh.compute(), w.compute()
    for k in want:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]), rtol=1e-6)


def test_collection_load_state_dict_strict_flag():
    col = MetricCollection([BinaryAccuracy(), BinaryF1Score()])
    col.update(*_batch(0))
    sd = col.state_dict()
    partial = {k: v for k, v in sd.items() if "Accuracy" in k}
    with pytest.raises(RuntimeError, match="strict=False"):
        MetricCollection([BinaryAccuracy(), BinaryF1Score()]).load_state_dict(partial)
    fresh = MetricCollection([BinaryAccuracy(), BinaryF1Score()])
    fresh.load_state_dict(partial, strict=False)  # intersection loads cleanly
