"""COCO-val-scale MeanAveragePrecision benchmark (round-2 VERDICT next #4).

Synthesizes a COCO-val-like workload — 5 000 images, 80 classes, ~7 gts and
~8 detections per image with realistic size spread — and times the full
evaluate (update stream + compute) of :class:`metrics_tpu.detection.MeanAveragePrecision`
on the default backend (real TPU when the tunnel is live, CPU otherwise; the
backend is probed via ``ensure_backend`` so a wedged tunnel cannot hang the run).

Usage::

    python tools/map_scale_bench.py              # ours only (JSON line to stdout)
    python tools/map_scale_bench.py --reference  # also time the reference's
                                                 # pure-torch backend (slow!)
    python tools/map_scale_bench.py --images 500 # smaller sweep

Writes ``MAP_SCALE_BENCH.json`` at the repo root with the machine-readable
result alongside the stdout line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def synth_dataset(n_images: int, n_classes: int, seed: int = 0, crowd_prob: float = 0.03):
    """COCO-val-like predictions/targets: mixed object sizes, crowd flags, score noise.

    ``crowd_prob=0`` generates a crowd-free set — required when comparing against
    the reference's legacy torch backend, which does not model crowd re-matching
    (our matcher does, oracled separately in ``tests/_map_oracle.py``).
    """
    rng = np.random.RandomState(seed)
    preds, target = [], []
    for _ in range(n_images):
        ng = rng.randint(1, 15)  # COCO val avg ≈ 7.3 gts/img
        # log-uniform object scale: many small, few large (COCO size dist)
        wh = np.exp(rng.uniform(np.log(6), np.log(300), (ng, 2)))
        xy = rng.rand(ng, 2) * (640 - wh.clip(max=600))
        gb = np.concatenate([xy, xy + wh], axis=1)
        glab = rng.randint(0, n_classes, ng)
        crowd = (rng.rand(ng) < crowd_prob).astype(np.int64)

        # detections: jittered copies of most gts (localization noise ∝ size),
        # some dropped, plus false positives
        keep = rng.rand(ng) < 0.85
        jitter = rng.randn(ng, 4) * (wh.mean(axis=1, keepdims=True) * 0.08)
        db_tp = (gb + jitter)[keep]
        lab_tp = glab[keep]
        n_fp = rng.randint(0, 6)
        wh_fp = np.exp(rng.uniform(np.log(6), np.log(300), (n_fp, 2)))
        xy_fp = rng.rand(n_fp, 2) * (640 - wh_fp.clip(max=600))
        db = np.concatenate([db_tp, np.concatenate([xy_fp, xy_fp + wh_fp], axis=1)])
        db[:, 2:] = np.maximum(db[:, 2:], db[:, :2] + 1)
        dlab = np.concatenate([lab_tp, rng.randint(0, n_classes, n_fp)])
        scores = np.clip(np.concatenate([rng.uniform(0.5, 1.0, keep.sum()), rng.uniform(0.05, 0.6, n_fp)]), 0, 1)

        preds.append({"boxes": db.astype(np.float32), "scores": scores.astype(np.float32), "labels": dlab})
        target.append({"boxes": gb.astype(np.float32), "labels": glab, "iscrowd": crowd})
    return preds, target


# the official 12-number COCO detection summary (reference ``detection/mean_ap.py:521-600``)
COCO_SUMMARY_KEYS = (
    "map", "map_50", "map_75", "map_small", "map_medium", "map_large",
    "mar_1", "mar_10", "mar_100", "mar_small", "mar_medium", "mar_large",
)


def _summarize(result, n_classes: int):
    """compute() dict → {12 summary numbers} + per-class AP/AR vectors."""
    summary = {k: round(float(result[k]), 6) for k in COCO_SUMMARY_KEYS}
    per_class_ap = np.full(n_classes, -1.0)
    per_class_ar = np.full(n_classes, -1.0)
    classes = np.asarray(result["classes"]).reshape(-1).astype(int)
    ap = np.asarray(result["map_per_class"]).reshape(-1)
    ar = np.asarray(result["mar_100_per_class"]).reshape(-1)
    if ap.size == classes.size:  # class_metrics=True path
        per_class_ap[classes] = ap
        per_class_ar[classes] = ar
    return summary, per_class_ap, per_class_ar


def bench_ours(preds, target, n_classes: int, repeats: int = 2):
    import jax.numpy as jnp

    from metrics_tpu.detection import MeanAveragePrecision

    j_preds = [{k: jnp.asarray(v) for k, v in d.items()} for d in preds]
    j_target = [{k: jnp.asarray(v) for k, v in d.items()} for d in target]

    def run():
        m = MeanAveragePrecision(class_metrics=True)
        m.update(j_preds, j_target)
        return m.compute()

    result = run()  # compile
    value = float(result["map"])
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        got = run()
        best = min(best, time.perf_counter() - t0)
        assert float(got["map"]) == value
    return best, _summarize(result, n_classes)


def bench_reference(preds, target, n_classes: int, repeats: int = 1):
    sys.path.insert(0, os.path.join(REPO, "tests", "_ref_shim"))
    sys.path.insert(0, "/root/reference/src")
    import torch
    from torchmetrics.detection._mean_ap import MeanAveragePrecision as RefMAP

    t_preds = [
        {k: torch.tensor(np.asarray(v), dtype=torch.long if k in ("labels", "iscrowd") else torch.float32)
         for k, v in d.items()}
        for d in preds
    ]
    t_target = [
        {k: torch.tensor(np.asarray(v), dtype=torch.long if k in ("labels", "iscrowd") else torch.float32)
         for k, v in d.items()}
        for d in target
    ]

    def run():
        m = RefMAP(class_metrics=True)
        m.update(t_preds, t_target)
        return m.compute()

    result = run()
    value = float(result["map"])
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        got = run()
        best = min(best, time.perf_counter() - t0)
        assert float(got["map"]) == value
    return best, _summarize({k: np.asarray(v) for k, v in result.items()}, n_classes)


def summarize_oracle(preds, target, n_classes: int):
    """12-number COCO summary + per-class AP from the sequential COCOeval
    transcription (``tests/_map_oracle.py``) — the TRUE-protocol oracle standing
    in for pycocotools (not installable here). Slow: pure-python loops."""
    from tests._map_oracle import evaluate_full

    precision, recall, classes = evaluate_full(preds, target)

    def _mean_valid(x):
        v = x[x > -1]
        return float(v.mean()) if v.size else -1.0

    # accumulate layout: precision (T, R, K, A, M), recall (T, K, A, M);
    # A = [all, small, medium, large], M = [1, 10, 100]
    summary = {
        "map": _mean_valid(precision[:, :, :, 0, 2]),
        "map_50": _mean_valid(precision[0, :, :, 0, 2]),
        "map_75": _mean_valid(precision[5, :, :, 0, 2]),
        "map_small": _mean_valid(precision[:, :, :, 1, 2]),
        "map_medium": _mean_valid(precision[:, :, :, 2, 2]),
        "map_large": _mean_valid(precision[:, :, :, 3, 2]),
        "mar_1": _mean_valid(recall[:, :, 0, 0]),
        "mar_10": _mean_valid(recall[:, :, 0, 1]),
        "mar_100": _mean_valid(recall[:, :, 0, 2]),
        "mar_small": _mean_valid(recall[:, :, 1, 2]),
        "mar_medium": _mean_valid(recall[:, :, 2, 2]),
        "mar_large": _mean_valid(recall[:, :, 3, 2]),
    }
    summary = {k: round(v, 6) for k, v in summary.items()}
    per_class_ap = np.full(n_classes, -1.0)
    per_class_ar = np.full(n_classes, -1.0)
    for ki, cls in enumerate(classes):
        per_class_ap[int(cls)] = _mean_valid(precision[:, :, ki, 0, 2])
        per_class_ar[int(cls)] = _mean_valid(recall[:, ki, 0, 2])
    return summary, per_class_ap, per_class_ar


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=5000)
    ap.add_argument("--classes", type=int, default=80)
    ap.add_argument("--reference", action="store_true", help="also time the reference torch backend")
    ap.add_argument("--oracle", action="store_true",
                    help="also check the full summary + per-class AP against the COCOeval transcription")
    ap.add_argument("--repeats", type=int, default=2)
    ap.add_argument("--out", default=None,
                    help="artifact path (default: MAP_SCALE_BENCH.json at full scale, "
                         "MAP_SCALE_BENCH_SMALL.json below 1000 images — small/under-load runs "
                         "must never clobber the full-scale evidence)")
    args = ap.parse_args()

    from metrics_tpu.utils.backend import ensure_backend

    platform = ensure_backend(min_devices=1)
    import jax

    backend = jax.default_backend()

    # crowd-free when the reference oracle runs (its legacy backend has no crowd model)
    preds, target = synth_dataset(args.images, args.classes, crowd_prob=0.0 if args.reference else 0.03)
    n_det = int(sum(len(p["scores"]) for p in preds))
    n_gt = int(sum(len(t["labels"]) for t in target))

    t_ours, (summary_ours, ap_ours, ar_ours) = bench_ours(preds, target, args.classes, repeats=args.repeats)
    out = {
        "metric": "mean_ap_coco_val_scale",
        "images": args.images,
        "classes": args.classes,
        "detections": n_det,
        "gts": n_gt,
        "backend": backend,
        "platform_probe": platform,
        "ours_s": round(t_ours, 3),
        "map": summary_ours["map"],
        "coco_summary": summary_ours,
        "map_per_class": [round(float(v), 6) for v in ap_ours],
    }
    if args.reference:
        t_ref, (summary_ref, ap_ref, ar_ref) = bench_reference(preds, target, args.classes)
        # The legacy torch backend deviates from the COCO protocol on AREA-RANGE
        # ignores (documented in tests/test_detection_map_parity.py:118-121): its
        # area-'all' keys are exact oracles, its small/medium/large keys are not —
        # those are asserted against the true-protocol COCOeval transcription
        # under --oracle instead. Report all diffs either way.
        strict_keys = ("map", "map_50", "map_75", "mar_1", "mar_10", "mar_100")
        diffs = {k: abs(summary_ours[k] - summary_ref[k]) for k in COCO_SUMMARY_KEYS}
        out["reference_s"] = round(t_ref, 3)
        out["speedup"] = round(t_ref / t_ours, 2)
        out["coco_summary_reference"] = summary_ref
        out["summary_max_abs_diff_area_all"] = round(max(diffs[k] for k in strict_keys), 6)
        out["summary_max_abs_diff_area_ranges"] = round(
            max(v for k, v in diffs.items() if k not in strict_keys), 6
        )
        assert max(diffs[k] for k in strict_keys) < 1e-4, {
            k: (summary_ours[k], summary_ref[k]) for k in strict_keys
        }
    if args.oracle:
        t0 = time.perf_counter()
        summary_orc, ap_orc, ar_orc = summarize_oracle(preds, target, args.classes)
        t_orc = time.perf_counter() - t0
        diffs = {k: abs(summary_ours[k] - summary_orc[k]) for k in COCO_SUMMARY_KEYS}
        per_class_diff = float(np.max(np.abs(ap_ours - ap_orc))) if len(ap_ours) else 0.0
        per_class_ar_diff = float(np.max(np.abs(ar_ours - ar_orc))) if len(ar_ours) else 0.0
        out["oracle_s"] = round(t_orc, 3)
        out["coco_summary_cocoeval_oracle"] = summary_orc
        out["oracle_summary_max_abs_diff"] = round(max(diffs.values()), 6)
        out["oracle_per_class_ap_max_abs_diff"] = round(per_class_diff, 6)
        out["oracle_per_class_ar_max_abs_diff"] = round(per_class_ar_diff, 6)
        assert max(diffs.values()) < 1e-4, {k: (summary_ours[k], summary_orc[k]) for k in COCO_SUMMARY_KEYS}
        assert per_class_diff < 1e-4, per_class_diff
        assert per_class_ar_diff < 1e-4, per_class_ar_diff

    print(json.dumps(out))
    default_name = "MAP_SCALE_BENCH.json" if args.images >= 1000 else "MAP_SCALE_BENCH_SMALL.json"
    with open(args.out or os.path.join(REPO, default_name), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
