"""COCO-val-scale MeanAveragePrecision benchmark (round-2 VERDICT next #4).

Synthesizes a COCO-val-like workload — 5 000 images, 80 classes, ~7 gts and
~8 detections per image with realistic size spread — and times the full
evaluate (update stream + compute) of :class:`metrics_tpu.detection.MeanAveragePrecision`
on the default backend (real TPU when the tunnel is live, CPU otherwise; the
backend is probed via ``ensure_backend`` so a wedged tunnel cannot hang the run).

Usage::

    python tools/map_scale_bench.py              # ours only (JSON line to stdout)
    python tools/map_scale_bench.py --reference  # also time the reference's
                                                 # pure-torch backend (slow!)
    python tools/map_scale_bench.py --images 500 # smaller sweep

Writes ``MAP_SCALE_BENCH.json`` at the repo root with the machine-readable
result alongside the stdout line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def synth_dataset(n_images: int, n_classes: int, seed: int = 0):
    """COCO-val-like predictions/targets: mixed object sizes, crowd flags, score noise."""
    rng = np.random.RandomState(seed)
    preds, target = [], []
    for _ in range(n_images):
        ng = rng.randint(1, 15)  # COCO val avg ≈ 7.3 gts/img
        # log-uniform object scale: many small, few large (COCO size dist)
        wh = np.exp(rng.uniform(np.log(6), np.log(300), (ng, 2)))
        xy = rng.rand(ng, 2) * (640 - wh.clip(max=600))
        gb = np.concatenate([xy, xy + wh], axis=1)
        glab = rng.randint(0, n_classes, ng)
        crowd = (rng.rand(ng) < 0.03).astype(np.int64)

        # detections: jittered copies of most gts (localization noise ∝ size),
        # some dropped, plus false positives
        keep = rng.rand(ng) < 0.85
        jitter = rng.randn(ng, 4) * (wh.mean(axis=1, keepdims=True) * 0.08)
        db_tp = (gb + jitter)[keep]
        lab_tp = glab[keep]
        n_fp = rng.randint(0, 6)
        wh_fp = np.exp(rng.uniform(np.log(6), np.log(300), (n_fp, 2)))
        xy_fp = rng.rand(n_fp, 2) * (640 - wh_fp.clip(max=600))
        db = np.concatenate([db_tp, np.concatenate([xy_fp, xy_fp + wh_fp], axis=1)])
        db[:, 2:] = np.maximum(db[:, 2:], db[:, :2] + 1)
        dlab = np.concatenate([lab_tp, rng.randint(0, n_classes, n_fp)])
        scores = np.clip(np.concatenate([rng.uniform(0.5, 1.0, keep.sum()), rng.uniform(0.05, 0.6, n_fp)]), 0, 1)

        preds.append({"boxes": db.astype(np.float32), "scores": scores.astype(np.float32), "labels": dlab})
        target.append({"boxes": gb.astype(np.float32), "labels": glab, "iscrowd": crowd})
    return preds, target


def bench_ours(preds, target, repeats: int = 2):
    import jax.numpy as jnp

    from metrics_tpu.detection import MeanAveragePrecision

    j_preds = [{k: jnp.asarray(v) for k, v in d.items()} for d in preds]
    j_target = [{k: jnp.asarray(v) for k, v in d.items()} for d in target]

    def run():
        m = MeanAveragePrecision()
        m.update(j_preds, j_target)
        return float(m.compute()["map"])

    value = run()  # compile
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        got = run()
        best = min(best, time.perf_counter() - t0)
        assert got == value
    return best, value


def bench_reference(preds, target, repeats: int = 1):
    sys.path.insert(0, os.path.join(REPO, "tests", "_ref_shim"))
    sys.path.insert(0, "/root/reference/src")
    import torch
    from torchmetrics.detection._mean_ap import MeanAveragePrecision as RefMAP

    t_preds = [
        {k: torch.tensor(np.asarray(v), dtype=torch.long if k in ("labels", "iscrowd") else torch.float32)
         for k, v in d.items()}
        for d in preds
    ]
    t_target = [
        {k: torch.tensor(np.asarray(v), dtype=torch.long if k in ("labels", "iscrowd") else torch.float32)
         for k, v in d.items()}
        for d in target
    ]

    def run():
        m = RefMAP()
        m.update(t_preds, t_target)
        return float(m.compute()["map"])

    value = run()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        got = run()
        best = min(best, time.perf_counter() - t0)
        assert got == value
    return best, value


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=5000)
    ap.add_argument("--classes", type=int, default=80)
    ap.add_argument("--reference", action="store_true", help="also time the reference torch backend")
    ap.add_argument("--repeats", type=int, default=2)
    args = ap.parse_args()

    from metrics_tpu.utils.backend import ensure_backend

    platform = ensure_backend(min_devices=1)
    import jax

    backend = jax.default_backend()

    preds, target = synth_dataset(args.images, args.classes)
    n_det = int(sum(len(p["scores"]) for p in preds))
    n_gt = int(sum(len(t["labels"]) for t in target))

    t_ours, v_ours = bench_ours(preds, target, repeats=args.repeats)
    out = {
        "metric": "mean_ap_coco_val_scale",
        "images": args.images,
        "classes": args.classes,
        "detections": n_det,
        "gts": n_gt,
        "backend": backend,
        "platform_probe": platform,
        "ours_s": round(t_ours, 3),
        "map": round(v_ours, 5),
    }
    if args.reference:
        t_ref, v_ref = bench_reference(preds, target)
        assert abs(v_ours - v_ref) < 5e-3, (v_ours, v_ref)
        out["reference_s"] = round(t_ref, 3)
        out["speedup"] = round(t_ref / t_ours, 2)

    print(json.dumps(out))
    with open(os.path.join(REPO, "MAP_SCALE_BENCH.json"), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
