"""Dump optimized HLO for the bench-critical metric programs (VERDICT r3 #1).

CPU-side HLO structure carries to hardware: fusion boundaries, scatter vs
matmul choices, and intermediate shapes are visible without a live chip. Writes
one ``.hlo.txt`` per program under ``hlo_dumps/`` and prints a one-line summary
(op counts per program) so a reviewer can diff compiler behavior across rounds.

Usage: ``python tools/hlo_dump.py [outdir]``
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_programs():
    """(name, build) pairs; build() returns a lowered jax computation."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.RandomState(0)
    programs = []

    def accuracy_update():
        from metrics_tpu.classification import MulticlassAccuracy

        m = MulticlassAccuracy(num_classes=10, average="micro", validate_args=False)
        preds = jnp.asarray(rng.randint(0, 10, 1 << 20))
        fn = m._lookup_shared_jit()
        return fn.lower(m._state, preds, preds)  # fresh _state already has the right avals

    programs.append(("accuracy_update", accuracy_update))

    def binned_curve_update():
        from metrics_tpu.functional.classification.precision_recall_curve import (
            _adjust_threshold_arg,
            _binary_precision_recall_curve_update,
        )

        thr = _adjust_threshold_arg(100)
        preds = jnp.asarray(rng.rand(1 << 20).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 2, 1 << 20))
        return jax.jit(lambda p, t: _binary_precision_recall_curve_update(p, t, thr)).lower(preds, target)

    programs.append(("binned_curve_update", binned_curve_update))

    def retrieval_score():
        from metrics_tpu.retrieval import RetrievalMAP
        from metrics_tpu.retrieval.base import GroupedQueries

        n = 4096 * 100
        indexes = jnp.asarray(np.repeat(np.arange(4096), 100))
        preds = jnp.asarray(rng.rand(n).astype(np.float32))
        target = jnp.asarray((rng.rand(n) < 0.1).astype(np.int32))
        m = RetrievalMAP()
        gq = GroupedQueries(indexes, preds, target)
        return jax.jit(lambda tree: m._score_groups(GroupedQueries.from_tree(tree))).lower(gq.as_tree())

    programs.append(("retrieval_score", retrieval_score))

    def ssim_psnr():
        from metrics_tpu.functional.image.psnr import peak_signal_noise_ratio
        from metrics_tpu.functional.image.ssim import structural_similarity_index_measure

        a = jnp.asarray(rng.rand(4, 3, 256, 256).astype(np.float32))

        def both(x, y):
            return (
                structural_similarity_index_measure(x, y, data_range=1.0),
                peak_signal_noise_ratio(x, y, data_range=1.0),
            )

        return jax.jit(both).lower(a, a)

    programs.append(("ssim_psnr", ssim_psnr))

    return programs


def main():
    from metrics_tpu.utils.backend import ensure_backend

    ensure_backend(min_devices=1)

    outdir = sys.argv[1] if len(sys.argv) > 1 else os.path.join(REPO, "hlo_dumps")
    os.makedirs(outdir, exist_ok=True)
    summary = {}
    for name, build in build_programs():
        lowered = build()
        compiled = lowered.compile()
        hlo = compiled.as_text()
        path = os.path.join(outdir, f"{name}.hlo.txt")
        with open(path, "w") as fh:
            fh.write(hlo)
        # opcode = last token before the first '(' on an assignment line; handles
        # ROOT-prefixed ops and tuple types (which contain spaces) alike
        ops = []
        for line in hlo.splitlines():
            if " = " not in line:
                continue
            rhs = line.split(" = ", 1)[1]
            if rhs.startswith("("):  # tuple-typed op: strip the parenthesized type first
                depth, i = 0, 0
                for i, ch in enumerate(rhs):
                    depth += ch == "("
                    depth -= ch == ")"
                    if depth == 0:
                        break
                rhs = rhs[i + 1 :].lstrip()
            head = rhs.split("(", 1)[0].split()
            if head:
                ops.append(head[-1])
        counts = {}
        for op in ops:
            counts[op] = counts.get(op, 0) + 1
        top = sorted(counts.items(), key=lambda kv: -kv[1])[:6]
        summary[name] = {"total_ops": len(ops), "fusions": counts.get("fusion", 0), "top": top}
        print(f"{name}: {len(ops)} ops, {counts.get('fusion', 0)} fusions, top={top} -> {path}")
    return summary


if __name__ == "__main__":
    main()
