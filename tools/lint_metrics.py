#!/usr/bin/env python
"""Lint CLI — the six static passes + dynamic harnesses over metrics_tpu.

Usage:
    python tools/lint_metrics.py [targets...]
                                 [--pass jitlint|distlint|donlint|hotlint|numlint|racelint
                                        |telemetry|donation|interleave|transfer|precision
                                        |aot|fleet|chaos|perf]
                                 [--all] [--json] [--list-rules]
                                 [--rules JL001,DL004,ML002,HL005,NL003,RC001]
                                 [--update-baseline]

Thin wrapper over :mod:`metrics_tpu.analysis.cli` so the tool works from a
checkout without installing the package (the ``jitlint`` console script is the
installed-form equivalent).
"""

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from metrics_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:] if "--root" in sys.argv else ["--root", _REPO_ROOT, *sys.argv[1:]]))
