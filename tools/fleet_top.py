#!/usr/bin/env python
"""``top`` for a metric fleet: one-command health report over observe snapshots.

Offline, it reads one or two ``observe.snapshot()`` JSON files (the dicts the
runtime half of :mod:`metrics_tpu.observe` emits — DESIGN §19) and renders a
fleet health report: occupancy, dispatch economy, WAL durability lag,
quarantine count, and per-phase DDSketch latency quantiles. With two
snapshots it diffs them — counter families become rates over the snapshots'
series-time window and gauge moves are signed — which is how a CI job or an
operator compares "before the incident" to "after".

Live, ``--live`` drives a self-contained demo fleet (a ``StreamEngine`` with
``--sessions`` ragged-length streams, the same workload shape as the fleet
contract smoke) inside this process and re-renders the report every
``--interval`` ticks, diffing each frame against the previous one. The
recorder is process-wide, so watching *your* fleet is the same one-liner in
your process::

    json.dump(observe.snapshot(), open("snap.json", "w"))   # twice, then
    python tools/fleet_top.py snap0.json snap1.json

Exit codes: 0 rendered, 2 usage/unreadable input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# ------------------------------------------------------------------ rendering

_PHASE_ORDER = (
    "tick", "shard_tick", "ingest", "wave_assembly", "dispatch", "flush",
    "fleet_compute", "wal", "ckpt", "expire", "update", "compute", "merge",
    "sync", "allreduce", "gather_all", "fused_update", "aot",
)


def _fmt_s(seconds: Optional[float]) -> str:
    if seconds is None:
        return "-"
    if seconds >= 1.0:
        return f"{seconds:.2f}s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.2f}ms"
    return f"{seconds * 1e6:.0f}us"


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}GiB"


def _series_window_s(snap: Dict[str, Any]) -> Optional[float]:
    series = snap.get("series") or []
    if len(series) >= 2:
        dt = float(series[-1]["t"]) - float(series[0]["t"])
        if dt > 0:
            return dt
    return None


def _gauge_total(snap: Dict[str, Any], name: str) -> float:
    return float(sum((snap.get("gauges", {}).get(name) or {}).values()))


def _counter_total(snap: Dict[str, Any], name: str) -> int:
    return int(sum((snap.get("counters", {}).get(name) or {}).values()))


def _delta(cur: float, prev: Optional[float]) -> str:
    if prev is None:
        return ""
    d = cur - prev
    if d == 0:
        return "  (=)"
    return f"  ({'+' if d > 0 else ''}{d:g})"


def render_report(snap: Dict[str, Any], prev: Optional[Dict[str, Any]] = None) -> str:
    """Render one snapshot (optionally diffed against ``prev``) as text."""
    lines: List[str] = []
    derived = snap.get("derived", {})
    pderived = (prev or {}).get("derived", {})
    series = snap.get("series") or []
    latest = series[-1] if series else {}

    lines.append("== fleet ==")
    occ = latest.get("occupancy_pct")
    rows = (latest.get("rows_active"), latest.get("rows_capacity"))
    if occ is not None:
        lines.append(f"occupancy        {occ:.1f}%  ({rows[0]}/{rows[1]} rows)")
    sessions = latest.get("sessions", derived.get("fleet_sessions"))
    if sessions is not None:
        lines.append(f"sessions         {sessions}{_delta(sessions, (prev or {}).get('series', [{}])[-1].get('sessions') if (prev or {}).get('series') else None)}")
    if series:
        dispatches = [s.get("dispatches", 0) for s in series]
        lines.append(
            f"dispatches/tick  {sum(dispatches) / len(dispatches):.2f}  "
            f"(last {dispatches[-1]}, {len(series)} samples)"
        )
    quarantined = latest.get("quarantined")
    if quarantined is not None:
        lines.append(f"quarantined      {quarantined}")

    lines.append("")
    lines.append("== durability ==")
    lag_r = derived.get("wal_lag_records", _gauge_total(snap, "wal_lag_records"))
    lag_b = derived.get("wal_lag_bytes", _gauge_total(snap, "wal_lag_bytes"))
    lines.append(f"wal lag          {int(lag_r)} records / {_fmt_bytes(float(lag_b))}"
                 f"{_delta(lag_r, pderived.get('wal_lag_records') if prev else None)}")
    age = (snap.get("gauges", {}).get("last_ckpt_age_s") or {})
    if age:
        lines.append(f"last checkpoint  {_fmt_s(max(age.values()))} ago")
    else:
        lines.append("last checkpoint  never")
    torn = derived.get("wal_torn_tails_total", _counter_total(snap, "wal_torn_tail"))
    if torn:
        lines.append(f"torn wal tails   {int(torn)}  (journal damage detected at restore)")

    # sharded fleet rung: one row per shard from the shard_* gauge families
    healthy = snap.get("gauges", {}).get("shard_healthy") or {}
    if healthy:
        lines.append("")
        lines.append("== shards ==")
        demoted = derived.get(
            "fleet_shards_demoted", sum(1 for v in healthy.values() if not v)
        )
        lines.append(
            f"{len(healthy)} shard(s), {int(demoted)} demoted"
            f"{_delta(demoted, pderived.get('fleet_shards_demoted') if prev else None)}"
        )
        lines.append(
            f"{'shard':<22}{'sess':>6}{'rows':>12}{'occ%':>7}{'wal lag':>16}{'health':>10}"
        )
        g = snap.get("gauges", {})
        for label in sorted(healthy):
            sess = int((g.get("shard_sessions") or {}).get(label, 0))
            r_act = int((g.get("shard_rows_active") or {}).get(label, 0))
            r_cap = int((g.get("shard_rows_capacity") or {}).get(label, 0))
            occ = f"{100.0 * r_act / r_cap:.0f}" if r_cap else "-"
            lag_rec = int((g.get("shard_wal_lag_records") or {}).get(label, 0))
            lag_by = float((g.get("shard_wal_lag_bytes") or {}).get(label, 0))
            state = "ok" if healthy[label] else "DEMOTED"
            lines.append(
                f"{label:<22}{sess:>6}{f'{r_act}/{r_cap}':>12}{occ:>7}"
                f"{f'{lag_rec}r/{_fmt_bytes(lag_by)}':>16}{state:>10}"
            )

    # watchdog rung: SLO alert state + recompile-cause attribution (DESIGN §22)
    firing = snap.get("gauges", {}).get("slo_firing") or {}
    samples = derived.get("watchdog_samples_total", 0)
    if firing or samples:
        lines.append("")
        lines.append("== alerts ==")
        n_firing = sum(1 for v in firing.values() if v)
        fired = derived.get("slo_alerts_fired_total", _counter_total(snap, "slo_fired"))
        resolved = derived.get(
            "slo_alerts_resolved_total", _counter_total(snap, "slo_resolved")
        )
        lines.append(
            f"watchdog         {int(samples)} samples; {n_firing} firing, "
            f"{int(fired)} fired / {int(resolved)} resolved lifetime"
            f"{_delta(fired, pderived.get('slo_alerts_fired_total') if prev else None)}"
        )
        for rule in sorted(firing):
            state = "FIRING" if firing[rule] else "ok"
            lines.append(f"{rule:<32}{state:>8}")
        signals = snap.get("gauges", {}).get("watchdog_signal") or {}
        for name in sorted(signals):
            lines.append(f"  {name:<30}{signals[name]:>12.4g}")

    explains = snap.get("counters", {}).get("compile_explain") or {}
    if explains:
        lines.append("")
        lines.append("== compiles ==")
        causes = snap.get("counters", {}).get("compile_cause") or {}
        cause_str = ", ".join(f"{c}={n}" for c, n in sorted(causes.items()))
        lines.append(
            f"attributed misses  {sum(explains.values())}  ({cause_str})"
        )
        for cache in sorted(explains):
            lines.append(f"  {cache:<20}{explains[cache]:>6}")
        recent = [e for e in snap.get("events") or [] if e.get("kind") == "compile_explain"]
        for e in recent[-4:]:
            lines.append(
                f"  {e.get('cache', '?')}:{e.get('label', '?')}  "
                f"cause={e.get('cause', '?')}"
            )

    lines.append("")
    lines.append("== phases (DDSketch quantiles) ==")
    latency = snap.get("latency") or {}
    header = f"{'phase':<14}{'label':<18}{'count':>8}{'p50':>10}{'p99':>10}{'max':>10}"
    lines.append(header)
    ordered = [p for p in _PHASE_ORDER if p in latency]
    ordered += sorted(p for p in latency if p not in _PHASE_ORDER)
    window = _series_window_s(snap)
    for phase in ordered:
        for label, s in sorted(latency[phase].items()):
            count = s.get("count", 0)
            prev_count = ((prev or {}).get("latency", {}).get(phase, {}).get(label, {}) or {}).get("count")
            rate = ""
            if prev_count is not None and window:
                rate = f"  ({(count - prev_count) / window:+.1f}/s)"
            lines.append(
                f"{phase:<14}{(label or '-'):<18}{count:>8}"
                f"{_fmt_s(s.get('p50_s')):>10}{_fmt_s(s.get('p99_s')):>10}"
                f"{_fmt_s(s.get('max_s')):>10}{rate}"
            )
    if not latency:
        lines.append("(no spans recorded — is telemetry enabled?)")

    spans_total = derived.get("spans_total")
    if spans_total is not None:
        lines.append("")
        lines.append(
            f"spans: {spans_total} recorded"
            f"{_delta(spans_total, pderived.get('spans_total') if prev else None)}"
            f"; jit compiles: {derived.get('jit_compiles_total', 0)}"
            f"; eager fallbacks: {_counter_total(snap, 'eager_fallback')}"
        )
    return "\n".join(lines)


# ------------------------------------------------------------------ live mode

def _demo_fleet(sessions: int, interval: int, frames: int, out) -> int:
    """Drive a demo StreamEngine and re-render every ``interval`` ticks."""
    import numpy as np

    from metrics_tpu import observe
    from metrics_tpu.classification.accuracy import MulticlassAccuracy
    from metrics_tpu.engine.stream import StreamEngine

    rng = np.random.default_rng(0)
    with observe.scope():
        observe.install_watchdog(min_interval_s=0.0)
        engine = StreamEngine(initial_capacity=max(8, sessions))
        sids = [engine.add_session(MulticlassAccuracy(num_classes=8)) for _ in range(sessions)]
        prev: Optional[Dict[str, Any]] = None
        for frame in range(frames):
            for _ in range(interval):
                for sid in sids:
                    if rng.random() < 0.8:  # ragged: not every stream every tick
                        n = int(rng.integers(4, 64))
                        engine.submit(sid, rng.integers(0, 8, n), rng.integers(0, 8, n))
                engine.tick()
            snap = observe.snapshot()
            print(f"--- frame {frame + 1}/{frames} "
                  f"(tick {engine.stats()['ticks']}) ---", file=out)
            print(render_report(snap, prev), file=out)
            print("", file=out)
            prev = snap
        observe.uninstall_watchdog()
    return 0


# ------------------------------------------------------------------ CLI

def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="fleet_top",
        description="Fleet health report from observe.snapshot() JSON — offline "
                    "(one or two snapshot files, second is diffed against the "
                    "first) or --live (in-process demo fleet).",
    )
    p.add_argument("snapshots", nargs="*",
                   help="snapshot JSON file(s): one to render, two to diff (old new)")
    p.add_argument("--live", action="store_true",
                   help="drive a demo StreamEngine and re-render per frame")
    p.add_argument("--sessions", type=int, default=32, help="live: fleet size (default 32)")
    p.add_argument("--interval", type=int, default=5, help="live: ticks per frame (default 5)")
    p.add_argument("--frames", type=int, default=3, help="live: frames to render (default 3)")
    args = p.parse_args(argv)

    if args.live:
        if args.snapshots:
            print("fleet_top: --live takes no snapshot files", file=sys.stderr)
            return 2
        return _demo_fleet(args.sessions, args.interval, args.frames, sys.stdout)

    if not 1 <= len(args.snapshots) <= 2:
        print("fleet_top: expected 1 or 2 snapshot files (or --live)", file=sys.stderr)
        return 2
    snaps: List[Dict[str, Any]] = []
    for path in args.snapshots:
        try:
            with open(path) as f:
                snaps.append(json.load(f))
        except (OSError, ValueError) as exc:
            print(f"fleet_top: cannot read {path}: {exc}", file=sys.stderr)
            return 2
    prev, cur = (None, snaps[0]) if len(snaps) == 1 else (snaps[0], snaps[1])
    print(render_report(cur, prev))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
