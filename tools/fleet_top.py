#!/usr/bin/env python
"""``top`` for a metric fleet: one-command health report over observe snapshots.

Offline, it reads one or two ``observe.snapshot()`` JSON files (the dicts the
runtime half of :mod:`metrics_tpu.observe` emits — DESIGN §19) and renders a
fleet health report: occupancy, dispatch economy (compile attribution plus
the annotated explicit host↔device transfer counters hotlint's
intentional-transfer sites emit — DESIGN §24), WAL durability lag,
quarantine count, tenant cost attribution (DESIGN §23), per-bucket memory
ledgers, and per-phase DDSketch latency quantiles. With two snapshots it
diffs them — counter families become rates over the snapshots' series-time
window and gauge moves are signed — which is how a CI job or an operator
compares "before the incident" to "after".

All diffing lives in ONE code path: :func:`build_report` computes the
section data (numbers, deltas, rates) and both the text renderer and
``--json`` consume that same structure, so the machine-readable report can
never drift from the human one.

Live, ``--live`` drives a self-contained demo fleet (a ``StreamEngine`` with
``--sessions`` ragged-length streams, the same workload shape as the fleet
contract smoke) inside this process and re-renders the report every
``--interval`` ticks, diffing each frame against the previous one. The
recorder is process-wide, so watching *your* fleet is the same one-liner in
your process::

    json.dump(observe.snapshot(), open("snap.json", "w"))   # twice, then
    python tools/fleet_top.py snap0.json snap1.json

Exit codes: 0 rendered, 2 usage/unreadable input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# ------------------------------------------------------------------ report data

_PHASE_ORDER = (
    "tick", "shard_tick", "ingest", "wave_assembly", "dispatch", "flush",
    "fleet_compute", "wal", "ckpt", "expire", "update", "compute", "merge",
    "sync", "allreduce", "gather_all", "fused_update", "aot",
)


def _series_window_s(snap: Dict[str, Any]) -> Optional[float]:
    series = snap.get("series") or []
    if len(series) >= 2:
        dt = float(series[-1]["t"]) - float(series[0]["t"])
        if dt > 0:
            return dt
    return None


def _gauge_total(snap: Dict[str, Any], name: str) -> float:
    return float(sum((snap.get("gauges", {}).get(name) or {}).values()))


def _counter_total(snap: Dict[str, Any], name: str) -> int:
    return int(sum((snap.get("counters", {}).get(name) or {}).values()))


def _diff(cur: Optional[float], prev: Optional[float]) -> Optional[float]:
    if cur is None or prev is None:
        return None
    return cur - prev


def build_report(snap: Dict[str, Any], prev: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The one snapshot-diff code path: section data for text AND ``--json``.

    Every value is plain JSON (numbers, strings, lists, dicts, None); deltas
    are ``None`` when there is no previous snapshot to diff against. Sections
    that do not apply to the snapshot (no shards, no watchdog, meter not
    installed, ...) are ``None``.
    """
    derived = snap.get("derived", {})
    pderived = (prev or {}).get("derived", {})
    g = snap.get("gauges", {})
    series = snap.get("series") or []
    latest = series[-1] if series else {}
    pseries = (prev or {}).get("series") or []
    platest = pseries[-1] if pseries else {}
    window = _series_window_s(snap)

    sessions = latest.get("sessions", derived.get("fleet_sessions"))
    dispatches = [s.get("dispatches", 0) for s in series]
    fleet = {
        "occupancy_pct": latest.get("occupancy_pct"),
        "rows_active": latest.get("rows_active"),
        "rows_capacity": latest.get("rows_capacity"),
        "sessions": sessions,
        "sessions_delta": _diff(sessions, platest.get("sessions")),
        "dispatches_per_tick": (sum(dispatches) / len(dispatches)) if dispatches else None,
        "dispatches_last": dispatches[-1] if dispatches else None,
        "samples": len(series),
        "quarantined": latest.get("quarantined"),
    }

    lag_r = derived.get("wal_lag_records", _gauge_total(snap, "wal_lag_records"))
    age = g.get("last_ckpt_age_s") or {}
    durability = {
        "wal_lag_records": lag_r,
        "wal_lag_records_delta": _diff(lag_r, pderived.get("wal_lag_records")) if prev else None,
        "wal_lag_bytes": derived.get("wal_lag_bytes", _gauge_total(snap, "wal_lag_bytes")),
        "last_ckpt_age_s": max(age.values()) if age else None,
        "torn_tails": int(derived.get("wal_torn_tails_total", _counter_total(snap, "wal_torn_tail"))),
    }

    shards = None
    healthy = g.get("shard_healthy") or {}
    if healthy:
        demoted = derived.get("fleet_shards_demoted", sum(1 for v in healthy.values() if not v))
        rows = []
        for label in sorted(healthy):
            r_cap = int((g.get("shard_rows_capacity") or {}).get(label, 0))
            r_act = int((g.get("shard_rows_active") or {}).get(label, 0))
            rows.append({
                "shard": label,
                "sessions": int((g.get("shard_sessions") or {}).get(label, 0)),
                "rows_active": r_act,
                "rows_capacity": r_cap,
                "occupancy_pct": (100.0 * r_act / r_cap) if r_cap else None,
                "wal_lag_records": int((g.get("shard_wal_lag_records") or {}).get(label, 0)),
                "wal_lag_bytes": float((g.get("shard_wal_lag_bytes") or {}).get(label, 0)),
                "healthy": bool(healthy[label]),
            })
        shards = {
            "count": len(healthy),
            "demoted": int(demoted),
            "demoted_delta": _diff(demoted, pderived.get("fleet_shards_demoted")) if prev else None,
            "rows": rows,
        }

    alerts = None
    firing = g.get("slo_firing") or {}
    samples = derived.get("watchdog_samples_total", 0)
    if firing or samples:
        fired = derived.get("slo_alerts_fired_total", _counter_total(snap, "slo_fired"))
        alerts = {
            "samples": int(samples),
            "firing": {rule: bool(firing[rule]) for rule in sorted(firing)},
            "fired": int(fired),
            "fired_delta": _diff(fired, pderived.get("slo_alerts_fired_total")) if prev else None,
            "resolved": int(derived.get("slo_alerts_resolved_total", _counter_total(snap, "slo_resolved"))),
            "signals": {k: (g.get("watchdog_signal") or {})[k] for k in sorted(g.get("watchdog_signal") or {})},
        }

    compiles = None
    explains = snap.get("counters", {}).get("compile_explain") or {}
    # annotated explicit host↔device transfers (hotlint intentional-transfer
    # sites: wave assembly, expiry slice, WAL journal, ...) ride the compiles
    # section — both are dispatch-economy signals
    transfers = snap.get("counters", {}).get("explicit_transfer") or {}
    if explains or transfers:
        compiles = {
            "attributed": sum(explains.values()),
            "causes": dict(sorted((snap.get("counters", {}).get("compile_cause") or {}).items())),
            "caches": {cache: explains[cache] for cache in sorted(explains)},
            "transfers": {site: transfers[site] for site in sorted(transfers)},
            "recent": [
                {"cache": e.get("cache"), "label": e.get("label"), "cause": e.get("cause")}
                for e in (snap.get("events") or [])
                if e.get("kind") == "compile_explain"
            ][-4:],
        }

    # tenant cost attribution + memory ledgers (DESIGN §23): the metering
    # section the installed FleetMeter contributes to snapshot()
    tenants = None
    memory = None
    metering = snap.get("metering") or {}
    if metering.get("installed"):
        totals = metering.get("totals", {})
        ptop = {
            r.get("session"): r
            for r in ((prev or {}).get("metering") or {}).get("top_sessions", [])
        }
        attributed = float(totals.get("attributed_s") or 0.0)
        srows = []
        for r in metering.get("top_sessions", []):
            disp = float(r.get("dispatch_s", 0.0))
            pdisp = ptop.get(r.get("session"), {}).get("dispatch_s")
            srows.append({
                "session": r.get("session"),
                "source": r.get("source"),
                "dispatch_s": disp,
                "dispatch_s_delta": _diff(disp, float(pdisp) if pdisp is not None else None),
                "share_pct": (100.0 * disp / attributed) if attributed > 0 else None,
                "error_s": r.get("error_s", 0.0),
                "updates": r.get("updates"),
                "est_flops": r.get("est_flops"),
                "est_bytes": r.get("est_bytes"),
                "loose_updates": r.get("loose_updates"),
                "quarantines": r.get("quarantines"),
                "wal_bytes": r.get("wal_bytes"),
                "ckpt_bytes": r.get("ckpt_bytes"),
            })
        quota = int(totals.get("quota_exceeded_total") or 0)
        tenants = {
            "tracked_exact": int(totals.get("sessions_exact") or 0),
            "tracked_sketched": int(totals.get("sessions_sketched") or 0),
            "top_k": metering.get("top_k"),
            "measured_dispatch_s": float(totals.get("measured_dispatch_s") or 0.0),
            "attributed_s": attributed,
            "attribution_pct": totals.get("attribution_pct"),
            "sketch_total_s": float(totals.get("sketch_total_s") or 0.0),
            "sketch_error_bound_s": float(totals.get("sketch_error_bound_s") or 0.0),
            "quota_exceeded": quota,
            "quota_exceeded_delta": (
                _diff(quota, ((prev or {}).get("metering") or {}).get("totals", {}).get("quota_exceeded_total"))
                if prev else None
            ),
            "policy": metering.get("policy"),
            "sessions": srows,
        }
        mem = metering.get("memory", {})
        mtot = mem.get("totals", {})
        pmtot = (((prev or {}).get("metering") or {}).get("memory") or {}).get("totals", {})
        memory = {
            "totals": dict(mtot),
            "live_bytes_delta": _diff(mtot.get("live_bytes"), pmtot.get("live_bytes")) if prev else None,
            "engines": dict(mem.get("engines", {})),
            "buckets": [
                {"bucket": key, **row} for key, row in sorted(mem.get("buckets", {}).items())
            ],
        }

    # serve front door (DESIGN §26): network ingest, admission verdicts, and
    # the autonomic reflex counters — None when no producer ever connected
    serve = None
    frames = derived.get("serve_frames_total", _counter_total(snap, "serve_frames"))
    producers = derived.get("serve_producers_connected", _gauge_total(snap, "serve_producers"))
    if frames or producers:
        deferred = int(derived.get("serve_deferred_total", 0))
        shed = int(
            derived.get(
                "serve_shed_total", (snap.get("counters", {}).get("serve_admission") or {}).get("shed", 0)
            )
        )
        admission = {
            "accept": int(derived.get("serve_admitted_total", 0)),
            "defer": deferred,
            "shed": shed,
            "reject": int(derived.get("serve_rejected_total", 0)),
        }
        actions = snap.get("counters", {}).get("autonomic_actions") or {}
        serve = {
            "producers": int(producers),
            "queue_depth": int(_gauge_total(snap, "serve_queue_depth")),
            "frames": int(frames),
            "frames_rate_per_s": (
                ((frames - pderived["serve_frames_total"]) / window)
                if (prev and "serve_frames_total" in pderived and window)
                else None
            ),
            "bytes_in": int(derived.get("serve_bytes_in_total", _counter_total(snap, "serve_bytes_in"))),
            "admission": admission,
            "defer_rate_per_s": (
                ((deferred - pderived["serve_deferred_total"]) / window)
                if (prev and "serve_deferred_total" in pderived and window)
                else None
            ),
            "shed_sessions": int(_counter_total(snap, "serve_shed_sessions")),
            "shed_rate_per_s": (
                ((shed - pderived["serve_shed_total"]) / window)
                if (prev and "serve_shed_total" in pderived and window)
                else None
            ),
            "dedup_skipped": int(derived.get("serve_dedup_skipped_total", _counter_total(snap, "serve_dedup_skipped"))),
            "protocol_errors": int(
                derived.get("serve_protocol_errors_total", _counter_total(snap, "serve_protocol_errors"))
            ),
            "autonomic": {action: int(actions[action]) for action in sorted(actions)},
        }

    latency = snap.get("latency") or {}
    ordered = [p for p in _PHASE_ORDER if p in latency]
    ordered += sorted(p for p in latency if p not in _PHASE_ORDER)
    phase_rows = []
    for phase in ordered:
        for label, s in sorted(latency[phase].items()):
            count = s.get("count", 0)
            prev_count = ((prev or {}).get("latency", {}).get(phase, {}).get(label, {}) or {}).get("count")
            phase_rows.append({
                "phase": phase,
                "label": label,
                "count": count,
                "p50_s": s.get("p50_s"),
                "p99_s": s.get("p99_s"),
                "max_s": s.get("max_s"),
                "rate_per_s": ((count - prev_count) / window) if (prev_count is not None and window) else None,
            })

    spans_total = derived.get("spans_total")
    footer = None
    if spans_total is not None:
        footer = {
            "spans_total": spans_total,
            "spans_delta": _diff(spans_total, pderived.get("spans_total")) if prev else None,
            "jit_compiles": derived.get("jit_compiles_total", 0),
            "eager_fallbacks": _counter_total(snap, "eager_fallback"),
        }

    return {
        "schema_version": snap.get("schema_version"),
        "window_s": window,
        "fleet": fleet,
        "durability": durability,
        "shards": shards,
        "alerts": alerts,
        "compiles": compiles,
        "tenants": tenants,
        "memory": memory,
        "serve": serve,
        "phases": phase_rows,
        "footer": footer,
    }


# ------------------------------------------------------------------ rendering

def _fmt_s(seconds: Optional[float]) -> str:
    if seconds is None:
        return "-"
    if seconds >= 1.0:
        return f"{seconds:.2f}s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.2f}ms"
    return f"{seconds * 1e6:.0f}us"


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}GiB"


def _fmt_delta(d: Optional[float]) -> str:
    if d is None:
        return ""
    if d == 0:
        return "  (=)"
    return f"  ({'+' if d > 0 else ''}{d:g})"


def render_report(snap: Dict[str, Any], prev: Optional[Dict[str, Any]] = None) -> str:
    """Render one snapshot (optionally diffed against ``prev``) as text."""
    r = build_report(snap, prev)
    lines: List[str] = []

    fleet = r["fleet"]
    lines.append("== fleet ==")
    if fleet["occupancy_pct"] is not None:
        lines.append(
            f"occupancy        {fleet['occupancy_pct']:.1f}%  "
            f"({fleet['rows_active']}/{fleet['rows_capacity']} rows)"
        )
    if fleet["sessions"] is not None:
        lines.append(f"sessions         {fleet['sessions']}{_fmt_delta(fleet['sessions_delta'])}")
    if fleet["dispatches_per_tick"] is not None:
        lines.append(
            f"dispatches/tick  {fleet['dispatches_per_tick']:.2f}  "
            f"(last {fleet['dispatches_last']}, {fleet['samples']} samples)"
        )
    if fleet["quarantined"] is not None:
        lines.append(f"quarantined      {fleet['quarantined']}")

    dur = r["durability"]
    lines.append("")
    lines.append("== durability ==")
    lines.append(
        f"wal lag          {int(dur['wal_lag_records'])} records / "
        f"{_fmt_bytes(float(dur['wal_lag_bytes']))}{_fmt_delta(dur['wal_lag_records_delta'])}"
    )
    if dur["last_ckpt_age_s"] is not None:
        lines.append(f"last checkpoint  {_fmt_s(dur['last_ckpt_age_s'])} ago")
    else:
        lines.append("last checkpoint  never")
    if dur["torn_tails"]:
        lines.append(f"torn wal tails   {dur['torn_tails']}  (journal damage detected at restore)")

    if r["shards"]:
        sh = r["shards"]
        lines.append("")
        lines.append("== shards ==")
        lines.append(
            f"{sh['count']} shard(s), {sh['demoted']} demoted{_fmt_delta(sh['demoted_delta'])}"
        )
        lines.append(
            f"{'shard':<22}{'sess':>6}{'rows':>12}{'occ%':>7}{'wal lag':>16}{'health':>10}"
        )
        for row in sh["rows"]:
            occ = f"{row['occupancy_pct']:.0f}" if row["occupancy_pct"] is not None else "-"
            state = "ok" if row["healthy"] else "DEMOTED"
            rows_str = f"{row['rows_active']}/{row['rows_capacity']}"
            lag_str = f"{row['wal_lag_records']}r/{_fmt_bytes(row['wal_lag_bytes'])}"
            lines.append(
                f"{row['shard']:<22}{row['sessions']:>6}"
                f"{rows_str:>12}{occ:>7}{lag_str:>16}{state:>10}"
            )

    if r["alerts"]:
        al = r["alerts"]
        lines.append("")
        lines.append("== alerts ==")
        n_firing = sum(1 for v in al["firing"].values() if v)
        lines.append(
            f"watchdog         {al['samples']} samples; {n_firing} firing, "
            f"{al['fired']} fired / {al['resolved']} resolved lifetime"
            f"{_fmt_delta(al['fired_delta'])}"
        )
        for rule, is_firing in al["firing"].items():
            lines.append(f"{rule:<32}{'FIRING' if is_firing else 'ok':>8}")
        for name, value in al["signals"].items():
            lines.append(f"  {name:<30}{value:>12.4g}")

    if r["compiles"]:
        co = r["compiles"]
        lines.append("")
        lines.append("== compiles ==")
        cause_str = ", ".join(f"{c}={n}" for c, n in co["causes"].items())
        lines.append(f"attributed misses  {co['attributed']}  ({cause_str})")
        for cache, n in co["caches"].items():
            lines.append(f"  {cache:<20}{n:>6}")
        for e in co["recent"]:
            lines.append(
                f"  {e.get('cache') or '?'}:{e.get('label') or '?'}  cause={e.get('cause') or '?'}"
            )
        if co.get("transfers"):
            site_str = ", ".join(f"{s}={n}" for s, n in co["transfers"].items())
            lines.append(f"transfers          {sum(co['transfers'].values())}  ({site_str})")

    if r["tenants"]:
        tn = r["tenants"]
        lines.append("")
        lines.append("== tenants ==")
        attr = (
            f"{tn['attribution_pct']:.1f}%" if tn["attribution_pct"] is not None else "-"
        )
        lines.append(
            f"metering         {tn['tracked_exact'] + tn['tracked_sketched']} tracked "
            f"({tn['tracked_exact']} exact + {tn['tracked_sketched']} sketched, "
            f"top_k={tn['top_k']}); attribution {attr} of "
            f"{_fmt_s(tn['measured_dispatch_s'])} measured"
        )
        lines.append(
            f"sketch           {_fmt_s(tn['sketch_total_s'])} folded, "
            f"error <= {_fmt_s(tn['sketch_error_bound_s'])} per estimate"
        )
        pol = tn["policy"]
        pol_str = "none" if pol is None else (
            f"action={pol.get('action')}"
            + (f", share<={pol.get('max_dispatch_share'):g}" if pol.get("max_dispatch_share") is not None else "")
            + (f", updates<={pol.get('max_updates')}" if pol.get("max_updates") is not None else "")
            + (f", wal<={_fmt_bytes(pol.get('max_wal_bytes'))}" if pol.get("max_wal_bytes") is not None else "")
        )
        lines.append(
            f"quota            {tn['quota_exceeded']} exceeded lifetime"
            f"{_fmt_delta(tn['quota_exceeded_delta'])}  (policy: {pol_str})"
        )
        lines.append(
            f"{'session':<18}{'src':<8}{'disp':>10}{'share%':>8}{'upd':>8}"
            f"{'flops':>12}{'wal':>10}"
        )
        for row in tn["sessions"]:
            share = f"{row['share_pct']:.1f}" if row["share_pct"] is not None else "-"
            disp = _fmt_s(row["dispatch_s"])
            if row["source"] == "sketch" and row["error_s"]:
                disp += "±"  # sketch estimate carries error; exact rows do not
            upd = row["updates"] if row["updates"] is not None else "-"
            flops = f"{row['est_flops']:.3g}" if row.get("est_flops") is not None else "-"
            wal = _fmt_bytes(row["wal_bytes"]) if row.get("wal_bytes") is not None else "-"
            lines.append(
                f"{str(row['session']):<18}{row['source']:<8}{disp:>10}{share:>8}"
                f"{upd:>8}{flops:>12}{wal:>10}{_fmt_delta(row['dispatch_s_delta'])}"
            )

    if r["memory"]:
        me = r["memory"]
        t = me["totals"]
        lines.append("")
        lines.append("== memory ==")
        lines.append(
            f"stacked state    {_fmt_bytes(t.get('live_bytes', 0))} live + "
            f"{_fmt_bytes(t.get('pad_waste_bytes', 0))} pad waste; "
            f"peak {_fmt_bytes(t.get('peak_capacity_bytes', 0))}, "
            f"next doubling {_fmt_bytes(t.get('projected_2x_bytes', 0))}"
            f"{_fmt_delta(me['live_bytes_delta'])}"
        )
        lines.append(
            f"{'bucket':<44}{'rows':>10}{'live':>10}{'waste':>10}{'proj@2x':>10}"
        )
        for row in me["buckets"]:
            name = row["bucket"]
            if len(name) > 43:
                name = name[:40] + "..."
            rows_str = f"{row['active']}/{row['capacity']}"
            lines.append(
                f"{name:<44}{rows_str:>10}"
                f"{_fmt_bytes(row['live_bytes']):>10}{_fmt_bytes(row['pad_waste_bytes']):>10}"
                f"{_fmt_bytes(row['projected_2x_bytes']):>10}"
            )

    if r["serve"]:
        sv = r["serve"]
        lines.append("")
        lines.append("== serve ==")
        frate = f"  ({sv['frames_rate_per_s']:+.1f}/s)" if sv["frames_rate_per_s"] is not None else ""
        lines.append(
            f"ingest           {sv['producers']} producer(s) connected, "
            f"queue depth {sv['queue_depth']}; {sv['frames']} frames / "
            f"{_fmt_bytes(float(sv['bytes_in']))}{frate}"
        )
        adm = sv["admission"]
        drate = f", defer {sv['defer_rate_per_s']:+.1f}/s" if sv["defer_rate_per_s"] is not None else ""
        srate = f", shed {sv['shed_rate_per_s']:+.1f}/s" if sv["shed_rate_per_s"] is not None else ""
        lines.append(
            f"admission        accept={adm['accept']} defer={adm['defer']} "
            f"shed={adm['shed']} reject={adm['reject']}{drate}{srate}"
        )
        lines.append(
            f"dedup/errors     {sv['dedup_skipped']} resends squelched; "
            f"{sv['protocol_errors']} protocol errors; "
            f"{sv['shed_sessions']} session(s) shed"
        )
        if sv["autonomic"]:
            act_str = ", ".join(f"{a}={n}" for a, n in sv["autonomic"].items())
            lines.append(f"autonomic        {act_str}")

    lines.append("")
    lines.append("== phases (DDSketch quantiles) ==")
    lines.append(f"{'phase':<14}{'label':<18}{'count':>8}{'p50':>10}{'p99':>10}{'max':>10}")
    for row in r["phases"]:
        rate = f"  ({row['rate_per_s']:+.1f}/s)" if row["rate_per_s"] is not None else ""
        lines.append(
            f"{row['phase']:<14}{(row['label'] or '-'):<18}{row['count']:>8}"
            f"{_fmt_s(row['p50_s']):>10}{_fmt_s(row['p99_s']):>10}"
            f"{_fmt_s(row['max_s']):>10}{rate}"
        )
    if not r["phases"]:
        lines.append("(no spans recorded — is telemetry enabled?)")

    if r["footer"]:
        f = r["footer"]
        lines.append("")
        lines.append(
            f"spans: {f['spans_total']} recorded{_fmt_delta(f['spans_delta'])}"
            f"; jit compiles: {f['jit_compiles']}"
            f"; eager fallbacks: {f['eager_fallbacks']}"
        )
    return "\n".join(lines)


# ------------------------------------------------------------------ live mode

def _demo_fleet(sessions: int, interval: int, frames: int, out) -> int:
    """Drive a demo StreamEngine and re-render every ``interval`` ticks."""
    import numpy as np

    from metrics_tpu import observe
    from metrics_tpu.classification.accuracy import MulticlassAccuracy
    from metrics_tpu.engine.stream import StreamEngine

    rng = np.random.default_rng(0)
    with observe.scope():
        observe.install_watchdog(min_interval_s=0.0)
        observe.install_meter()
        engine = StreamEngine(initial_capacity=max(8, sessions))
        sids = [engine.add_session(MulticlassAccuracy(num_classes=8)) for _ in range(sessions)]
        prev: Optional[Dict[str, Any]] = None
        for frame in range(frames):
            for _ in range(interval):
                for sid in sids:
                    if rng.random() < 0.8:  # ragged: not every stream every tick
                        n = int(rng.integers(4, 64))
                        engine.submit(sid, rng.integers(0, 8, n), rng.integers(0, 8, n))
                engine.tick()
            snap = observe.snapshot()
            print(f"--- frame {frame + 1}/{frames} "
                  f"(tick {engine.stats()['ticks']}) ---", file=out)
            print(render_report(snap, prev), file=out)
            print("", file=out)
            prev = snap
        observe.uninstall_meter()
        observe.uninstall_watchdog()
    return 0


# ------------------------------------------------------------------ CLI

def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="fleet_top",
        description="Fleet health report from observe.snapshot() JSON — offline "
                    "(one or two snapshot files, second is diffed against the "
                    "first) or --live (in-process demo fleet).",
    )
    p.add_argument("snapshots", nargs="*",
                   help="snapshot JSON file(s): one to render, two to diff (old new)")
    p.add_argument("--json", action="store_true",
                   help="emit the report as JSON (same data the text view renders)")
    p.add_argument("--live", action="store_true",
                   help="drive a demo StreamEngine and re-render per frame")
    p.add_argument("--sessions", type=int, default=32, help="live: fleet size (default 32)")
    p.add_argument("--interval", type=int, default=5, help="live: ticks per frame (default 5)")
    p.add_argument("--frames", type=int, default=3, help="live: frames to render (default 3)")
    args = p.parse_args(argv)

    if args.live:
        if args.snapshots:
            print("fleet_top: --live takes no snapshot files", file=sys.stderr)
            return 2
        return _demo_fleet(args.sessions, args.interval, args.frames, sys.stdout)

    if not 1 <= len(args.snapshots) <= 2:
        print("fleet_top: expected 1 or 2 snapshot files (or --live)", file=sys.stderr)
        return 2
    snaps: List[Dict[str, Any]] = []
    for path in args.snapshots:
        try:
            with open(path) as f:
                snaps.append(json.load(f))
        except (OSError, ValueError) as exc:
            print(f"fleet_top: cannot read {path}: {exc}", file=sys.stderr)
            return 2
    prev, cur = (None, snaps[0]) if len(snaps) == 1 else (snaps[0], snaps[1])
    if args.json:
        print(json.dumps(build_report(cur, prev), indent=2, sort_keys=True))
    else:
        print(render_report(cur, prev))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
