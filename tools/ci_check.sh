#!/usr/bin/env bash
# One CI entry point, one verdict: every static lint pass (jitlint + distlint
# + donlint + hotlint + numlint — hotlint covering host-sync & dispatch-economy
# rules HL001–HL006 over the hot-path modules, baselined expected-empty in
# tools/hotlint_baseline.json; numlint covering numerical-soundness rules
# NL001–NL006 — unguarded division, cancellation, domain edges, narrow
# accumulators, fold demotion, undeclared reassociation — baselined
# expected-empty in the `rules` section of tools/numlint_baseline.json),
# the precision-contract cross-check (every jit-eligible class replayed
# through the x32 jitted path vs a float64 eager oracle plus adversarial
# large-offset / long-horizon / cancellation / 2^31-overflow / decay regimes,
# with static verdict, declared per-state precision= contract and observed
# drift in three-way agreement against the `precision` section of the same
# baseline), the telemetry overhead smoke (disabled-mode
# cost pin plus the enabled-watchdog sampling budget and the enabled-meter
# attribution budget: per-session dispatch share, loose path, rate-limited
# quota poll), the donation
# three-way cross-check, the transfer-guard cross-check (steady-state update
# loops and 100-session fleet ticks under jax.transfer_guard("disallow"),
# agreeing with hotlint's static verdicts and each class's declared jit
# eligibility), the AOT executable-cache round-trip pass (serialize
# → fresh-dir reload with zero compiles → bit-exact vs a fresh trace,
# baselined in tools/aot_baseline.json), the chaos fault-injection harness
# (metric faults + fleet recovery + sharded-fleet recovery, baselined in the
# `chaos`/`fleet`/`shard` sections of tools/chaos_baseline.json), and the
# perf cost ratchet (which
# also drives the 64-stream StreamEngine smoke and pins its dispatch economy
# against the `fleet` section of tools/perf_baseline.json), the racelint
# concurrency & ordering pass (rules RC001–RC006 over serve/ + engine/ —
# multi-context attribute writes, ack-before-fsync, in-flight wave-buffer
# mutation, off-allowlist/ungated autonomic actions, latch-blind WAL appends,
# iterate-while-mutate — with a PINNED-EMPTY baseline in
# tools/racelint_baseline.json: ordering bugs get fixed, never baselined),
# and the interleaving harness (1000+ permuted/adversarial schedules with
# kill-points driven through the real server/engine/autonomic stack, asserting
# contiguous resolved prefix, acked=>durable, oracle-exact reads and
# tick/autonomic serialization) — all via
# `lint_metrics.py --all`, which aggregates their exit codes. The default
# target sweeps all of metrics_tpu/ including the sketch family
# (sketches/ + functional/sketches/, registered in every dynamic-pass
# registry), and `--json` reports per-pass wall time for CI timing budgets.
#
#   tools/ci_check.sh            # text report, exit 0 clean / 1 violations / 2 usage
#   tools/ci_check.sh --json     # one machine-readable document on stdout
#   tools/ci_check.sh --tier1    # the tier-1 test suite (CPU, not-slow) with
#                                # --durations=20 so CI logs name the slowest
#                                # tests when the timing budget drifts, then
#                                # the <=30s serve front-door smoke (loopback
#                                # producer, 100 sessions, one forced
#                                # overload -> shed -> recover cycle), then
#                                # the full lint sweep (`lint_metrics.py
#                                # --all`, all six static passes incl.
#                                # racelint + every dynamic harness incl. the
#                                # interleave scheduler) under a hard
#                                # wall-clock budget so a wedged harness fails
#                                # the gate instead of hanging it
set -euo pipefail
cd "$(dirname "$0")/.."

# wall-clock budget (seconds) for the tier-1 lint sweep; override per-runner
LINT_ALL_BUDGET="${LINT_ALL_BUDGET:-900}"

if [[ "${1:-}" == "--tier1" ]]; then
  shift
  rc=0
  env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors --durations=20 \
    -p no:cacheprovider -p no:xdist -p no:randomly "$@" || rc=$?
  env JAX_PLATFORMS=cpu timeout -k 5 60 python -m metrics_tpu.serve.smoke || rc=1
  env JAX_PLATFORMS=cpu timeout -k 10 "$LINT_ALL_BUDGET" \
    python tools/lint_metrics.py --all || rc=1
  exit "$rc"
fi

exec python tools/lint_metrics.py --all "$@"
