#!/usr/bin/env python
"""Explain recompiles from an ``observe.snapshot()`` JSON dump.

Usage:
    python tools/why_recompile.py snap.json [--tail N]
    python tools/why_recompile.py - < snap.json

Renders the "why recompile" report: attributed cache misses per cache and per
cause (first / single component / multiple / rebuild), plus the last N misses
with the exact key component that changed and its prior->now values — the
answer to "why did my fleet recompile at step 4000?" without reading XLA logs
(DESIGN §22).

Thin wrapper over :mod:`metrics_tpu.observe.explain` so the tool works from a
checkout without installing the package (the ``why-recompile`` console script
is the installed-form equivalent).
"""

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from metrics_tpu.observe.explain import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
