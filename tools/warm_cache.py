#!/usr/bin/env python
"""Pre-populate the AOT executable cache for the whole metric registry.

Usage:
    python tools/warm_cache.py --cache-dir DIR [--classes Binary,MeanSquared]
                               [--purge] [-v]

One real update per profiled registry class (~58, the perf-ratchet cases) with
the disk cache enabled: every compile is serialized so the NEXT process — every
fleet worker mounting DIR — starts with zero cold-start compiles. Idempotent;
re-runs report hits and refresh only stale entries.

Thin wrapper over :mod:`metrics_tpu.aot.warm` so the tool works from a
checkout without installing the package (the ``warm-cache`` console script is
the installed-form equivalent).
"""

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from metrics_tpu.aot.warm import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
