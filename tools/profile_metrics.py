#!/usr/bin/env python
"""Perf CLI — XLA cost profiling of compiled metric updates.

Usage:
    python tools/profile_metrics.py [--classes A,B] [--update-baseline]
                                    [--tolerance 1.5] [--no-memory] [--format json]

Thin wrapper over :mod:`metrics_tpu.observe.profile` so the tool works from a
checkout without installing the package (the ``profile-metrics`` console
script is the installed-form equivalent). Ratchets against
``tools/perf_baseline.json`` exactly like the jitlint/distlint baselines.
"""

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from metrics_tpu.observe.profile import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:] if "--root" in sys.argv else ["--root", _REPO_ROOT, *sys.argv[1:]]))
