"""Real-TPU validation sweep: every domain's headline metrics on the actual chip.

Runs a representative metric from each domain twice — once on the default
backend (the real TPU when the tunnel is live) and once pinned to the host CPU
backend — and records the worst elementwise deviation plus the TPU wall time.
This is the evidence that the compute paths (MXU matmul-bincount, Pallas SSIM
window kernel, segment-reduce retrieval, batched IoU matching, FFT audio paths)
produce correct numbers ON TPU, not just under the CPU test rig.

Writes ``TPU_VALIDATION.json`` at the repo root and prints one JSON line.
Usage: ``python tools/tpu_validate.py`` (skips gracefully to a "cpu-only"
record when no accelerator is reachable).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _tree_max_diff(a, b) -> float:
    import jax

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    worst = 0.0
    for x, y in zip(la, lb):
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        if x.shape != y.shape:
            return float("inf")
        if x.size:
            denom = np.maximum(np.abs(y), 1.0)
            worst = max(worst, float(np.max(np.abs(x - y) / denom)))
    return worst


def build_cases():
    """(name, fn) pairs; each fn is a zero-arg closure returning a pytree of arrays."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    cases = []

    # ---------------- classification: stat scores + curve + calibration
    p_cls = rng.randint(0, 10, 100_000).astype(np.int32)
    t_cls = rng.randint(0, 10, 100_000).astype(np.int32)
    p_soft = rng.rand(50_000, 5).astype(np.float32)
    p_soft /= p_soft.sum(1, keepdims=True)
    t_soft = rng.randint(0, 5, 50_000).astype(np.int32)
    p_bin = rng.rand(100_000).astype(np.float32)
    t_bin = rng.randint(0, 2, 100_000).astype(np.int32)

    def classification():
        from metrics_tpu.functional.classification import (
            binary_auroc,
            binary_average_precision,
            binary_calibration_error,
            multiclass_accuracy,
            multiclass_confusion_matrix,
            multiclass_f1_score,
        )

        return (
            multiclass_accuracy(jnp.asarray(p_cls), jnp.asarray(t_cls), num_classes=10, average="macro"),
            multiclass_f1_score(jnp.asarray(p_cls), jnp.asarray(t_cls), num_classes=10, average="weighted"),
            multiclass_confusion_matrix(jnp.asarray(p_soft), jnp.asarray(t_soft), num_classes=5),
            binary_auroc(jnp.asarray(p_bin), jnp.asarray(t_bin)),
            binary_average_precision(jnp.asarray(p_bin), jnp.asarray(t_bin)),
            binary_calibration_error(jnp.asarray(p_bin), jnp.asarray(t_bin), n_bins=15),
        )

    cases.append(("classification", classification))

    # ---------------- regression
    pr = rng.rand(200_000).astype(np.float32)
    tr = (pr + rng.randn(200_000).astype(np.float32) * 0.1).astype(np.float32)

    def regression():
        from metrics_tpu.functional.regression import (
            mean_squared_error,
            pearson_corrcoef,
            r2_score,
            spearman_corrcoef,
        )

        return (
            mean_squared_error(jnp.asarray(pr), jnp.asarray(tr)),
            pearson_corrcoef(jnp.asarray(pr), jnp.asarray(tr)),
            spearman_corrcoef(jnp.asarray(pr), jnp.asarray(tr)),
            r2_score(jnp.asarray(pr), jnp.asarray(tr)),
        )

    cases.append(("regression", regression))

    # ---------------- retrieval (segment-reduce engine)
    q_n, d_n = 1024, 50
    ret_idx = np.repeat(np.arange(q_n), d_n).astype(np.int64)
    ret_p = rng.rand(q_n * d_n).astype(np.float32)
    ret_t = (rng.rand(q_n * d_n) < 0.15).astype(np.int64)
    ret_t[::d_n] = 1

    def retrieval():
        from metrics_tpu.functional.retrieval import (
            retrieval_average_precision,
            retrieval_normalized_dcg,
            retrieval_reciprocal_rank,
        )
        from metrics_tpu.retrieval import RetrievalMAP

        m = RetrievalMAP()
        m.update(jnp.asarray(ret_p), jnp.asarray(ret_t), indexes=jnp.asarray(ret_idx))
        one_p, one_t = jnp.asarray(ret_p[:d_n]), jnp.asarray(ret_t[:d_n])
        return (
            m.compute(),
            retrieval_average_precision(one_p, one_t),
            retrieval_reciprocal_rank(one_p, one_t),
            retrieval_normalized_dcg(one_p, one_t),
        )

    cases.append(("retrieval", retrieval))

    # ---------------- image (SSIM rides Pallas on TPU, XLA stencil on CPU;
    # MS-SSIM's 5-beta cascade needs ≥176px after 4 halvings)
    img_a = rng.rand(2, 3, 192, 192).astype(np.float32)
    img_b = np.clip(img_a + rng.randn(2, 3, 192, 192).astype(np.float32) * 0.05, 0, 1)

    def image():
        from metrics_tpu.functional.image.psnr import peak_signal_noise_ratio
        from metrics_tpu.functional.image.ssim import (
            multiscale_structural_similarity_index_measure,
            structural_similarity_index_measure,
        )
        from metrics_tpu.functional.image.metrics import universal_image_quality_index

        return (
            structural_similarity_index_measure(jnp.asarray(img_a), jnp.asarray(img_b), data_range=1.0),
            multiscale_structural_similarity_index_measure(jnp.asarray(img_a), jnp.asarray(img_b), data_range=1.0),
            peak_signal_noise_ratio(jnp.asarray(img_a), jnp.asarray(img_b), data_range=1.0),
            universal_image_quality_index(jnp.asarray(img_a), jnp.asarray(img_b)),
        )

    cases.append(("image", image))

    # ---------------- audio (FFT autocorr + Toeplitz solve)
    sig_t = rng.randn(2, 8000).astype(np.float32)
    sig_p = (sig_t + rng.randn(2, 8000).astype(np.float32) * 0.3).astype(np.float32)

    def audio():
        from metrics_tpu.functional.audio.metrics import (
            scale_invariant_signal_distortion_ratio,
            signal_distortion_ratio,
            signal_noise_ratio,
        )

        return (
            scale_invariant_signal_distortion_ratio(jnp.asarray(sig_p), jnp.asarray(sig_t)),
            signal_noise_ratio(jnp.asarray(sig_p), jnp.asarray(sig_t)),
            signal_distortion_ratio(jnp.asarray(sig_p), jnp.asarray(sig_t)),
        )

    cases.append(("audio", audio))

    # ---------------- detection (batched IoU + device-native COCO matching)
    n_img, n_cls = 12, 3
    det_p, det_t = [], []
    for _ in range(n_img):
        ng = rng.randint(2, 8)
        gb = rng.rand(ng, 4) * 100
        gb[:, 2:] = gb[:, :2] + 2 + rng.rand(ng, 2) * 60
        nd = ng + rng.randint(0, 3)
        db = np.concatenate([gb + rng.randn(ng, 4) * 3, rng.rand(nd - ng, 4) * 100])
        db[:, 2:] = np.maximum(db[:, 2:], db[:, :2] + 1)
        det_p.append({"boxes": db.astype(np.float32), "scores": rng.rand(nd).astype(np.float32),
                      "labels": rng.randint(0, n_cls, nd)})
        det_t.append({"boxes": gb.astype(np.float32), "labels": rng.randint(0, n_cls, ng)})

    def detection():
        from metrics_tpu.detection import MeanAveragePrecision
        from metrics_tpu.functional.detection.iou import intersection_over_union

        m = MeanAveragePrecision()
        m.update([{k: jnp.asarray(v) for k, v in d.items()} for d in det_p],
                 [{k: jnp.asarray(v) for k, v in d.items()} for d in det_t])
        res = m.compute()
        iou = intersection_over_union(jnp.asarray(det_p[0]["boxes"]), jnp.asarray(det_t[0]["boxes"]))
        return (res["map"], res["map_50"], res["mar_100"], iou)

    cases.append(("detection", detection))

    # ---------------- clustering + pairwise + segmentation + text
    lab_a = rng.randint(0, 8, 20_000)
    lab_b = rng.randint(0, 8, 20_000)
    seg_p = rng.randint(0, 2, (4, 1, 64, 64)).astype(np.int32)
    seg_t = rng.randint(0, 2, (4, 1, 64, 64)).astype(np.int32)
    emb = rng.rand(512, 64).astype(np.float32)
    logits = rng.randn(4, 50, 1000).astype(np.float32)
    tok = rng.randint(0, 1000, (4, 50))

    def small_domains():
        from metrics_tpu.functional.clustering import adjusted_rand_score, normalized_mutual_info_score
        from metrics_tpu.functional.pairwise import pairwise_cosine_similarity
        from metrics_tpu.functional.segmentation import dice_score
        from metrics_tpu.functional.text import perplexity

        return (
            adjusted_rand_score(jnp.asarray(lab_a), jnp.asarray(lab_b)),
            normalized_mutual_info_score(jnp.asarray(lab_a), jnp.asarray(lab_b)),
            pairwise_cosine_similarity(jnp.asarray(emb[:64])),
            dice_score(jnp.asarray(seg_p), jnp.asarray(seg_t), num_classes=2, input_format="index"),
            perplexity(jnp.asarray(logits), jnp.asarray(tok)),
        )

    cases.append(("small_domains", small_domains))

    # ---------------- round-4 additions: binned-curve Pallas kernel, Hungarian PIT,
    # shared-view retrieval pair (the exact code paths changed this round)
    bc_p = rng.rand(200_000, 4).astype(np.float32)
    bc_t = rng.randint(0, 4, 200_000).astype(np.int32)

    def binned_curves():
        """On TPU the update routes through ops/binned_hist.py; compare vs the CPU
        XLA histogram path AND the forced-XLA path on the accelerator itself."""
        import os as _os

        from metrics_tpu.functional.classification import (
            multiclass_average_precision,
            multiclass_roc,
        )

        pj, tj = jnp.asarray(bc_p), jnp.asarray(bc_t)
        auto = multiclass_average_precision(pj, tj, num_classes=4, thresholds=200, average="macro")
        roc = multiclass_roc(pj, tj, num_classes=4, thresholds=100)
        prior = _os.environ.get("METRICS_TPU_CURVE_KERNEL")
        _os.environ["METRICS_TPU_CURVE_KERNEL"] = "xla"
        try:
            forced_xla = multiclass_average_precision(pj, tj, num_classes=4, thresholds=200, average="macro")
        finally:  # restore the operator's own override, if any
            if prior is None:
                _os.environ.pop("METRICS_TPU_CURVE_KERNEL", None)
            else:
                _os.environ["METRICS_TPU_CURVE_KERNEL"] = prior
        return (auto, forced_xla, auto - forced_xla) + tuple(roc[:2])

    cases.append(("binned_curves_pallas", binned_curves))

    pit_p = rng.randn(4, 6, 400).astype(np.float32)
    pit_t = rng.randn(4, 6, 400).astype(np.float32)

    def pit_hungarian():
        from metrics_tpu.functional.audio.metrics import (
            permutation_invariant_training,
            scale_invariant_signal_distortion_ratio,
        )

        best, perm = permutation_invariant_training(
            jnp.asarray(pit_p), jnp.asarray(pit_t), scale_invariant_signal_distortion_ratio
        )
        return best, perm.astype(jnp.float32)

    cases.append(("pit_hungarian", pit_hungarian))

    def retrieval_shared_view():
        """MAP+MRR through the shared sorted view (on-device lexsort on TPU)."""
        from metrics_tpu.retrieval import RetrievalMAP, RetrievalMRR

        vals = []
        for cls in (RetrievalMAP, RetrievalMRR):
            m = cls()
            m.update(jnp.asarray(ret_p), jnp.asarray(ret_t), indexes=jnp.asarray(ret_idx))
            vals.append(m.compute())
        return tuple(vals)

    cases.append(("retrieval_shared_view", retrieval_shared_view))

    return cases


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="artifact path (default TPU_VALIDATION.json; pass a side file for "
                         "smoke/under-load runs so they never clobber the idle-machine record)")
    args = ap.parse_args()

    from metrics_tpu.utils.backend import ensure_backend

    ensure_backend(min_devices=1)

    import jax

    backend = jax.default_backend()
    cpu_dev = jax.devices("cpu")[0]
    records = {}
    for name, fn in build_cases():
        try:
            jax.block_until_ready(fn())  # compile both executables (slow on a tunneled chip)
            t0 = time.perf_counter()
            accel = fn()
            jax.block_until_ready(accel)
            t_accel = time.perf_counter() - t0
            # the host reference leg must not pick the compiled TPU kernels even
            # though the process backend is still "tpu" inside this context
            priors = {k: os.environ.get(k) for k in ("METRICS_TPU_SSIM_KERNEL", "METRICS_TPU_CURVE_KERNEL")}
            os.environ["METRICS_TPU_SSIM_KERNEL"] = "stencil"
            os.environ["METRICS_TPU_CURVE_KERNEL"] = "xla"
            try:
                with jax.default_device(cpu_dev):
                    host = fn()
            finally:
                for k, v in priors.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            diff = _tree_max_diff(accel, host)
            records[name] = {"ok": bool(diff < 5e-3), "max_rel_diff": float(diff),
                             "accel_ms": round(1000 * t_accel, 2)}
        except Exception as err:  # noqa: BLE001 — record, keep sweeping
            records[name] = {"ok": False, "error": f"{type(err).__name__}: {err}"}
    summary = {
        "backend": backend,
        "device": str(jax.devices()[0]),
        "all_ok": all(r.get("ok") for r in records.values()),
        "domains": records,
    }
    with open(args.out or os.path.join(REPO, "TPU_VALIDATION.json"), "w") as fh:
        json.dump(summary, fh, indent=2)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
