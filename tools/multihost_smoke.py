"""Real 2-process multihost sync smoke (round-4 VERDICT weak #5 / item 4).

Executes the one comm path that mocks cannot reach: ``jax.distributed.initialize``
across N real OS processes (localhost coordinator, CPU backend), then
``Metric.sync`` → ``gather_all_states`` → reduction, end-to-end, with the full
ragged contract — unequal per-rank cat lengths AND a rank that never updated.
The analog of the reference's 2-process gloo pool
(``/root/reference/tests/unittests/conftest.py:47-84``).

Run as a single command (it spawns its own workers):

    python tools/multihost_smoke.py            # 2 processes
    python tools/multihost_smoke.py --num-processes 4

Exit code 0 + a final ``MULTIHOST_OK`` line means every check passed in every
worker. Each worker compares its synced compute() against the single-stream
expectation computed locally from the SAME deterministic per-rank data.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile


def _worker(process_id: int, num_processes: int, coordinator: str, out_path: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")  # before any backend touch (axon tunnel can wedge)
    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=num_processes, process_id=process_id
    )
    assert jax.process_count() == num_processes, jax.process_count()

    import jax.numpy as jnp
    import numpy as np

    from metrics_tpu.aggregation import CatMetric, MeanMetric, SumMetric
    from metrics_tpu.classification import MulticlassAccuracy

    results = {}

    # deterministic per-rank data, recomputable by every rank for the expectation
    def rank_samples(r: int):
        rng = np.random.RandomState(100 + r)
        return rng.rand(3 + 2 * r).astype(np.float32)  # ragged: 3, 5, 7, ...

    # 1) ragged cat state through compute()'s auto sync→gather→unsync: every rank
    #    holds a different sample count (3, 5, 7, ...)
    cat = CatMetric()
    cat.update(jnp.asarray(rank_samples(process_id)))
    got = np.sort(np.asarray(cat.compute()))
    want = np.sort(np.concatenate([rank_samples(r) for r in range(num_processes)]))
    results["ragged_cat"] = bool(np.allclose(got, want, atol=1e-6))
    # auto-unsync must have restored the rank-local state after compute
    local = np.concatenate([np.atleast_1d(np.asarray(v)) for v in cat.value])
    results["unsync_restores_local"] = bool(np.allclose(local, rank_samples(process_id), atol=1e-6))

    # 2) empty-rank cat state: rank 0 never updates — the zero-length placeholder
    #    must ride the gather without deadlock and vanish from the merged result
    empty_cat = CatMetric()
    if process_id != 0:
        empty_cat.update(jnp.asarray(rank_samples(process_id)))
    got = np.sort(np.asarray(jnp.atleast_1d(empty_cat.compute())))
    want = np.sort(np.concatenate([rank_samples(r) for r in range(1, num_processes)]))
    results["empty_rank_cat"] = bool(np.allclose(got, want, atol=1e-6))

    # 3) manual sync()/unsync() round trip, merged state inspected directly
    s = SumMetric()
    s.update(jnp.asarray(float(process_id + 1)))
    s.sync()
    merged = float(jnp.asarray(s.value).sum())
    results["manual_sync_sum"] = abs(merged - num_processes * (num_processes + 1) / 2) < 1e-6
    s.unsync()
    results["manual_unsync_sum"] = abs(float(jnp.asarray(s.value).sum()) - (process_id + 1)) < 1e-6

    # 4) weighted mean across ranks of unequal sample counts
    mean = MeanMetric()
    mean.update(jnp.asarray(rank_samples(process_id)))
    want_mean = float(np.mean(np.concatenate([rank_samples(r) for r in range(num_processes)])))
    results["weighted_mean"] = abs(float(mean.compute()) - want_mean) < 1e-5

    # 5) a real metric with dense sum states (stat-score counts) end to end
    acc = MulticlassAccuracy(num_classes=4, average="micro", validate_args=False)
    rng = np.random.RandomState(200 + process_id)
    preds = rng.randint(0, 4, 50)
    target = rng.randint(0, 4, 50)
    acc.update(jnp.asarray(preds), jnp.asarray(target))
    synced_val = float(acc.compute())
    all_p = np.concatenate([np.random.RandomState(200 + r).randint(0, 4, 50) for r in range(num_processes)])
    # target stream is the SECOND draw from each rank's rng, exactly as generated above
    all_t = np.concatenate([
        (lambda g: (g.randint(0, 4, 50), g.randint(0, 4, 50))[1])(np.random.RandomState(200 + r))
        for r in range(num_processes)
    ])
    results["accuracy_global"] = abs(synced_val - float(np.mean(all_p == all_t))) < 1e-6

    with open(out_path, "w") as fh:
        json.dump({"process_id": process_id, "checks": results}, fh)
    if not all(results.values()):
        sys.exit(1)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-processes", type=int, default=2)
    parser.add_argument("--port", type=int, default=12731)
    parser.add_argument("--process-id", type=int, default=None, help="internal: worker mode")
    parser.add_argument("--out", default=None, help="internal: worker result file")
    args = parser.parse_args()

    coordinator = f"localhost:{args.port}"
    if args.process_id is not None:
        _worker(args.process_id, args.num_processes, coordinator, args.out)
        return 0

    tmpdir = tempfile.mkdtemp(prefix="multihost_smoke_")
    procs = []
    outs = []
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pythonpath = repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": pythonpath}
    for rank in range(args.num_processes):
        out = os.path.join(tmpdir, f"rank{rank}.json")
        outs.append(out)
        procs.append(
            subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--process-id", str(rank), "--num-processes", str(args.num_processes),
                 "--port", str(args.port), "--out", out],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    failed = False
    for rank, p in enumerate(procs):
        try:
            stdout, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            stdout = "(timeout after 240 s)"
        if p.returncode != 0:
            failed = True
            print(f"--- rank {rank} FAILED (rc={p.returncode}) ---\n{stdout}")
    reports = []
    for out in outs:
        if os.path.exists(out):
            with open(out) as fh:
                reports.append(json.load(fh))
    print(json.dumps({"num_processes": args.num_processes, "reports": reports}, indent=2))
    ok = (not failed) and len(reports) == args.num_processes and all(
        all(r["checks"].values()) for r in reports
    )
    print("MULTIHOST_OK" if ok else "MULTIHOST_FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
