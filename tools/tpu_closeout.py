"""Zero-prep on-chip closeout — ONE command for the moment the TPU tunnel revives.

Round-5 VERDICT item 1. Runs, in order:

  (a) backend probe via ``ensure_backend`` (wedge-safe: killable subprocess),
  (b) ``bench.py`` (5 BASELINE configs + device-sort extra, roofline/MFU),
      ``tools/tpu_validate.py`` (per-domain TPU-vs-CPU deviation sweep), and
      ``tools/map_scale_bench.py --reference`` (COCO-val-scale MAP),
  (c) COMPILED Pallas kernel timings vs their XLA paths — ``ops/binned_hist``
      (multi-threshold curve histogram) and ``ops/ssim_window`` (separable
      window stencil) — the two kernels that have never executed compiled,
  (d) a refreshed ``BENCH_TPU_live.json`` bundling all of it.

On a CPU fallback (tunnel still wedged) everything still runs — Pallas in
interpreter mode, labeled as such — but the bundle is written to
``TPU_CLOSEOUT_SMOKE.json`` instead, so the round-2 ``BENCH_TPU_live.json``
(the last real hardware truth) is never overwritten by proxy numbers.

Usage::

    python tools/tpu_closeout.py            # full closeout
    python tools/tpu_closeout.py --smoke    # small shapes, quick CPU dry run
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _best_of(fn, repeats=5):
    import jax

    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def _run_tool(cmd, timeout):
    """Run a repo tool as a subprocess; return (ok, last JSON line or error)."""
    proc = subprocess.run(
        [sys.executable] + cmd, cwd=REPO, capture_output=True, text=True, timeout=timeout
    )
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return proc.returncode == 0, json.loads(line)
            except json.JSONDecodeError:
                break
    return False, {"error": f"rc={proc.returncode}", "stderr": proc.stderr[-2000:]}


def kernel_timings(on_tpu: bool, smoke: bool) -> dict:
    """Compiled-Pallas vs XLA timings + max deviation for both kernels."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu.functional.image._helpers import separable_depthwise_conv
    from metrics_tpu.functional.image.ssim import _gaussian_taps_np
    from metrics_tpu.ops.binned_hist import binned_counts_pallas, pallas_binned_fits
    from metrics_tpu.ops.ssim_window import windowed_sum_nchw

    out = {"pallas_mode": "compiled" if on_tpu else "interpret (no TPU — not a hardware number)"}
    interpret = not on_tpu

    # --- binned multi-threshold histogram (ops/binned_hist.py) ---
    n, t_len = (1 << 14, 50) if smoke or interpret else (1 << 22, 200)
    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(n, 1).astype(np.float32))
    target01 = jnp.asarray(rng.randint(0, 2, (n, 1)).astype(np.int32))
    valid = jnp.ones((n, 1), bool)
    thr = jnp.asarray(np.linspace(0.0, 1.0, t_len).astype(np.float32))
    assert pallas_binned_fits(n, 1, t_len)

    def pallas_hist():
        return binned_counts_pallas(preds, target01, valid, thr, interpret=interpret)

    def xla_hist():
        from metrics_tpu.utils.data import bincount

        bucket = jnp.searchsorted(thr, preds, side="right").astype(jnp.int32)
        flat = bucket[:, 0]
        is_pos = valid[:, 0] & (target01[:, 0] == 1)
        dead = t_len + 1
        pos_hist = bincount(jnp.where(is_pos, flat, dead), dead + 1)[:dead]
        neg_hist = bincount(jnp.where(~is_pos, flat, dead), dead + 1)[:dead]
        tp = (pos_hist.sum() - jnp.cumsum(pos_hist))[:t_len]
        fp = (neg_hist.sum() - jnp.cumsum(neg_hist))[:t_len]
        return tp, fp, pos_hist.sum()[None], neg_hist.sum()[None]

    xla_hist_j = jax.jit(xla_hist)
    try:
        got_p = jax.block_until_ready(pallas_hist())  # compile + correctness probe
        got_x = jax.block_until_ready(xla_hist_j())
        diff = max(
            float(np.max(np.abs(np.asarray(a, np.float64) - np.asarray(b.reshape(np.asarray(a).shape), np.float64))))
            for a, b in zip(got_p[:2], got_x[:2])
        )
        out["binned_hist"] = {
            "n": n, "thresholds": t_len,
            "pallas_ms": round(1000 * _best_of(pallas_hist), 3),
            "xla_ms": round(1000 * _best_of(xla_hist_j), 3),
            "max_abs_diff": diff,
        }
    except Exception as err:  # noqa: BLE001 — a kernel failure must not kill the closeout
        out["binned_hist"] = {"error": f"{type(err).__name__}: {err}"}

    # --- SSIM separable window (ops/ssim_window.py) ---
    shape = (2, 1, 64, 64) if smoke or interpret else (20, 3, 256, 256)
    x = jnp.asarray(np.random.RandomState(1).rand(*shape).astype(np.float32))
    taps = [_gaussian_taps_np(11, 1.5), _gaussian_taps_np(11, 1.5)]
    kernels = [jnp.asarray(t) for t in taps]

    def pallas_win():
        return windowed_sum_nchw(x, taps, interpret=interpret)

    conv_j = jax.jit(lambda v: separable_depthwise_conv(v, kernels))
    try:
        got_p = jax.block_until_ready(pallas_win())
        got_x = jax.block_until_ready(conv_j(x))
        out["ssim_window"] = {
            "shape": list(shape),
            "pallas_ms": round(1000 * _best_of(pallas_win), 3),
            "xla_ms": round(1000 * _best_of(lambda: conv_j(x)), 3),
            "max_abs_diff": float(np.max(np.abs(np.asarray(got_p, np.float64) - np.asarray(got_x, np.float64)))),
        }
    except Exception as err:  # noqa: BLE001
        out["ssim_window"] = {"error": f"{type(err).__name__}: {err}"}
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="small shapes + small MAP sweep (CPU dry run)")
    args = ap.parse_args()

    from metrics_tpu.utils.backend import ensure_backend

    platform = ensure_backend(min_devices=1)
    import jax

    on_tpu = jax.default_backend() == "tpu"
    bundle = {
        "closeout": "round-5",
        "platform_probe": platform,
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
    }

    print(f"[closeout] backend={bundle['backend']} device={bundle['device_kind']}", file=sys.stderr)
    # Proxy runs (smoke or CPU fallback) route EVERY sub-tool artifact to a side
    # file: canonical artifacts (TPU_VALIDATION.json, MAP_SCALE_BENCH.json) hold
    # idle-machine / on-chip evidence and must never be clobbered by proxies.
    proxy = args.smoke or not on_tpu
    validate_out = ["--out", os.path.join(REPO, "TPU_VALIDATION_SMOKE.json")] if proxy else []
    map_out = ["--out", os.path.join(REPO, "MAP_SCALE_BENCH_SMALL.json")] if proxy else []
    steps = [
        ("bench", ["bench.py"], 3600),
        ("tpu_validate", ["tools/tpu_validate.py", *validate_out], 3600),
        ("map_scale", ["tools/map_scale_bench.py", "--reference", *map_out]
         + (["--images", "200", "--classes", "10"] if args.smoke else []), 3600),
    ]
    for name, cmd, timeout in steps:
        print(f"[closeout] running {name}...", file=sys.stderr)
        try:
            ok, payload = _run_tool(cmd, timeout)
        except subprocess.TimeoutExpired:
            ok, payload = False, {"error": f"timeout after {timeout}s"}
        bundle[name] = payload
        bundle[f"{name}_ok"] = ok

    print("[closeout] timing Pallas kernels vs XLA...", file=sys.stderr)
    bundle["kernels"] = kernel_timings(on_tpu, args.smoke)

    # key on `not proxy`, not on_tpu: --smoke on a live chip must also land in
    # the side file (smoke shapes are not hardware evidence either)
    target = os.path.join(REPO, "TPU_CLOSEOUT_SMOKE.json" if proxy else "BENCH_TPU_live.json")
    bundle["hardware_truth"] = not proxy
    with open(target, "w") as fh:
        json.dump(bundle, fh, indent=1)
    print(json.dumps({
        "metric": "tpu_closeout",
        "value": 0 if proxy else 1,
        "unit": "1 = on-chip artifact refreshed, 0 = proxy (cpu or smoke) only",
        "vs_baseline": bundle.get("bench", {}).get("value", -1),
        "artifact": os.path.basename(target),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
