"""Modular image metrics (reference ``torchmetrics/image/__init__.py``)."""

from metrics_tpu.image.metrics import (
    ErrorRelativeGlobalDimensionlessSynthesis,
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    PeakSignalNoiseRatioWithBlockedEffect,
    QualityWithNoReference,
    RelativeAverageSpectralError,
    RootMeanSquaredErrorUsingSlidingWindow,
    SpatialCorrelationCoefficient,
    SpatialDistortionIndex,
    SpectralAngleMapper,
    SpectralDistortionIndex,
    StructuralSimilarityIndexMeasure,
    TotalVariation,
    UniversalImageQualityIndex,
    VisualInformationFidelity,
)

__all__ = [
    "ErrorRelativeGlobalDimensionlessSynthesis",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "PeakSignalNoiseRatio",
    "PeakSignalNoiseRatioWithBlockedEffect",
    "QualityWithNoReference",
    "RelativeAverageSpectralError",
    "RootMeanSquaredErrorUsingSlidingWindow",
    "SpatialCorrelationCoefficient",
    "SpatialDistortionIndex",
    "SpectralAngleMapper",
    "SpectralDistortionIndex",
    "StructuralSimilarityIndexMeasure",
    "TotalVariation",
    "UniversalImageQualityIndex",
    "VisualInformationFidelity",
]
