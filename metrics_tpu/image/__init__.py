"""Modular image metrics (reference ``torchmetrics/image/__init__.py``)."""

from metrics_tpu.image.generative import (
    FrechetInceptionDistance,
    InceptionScore,
    KernelInceptionDistance,
    MemorizationInformedFrechetInceptionDistance,
)
from metrics_tpu.image.lpips import LearnedPerceptualImagePatchSimilarity, PerceptualPathLength

from metrics_tpu.image.metrics import (
    ErrorRelativeGlobalDimensionlessSynthesis,
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    PeakSignalNoiseRatioWithBlockedEffect,
    QualityWithNoReference,
    RelativeAverageSpectralError,
    RootMeanSquaredErrorUsingSlidingWindow,
    SpatialCorrelationCoefficient,
    SpatialDistortionIndex,
    SpectralAngleMapper,
    SpectralDistortionIndex,
    StructuralSimilarityIndexMeasure,
    TotalVariation,
    UniversalImageQualityIndex,
    VisualInformationFidelity,
)

__all__ = [
    "FrechetInceptionDistance",
    "InceptionScore",
    "KernelInceptionDistance",
    "LearnedPerceptualImagePatchSimilarity",
    "MemorizationInformedFrechetInceptionDistance",
    "PerceptualPathLength",
    "ErrorRelativeGlobalDimensionlessSynthesis",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "PeakSignalNoiseRatio",
    "PeakSignalNoiseRatioWithBlockedEffect",
    "QualityWithNoReference",
    "RelativeAverageSpectralError",
    "RootMeanSquaredErrorUsingSlidingWindow",
    "SpatialCorrelationCoefficient",
    "SpatialDistortionIndex",
    "SpectralAngleMapper",
    "SpectralDistortionIndex",
    "StructuralSimilarityIndexMeasure",
    "TotalVariation",
    "UniversalImageQualityIndex",
    "VisualInformationFidelity",
]
