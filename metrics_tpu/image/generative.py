"""Generative-image metrics: FID, KID, InceptionScore, MiFID.

Parity with reference ``image/fid.py:183`` (streaming mean + outer-product
covariance states ``:351-357``, matrix-sqrt compute ``:160``), ``kid.py``,
``inception.py``, ``mifid.py``. The reference pulls an InceptionV3 through
torch-fidelity (SURVEY §2.9); in this no-egress build the feature extractor is
**injected**: pass any callable (e.g. a flax module apply fn) mapping image
batches to features, or update with precomputed feature arrays directly
(``update(features, real=...)``). The FID matrix sqrt uses the symmetric
``sqrt(cov1)·cov2·sqrt(cov1)`` eigendecomposition — ``eigh`` twice, no scipy.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.utils.compute import count_dtype


def _sqrtm_trace_product(cov1: np.ndarray, cov2: np.ndarray) -> float:
    """trace(sqrtm(cov1 @ cov2)) for PSD inputs via two eigh calls (float64)."""
    vals1, vecs1 = np.linalg.eigh(cov1)
    vals1 = np.clip(vals1, 0, None)
    sqrt_cov1 = (vecs1 * np.sqrt(vals1)) @ vecs1.T
    inner = sqrt_cov1 @ cov2 @ sqrt_cov1
    vals = np.linalg.eigvalsh((inner + inner.T) / 2)
    return float(np.sqrt(np.clip(vals, 0, None)).sum())


def _fid_from_stats(
    sum1: np.ndarray, cov_sum1: np.ndarray, n1: float, sum2: np.ndarray, cov_sum2: np.ndarray, n2: float
) -> float:
    """FID from streaming sums (reference ``fid.py:118-160``)."""
    mu1 = sum1 / n1
    mu2 = sum2 / n2
    cov1 = (cov_sum1 - n1 * np.outer(mu1, mu1)) / (n1 - 1)
    cov2 = (cov_sum2 - n2 * np.outer(mu2, mu2)) / (n2 - 1)
    diff = mu1 - mu2
    return float(diff @ diff + np.trace(cov1) + np.trace(cov2) - 2 * _sqrtm_trace_product(cov1, cov2))


class FrechetInceptionDistance(Metric):
    """Fréchet Inception Distance (reference ``image/fid.py:183``).

    Args:
        feature: an int is NOT supported offline (the reference downloads
            torch-fidelity InceptionV3 weights); pass a callable mapping an image
            batch to (N, D) features, or update with feature arrays directly.
        reset_real_features: keep real-set statistics across ``reset`` calls.

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(42)
    >>> fid = FrechetInceptionDistance(feature=lambda x: x.reshape(x.shape[0], -1))
    >>> real = jnp.asarray(rng.randn(64, 16).astype(np.float32))
    >>> fake = jnp.asarray(rng.randn(64, 16).astype(np.float32) + 0.5)
    >>> fid.update(real, real=True)
    >>> fid.update(fake, real=False)
    >>> float(fid.compute()) > 0
    True
    """

    __jit_ineligible__ = True  # f64 eigendecompositions run at the host boundary
    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        feature: Union[Callable, int, None] = None,
        reset_real_features: bool = True,
        normalize: bool = False,
        num_features: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if isinstance(feature, int):
            # the reference's default path (torch-fidelity InceptionV3, fid.py:30-45):
            # resolved against LOCAL weights via the hub — raises a clear error if absent
            from metrics_tpu.models.hub import load_feature_extractor

            if num_features is None:
                num_features = feature
            feature = load_feature_extractor("inception_v3_fid", feature=feature)
        self.feature_extractor = feature
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize
        self._initialized = False
        if num_features is not None:
            # declared feature dimension: initialize states eagerly so the metric
            # is mergeable/serializable before the first update, and mismatched
            # extractor outputs fail loudly in _update_features' shape check
            self._init_states(int(num_features))

    def _init_states(self, d: int) -> None:
        self.add_state("real_features_sum", jnp.zeros(d), "sum")
        self.add_state("real_features_cov_sum", jnp.zeros((d, d)), "sum")
        self.add_state("real_features_num_samples", jnp.zeros((), dtype=count_dtype()), "sum")
        self.add_state("fake_features_sum", jnp.zeros(d), "sum")
        self.add_state("fake_features_cov_sum", jnp.zeros((d, d)), "sum")
        self.add_state("fake_features_num_samples", jnp.zeros((), dtype=count_dtype()), "sum")
        self._initialized = True

    def _extract(self, imgs: Array) -> Array:
        if self.normalize:
            # reference semantics: normalize=True marks [0,1] float inputs, which the
            # backbone preprocessing scales to the uint8 range
            imgs = imgs * 255.0
        return self.feature_extractor(imgs) if self.feature_extractor is not None else imgs

    def update(self, imgs: Array, real: bool) -> None:
        """Update with an image batch (features extracted) or a feature batch."""
        self._update_features(self._extract(imgs), real)

    def _update_features(self, feats: Array, real: bool) -> None:
        feats = np.asarray(feats, dtype=np.float64)
        if feats.ndim != 2:
            raise ValueError(f"Expected features to be 2d (N, D) but got shape {feats.shape}")
        if not self._initialized:
            self._init_states(feats.shape[1])
        expected = self._state["real_features_sum"].shape[0]
        if feats.shape[1] != expected:
            raise ValueError(
                f"Expected features of dimension {expected} (from `num_features`/first update)"
                f" but the extractor returned dimension {feats.shape[1]}"
            )
        key = "real" if real else "fake"
        # INCREMENTAL accumulation on the registered states: merge_state/sync/forward
        # combine these like any other sum state (float32 on device; the float64
        # covariance precision of the reference is preserved at compute time).
        self._state[f"{key}_features_sum"] = self._state[f"{key}_features_sum"] + jnp.asarray(
            feats.sum(0), dtype=jnp.float32)
        self._state[f"{key}_features_cov_sum"] = self._state[f"{key}_features_cov_sum"] + jnp.asarray(
            feats.T @ feats, dtype=jnp.float32)
        self._state[f"{key}_features_num_samples"] = self._state[f"{key}_features_num_samples"] + feats.shape[0]

    def compute(self) -> Array:
        """Compute FID from the accumulated statistics (float64 at the host boundary)."""
        n_real = int(self.real_features_num_samples) if self._initialized else 0
        n_fake = int(self.fake_features_num_samples) if self._initialized else 0
        if n_real < 2 or n_fake < 2:
            raise RuntimeError("More than one sample is required for both the real and fake distributions")
        val = _fid_from_stats(
            np.asarray(self.real_features_sum, dtype=np.float64),
            np.asarray(self.real_features_cov_sum, dtype=np.float64), n_real,
            np.asarray(self.fake_features_sum, dtype=np.float64),
            np.asarray(self.fake_features_cov_sum, dtype=np.float64), n_fake,
        )
        return jnp.asarray(val, dtype=jnp.float32)

    def reset(self) -> None:
        """Reset; optionally keep real-set statistics (reference ``fid.py`` ``reset_real_features``)."""
        if not self._initialized:
            return super().reset()
        if not self.reset_real_features:
            keep = {k: self._state[k] for k in
                    ("real_features_sum", "real_features_cov_sum", "real_features_num_samples")}
            super().reset()
            self._state.update(keep)
        else:
            super().reset()


class KernelInceptionDistance(Metric):
    """Kernel Inception Distance (reference ``image/kid.py:48``): polynomial-kernel MMD over feature subsets.

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(42)
    >>> kid = KernelInceptionDistance(feature=lambda x: x, subsets=3, subset_size=50)
    >>> kid.update(jnp.asarray(rng.randn(100, 16).astype(np.float32)), real=True)
    >>> kid.update(jnp.asarray(rng.randn(100, 16).astype(np.float32) + 1), real=False)
    >>> mean, std = kid.compute()
    >>> float(mean) > 0
    True
    """

    __jit_ineligible__ = True
    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        feature: Union[Callable, int, None] = None,
        subsets: int = 100,
        subset_size: int = 1000,
        degree: int = 3,
        gamma: Optional[float] = None,
        coef: float = 1.0,
        reset_real_features: bool = True,
        normalize: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if isinstance(feature, int):
            from metrics_tpu.models.hub import load_feature_extractor

            feature = load_feature_extractor("inception_v3_fid", feature=feature)
        self.feature_extractor = feature
        if not (isinstance(subsets, int) and subsets > 0):
            raise ValueError("Argument `subsets` expected to be integer larger than 0")
        if not (isinstance(subset_size, int) and subset_size > 0):
            raise ValueError("Argument `subset_size` expected to be integer larger than 0")
        self.subsets = subsets
        self.subset_size = subset_size
        self.degree = degree
        self.gamma = gamma
        self.coef = coef
        self.reset_real_features = reset_real_features
        self.normalize = normalize
        self.add_state("real_features", [], dist_reduce_fx=None)
        self.add_state("fake_features", [], dist_reduce_fx=None)

    def update(self, imgs: Array, real: bool) -> None:
        """Update with an image batch (features extracted) or a feature batch."""
        if self.normalize:
            imgs = imgs * 255.0
        feats = self.feature_extractor(imgs) if self.feature_extractor is not None else imgs
        feats = jnp.asarray(feats, dtype=jnp.float32)
        (self.real_features if real else self.fake_features).append(feats)

    def reset(self) -> None:
        """Reset; optionally keep the accumulated real features (reference ``kid.py``)."""
        if not self.reset_real_features:
            keep = list(self.real_features)
            super().reset()
            self._state["real_features"] = keep
        else:
            super().reset()

    def _poly_kernel(self, x: np.ndarray, y: np.ndarray) -> np.ndarray:
        gamma = self.gamma if self.gamma is not None else 1.0 / x.shape[1]
        return (x @ y.T * gamma + self.coef) ** self.degree

    def _mmd(self, x: np.ndarray, y: np.ndarray) -> float:
        m = x.shape[0]
        k_xx = self._poly_kernel(x, x)
        k_yy = self._poly_kernel(y, y)
        k_xy = self._poly_kernel(x, y)
        diag_sum_xx = (k_xx.sum() - np.trace(k_xx)) / (m * (m - 1))
        diag_sum_yy = (k_yy.sum() - np.trace(k_yy)) / (m * (m - 1))
        return float(diag_sum_xx + diag_sum_yy - 2 * k_xy.mean())

    def compute(self) -> Tuple[Array, Array]:
        """KID mean/std over random subsets (reference ``kid.py:27-45``)."""
        real = np.concatenate([np.asarray(f) for f in self.real_features]).astype(np.float64)
        fake = np.concatenate([np.asarray(f) for f in self.fake_features]).astype(np.float64)
        n_real, n_fake = real.shape[0], fake.shape[0]
        if n_real < self.subset_size or n_fake < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")
        rng = np.random.RandomState(0)
        vals = []
        for _ in range(self.subsets):
            r = real[rng.choice(n_real, self.subset_size, replace=False)]
            f = fake[rng.choice(n_fake, self.subset_size, replace=False)]
            vals.append(self._mmd(r, f))
        vals = np.asarray(vals)
        return jnp.asarray(vals.mean(), dtype=jnp.float32), jnp.asarray(vals.std(ddof=1), dtype=jnp.float32)


class InceptionScore(Metric):
    """Inception Score (reference ``image/inception.py:36``): exp(E KL(p(y|x) || p(y))).

    >>> import jax, jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(42)
    >>> iscore = InceptionScore(feature=lambda x: x)  # x already = class logits
    >>> iscore.update(jnp.asarray(rng.randn(100, 10).astype(np.float32)))
    >>> mean, std = iscore.compute()
    >>> float(mean) > 1
    True
    """

    __jit_ineligible__ = True
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, feature: Union[Callable, int, None] = None, splits: int = 10,
                 normalize: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if isinstance(feature, (int, str)):
            raise ModuleNotFoundError("Integer `feature` needs downloaded InceptionV3 weights (unavailable offline).")
        self.feature_extractor = feature
        self.splits = splits
        self.normalize = normalize
        self.add_state("features", [], dist_reduce_fx=None)

    def update(self, imgs: Array) -> None:
        """Update with an image batch (logits extracted) or a logit batch."""
        if self.normalize:
            imgs = imgs * 255.0
        feats = self.feature_extractor(imgs) if self.feature_extractor is not None else imgs
        self.features.append(jnp.asarray(feats, dtype=jnp.float32))

    def compute(self) -> Tuple[Array, Array]:
        """Compute IS mean/std over splits."""
        import jax

        logits = jnp.concatenate(self.features)
        probs = jax.nn.softmax(logits, axis=-1)
        n = probs.shape[0]
        idx = np.array_split(np.arange(n), self.splits)
        scores = []
        for ix in idx:
            p = probs[jnp.asarray(ix)]
            marginal = p.mean(0, keepdims=True)
            kl = jnp.sum(p * (jnp.log(p + 1e-10) - jnp.log(marginal + 1e-10)), axis=1)
            scores.append(float(jnp.exp(kl.mean())))
        scores = np.asarray(scores)
        return jnp.asarray(scores.mean(), dtype=jnp.float32), jnp.asarray(scores.std(ddof=1), dtype=jnp.float32)


class MemorizationInformedFrechetInceptionDistance(FrechetInceptionDistance):
    """MiFID (reference ``image/mifid.py:35``): FID scaled by a memorization penalty.

    The full feature sets (needed for the per-sample nearest-cosine memorization
    distance) are REGISTERED cat-reduce list states alongside the streaming FID
    statistics — the generic merge/pickle/sync/forward machinery handles them
    like KID's and InceptionScore's feature lists.
    """

    def __init__(self, feature: Union[Callable, int, None] = None, cosine_distance_eps: float = 0.1,
                 **kwargs: Any) -> None:
        super().__init__(feature=feature, **kwargs)
        if not (isinstance(cosine_distance_eps, float) and 0 < cosine_distance_eps <= 1):
            raise ValueError("Argument `cosine_distance_eps` expected to be a float greater than 0 and less than 1")
        self.cosine_distance_eps = cosine_distance_eps
        self.add_state("real_feature_store", [], dist_reduce_fx="cat")
        self.add_state("fake_feature_store", [], dist_reduce_fx="cat")

    def update(self, imgs: Array, real: bool) -> None:
        """Update streaming stats and keep the features for the memorization term."""
        feats = self._extract(imgs)  # extract ONCE; shared by FID stats and memorization term
        self._update_features(feats, real)
        (self.real_feature_store if real else self.fake_feature_store).append(
            jnp.asarray(feats, dtype=jnp.float32)
        )

    def compute(self) -> Array:
        """FID / max(memorization distance, eps)."""
        from metrics_tpu.utils.data import dim_zero_cat

        fid = float(super().compute())
        real = np.asarray(dim_zero_cat(self.real_feature_store), dtype=np.float64)
        fake = np.asarray(dim_zero_cat(self.fake_feature_store), dtype=np.float64)
        real_n = real / np.clip(np.linalg.norm(real, axis=1, keepdims=True), 1e-12, None)
        fake_n = fake / np.clip(np.linalg.norm(fake, axis=1, keepdims=True), 1e-12, None)
        cos = fake_n @ real_n.T
        d = 1 - np.abs(cos)
        mem_dist = float(d.min(axis=1).mean())
        penalty = mem_dist if mem_dist < self.cosine_distance_eps else 1.0
        return jnp.asarray(fid / penalty, dtype=jnp.float32)

    def forward(self, *args: Any, **kwargs: Any) -> Array:
        """Generic forward + all-or-nothing rollback.

        ``update`` is one-sided (real XOR fake), so the batch-local compute
        raises whenever the batch lacks the other distribution; roll the whole
        forward back (state, counters, sync flags) instead of leaving the
        batch-only state the generic path stops in.
        """
        state_backup = self._copy_state()
        count_backup = self._update_count
        try:
            return super().forward(*args, **kwargs)
        except Exception:
            # the backup is a private _copy_state() snapshot — restoring it
            # creates no outside alias
            self.__dict__["_state"] = state_backup  # donlint: disable=ML001
            self._update_count = count_backup
            self._computed = None
            self._to_sync = self.sync_on_compute
            self._should_unsync = True
            self._is_synced = False
            raise

    def reset(self) -> None:
        """Reset; optionally keep the real features (stats AND store)."""
        if self._initialized and not self.reset_real_features:
            keep = list(self.real_feature_store)
            super().reset()  # FID.reset keeps the streaming real stats
            self._state["real_feature_store"] = keep
        else:
            super().reset()
