"""LPIPS and PerceptualPathLength with injectable backbones.

Parity with reference ``image/lpips.py`` (torchvision VGG/Alex/Squeeze + vendored
``lpips_models/*.pth`` weights — SURVEY §2.9) and ``image/perceptual_path_length.py``.
Offline build: the per-layer feature function is injected; the metric implements
the LPIPS distance math (unit-normalize per channel, squared diff, spatial mean,
layer sum).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.metric import Metric


def _lpips_distance(feats_a: Sequence[Array], feats_b: Sequence[Array],
                    weights: Optional[Sequence[Callable]] = None) -> Array:
    """LPIPS distance given per-layer feature maps (N, C, H, W)."""
    total = None
    for i, (fa, fb) in enumerate(zip(feats_a, feats_b)):
        na = fa / jnp.clip(jnp.linalg.norm(fa, axis=1, keepdims=True), 1e-10, None)
        nb = fb / jnp.clip(jnp.linalg.norm(fb, axis=1, keepdims=True), 1e-10, None)
        diff = (na - nb) ** 2
        if weights is not None:
            diff = weights[i](diff)
            layer = diff.reshape(diff.shape[0], -1).mean(-1) if diff.ndim > 1 else diff
        else:
            layer = diff.sum(1).reshape(diff.shape[0], -1).mean(-1)
        total = layer if total is None else total + layer
    return total


class LearnedPerceptualImagePatchSimilarity(Metric):
    """LPIPS (reference ``image/lpips.py:55``).

    Args:
        net: callable mapping an image batch to a LIST of per-layer feature maps
            (the reference's pretrained VGG/Alex backbones need downloaded weights,
            unavailable offline — inject your flax backbone here).
        reduction: 'mean' or 'sum' over the accumulated pairs.

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(42)
    >>> net = lambda x: [x, x[:, :, ::2, ::2]]  # toy 2-layer feature pyramid
    >>> lpips = LearnedPerceptualImagePatchSimilarity(net=net)
    >>> a = jnp.asarray(rng.rand(4, 3, 16, 16).astype(np.float32))
    >>> b = jnp.asarray(rng.rand(4, 3, 16, 16).astype(np.float32))
    >>> lpips.update(a, b)
    >>> float(lpips.compute()) > 0
    True
    """

    __jit_ineligible__ = True
    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        net: Optional[Callable] = None,
        net_type: str = "alex",
        reduction: str = "mean",
        normalize: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if net_type not in ("alex", "vgg", "squeeze"):
            raise ValueError(f"Argument `net_type` must be one of 'alex', 'vgg', 'squeeze', but got {net_type}")
        self._scorer: Optional[Callable] = None
        if net is None:
            # default path = named backbone resolved against local weights (the
            # reference vendors lin heads + downloads torchvision towers,
            # functional/image/lpips.py:63-150); raises a clear error if absent
            from metrics_tpu.models.hub import load_lpips

            self._scorer = load_lpips(net_type)
        self.net = net
        if reduction not in ("mean", "sum"):
            raise ValueError(f"Argument `reduction` must be one of 'sum' or 'mean' but got {reduction}")
        self.reduction = reduction
        self.normalize = normalize
        self.add_state("sum_scores", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, img1: Array, img2: Array) -> None:
        """Update with a pair of image batches."""
        if self._scorer is not None:
            d = self._scorer(img1, img2, self.normalize)
            self.sum_scores = self.sum_scores + d.sum()
            self.total = self.total + d.shape[0]
            return
        if self.normalize:
            img1 = 2 * img1 - 1
            img2 = 2 * img2 - 1
        d = _lpips_distance(self.net(img1), self.net(img2))
        self.sum_scores = self.sum_scores + d.sum()
        self.total = self.total + d.shape[0]

    def compute(self) -> Array:
        """Compute metric."""
        if self.reduction == "mean":
            return (self.sum_scores / self.total).astype(jnp.float32)
        return self.sum_scores.astype(jnp.float32)


class PerceptualPathLength(Metric):
    """Perceptual Path Length (reference ``image/perceptual_path_length.py:36``).

    Measures LPIPS distance between images generated from perturbed latent
    interpolations. Requires a generator callable and an LPIPS ``net`` (see
    :class:`LearnedPerceptualImagePatchSimilarity`).
    """

    __jit_ineligible__ = True
    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(
        self,
        generator: Optional[Callable] = None,
        net: Optional[Callable] = None,
        num_samples: int = 10000,
        conditional: bool = False,
        epsilon: float = 1e-4,
        resize: Optional[int] = 64,
        lower_discard: Optional[float] = 0.01,
        upper_discard: Optional[float] = 0.99,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if generator is None or net is None:
            raise ModuleNotFoundError(
                "PerceptualPathLength needs a `generator` callable (z -> images) and an LPIPS `net`"
                " feature callable; pretrained defaults are unavailable in this offline build."
            )
        self.generator = generator
        self.net = net
        self.num_samples = num_samples
        self.epsilon = epsilon
        self.lower_discard = lower_discard
        self.upper_discard = upper_discard
        self.add_state("distances", [], dist_reduce_fx="cat")

    def update(self, z0: Array, z1: Array) -> None:
        """Update with latent pairs: generates endpoints of an ε-step interpolation."""
        t = np.random.RandomState(0).rand(z0.shape[0]).astype(np.float32)[:, None]
        zt0 = z0 * (1 - t) + z1 * t
        zt1 = z0 * (1 - (t + self.epsilon)) + z1 * (t + self.epsilon)
        img0 = self.generator(zt0)
        img1 = self.generator(zt1)
        d = _lpips_distance(self.net(img0), self.net(img1)) / (self.epsilon**2)
        self.distances.append(d)

    def compute(self) -> Array:
        """Mean PPL with tail discards."""
        from metrics_tpu.utils.data import dim_zero_cat

        d = np.asarray(dim_zero_cat(self.distances))
        lo = np.quantile(d, self.lower_discard) if self.lower_discard else d.min()
        hi = np.quantile(d, self.upper_discard) if self.upper_discard else d.max()
        kept = d[(d >= lo) & (d <= hi)]
        return jnp.asarray(kept.mean() if kept.size else 0.0, dtype=jnp.float32)
