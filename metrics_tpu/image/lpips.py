"""LPIPS and PerceptualPathLength with injectable backbones.

Parity with reference ``image/lpips.py`` (torchvision VGG/Alex/Squeeze + vendored
``lpips_models/*.pth`` weights — SURVEY §2.9) and ``image/perceptual_path_length.py``.
Offline build: the per-layer feature function is injected; the metric implements
the LPIPS distance math (unit-normalize per channel, squared diff, spatial mean,
layer sum).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.utils.compute import count_dtype


def _lpips_distance(feats_a: Sequence[Array], feats_b: Sequence[Array],
                    weights: Optional[Sequence[Callable]] = None) -> Array:
    """LPIPS distance given per-layer feature maps (N, C, H, W)."""
    total = None
    for i, (fa, fb) in enumerate(zip(feats_a, feats_b)):
        na = fa / jnp.clip(jnp.linalg.norm(fa, axis=1, keepdims=True), 1e-10, None)
        nb = fb / jnp.clip(jnp.linalg.norm(fb, axis=1, keepdims=True), 1e-10, None)
        diff = (na - nb) ** 2
        if weights is not None:
            diff = weights[i](diff)
            layer = diff.reshape(diff.shape[0], -1).mean(-1) if diff.ndim > 1 else diff
        else:
            layer = diff.sum(1).reshape(diff.shape[0], -1).mean(-1)
        total = layer if total is None else total + layer
    return total


class LearnedPerceptualImagePatchSimilarity(Metric):
    """LPIPS (reference ``image/lpips.py:55``).

    Args:
        net: callable mapping an image batch to a LIST of per-layer feature maps
            (the reference's pretrained VGG/Alex backbones need downloaded weights,
            unavailable offline — inject your flax backbone here).
        reduction: 'mean' or 'sum' over the accumulated pairs.

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(42)
    >>> net = lambda x: [x, x[:, :, ::2, ::2]]  # toy 2-layer feature pyramid
    >>> lpips = LearnedPerceptualImagePatchSimilarity(net=net)
    >>> a = jnp.asarray(rng.rand(4, 3, 16, 16).astype(np.float32))
    >>> b = jnp.asarray(rng.rand(4, 3, 16, 16).astype(np.float32))
    >>> lpips.update(a, b)
    >>> float(lpips.compute()) > 0
    True
    """

    __jit_ineligible__ = True
    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        net: Optional[Callable] = None,
        net_type: str = "alex",
        reduction: str = "mean",
        normalize: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if net_type not in ("alex", "vgg", "squeeze"):
            raise ValueError(f"Argument `net_type` must be one of 'alex', 'vgg', 'squeeze', but got {net_type}")
        self._scorer: Optional[Callable] = None
        if net is None:
            # default path = named backbone resolved against local weights (the
            # reference vendors lin heads + downloads torchvision towers,
            # functional/image/lpips.py:63-150); raises a clear error if absent
            from metrics_tpu.models.hub import load_lpips

            self._scorer = load_lpips(net_type)
        self.net = net
        if reduction not in ("mean", "sum"):
            raise ValueError(f"Argument `reduction` must be one of 'sum' or 'mean' but got {reduction}")
        self.reduction = reduction
        self.normalize = normalize
        self.add_state("sum_scores", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=count_dtype()), dist_reduce_fx="sum")

    def update(self, img1: Array, img2: Array) -> None:
        """Update with a pair of image batches."""
        from metrics_tpu.functional.image.perceptual import _validate_lpips_images

        _validate_lpips_images(img1, img2, self.normalize)
        if self._scorer is not None:
            d = self._scorer(img1, img2, self.normalize)
            self.sum_scores = self.sum_scores + d.sum()
            self.total = self.total + d.shape[0]
            return
        if self.normalize:
            img1 = 2 * img1 - 1
            img2 = 2 * img2 - 1
        d = _lpips_distance(self.net(img1), self.net(img2))
        self.sum_scores = self.sum_scores + d.sum()
        self.total = self.total + d.shape[0]

    def compute(self) -> Array:
        """Compute metric."""
        if self.reduction == "mean":
            return (self.sum_scores / self.total).astype(jnp.float32)
        return self.sum_scores.astype(jnp.float32)


def _interpolate_latents(z1: Array, z2: Array, epsilon: float, method: str) -> Array:
    """ε-step from ``z1`` toward ``z2`` (reference ``functional/image/perceptual_path_length.py:107-151``)."""
    eps = 1e-7
    if z1.shape != z2.shape:
        raise ValueError("Latents must have the same shape.")
    if method == "lerp":
        return z1 + (z2 - z1) * epsilon
    if method in ("slerp_any", "slerp_unit"):
        n1 = z1 / jnp.clip(jnp.sqrt((z1**2).sum(-1, keepdims=True)), eps, None)
        n2 = z2 / jnp.clip(jnp.sqrt((z2**2).sum(-1, keepdims=True)), eps, None)
        d = (n1 * n2).sum(-1, keepdims=True)
        degenerate = (
            (jnp.linalg.norm(n1, axis=-1, keepdims=True) < eps)
            | (jnp.linalg.norm(n2, axis=-1, keepdims=True) < eps)
            | (d > 1 - eps)
            | (d < -1 + eps)
        )
        omega = jnp.arccos(jnp.clip(d, -1.0, 1.0))
        denom = jnp.clip(jnp.sin(omega), eps, None)
        out = (jnp.sin((1 - epsilon) * omega) / denom) * z1 + (jnp.sin(epsilon * omega) / denom) * z2
        out = jnp.where(degenerate, z1 + (z2 - z1) * epsilon, out)
        if method == "slerp_unit":
            out = out / jnp.clip(jnp.sqrt((out**2).sum(-1, keepdims=True)), eps, None)
        return out
    raise ValueError(f"Interpolation method {method} not supported. Choose from 'lerp', 'slerp_any', 'slerp_unit'.")


def _adaptive_avg_matrix(n_in: int, n_out: int) -> np.ndarray:
    """(n_out, n_in) row-stochastic matrix equal to torch adaptive_avg_pool1d:
    output i averages whole input pixels [floor(i*n/o), ceil((i+1)*n/o))."""
    m = np.zeros((n_out, n_in), dtype=np.float32)
    for i in range(n_out):
        start = (i * n_in) // n_out
        end = -(-((i + 1) * n_in) // n_out)  # ceil
        m[i, start:end] = 1.0 / (end - start)
    return m


def _resize_images(x: Array, size: Optional[int]) -> Array:
    """Resize (N, C, H, W) images to ``(size, size)`` with the reference's
    ``_resize_tensor`` rule (``functional/image/lpips.py:219-224``): torch
    ``area`` mode (= adaptive average pooling) when BOTH dims are strictly
    larger than ``size``, bilinear (align_corners=False) otherwise."""
    if size is None:
        return x
    n, c, h, w = x.shape
    if h > size and w > size:
        mh = jnp.asarray(_adaptive_avg_matrix(h, size))
        mw = jnp.asarray(_adaptive_avg_matrix(w, size))
        return jnp.einsum("oh,nchw,pw->ncop", mh, x, mw)
    import jax

    return jax.image.resize(x, (n, c, size, size), method="bilinear", antialias=False)


def _ppl_validate_args(
    num_samples: int,
    conditional: bool,
    batch_size: int,
    interpolation_method: str,
    epsilon: float,
    resize: Optional[int],
    lower_discard: Optional[float],
    upper_discard: Optional[float],
) -> None:
    """Reference ``_perceptual_path_length_validate_arguments`` (``functional/image/perceptual_path_length.py:71``)."""
    if not (isinstance(num_samples, int) and num_samples > 0):
        raise ValueError(f"Argument `num_samples` must be a positive integer, but got {num_samples}.")
    if not isinstance(conditional, bool):
        raise ValueError(f"Argument `conditional` must be a boolean, but got {conditional}.")
    if not (isinstance(batch_size, int) and batch_size > 0):
        raise ValueError(f"Argument `batch_size` must be a positive integer, but got {batch_size}.")
    if interpolation_method not in ("lerp", "slerp_any", "slerp_unit"):
        raise ValueError(
            f"Argument `interpolation_method` must be one of 'lerp', 'slerp_any', 'slerp_unit',"
            f"got {interpolation_method}."
        )
    if not (isinstance(epsilon, float) and epsilon > 0):
        raise ValueError(f"Argument `epsilon` must be a positive float, but got {epsilon}.")
    if resize is not None and not (isinstance(resize, int) and resize > 0):
        raise ValueError(f"Argument `resize` must be a positive integer or `None`, but got {resize}.")
    if lower_discard is not None and not (isinstance(lower_discard, float) and 0 <= lower_discard <= 1):
        raise ValueError(
            f"Argument `lower_discard` must be a float between 0 and 1 or `None`, but got {lower_discard}."
        )
    if upper_discard is not None and not (isinstance(upper_discard, float) and 0 <= upper_discard <= 1):
        raise ValueError(
            f"Argument `upper_discard` must be a float between 0 and 1 or `None`, but got {upper_discard}."
        )


def _resolve_sim_net(sim_net: Any, resize: Optional[int]) -> Callable:
    """``None``/name → LPIPS scorer from local weights (with the reference's
    in-net resize); custom callables pass through untouched; anything else raises."""
    if sim_net is None or isinstance(sim_net, str):
        name = "vgg" if sim_net is None else sim_net
        if name not in ("alex", "vgg", "squeeze"):
            raise ValueError(f"sim_net must be a callable or one of 'alex', 'vgg', 'squeeze', got {sim_net}")
        from metrics_tpu.models.hub import load_lpips

        scorer = load_lpips(name)
        # resampling (bilinear or area) commutes with the scorer's per-channel
        # affine input normalization (resampling weights sum to 1), so pre-resizing
        # here equals the reference's post-scaling-layer resize inside _LPIPS
        return lambda a, b: scorer(_resize_images(a, resize), _resize_images(b, resize), False)
    if not callable(sim_net):
        raise ValueError(f"sim_net must be a callable or one of 'alex', 'vgg', 'squeeze', got {sim_net}")
    return sim_net


def _validate_ppl_generator(generator: Any, conditional: bool) -> None:
    """Reference ``_validate_generator_model`` contract (sample method, num_classes when conditional)."""
    if not hasattr(generator, "sample"):
        raise NotImplementedError(
            "The generator must have a `sample` method with signature `sample(num_samples: int) -> Array` where the"
            " returned array has shape `(num_samples, z_size)`."
        )
    if not callable(generator.sample):
        raise ValueError("The generator's `sample` method must be callable.")
    if conditional and not hasattr(generator, "num_classes"):
        raise AttributeError("The generator must have a `num_classes` attribute when `conditional=True`.")
    if conditional and not isinstance(getattr(generator, "num_classes", None), int):
        raise ValueError("The generator's `num_classes` attribute must be an integer when `conditional=True`.")


def perceptual_path_length(
    generator: Any,
    num_samples: int = 10_000,
    conditional: bool = False,
    batch_size: int = 64,
    interpolation_method: str = "lerp",
    epsilon: float = 1e-4,
    resize: Optional[int] = 64,
    lower_discard: Optional[float] = 0.01,
    upper_discard: Optional[float] = 0.99,
    sim_net: Optional[Callable] = None,
    seed: int = 0,
) -> tuple:
    """Perceptual path length of a generator (reference ``functional/image/perceptual_path_length.py:154``).

    ``generator``: object with ``sample(n) -> (n, z)`` latents and ``__call__(z[, labels]) -> images``
    scaled to [0, 255]. ``sim_net``: similarity callable ``(img1, img2) -> (N,)`` distances
    (e.g. an LPIPS scorer from :func:`metrics_tpu.models.lpips_nets.build_lpips` partially
    applied); when ``None``, the vgg LPIPS backbone is resolved from local weights.
    ``resize``: only the built-in LPIPS path resizes its inputs to ``(resize, resize)``
    (area-averaged for integer downsampling, bilinear otherwise) — a custom ``sim_net``
    receives the raw generator output, exactly as in the reference, where ``resize`` is a
    ``_LPIPS`` constructor argument and custom similarity modules are used as-is.

    Returns ``(mean, std, distances)`` after quantile tail discards — the reference's contract.
    """
    _ppl_validate_args(
        num_samples, conditional, batch_size, interpolation_method, epsilon, resize, lower_discard, upper_discard
    )
    _validate_ppl_generator(generator, conditional)
    sim_net = _resolve_sim_net(sim_net, resize)

    latent1 = generator.sample(num_samples)
    latent2 = generator.sample(num_samples)
    latent2 = _interpolate_latents(latent1, latent2, epsilon, interpolation_method)
    labels = None
    if conditional:
        labels = jnp.asarray(np.random.default_rng(seed).integers(0, generator.num_classes, (num_samples,)))

    distances = []
    num_batches = int(np.ceil(num_samples / batch_size))
    for i in range(num_batches):
        b1 = latent1[i * batch_size : (i + 1) * batch_size]
        b2 = latent2[i * batch_size : (i + 1) * batch_size]
        if conditional:
            lab = labels[i * batch_size : (i + 1) * batch_size]
            outputs = generator(jnp.concatenate([b1, b2], 0), jnp.concatenate([lab, lab], 0))
        else:
            outputs = generator(jnp.concatenate([b1, b2], 0))
        out1, out2 = jnp.split(outputs, 2, axis=0)
        # rescale to the LPIPS domain: [0, 255] -> [-1, 1]
        out1 = 2 * (out1 / 255) - 1
        out2 = 2 * (out2 / 255) - 1
        distances.append(np.asarray(sim_net(out1, out2)).reshape(-1) / epsilon**2)

    d = np.concatenate(distances)
    # reference uses torch.quantile(interpolation="lower")
    lower = np.quantile(d, lower_discard, method="lower") if lower_discard is not None else 0.0
    upper = np.quantile(d, upper_discard, method="lower") if upper_discard is not None else d.max()
    kept = d[(d >= lower) & (d <= upper)]
    return (
        jnp.asarray(kept.mean(), dtype=jnp.float32),
        jnp.asarray(kept.std(ddof=1), dtype=jnp.float32),
        jnp.asarray(kept),
    )


class PerceptualPathLength(Metric):
    """Perceptual Path Length (reference ``image/perceptual_path_length.py:36``).

    Measures LPIPS distance between images generated from ε-separated latent
    interpolations. ``update(generator)`` stores the generator; ``compute()``
    samples ``num_samples`` latent pairs through it and returns
    ``(mean, std, distances)`` — the reference's exact lifecycle.

    ``sim_net``: similarity callable ``(img1, img2) -> (N,)``; defaults to the
    named LPIPS backbone resolved from local weights (offline build).
    """

    __jit_ineligible__ = True
    is_differentiable = False
    higher_is_better = True
    full_state_update = True

    def __init__(
        self,
        num_samples: int = 10_000,
        conditional: bool = False,
        batch_size: int = 128,
        interpolation_method: str = "lerp",
        epsilon: float = 1e-4,
        resize: Optional[int] = 64,
        lower_discard: Optional[float] = 0.01,
        upper_discard: Optional[float] = 0.99,
        sim_net: Optional[Callable] = None,
        seed: int = 0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _ppl_validate_args(
            num_samples, conditional, batch_size, interpolation_method, epsilon, resize, lower_discard, upper_discard
        )
        # resolve once at construction (the reference builds its _LPIPS in
        # __init__ too): weights load a single time, and a misconfigured
        # offline environment fails here, not at compute()
        self._sim = _resolve_sim_net(sim_net, resize)
        self.num_samples = num_samples
        self.conditional = conditional
        self.batch_size = batch_size
        self.interpolation_method = interpolation_method
        self.epsilon = epsilon
        self.resize = resize
        self.lower_discard = lower_discard
        self.upper_discard = upper_discard
        self.seed = seed
        self.generator: Optional[Any] = None

    def update(self, generator: Any) -> None:
        """Store the generator model (validated against the reference's contract)."""
        _validate_ppl_generator(generator, self.conditional)
        self.generator = generator

    def compute(self) -> tuple:
        """Sample latent pairs through the stored generator and compute PPL."""
        if self.generator is None:
            raise RuntimeError("`update(generator)` must be called before `compute()`.")
        return perceptual_path_length(
            generator=self.generator,
            num_samples=self.num_samples,
            conditional=self.conditional,
            batch_size=self.batch_size,
            interpolation_method=self.interpolation_method,
            epsilon=self.epsilon,
            resize=self.resize,
            lower_discard=self.lower_discard,
            upper_discard=self.upper_discard,
            sim_net=self._sim,
            seed=self.seed,
        )
