"""Modular image metrics.

Parity with reference ``torchmetrics/image/``: ``psnr.py`` (sum states or min/max
data-range tracking), ``ssim.py``/``ms_ssim`` (per-image similarity list or sum
states), ``uqi.py``, ``sam.py``, ``ergas.py``, ``rase.py``, ``rmse_sw.py``,
``tv.py``, ``scc.py``, ``psnrb.py``, ``vif.py``, ``d_lambda.py``, ``d_s.py``,
``qnr.py``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.metrics import (
    error_relative_global_dimensionless_synthesis,
    peak_signal_noise_ratio_with_blocked_effect,
    quality_with_no_reference,
    relative_average_spectral_error,
    root_mean_squared_error_using_sliding_window,
    spatial_correlation_coefficient,
    spatial_distortion_index,
    spectral_angle_mapper,
    spectral_distortion_index,
    total_variation,
    universal_image_quality_index,
)
from metrics_tpu.functional.image.psnr import _psnr_compute, _psnr_update
from metrics_tpu.functional.image.ssim import (
    _multiscale_ssim_update,
    _ssim_check_inputs,
    _ssim_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.compute import count_dtype


class PeakSignalNoiseRatio(Metric):
    """Compute PSNR (reference ``image/psnr.py:29``).

    >>> import jax.numpy as jnp
    >>> psnr = PeakSignalNoiseRatio()
    >>> preds = jnp.array([[0.0, 1.0], [2.0, 3.0]])
    >>> target = jnp.array([[3.0, 2.0], [1.0, 0.0]])
    >>> psnr.update(preds, target)
    >>> psnr.compute()
    Array(2.552725, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        data_range: Optional[Union[float, Tuple[float, float]]] = None,
        base: float = 10.0,
        reduction: Optional[str] = "elementwise_mean",
        dim: Optional[Union[int, Tuple[int, ...]]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if dim is None and reduction != "elementwise_mean":
            from metrics_tpu.utils.prints import rank_zero_warn

            rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")
        self.base = base
        self.reduction = reduction
        self.dim = tuple(dim) if isinstance(dim, Sequence) else dim
        self.clamping_fn = None
        if dim is None:
            self.data_range_val = None
            self.add_state("sum_squared_error", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("total", jnp.zeros((), dtype=count_dtype()), dist_reduce_fx="sum")
        else:
            self.add_state("sum_squared_error", [], dist_reduce_fx="cat")
            self.add_state("total", [], dist_reduce_fx="cat")
        if data_range is None:
            if dim is not None:
                raise ValueError("The `data_range` must be given when `dim` is not None.")
            self.data_range = None
            self.add_state("min_target", jnp.asarray(jnp.inf), dist_reduce_fx="min")
            self.add_state("max_target", jnp.asarray(-jnp.inf), dist_reduce_fx="max")
        elif isinstance(data_range, tuple):
            self.clamping_fn = lambda x: jnp.clip(x, data_range[0], data_range[1])
            self.data_range = jnp.asarray(data_range[1] - data_range[0])
        else:
            self.data_range = jnp.asarray(float(data_range))

    def update(self, preds: Array, target: Array) -> None:
        """Update state with predictions and targets."""
        if self.clamping_fn is not None:
            preds = self.clamping_fn(preds)
            target = self.clamping_fn(target)
        sum_squared_error, num_obs = _psnr_update(preds, target, dim=self.dim)
        if self.dim is None:
            if self.data_range is None:
                self.min_target = jnp.minimum(jnp.min(target), self.min_target)
                self.max_target = jnp.maximum(jnp.max(target), self.max_target)
            self.sum_squared_error = self.sum_squared_error + sum_squared_error
            self.total = self.total + num_obs
        else:
            self.sum_squared_error.append(jnp.atleast_1d(sum_squared_error))
            self.total.append(jnp.broadcast_to(jnp.atleast_1d(num_obs), jnp.atleast_1d(sum_squared_error).shape))

    def compute(self) -> Array:
        """Compute metric."""
        data_range = self.data_range if self.data_range is not None else self.max_target - self.min_target
        if self.dim is None:
            return _psnr_compute(self.sum_squared_error, self.total, data_range, self.base, self.reduction)
        return _psnr_compute(
            dim_zero_cat(self.sum_squared_error), dim_zero_cat(self.total), data_range, self.base, self.reduction
        )


class StructuralSimilarityIndexMeasure(Metric):
    """Compute SSIM (reference ``image/ssim.py:30``).

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(42)
    >>> preds = jnp.asarray(rng.rand(3, 3, 32, 32).astype(np.float32))
    >>> target = jnp.asarray(np.asarray(preds) * 0.75)
    >>> ssim = StructuralSimilarityIndexMeasure(data_range=1.0)
    >>> ssim.update(preds, target)
    >>> round(float(ssim.compute()), 4)
    0.9219
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        gaussian_kernel: bool = True,
        sigma: Union[float, Sequence[float]] = 1.5,
        kernel_size: Union[int, Sequence[int]] = 11,
        reduction: Optional[str] = "elementwise_mean",
        data_range: Optional[Union[float, Tuple[float, float]]] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        return_full_image: bool = False,
        return_contrast_sensitivity: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_reduction = ("elementwise_mean", "sum", "none", None)
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        if reduction in ("elementwise_mean", "sum"):
            self.add_state("similarity", jnp.zeros(()), dist_reduce_fx="sum")
        else:
            self.add_state("similarity", [], dist_reduce_fx="cat")
        self.add_state("total", jnp.zeros((), dtype=count_dtype()), dist_reduce_fx="sum")
        if return_full_image or return_contrast_sensitivity:
            self.add_state("image_return", [], dist_reduce_fx="cat")
        self.gaussian_kernel = gaussian_kernel
        self.sigma = sigma
        self.kernel_size = kernel_size
        self.reduction = reduction
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.return_full_image = return_full_image
        self.return_contrast_sensitivity = return_contrast_sensitivity

    def update(self, preds: Array, target: Array) -> None:
        """Update state with predictions and targets."""
        preds, target = _ssim_check_inputs(preds, target)
        out = _ssim_update(
            preds, target, self.gaussian_kernel, self.sigma, self.kernel_size, self.data_range,
            self.k1, self.k2, self.return_full_image, self.return_contrast_sensitivity,
        )
        if isinstance(out, tuple):
            similarity, image = out
            self.image_return.append(image)
        else:
            similarity = out
        if self.reduction in ("elementwise_mean", "sum"):
            self.similarity = self.similarity + similarity.sum()
        else:
            self.similarity.append(similarity)
        self.total = self.total + preds.shape[0]

    def compute(self):
        """Compute metric."""
        if self.reduction == "elementwise_mean":
            similarity = self.similarity / self.total
        elif self.reduction == "sum":
            similarity = self.similarity
        else:
            similarity = dim_zero_cat(self.similarity)
        if self.return_full_image or self.return_contrast_sensitivity:
            return similarity, dim_zero_cat(self.image_return)
        return similarity


class MultiScaleStructuralSimilarityIndexMeasure(Metric):
    """Compute MS-SSIM (reference ``image/ms_ssim`` in ``image/ssim.py:190``).

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(42)
    >>> preds = jnp.asarray(rng.rand(3, 3, 180, 180).astype(np.float32))
    >>> target = jnp.asarray(np.asarray(preds) * 0.75)
    >>> ms_ssim = MultiScaleStructuralSimilarityIndexMeasure(data_range=1.0)
    >>> ms_ssim.update(preds, target)
    >>> round(float(ms_ssim.compute()), 4)
    0.963
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        gaussian_kernel: bool = True,
        kernel_size: Union[int, Sequence[int]] = 11,
        sigma: Union[float, Sequence[float]] = 1.5,
        reduction: Optional[str] = "elementwise_mean",
        data_range: Optional[Union[float, Tuple[float, float]]] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
        normalize: Optional[str] = "relu",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_reduction = ("elementwise_mean", "sum", "none", None)
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        if reduction in ("elementwise_mean", "sum"):
            self.add_state("similarity", jnp.zeros(()), dist_reduce_fx="sum")
        else:
            self.add_state("similarity", [], dist_reduce_fx="cat")
        self.add_state("total", jnp.zeros((), dtype=count_dtype()), dist_reduce_fx="sum")
        if not isinstance(betas, tuple) or not all(isinstance(b, float) for b in betas):
            raise ValueError("Argument `betas` is expected to be of a type tuple of floats.")
        if normalize not in ("relu", "simple", None):
            raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")
        self.gaussian_kernel = gaussian_kernel
        self.kernel_size = kernel_size
        self.sigma = sigma
        self.reduction = reduction
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.betas = betas
        self.normalize = normalize

    def update(self, preds: Array, target: Array) -> None:
        """Update state with predictions and targets."""
        preds, target = _ssim_check_inputs(preds, target)
        similarity = _multiscale_ssim_update(
            preds, target, self.gaussian_kernel, self.sigma, self.kernel_size, self.data_range,
            self.k1, self.k2, self.betas, self.normalize,
        )
        if self.reduction in ("elementwise_mean", "sum"):
            self.similarity = self.similarity + similarity.sum()
        else:
            self.similarity.append(similarity)
        self.total = self.total + preds.shape[0]

    def compute(self) -> Array:
        """Compute metric."""
        if self.reduction == "elementwise_mean":
            return self.similarity / self.total
        if self.reduction == "sum":
            return self.similarity
        return dim_zero_cat(self.similarity)


class _SampleStoreImageMetric(Metric):
    """Shared plumbing for image metrics that concatenate per-batch inputs."""

    is_differentiable = True
    full_state_update = False
    preds: list
    target: list

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Update state with predictions and targets."""
        self.preds.append(preds)
        self.target.append(target)


class UniversalImageQualityIndex(_SampleStoreImageMetric):
    """Compute UQI (reference ``image/uqi.py:27``)."""

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, kernel_size: Sequence[int] = (11, 11), sigma: Sequence[float] = (1.5, 1.5),
                 reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.kernel_size = kernel_size
        self.sigma = sigma
        self.reduction = reduction

    def compute(self) -> Array:
        """Compute metric."""
        return universal_image_quality_index(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.kernel_size, self.sigma, self.reduction
        )


class SpectralAngleMapper(_SampleStoreImageMetric):
    """Compute SAM (reference ``image/sam.py:27``)."""

    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(self, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.reduction = reduction

    def compute(self) -> Array:
        """Compute metric."""
        return spectral_angle_mapper(dim_zero_cat(self.preds), dim_zero_cat(self.target), self.reduction)


class ErrorRelativeGlobalDimensionlessSynthesis(_SampleStoreImageMetric):
    """Compute ERGAS (reference ``image/ergas.py:27``)."""

    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(self, ratio: float = 4, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.ratio = ratio
        self.reduction = reduction

    def compute(self) -> Array:
        """Compute metric."""
        return error_relative_global_dimensionless_synthesis(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.ratio, self.reduction
        )


class RelativeAverageSpectralError(_SampleStoreImageMetric):
    """Compute RASE (reference ``image/rase.py:26``)."""

    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(self, window_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(window_size, int) or window_size < 1:
            raise ValueError(f"Argument `window_size` is expected to be a positive integer, but got {window_size}")
        self.window_size = window_size

    def compute(self) -> Array:
        """Compute metric."""
        return relative_average_spectral_error(dim_zero_cat(self.preds), dim_zero_cat(self.target), self.window_size)


class RootMeanSquaredErrorUsingSlidingWindow(_SampleStoreImageMetric):
    """Compute sliding-window RMSE (reference ``image/rmse_sw.py:26``)."""

    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(self, window_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(window_size, int) or window_size < 1:
            raise ValueError(f"Argument `window_size` is expected to be a positive integer, but got {window_size}")
        self.window_size = window_size

    def compute(self) -> Array:
        """Compute metric."""
        return root_mean_squared_error_using_sliding_window(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.window_size
        )


class TotalVariation(Metric):
    """Compute total variation (reference ``image/tv.py:26``).

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(42)
    >>> tv = TotalVariation()
    >>> tv.update(jnp.asarray(rng.rand(2, 3, 16, 16).astype(np.float32)))
    >>> float(tv.compute()) > 0
    True
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, reduction: Optional[str] = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if reduction is not None and reduction not in ("sum", "mean", "none"):
            raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")
        self.reduction = reduction
        if reduction in ("sum", "mean"):
            self.add_state("score", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("num_elements", jnp.zeros((), dtype=count_dtype()), dist_reduce_fx="sum")
        else:
            self.add_state("score_list", [], dist_reduce_fx="cat")

    def update(self, img: Array) -> None:
        """Update state with an image batch."""
        score = total_variation(img, reduction="none")
        if self.reduction in ("sum", "mean"):
            self.score = self.score + score.sum()
            self.num_elements = self.num_elements + img.shape[0]
        else:
            self.score_list.append(score)

    def compute(self) -> Array:
        """Compute metric."""
        if self.reduction == "sum":
            return self.score
        if self.reduction == "mean":
            return self.score / self.num_elements
        return dim_zero_cat(self.score_list)


class SpatialCorrelationCoefficient(_SampleStoreImageMetric):
    """Compute SCC (reference ``image/scc.py:25``)."""

    higher_is_better = True
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(self, hp_filter: Optional[Array] = None, window_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.hp_filter = hp_filter
        self.window_size = window_size

    def compute(self) -> Array:
        """Compute metric."""
        return spatial_correlation_coefficient(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.hp_filter, self.window_size
        )


class PeakSignalNoiseRatioWithBlockedEffect(_SampleStoreImageMetric):
    """Compute PSNR-B (reference ``image/psnrb.py:26``)."""

    higher_is_better = True
    plot_lower_bound = 0.0

    def __init__(self, block_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(block_size, int) or block_size < 1:
            raise ValueError("Argument `block_size` should be a positive integer")
        self.block_size = block_size

    def compute(self) -> Array:
        """Compute metric."""
        return peak_signal_noise_ratio_with_blocked_effect(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.block_size
        )


class VisualInformationFidelity(_SampleStoreImageMetric):
    """Compute VIF-p (reference ``image/vif.py:25``)."""

    higher_is_better = True
    plot_lower_bound = 0.0

    def __init__(self, sigma_n_sq: float = 2.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(sigma_n_sq, (float, int)) or sigma_n_sq < 0:
            raise ValueError(f"Argument `sigma_n_sq` is expected to be a positive float or int, but got {sigma_n_sq}")
        self.sigma_n_sq = float(sigma_n_sq)

    def compute(self) -> Array:
        """Compute metric."""
        from metrics_tpu.functional.image.metrics import visual_information_fidelity

        return visual_information_fidelity(dim_zero_cat(self.preds), dim_zero_cat(self.target), self.sigma_n_sq)


class SpectralDistortionIndex(_SampleStoreImageMetric):
    """Compute D_λ (reference ``image/d_lambda.py:26``)."""

    higher_is_better = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, p: int = 1, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(p, int) or p <= 0:
            raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
        self.p = p
        self.reduction = reduction

    def compute(self) -> Array:
        """Compute metric."""
        return spectral_distortion_index(dim_zero_cat(self.preds), dim_zero_cat(self.target), self.p, self.reduction)


class SpatialDistortionIndex(Metric):
    """Compute D_s (reference ``image/d_s.py:28``)."""

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, norm_order: int = 1, window_size: int = 7, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.norm_order = norm_order
        self.window_size = window_size
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("ms", [], dist_reduce_fx="cat")
        self.add_state("pan", [], dist_reduce_fx="cat")
        self.add_state("pan_lr", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Dict[str, Array]) -> None:
        """Update state with fused prediction + {ms, pan[, pan_lr]} target dict."""
        if not isinstance(target, dict) or "ms" not in target or "pan" not in target:
            raise ValueError("Expected `target` to be a dict with keys ('ms', 'pan').")
        self.preds.append(preds)
        self.ms.append(target["ms"])
        self.pan.append(target["pan"])
        if "pan_lr" in target:
            self.pan_lr.append(target["pan_lr"])

    def _target_dict(self) -> Dict[str, Array]:
        target = {"ms": dim_zero_cat(self.ms), "pan": dim_zero_cat(self.pan)}
        if self.pan_lr:
            target["pan_lr"] = dim_zero_cat(self.pan_lr)
        return target

    def compute(self) -> Array:
        """Compute metric."""
        return spatial_distortion_index(
            dim_zero_cat(self.preds), self._target_dict(), norm_order=self.norm_order, window_size=self.window_size
        )


class QualityWithNoReference(SpatialDistortionIndex):
    """Compute QNR (reference ``image/qnr.py:28``)."""

    higher_is_better = True

    def __init__(self, alpha: float = 1.0, beta: float = 1.0, norm_order: int = 1, window_size: int = 7,
                 **kwargs: Any) -> None:
        super().__init__(norm_order, window_size, **kwargs)
        self.alpha = alpha
        self.beta = beta

    def compute(self) -> Array:
        """Compute metric."""
        return quality_with_no_reference(
            dim_zero_cat(self.preds),
            self._target_dict(),
            alpha=self.alpha,
            beta=self.beta,
            norm_order=self.norm_order,
            window_size=self.window_size,
        )
