"""Aggregation metrics.

Parity with reference ``torchmetrics/aggregation.py`` (``BaseAggregator :31``,
``MaxMetric :114``, ``MinMetric :219``, ``SumMetric :324``, ``CatMetric :429``,
``MeanMetric :493``; Running variants are re-exported from ``wrappers/running``).

TPU notes: NaN handling is branch-free under jit for the ``ignore``/replace
strategies (``jnp.where`` with the reduction's identity element); the ``error``/
``warn`` strategies need a host-visible value check and therefore run the update
eagerly (still pure jnp ops, just not one fused executable).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Union

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.utils.compute import neumaier_add, neumaier_value
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn

__all__ = ["BaseAggregator", "CatMetric", "MaxMetric", "MeanMetric", "MinMetric", "RunningMean", "RunningSum", "SumMetric"]


class BaseAggregator(Metric):
    """Base class for aggregation metrics (reference ``aggregation.py:31-111``).

    Args:
        fn: reduction applied at update ("sum", "max", "min", "cat" or callable)
        default_value: default state value
        nan_strategy: "error", "warn", "ignore", "disable" or a float replacement value
    """

    is_differentiable = None
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        fn: Union[Callable, str],
        default_value: Union[Array, List],
        nan_strategy: Union[str, float] = "error",
        state_name: str = "value",
        **kwargs: Any,
    ) -> None:
        # builtin string reductions carry known algebra; a custom callable must
        # declare its own merge_associative (DL001)
        merge_associative = kwargs.pop("merge_associative", None)
        if merge_associative is None and isinstance(fn, str):
            merge_associative = fn in ("sum", "mean", "min", "max")
        super().__init__(**kwargs)
        allowed_nan_strategy = ("error", "warn", "ignore", "disable")
        if nan_strategy not in allowed_nan_strategy and not isinstance(nan_strategy, float):
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed_nan_strategy} but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        if nan_strategy in ("error", "warn"):
            self._jit_update_opt = False  # value inspection needs the host
        self.state_name = state_name
        self.add_state(state_name, default=default_value, dist_reduce_fx=fn, merge_associative=merge_associative)

    @property
    def value(self) -> Any:
        return self._state[self.state_name]

    @value.setter
    def value(self, new_value: Any) -> None:
        self._state[self.state_name] = new_value

    def _cast_and_nan_check_input(self, x: Union[float, Array], weight: Optional[Union[float, Array]] = None):
        """Convert input ``x`` to a float array and apply the NaN strategy (reference ``aggregation.py:63-103``).

        Returns ``(x, weight, keep_mask)`` — under the ``ignore``/replace strategies the
        mask marks elements to drop, applied branch-free by the caller.
        """
        x = jnp.asarray(x, dtype=self._dtype)
        weight = jnp.asarray(1.0 if weight is None else weight, dtype=self._dtype)
        weight_was_scalar = weight.ndim == 0 or weight.size == 1
        weight = jnp.broadcast_to(weight, x.shape)
        # drop/replace where EITHER the value or its weight is NaN
        # (reference ``aggregation.py:84-102``)
        nan_mask = jnp.isnan(x) | jnp.isnan(weight)
        if self.nan_strategy in ("error", "warn"):
            from metrics_tpu.utils.checks import _is_traced

            if _is_traced(x):
                # inside jit: a host-visible value check is impossible. "warn" degrades to
                # a trace-time notice + branch-free drop; "error" must fail loudly at trace
                # time since raising on data is unrepresentable in XLA.
                if self.nan_strategy == "error":
                    raise RuntimeError(
                        "nan_strategy='error' requires a host-side value check and cannot run "
                        "inside jit. Use 'warn', 'ignore', 'disable' or a float replacement."
                    )
                rank_zero_warn(
                    "nan_strategy='warn' inside jit cannot inspect values; NaNs are dropped "
                    "branch-free without a runtime warning.",
                    UserWarning,
                )
                return x, weight, ~nan_mask
            if bool(jnp.any(nan_mask)):
                if self.nan_strategy == "error":
                    raise RuntimeError("Encountered `nan` values in tensor")
                rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)
                return x, weight, ~nan_mask
            return x, weight, jnp.ones_like(nan_mask, dtype=bool) | True
        if self.nan_strategy == "ignore":
            return x, weight, ~nan_mask
        if self.nan_strategy == "disable":
            return x, weight, jnp.ones_like(nan_mask) | True
        # float replacement (reference ``aggregation.py:101-102``): values are
        # replaced; a per-element weight tensor gets the replacement at the same
        # positions (matches the reference's contiguous-tensor path exactly). A
        # SCALAR weight is replaced only if it is itself NaN (then the reference's
        # stride-0 view write poisons every cell — same result). A finite scalar
        # weight stays untouched — here we deliberately diverge from the
        # reference, whose view-write quirk makes a NaN-containing batch's
        # weights all equal the replacement: stream-dependent means for nonzero
        # strategies and 0/0 = NaN for strategy 0.0. Divergence pinned in
        # tests/parity/test_parity_wrappers.py::test_aggregation_nan_float_documented_divergence.
        repl = jnp.asarray(self.nan_strategy, dtype=x.dtype)
        if weight_was_scalar:
            new_weight = jnp.where(jnp.isnan(weight), repl, weight)
        else:
            new_weight = jnp.where(nan_mask, repl, weight)
        return jnp.where(nan_mask, repl, x), new_weight, jnp.ones_like(nan_mask) | True

    def update(self, value: Union[float, Array]) -> None:  # noqa: D102
        raise NotImplementedError

    def compute(self) -> Array:
        """Aggregated value."""
        return self.value


class MaxMetric(BaseAggregator):
    """Aggregate a stream of values into their maximum (reference ``aggregation.py:114``).

    >>> from metrics_tpu.aggregation import MaxMetric
    >>> metric = MaxMetric()
    >>> metric.update(1.0)
    >>> metric.update(3.0)
    >>> float(metric.compute())
    3.0
    """

    full_state_update = True
    plot_lower_bound = None

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max", jnp.asarray(-jnp.inf), nan_strategy, state_name="max_value", **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _, keep = self._cast_and_nan_check_input(value)
        masked = jnp.where(keep, value, -jnp.inf)
        self.max_value = jnp.maximum(self.max_value, jnp.max(masked) if masked.size else self.max_value)


class MinMetric(BaseAggregator):
    """Aggregate a stream of values into their minimum (reference ``aggregation.py:219``)."""

    full_state_update = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min", jnp.asarray(jnp.inf), nan_strategy, state_name="min_value", **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _, keep = self._cast_and_nan_check_input(value)
        masked = jnp.where(keep, value, jnp.inf)
        self.min_value = jnp.minimum(self.min_value, jnp.min(masked) if masked.size else self.min_value)


class SumMetric(BaseAggregator):
    """Aggregate a stream of values into their sum (reference ``aggregation.py:324``).

    >>> from metrics_tpu.aggregation import SumMetric
    >>> metric = SumMetric()
    >>> metric.update(1.0)
    >>> metric.update(2.0)
    >>> float(metric.compute())
    3.0

    ``compensated=True`` opts into Neumaier (improved-Kahan) accumulation: the
    running sum carries a ``sum_value_comp`` residual state so the x32 error
    stays O(eps) instead of O(n*eps) on long adversarial streams (numlint
    NL004 / DESIGN §25). Both states merge by "sum", so cross-shard folds and
    fleet contracts are unchanged — the residuals add just like the totals.
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", compensated: bool = False, **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0), nan_strategy, state_name="sum_value", **kwargs)
        self.compensated = bool(compensated)
        if self.compensated:
            self._precision["sum_value"] = "compensated"
            self.add_state("sum_value_comp", default=jnp.asarray(0.0), dist_reduce_fx="sum", precision="compensated")

    def update(self, value: Union[float, Array]) -> None:
        value, _, keep = self._cast_and_nan_check_input(value)
        batch = jnp.sum(jnp.where(keep, value, 0.0))
        if self.compensated:
            self.sum_value, self.sum_value_comp = neumaier_add(self.sum_value, self.sum_value_comp, batch)
        else:
            self.sum_value = self.sum_value + batch

    def compute(self) -> Array:
        """Aggregated value; folds the Neumaier residual back in when compensated."""
        if self.compensated:
            return neumaier_value(self.sum_value, self.sum_value_comp)
        return super().compute()


class CatMetric(BaseAggregator):
    """Concatenate a stream of values (reference ``aggregation.py:429``)."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("cat", [], nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _, keep = self._cast_and_nan_check_input(value)
        import numpy as np

        kept = value.reshape(-1)[np.asarray(keep).reshape(-1)]  # list state → host-side filter OK
        if kept.size:
            self.value.append(kept)

    def compute(self) -> Array:
        if isinstance(self.value, list) and self.value:
            return dim_zero_cat(self.value)
        return self.value if not isinstance(self.value, list) else jnp.zeros(0, dtype=self._dtype)


class MeanMetric(BaseAggregator):
    """Aggregate a stream of values into their (weighted) mean (reference ``aggregation.py:493``).

    >>> from metrics_tpu.aggregation import MeanMetric
    >>> metric = MeanMetric()
    >>> metric.update(1.0)
    >>> metric.update(3.0)
    >>> float(metric.compute())
    2.0

    ``compensated=True`` opts into Neumaier accumulation of the weighted-value
    sum (``mean_value_comp`` residual state; see :class:`SumMetric`). The
    weight sum stays plain — it grows by O(1) per update and is not the term
    that cancels.
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", compensated: bool = False, **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0), nan_strategy, state_name="mean_value", **kwargs)
        self.add_state("weight", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.compensated = bool(compensated)
        if self.compensated:
            self._precision["mean_value"] = "compensated"
            self.add_state("mean_value_comp", default=jnp.asarray(0.0), dist_reduce_fx="sum", precision="compensated")

    def update(self, value: Union[float, Array], weight: Union[float, Array] = 1.0) -> None:
        """Update state with data; ``weight`` is broadcast to ``value``'s shape."""
        value, weight, keep = self._cast_and_nan_check_input(value, weight)
        batch = jnp.sum(jnp.where(keep, value * weight, 0.0))
        if self.compensated:
            self.mean_value, self.mean_value_comp = neumaier_add(self.mean_value, self.mean_value_comp, batch)
        else:
            self.mean_value = self.mean_value + batch
        self.weight = self.weight + jnp.sum(jnp.where(keep, weight, 0.0))

    def compute(self) -> Array:
        from metrics_tpu.utils.compute import _safe_divide

        value = neumaier_value(self.mean_value, self.mean_value_comp) if self.compensated else self.mean_value
        return _safe_divide(value, self.weight)


from metrics_tpu.wrappers.running import Running  # noqa: E402  (bottom import avoids a cycle at package init)


class RunningMean(Running):
    """Mean over a running window of updates (reference ``aggregation.py:616``).

    >>> from metrics_tpu.aggregation import RunningMean
    >>> metric = RunningMean(window=2)
    >>> for i in range(5):
    ...     metric.update(float(i))
    >>> float(metric.compute())  # mean of [3, 4]
    3.5
    """

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(MeanMetric(nan_strategy=nan_strategy, **kwargs), window=window)


class RunningSum(Running):
    """Sum over a running window of updates (reference ``aggregation.py:673``).

    >>> from metrics_tpu.aggregation import RunningSum
    >>> metric = RunningSum(window=2)
    >>> for i in range(5):
    ...     metric.update(float(i))
    >>> float(metric.compute())  # 3 + 4
    7.0
    """

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(SumMetric(nan_strategy=nan_strategy, **kwargs), window=window)
