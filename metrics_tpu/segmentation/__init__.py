"""Modular segmentation metrics (reference ``torchmetrics/segmentation/__init__.py``)."""

from metrics_tpu.segmentation.metrics import (
    DiceScore,
    GeneralizedDiceScore,
    HausdorffDistance,
    MeanIoU,
)

__all__ = ["DiceScore", "GeneralizedDiceScore", "HausdorffDistance", "MeanIoU"]
