"""Modular segmentation metrics (reference ``torchmetrics/segmentation/`` — per-class sums, SURVEY §2.8)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.segmentation.metrics import (
    _dice_score_compute,
    _dice_update,
    _format_inputs,
    generalized_dice_score,
    hausdorff_distance,
    mean_iou,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.compute import count_dtype


class DiceScore(Metric):
    """Compute the Dice score for semantic segmentation (reference ``segmentation/dice.py:33``).

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(0)
    >>> metric = DiceScore(num_classes=3)
    >>> metric.update(jnp.asarray(rng.randint(0, 2, (4, 3, 16, 16))), jnp.asarray(rng.randint(0, 2, (4, 3, 16, 16))))
    >>> round(float(metric.compute()), 3)
    0.494
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: int,
        include_background: bool = True,
        average: Optional[str] = "micro",
        input_format: str = "one-hot",
        aggregation_level: str = "samplewise",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if average not in ("micro", "macro", "weighted", "none", None):
            raise ValueError(
                f"Expected argument `average` to be one of ('micro','macro','weighted','none'), got {average}"
            )
        if input_format not in ("one-hot", "index"):
            raise ValueError(f"Expected argument `input_format` to be one of 'one-hot', 'index', got {input_format}")
        if aggregation_level not in ("samplewise", "global"):
            raise ValueError(
                f"Expected argument `aggregation_level` to be one of 'samplewise', 'global', got {aggregation_level}"
            )
        self.num_classes = num_classes
        self.include_background = include_background
        self.average = average
        self.input_format = input_format
        self.aggregation_level = aggregation_level
        self.add_state("numerator", [], dist_reduce_fx="cat")
        self.add_state("denominator", [], dist_reduce_fx="cat")
        self.add_state("support", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Update state with per-sample per-class sums."""
        preds, target = _format_inputs(preds, target, self.num_classes, self.input_format, self.include_background)
        numerator, denominator, support, _ = _dice_update(preds, target)
        self.numerator.append(numerator)
        self.denominator.append(denominator)
        self.support.append(support)

    def compute(self) -> Array:
        """Sample-mean of per-sample Dice (reference ``segmentation/dice.py:136-143``)."""
        numerator = dim_zero_cat(self.numerator)
        denominator = dim_zero_cat(self.denominator)
        support = dim_zero_cat(self.support)
        if self.aggregation_level == "global":
            numerator = numerator.sum(axis=0, keepdims=True)
            denominator = denominator.sum(axis=0, keepdims=True)
            support = support.sum(axis=0, keepdims=True)
        return _dice_score_compute(
            numerator, denominator, self.average, support=support if self.average == "weighted" else None
        ).mean(0)


class GeneralizedDiceScore(Metric):
    """Compute the Generalized Dice score (reference ``segmentation/generalized_dice.py:33``)."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: int,
        include_background: bool = True,
        per_class: bool = False,
        weight_type: str = "square",
        input_format: str = "one-hot",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.include_background = include_background
        self.per_class = per_class
        self.weight_type = weight_type
        self.input_format = input_format
        self.add_state("score", jnp.zeros(num_classes - (0 if include_background else 1)) if per_class
                       else jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("samples", jnp.zeros((), dtype=count_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Update state."""
        score = generalized_dice_score(
            preds, target, self.num_classes, self.include_background, self.per_class,
            self.weight_type, self.input_format,
        )
        n = preds.shape[0]
        self.score = self.score + score.sum(0)
        self.samples = self.samples + n

    def compute(self) -> Array:
        """Compute metric."""
        return self.score / self.samples


class MeanIoU(Metric):
    """Compute mean intersection over union (reference ``segmentation/mean_iou.py:30``).

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(0)
    >>> metric = MeanIoU(num_classes=3, input_format="index")
    >>> metric.update(jnp.asarray(rng.randint(0, 3, (4, 16, 16))), jnp.asarray(rng.randint(0, 3, (4, 16, 16))))
    >>> round(float(metric.compute()), 3)
    0.198
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: int,
        include_background: bool = True,
        per_class: bool = False,
        input_format: str = "one-hot",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.include_background = include_background
        self.per_class = per_class
        self.input_format = input_format
        n_out = num_classes - (0 if include_background else 1)
        self.add_state("score", jnp.zeros(n_out) if per_class else jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("num_batches", jnp.zeros((), dtype=count_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate batch-mean IoU (reference ``segmentation/mean_iou.py:117-124``)."""
        score = mean_iou(
            preds, target, self.num_classes, self.include_background, self.per_class, self.input_format
        )
        self.score = self.score + (score.mean(0) if self.per_class else score.mean())
        self.num_batches = self.num_batches + 1

    def compute(self) -> Array:
        """Compute metric."""
        return self.score / self.num_batches


class HausdorffDistance(Metric):
    """Compute the Hausdorff distance between segmentation masks (reference ``segmentation/hausdorff_distance.py:31``)."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        num_classes: int,
        include_background: bool = False,
        distance_metric: str = "euclidean",
        spacing: Optional[Tuple[float, ...]] = None,
        directed: bool = False,
        input_format: str = "one-hot",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.include_background = include_background
        self.distance_metric = distance_metric
        self.spacing = spacing
        self.directed = directed
        self.input_format = input_format
        self.add_state("score", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=count_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Update state."""
        score = hausdorff_distance(
            preds, target, self.num_classes, self.include_background, self.distance_metric,
            self.spacing, self.directed, self.input_format,
        )
        # mean over every (sample, class) cell (reference ``hausdorff_distance.py:110-127``)
        self.score = self.score + score.sum()
        self.total = self.total + score.size

    def compute(self) -> Array:
        """Compute metric."""
        return self.score / self.total


HausdorffDistance.__jit_ineligible__ = True  # host-side point-set distances
