"""ProcrustesDisparity (reference ``torchmetrics/shape/procrustes.py:154 LoC`` — SVD alignment)."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.shape.procrustes import procrustes_disparity
from metrics_tpu.metric import Metric
from metrics_tpu.utils.compute import count_dtype


class ProcrustesDisparity(Metric):
    """Compute the Procrustes disparity between batches of point clouds (reference ``shape/procrustes.py:30``).

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(42)
    >>> metric = ProcrustesDisparity()
    >>> metric.update(jnp.asarray(rng.rand(10, 3).astype(np.float32)), jnp.asarray(rng.rand(10, 3).astype(np.float32)))
    >>> round(float(metric.compute()), 4)
    0.7251
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, reduction: str = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if reduction not in ("mean", "sum"):
            raise ValueError(f"Argument `reduction` must be one of `mean` or `sum`, but got {reduction}")
        self.reduction = reduction
        self.add_state("disparity", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=count_dtype()), dist_reduce_fx="sum")

    def update(self, point_cloud1: Array, point_cloud2: Array) -> None:
        """Update state with a batch (or a single pair) of point clouds."""
        if point_cloud1.ndim == 2:
            point_cloud1 = point_cloud1[None]
            point_cloud2 = point_cloud2[None]
        self.disparity = self.disparity + procrustes_disparity(point_cloud1, point_cloud2).sum()
        self.total = self.total + point_cloud1.shape[0]

    def compute(self) -> Array:
        """Compute metric."""
        if self.reduction == "mean":
            return self.disparity / self.total
        return self.disparity
