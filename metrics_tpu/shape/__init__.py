"""Modular shape metrics (reference ``torchmetrics/shape/__init__.py``)."""

from metrics_tpu.shape.procrustes import ProcrustesDisparity

__all__ = ["ProcrustesDisparity"]
