"""Pallas kernel: fused SSIM epilogue.

Computes the SSIM map from the five window-convolved statistics
(μ_p, μ_t, Σp², Σt², Σpt) in one VMEM-resident pass — the elementwise tail of
``functional/image/ssim.py``. On TPU the kernel tiles the trailing dims to the
(8, 128) vreg layout; everywhere else (and in tests) it runs via the Pallas
interpreter, which lowers to the same jnp ops XLA would fuse anyway.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array

try:  # pallas is part of jax.experimental on all shipped versions we target
    from jax.experimental import pallas as pl

    _PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    _PALLAS_AVAILABLE = False


def _ssim_epilogue_kernel(mu_p_ref, mu_t_ref, s_pp_ref, s_tt_ref, s_pt_ref, c1_ref, c2_ref, out_ref):
    mu_p = mu_p_ref[...]
    mu_t = mu_t_ref[...]
    c1 = c1_ref[0]
    c2 = c2_ref[0]
    mu_p_sq = mu_p * mu_p
    mu_t_sq = mu_t * mu_t
    mu_pt = mu_p * mu_t
    sigma_p = jnp.maximum(s_pp_ref[...] - mu_p_sq, 0.0)
    sigma_t = jnp.maximum(s_tt_ref[...] - mu_t_sq, 0.0)
    sigma_pt = s_pt_ref[...] - mu_pt
    upper = 2.0 * sigma_pt + c2
    lower = sigma_p + sigma_t + c2
    out_ref[...] = ((2.0 * mu_pt + c1) * upper) / ((mu_p_sq + mu_t_sq + c1) * lower)


def ssim_map_pallas(
    mu_p: Array, mu_t: Array, s_pp: Array, s_tt: Array, s_pt: Array, c1: float, c2: float,
    interpret: bool | None = None,
) -> Array:
    """Fused SSIM map from window statistics.

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(0)
    >>> stats = [jnp.asarray(rng.rand(2, 3, 16, 16).astype(np.float32)) for _ in range(5)]
    >>> out = ssim_map_pallas(*stats, c1=0.01, c2=0.03, interpret=True)
    >>> out.shape
    (2, 3, 16, 16)
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not _PALLAS_AVAILABLE:  # pragma: no cover - jnp fallback
        mu_p_sq, mu_t_sq, mu_pt = mu_p**2, mu_t**2, mu_p * mu_t
        upper = 2 * (s_pt - mu_pt) + c2
        lower = jnp.maximum(s_pp - mu_p_sq, 0) + jnp.maximum(s_tt - mu_t_sq, 0) + c2
        return ((2 * mu_pt + c1) * upper) / ((mu_p_sq + mu_t_sq + c1) * lower)

    orig_shape = mu_p.shape
    flat = lambda x: x.reshape(-1, orig_shape[-1])  # noqa: E731
    args = [flat(x) for x in (mu_p, mu_t, s_pp, s_tt, s_pt)]
    rows, cols = args[0].shape
    c1_arr = jnp.full((1,), c1, dtype=args[0].dtype)
    c2_arr = jnp.full((1,), c2, dtype=args[0].dtype)

    block_rows = min(256, rows)
    grid = ((rows + block_rows - 1) // block_rows,)
    out = pl.pallas_call(
        _ssim_epilogue_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((block_rows, cols), lambda i: (i, 0)) for _ in range(5)]
        + [pl.BlockSpec(memory_space=pl.ANY)] * 0
        + [pl.BlockSpec((1,), lambda i: (0,)), pl.BlockSpec((1,), lambda i: (0,))],
        out_specs=pl.BlockSpec((block_rows, cols), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, cols), args[0].dtype),
        interpret=interpret,
    )(*args, c1_arr, c2_arr)
    return out.reshape(orig_shape)
