"""Pallas TPU kernel for the binned curve-family update (VERDICT r3 #8).

The binned PRC/ROC/calibration update reduces (N, C) scores against T
thresholds into per-threshold tp/fp counts (reference
``precision_recall_curve.py:211-227``). The XLA path here
(:func:`metrics_tpu.functional.classification.precision_recall_curve._binned_confusion_tensor`)
is the O(N·C) bucket-histogram redesign — searchsorted + bincount + suffix
cumsum — which reads the scores twice (bucketize, then scatter) and pays TPU's
serialized scatter on the histogram.

This kernel fuses the whole reduction into ONE pass over the scores: each grid
step loads a (tile, C) block into VMEM, compares it against a T-chunk of
thresholds on the VPU, and accumulates ``tp[c, t] = Σ pos & (score >= thr_t)``
/ ``fp[c, t]`` directly into VMEM output accumulators that persist across the
sequential TPU grid. No (N, T) intermediate, no scatter, one HBM read of the
scores.

Selection is automatic (:func:`use_pallas_binned`): compiled Pallas on a real
TPU backend, the XLA histogram path elsewhere; override with
``METRICS_TPU_CURVE_KERNEL=pallas|xla`` (interpret mode is for tests).
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

__all__ = [
    "binned_counts_pallas",
    "binned_kernel_plan",
    "histogram_counts",
    "pallas_binned_fits",
    "use_pallas_binned",
]

_T_CHUNK = 128  # threshold-chunk width: one lane-aligned block of compares per step
_VMEM_ELEMS = 1 << 20  # budget for the (tile, C, T_CHUNK) compare block
_MAX_EXACT_N = 1 << 24  # f32 accumulators count exactly below 2^24 per cell
_MAX_ACC_ELEMS = 1 << 19  # (C, t_pad) ×2 f32 accumulators must sit in VMEM


def pallas_binned_fits(n: int, num_c: int, len_t: int) -> bool:
    """Does the fused kernel's exactness/VMEM envelope cover this shape?

    Counts accumulate in f32 — exact only below 2^24 per cell, so huge updates
    fall back to the XLA histogram (whose scatter path is exact). The two
    (C, t_pad) accumulators plus the (tile, C, T_CHUNK) compare block must also
    fit VMEM with a non-degenerate tile.
    """
    t_pad = max(_T_CHUNK, ((len_t + _T_CHUNK - 1) // _T_CHUNK) * _T_CHUNK)
    return n < _MAX_EXACT_N and num_c * t_pad <= _MAX_ACC_ELEMS and _VMEM_ELEMS // (num_c * _T_CHUNK) >= 8


def _compiled_kernel_ok() -> bool:
    """Can the COMPILED TPU kernel legally run right now?

    False when the process backend is not TPU, or when a ``jax.default_device``
    pin (device object OR platform string — jax accepts both) routes execution
    off the accelerator. Unknown pin types fail CLOSED.
    """
    try:
        if jax.default_backend() != "tpu":
            return False
        pinned = jax.config.jax_default_device
        if pinned is None:
            return True
        platform = getattr(pinned, "platform", None)
        if platform is None:  # string pins like 'cpu'; anything unrecognized fails closed
            platform = str(pinned).lower()
        return platform == "tpu"
    except Exception:  # backend probe failed — stay on the XLA path
        return False


def binned_kernel_plan() -> Tuple[bool, bool]:
    """The single routing decision: ``(use_kernel, interpret)``.

    ``interpret`` is only ever True for a FORCED ``pallas`` choice somewhere the
    compiled kernel cannot run (tests, CPU rigs)."""
    choice = os.environ.get("METRICS_TPU_CURVE_KERNEL", "auto").lower()
    if choice == "pallas":
        return True, not _compiled_kernel_ok()
    if choice == "xla":
        return False, False
    return _compiled_kernel_ok(), False


def use_pallas_binned() -> bool:
    """Route the binned curve update through the Pallas kernel?"""
    return binned_kernel_plan()[0]


def histogram_counts(values: Array, valid: Array, edges: Array) -> Array:
    """Masked bucket counts over explicit edges with PINNED dtypes.

    Returns (len(edges)−1,) int32 counts of ``values`` falling in
    ``[edges[i], edges[i+1])`` (under-/overflow clamped into the end bins,
    NaNs and masked rows dropped). The compare runs in f32 and the
    accumulator is int32 *by construction*: under ``jax_enable_x64`` a
    freshly-built edge array (``jnp.linspace``) is f64, and letting it meet
    f32 values would silently upcast the bucketize compare — and any
    weighted accumulation keyed on it — to 64 bit, changing the histogram's
    dtype (and therefore the state aval, breaking donation/jit-cache reuse)
    between 32- and 64-bit runs. Every sketch-state histogram goes through
    here for exactly that reason.
    """
    from metrics_tpu.utils.data import bincount

    num_bins = edges.shape[0] - 1
    v = values.astype(jnp.float32).reshape(-1)
    ok = jnp.asarray(valid, bool).reshape(-1) & ~jnp.isnan(v)
    idx = jnp.clip(
        jnp.searchsorted(edges.astype(jnp.float32), v, side="right") - 1,
        0,
        num_bins - 1,
    ).astype(jnp.int32)
    # masked rows scatter into a discarded overflow bin — branch-free
    return bincount(jnp.where(ok, idx, num_bins), num_bins + 1)[:num_bins]


def _kernel(p_ref, pos_ref, neg_ref, thr_ref, tp_ref, fp_ref, ptot_ref, ntot_ref, *, t_pad: int):
    """One (tile, C) block: accumulate per-threshold tp/fp and the pos/neg totals."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        tp_ref[...] = jnp.zeros_like(tp_ref)
        fp_ref[...] = jnp.zeros_like(fp_ref)
        ptot_ref[...] = jnp.zeros_like(ptot_ref)
        ntot_ref[...] = jnp.zeros_like(ntot_ref)

    p = p_ref[...]  # (tile, C) scores
    pos = pos_ref[...]  # (tile, C) f32 {0, 1}: valid positives
    neg = neg_ref[...]  # (tile, C) f32 {0, 1}: valid negatives
    ptot_ref[...] += pos.sum(axis=0, keepdims=True)
    ntot_ref[...] += neg.sum(axis=0, keepdims=True)
    for c0 in range(0, t_pad, _T_CHUNK):
        thr = thr_ref[0, c0 : c0 + _T_CHUNK]  # (T_CHUNK,)
        ge = (p[:, :, None] >= thr[None, None, :]).astype(jnp.float32)  # (tile, C, T_CHUNK)
        tp_ref[:, c0 : c0 + _T_CHUNK] += jnp.einsum("nc,nct->ct", pos, ge)
        fp_ref[:, c0 : c0 + _T_CHUNK] += jnp.einsum("nc,nct->ct", neg, ge)


@functools.partial(jax.jit, static_argnames=("interpret",))
def binned_counts_pallas(
    preds: Array, target01: Array, valid: Array, thresholds: Array, interpret: bool = False
) -> Tuple[Array, Array, Array, Array]:
    """Fused per-threshold counts: ``(tp, fp, pos_tot, neg_tot)``.

    ``preds``/``target01``/``valid`` are (N, C); ``thresholds`` (T,) ascending.
    Returns tp/fp of shape (C, T) and totals of shape (C,), all int32 — the
    exact quantities :func:`_binned_confusion_tensor` derives its (T, C, 2, 2)
    tensor from.
    """
    n, num_c = preds.shape
    len_t = thresholds.shape[0]
    t_pad = max(_T_CHUNK, ((len_t + _T_CHUNK - 1) // _T_CHUNK) * _T_CHUNK)
    tile = max(8, min(4096, _VMEM_ELEMS // (num_c * _T_CHUNK)))
    n_pad = max(tile, ((n + tile - 1) // tile) * tile)

    p = preds.astype(jnp.float32)
    # NaN scores satisfy no threshold (comparison semantics); +inf thresholds pad
    # the chunk tail and are never satisfied by finite scores
    p = jnp.where(jnp.isnan(p), -jnp.inf, p)
    p = jnp.pad(p, ((0, n_pad - n), (0, 0)), constant_values=-jnp.inf)
    pos = jnp.pad((valid & (target01 == 1)).astype(jnp.float32), ((0, n_pad - n), (0, 0)))
    neg = jnp.pad((valid & (target01 == 0)).astype(jnp.float32), ((0, n_pad - n), (0, 0)))
    thr = jnp.pad(thresholds.astype(jnp.float32), (0, t_pad - len_t), constant_values=jnp.inf)[None, :]

    grid = (n_pad // tile,)
    tp, fp, ptot, ntot = pl.pallas_call(
        functools.partial(_kernel, t_pad=t_pad),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, num_c), lambda i: (i, 0)),
            pl.BlockSpec((tile, num_c), lambda i: (i, 0)),
            pl.BlockSpec((tile, num_c), lambda i: (i, 0)),
            pl.BlockSpec((1, t_pad), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((num_c, t_pad), lambda i: (0, 0)),
            pl.BlockSpec((num_c, t_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, num_c), lambda i: (0, 0)),
            pl.BlockSpec((1, num_c), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((num_c, t_pad), jnp.float32),
            jax.ShapeDtypeStruct((num_c, t_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, num_c), jnp.float32),
            jax.ShapeDtypeStruct((1, num_c), jnp.float32),
        ],
        interpret=interpret,
    )(p, pos, neg, thr)
    return (
        tp[:, :len_t].astype(jnp.int32),
        fp[:, :len_t].astype(jnp.int32),
        ptot[0].astype(jnp.int32),
        ntot[0].astype(jnp.int32),
    )
