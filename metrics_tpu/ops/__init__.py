"""Custom TPU kernels (Pallas).

XLA already fuses the overwhelming majority of this framework's compute (the
SURVEY §7 design keeps every hot path as fusable jnp/conv/scatter ops). This
package holds the hand-written kernels for the cases worth owning the schedule:
currently the SSIM epilogue (``ssim_map``), with the windowed-conv kernel planned
next (see ``/opt/skills/guides/pallas_guide.md``).
"""

from metrics_tpu.ops.ssim_epilogue import ssim_map_pallas

__all__ = ["ssim_map_pallas"]
