"""Custom TPU kernels (Pallas).

XLA already fuses the overwhelming majority of this framework's compute (the
SURVEY §7 design keeps every hot path as fusable jnp/conv/scatter ops). This
package holds the hand-written kernels for the cases worth owning the schedule:

* ``ssim_window`` — the SSIM separable gaussian-window pass (SURVEY P8): both
  1-D tap loops fused over a VMEM-resident plane; auto-selected on real TPU
  backends (``METRICS_TPU_SSIM_KERNEL`` overrides).

The SSIM elementwise tail deliberately stays as jnp ops in
``functional/image/ssim.py`` — XLA fuses it with the following mean-reduce,
which a standalone kernel would prevent.
"""

from metrics_tpu.ops.ssim_window import ssim_window_pallas, use_pallas_window

__all__ = ["ssim_window_pallas", "use_pallas_window"]
