"""Time-semantics kernels: exponential decay, pane rotation, CUSUM segment folds.

The L1 layer under ``metrics_tpu/windows/`` and ``metrics_tpu/drift/``
(DESIGN §20). Everything here is branch-free fixed-shape jnp — jit, vmap and
donation clean — and everything is expressed so the L2 metric states stay
*mergeable by declared algebra*:

* **decay** — exponential time-decay as a scalar rescale. A sum-algebra state
  observed at time ``last_t`` re-weighted to a later reference time ``ref``
  is ``state * 2^(-(ref - last_t)/half_life)``; the rescale distributes over
  ``+`` (and over ``max`` for positive registers), so two decayed states
  brought to a *common* reference time merge with their original algebra.
  This is the state-space-dual recurrence view of windowed aggregation
  (PAPERS: 2603.09555): O(1) per update, no buffer splice.
* **panes** — tumbling-pane bookkeeping for exact sliding windows: each pane
  is addressed by its absolute pane number ``floor(t / pane_s)``, stored in
  slot ``pane_id % n_panes``. Writes rotate; nothing is ever spliced.
* **cusum** — the associative (but order-sensitive) segment summary for
  CUSUM change detection: per side, ``(total, stat, prefix, watermark)``
  composes across stream segments exactly (Lin's max-plus segment algebra),
  so per-shard partials fold to the single-pass trajectory statistic.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array, lax

__all__ = [
    "cusum_compose",
    "cusum_segment",
    "decay_weights",
    "decayed_hll_estimate",
    "pane_id",
    "pane_slot_onehot",
]


def decay_weights(last_t: Array, t: Array, half_life_s: float) -> Tuple[Array, Array, Array]:
    """Common reference time and the two decay factors that bring a state pair to it.

    Returns ``(ref, w_old, w_new)`` with ``ref = max(last_t, t)``,
    ``w_old = 2^(-(ref - last_t)/half_life)`` applied to the accumulated state
    and ``w_new = 2^(-(ref - t)/half_life)`` applied to the incoming batch
    state. Branch-free: an in-order batch (``t >= last_t``) gets
    ``w_new = 1`` and decays the accumulator; an out-of-order batch decays
    *itself* by its own age instead, so the fold is order-invariant —
    the state is always ``Σ_i batch_i · 2^(-(ref - t_i)/half_life)``.

    Both exponents are ≥ 0 by construction, so the weights live in (0, 1] and
    underflow monotonically to 0.0 for ancient states — no NaN, no Inf.
    """
    t = jnp.asarray(t, jnp.float32)
    last_t = jnp.asarray(last_t, jnp.float32)
    ref = jnp.maximum(last_t, t)
    inv_hl = jnp.float32(1.0 / float(half_life_s))
    w_old = jnp.exp2(-(ref - last_t) * inv_hl)
    w_new = jnp.exp2(-(ref - t) * inv_hl)
    return ref, w_old, w_new


def pane_id(t: Array, pane_s: float) -> Array:
    """Absolute pane number of timestamp ``t``: ``floor(t / pane_s)``, () int32."""
    return jnp.floor(jnp.asarray(t, jnp.float32) / jnp.float32(pane_s)).astype(jnp.int32)  # numlint: disable=NL001 — pane_s > 0 validated at window construction


def pane_slot_onehot(cur_id: Array, n_panes: int) -> Array:
    """(n_panes,) bool mask selecting the rotating slot ``cur_id % n_panes``."""
    return jnp.arange(n_panes, dtype=jnp.int32) == jnp.mod(cur_id, n_panes)


def cusum_segment(y: Array, valid: Array) -> Array:
    """Fold one batch of deviations into a (4,) f32 CUSUM segment summary.

    For a segment with deviations ``y_1..y_n`` (invalid rows contribute 0,
    the identity of every component) the summary is

    * ``T`` — total ``Σ y_i``;
    * ``S`` — max suffix sum including the empty suffix: the CUSUM statistic
      ``s_i = max(0, s_{i-1} + y_i)`` after the segment, started from 0;
    * ``P`` — max prefix sum including the empty prefix;
    * ``M`` — the watermark ``max_i s_i``: the highest the statistic got
      anywhere inside the segment.

    All four come from one prefix-sum pass: with ``c_i = Σ_{j<=i} y_j`` and a
    virtual ``c_0 = 0``, ``S = c_n − min_i c_i``, ``P = max_i c_i`` and
    ``M = max_i (c_i − min_{j<=i} c_j)`` (running drawup via ``cummin``).
    """
    y = jnp.where(jnp.asarray(valid, bool).reshape(-1), jnp.asarray(y, jnp.float32).reshape(-1), 0.0)
    c = jnp.concatenate([jnp.zeros((1,), jnp.float32), jnp.cumsum(y)])
    total = c[-1]
    stat = total - jnp.min(c)
    prefix = jnp.max(c)
    watermark = jnp.max(c - lax.cummin(c))
    return jnp.stack([total, stat, prefix, watermark])


def cusum_compose(a: Array, b: Array) -> Array:
    """Compose two (…, 4) segment summaries, ``a`` strictly *before* ``b`` in stream order.

    The fold is associative but NOT commutative — a CUSUM trajectory is an
    order statistic — which is exactly the CAT_ORDER_SENSITIVE classification
    the merge harness records for :class:`metrics_tpu.drift.CUSUM`:

    * ``T = T_a + T_b``
    * ``S = max(S_b, S_a + T_b)``  (suffix inside b, or spanning a's suffix)
    * ``P = max(P_a, T_a + P_b)``
    * ``M = max(M_a, M_b, S_a + P_b)``  (peak in a, peak in b from 0, or
      a's carried statistic riding b's best prefix)
    """
    ta, sa, pa, ma = a[..., 0], a[..., 1], a[..., 2], a[..., 3]
    tb, sb, pb, mb = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack(
        [
            ta + tb,
            jnp.maximum(sb, sa + tb),
            jnp.maximum(pa, ta + pb),
            jnp.maximum(jnp.maximum(ma, mb), sa + pb),
        ],
        axis=-1,
    )


def decayed_hll_estimate(registers: Array, zero_rank: float = 0.5) -> Array:
    """HyperLogLog estimate over *fractional* (time-decayed) ranks; () f32.

    Identical to :func:`metrics_tpu.functional.sketches.hll.hll_estimate`
    except the linear-counting correction treats a register whose decayed rank
    fell below ``zero_rank`` as empty — a register that has lost more than
    half its original (≥ 1) rank is "mostly forgotten", and without this the
    estimate would floor at ``α·m`` instead of decaying toward 0.
    """
    m = registers.shape[0]
    alpha_m = {16: 0.673, 32: 0.697, 64: 0.709}.get(m, 0.7213 / (1.0 + 1.079 / m))
    regs = registers.astype(jnp.float32)
    raw = alpha_m * m * m / jnp.sum(jnp.exp2(-regs))
    zeros = jnp.sum(regs < zero_rank).astype(jnp.float32)
    linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    est = jnp.where((raw <= 2.5 * m) & (zeros > 0), linear, raw)
    two32 = 4294967296.0
    large = -two32 * jnp.log(jnp.maximum(1.0 - est / two32, 1e-12))
    return jnp.where(est > two32 / 30.0, large, est)
