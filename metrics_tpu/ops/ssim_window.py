"""Pallas TPU kernel for the SSIM gaussian-window pass (SURVEY P8, BASELINE config 4).

The SSIM hot loop is a separable windowed sum over the stacked
``(5·B·C, H+K-1, W+K-1)`` planes (pred/target/pred²/target²/pred·target share
one window). On TPU the XLA fallback is the shifted-slice stencil in
``functional/image/_helpers.py``; this kernel fuses both 1-D passes over a
plane held in VMEM, so each input element is read once from HBM and the
K_h + K_w multiply-adds run on the VPU without intermediate HBM round-trips.

Grid: one program per plane. The window taps are compile-time constants baked
into the unrolled tap loops (K ≤ ~33 for the SSIM kernels in practice).

Selection is automatic (:func:`use_pallas_window`): compiled Pallas on a real
TPU backend, interpret mode or the XLA stencil elsewhere; override with
``METRICS_TPU_SSIM_KERNEL=pallas|stencil``.
"""

from __future__ import annotations

import functools
import os
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

__all__ = ["ssim_window_pallas", "use_pallas_window"]


def use_pallas_window() -> bool:
    """Route SSIM's window pass through the Pallas kernel?"""
    choice = os.environ.get("METRICS_TPU_SSIM_KERNEL", "auto").lower()
    if choice == "pallas":
        return True
    if choice == "stencil":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # backend probe failed — stay on the XLA path
        return False


def _window_kernel(x_ref, o_ref, *, kh: Tuple[float, ...], kw: Tuple[float, ...], h: int, w: int):
    """One plane: vertical taps then horizontal taps, fully unrolled in VMEM."""
    x = x_ref[0]
    acc = None
    for i, tap in enumerate(kh):
        term = x[i : i + h, :] * tap
        acc = term if acc is None else acc + term
    out = None
    for j, tap in enumerate(kw):
        term = acc[:, j : j + w] * tap
        out = term if out is None else out + term
    o_ref[0] = out


@functools.partial(jax.jit, static_argnames=("kh", "kw", "interpret"))
def ssim_window_pallas(x: Array, kh: Tuple[float, ...], kw: Tuple[float, ...], interpret: bool = False) -> Array:
    """Separable VALID windowed sum over ``(N, H_pad, W_pad)`` planes → ``(N, H, W)``.

    ``kh``/``kw`` are static tap tuples (baked into the kernel); ``interpret``
    runs the Pallas interpreter (CPU testing).
    """
    n, h_pad, w_pad = x.shape
    h = h_pad - len(kh) + 1
    w = w_pad - len(kw) + 1
    kernel = functools.partial(_window_kernel, kh=kh, kw=kw, h=h, w=w)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[pl.BlockSpec((1, h_pad, w_pad), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, h, w), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h, w), x.dtype),
        interpret=interpret,
    )(x)


def windowed_sum_nchw(x: Array, kernels_1d: Sequence[Array], interpret: bool = False) -> Array:
    """(B, C, H_pad, W_pad) → (B, C, H, W) through the Pallas kernel."""
    b, c, h_pad, w_pad = x.shape
    kh = tuple(float(v) for v in kernels_1d[0])
    kw = tuple(float(v) for v in kernels_1d[1])
    flat = x.reshape(b * c, h_pad, w_pad)
    out = ssim_window_pallas(flat, kh, kw, interpret=interpret)
    return out.reshape(b, c, out.shape[1], out.shape[2])
