"""Local-weights backbone hub: resolve named pretrained models without egress.

The reference downloads its backbones at first use (torch-fidelity InceptionV3,
torchvision VGG/Alex, HF CLIP/BERT — SURVEY §2.9). This build never downloads:
:func:`load_feature_extractor` resolves a name against a local weights directory
(``weights_dir`` argument or ``METRICS_TPU_WEIGHTS`` env var) and returns ready
callables. Accepted on-disk formats per name:

============================  =====================================================
name                          files searched in the weights dir
============================  =====================================================
``inception_v3_fid``          ``inception_v3_fid.msgpack`` (flax) or ``pt_inception*.pth`` /
                              ``inception_v3_fid.pth`` (torch state dict → converted)
``vgg16_lpips`` /             ``<name>.msgpack`` or torchvision ``vgg16.pth`` /
``alexnet_lpips``             ``alexnet.pth`` + LPIPS ``lpips_vgg.pth`` / ``lpips_alex.pth``
``clip-vit-base-patch16`` …   a HF checkpoint directory of that name (Flax CLIP)
``bert-*`` / ``roberta-*`` …  a HF checkpoint directory of that name (Flax AutoModel)
============================  =====================================================

torch state dicts are read with the baked-in CPU torch; msgpack with flax
serialization. Every model-based metric ALSO accepts an injected callable, so
nothing below is required to use the metric math.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional, Tuple

__all__ = ["load_clip", "load_feature_extractor", "load_lpips", "load_text_encoder", "resolve_weights_dir"]

_INCEPTION_FEATURES = (64, 192, 768, 2048, "logits_unbiased", "logits")


def resolve_weights_dir(weights_dir: Optional[str] = None) -> Optional[str]:
    return weights_dir or os.environ.get("METRICS_TPU_WEIGHTS")


def _missing(name: str, looked_for: str) -> "ModuleNotFoundError":
    return ModuleNotFoundError(
        f"Pretrained backbone {name!r} needs local weights ({looked_for}) in the directory given by"
        " `weights_dir` or the METRICS_TPU_WEIGHTS env var. This offline build never downloads;"
        " model-based metrics also accept any injected callable instead."
    )


def _load_torch_sd(path: str):
    import torch

    return torch.load(path, map_location="cpu")


def _find(weights_dir: str, *candidates: str) -> Optional[str]:
    import glob

    for c in candidates:
        hits = sorted(glob.glob(os.path.join(weights_dir, c)))
        if hits:
            return hits[0]
    return None


def load_feature_extractor(
    name: str, weights_dir: Optional[str] = None, feature: Any = 2048
) -> Callable:
    """Resolve a named image backbone into a pure ``images → features`` callable."""
    weights_dir = resolve_weights_dir(weights_dir)
    if name in ("inception_v3_fid", "inception-v3-compat", "inception_v3"):
        from metrics_tpu.models.inception_v3 import convert_torch_state_dict, make_feature_extractor

        if feature not in _INCEPTION_FEATURES:
            raise ValueError(
                f"Integer `feature` must be one of {_INCEPTION_FEATURES} for the FID InceptionV3,"
                f" but got {feature!r}."
            )

        if not weights_dir:
            raise _missing(name, "inception_v3_fid.msgpack or pt_inception*.pth")
        msgpack = _find(weights_dir, "inception_v3_fid.msgpack")
        if msgpack:
            variables = _read_msgpack_variables(msgpack)
            return make_feature_extractor(variables, feature)
        pth = _find(weights_dir, "pt_inception*.pth", "inception_v3_fid.pth", "inception*.pth")
        if pth:
            variables = convert_torch_state_dict(_load_torch_sd(pth))
            return make_feature_extractor(variables, feature)
        raise _missing(name, "inception_v3_fid.msgpack or pt_inception*.pth")
    if name in ("vgg16_lpips", "alexnet_lpips", "squeeze_lpips", "vgg", "alex", "squeeze"):
        raise ValueError(
            f"{name!r} is an LPIPS scorer, not an image→features extractor — use"
            " metrics_tpu.models.load_lpips(net_type) instead (its callable takes TWO image batches)."
        )
    if name == "simple_cnn":
        from metrics_tpu.models.simple_cnn import SimpleFeatureCNN

        return SimpleFeatureCNN().bind_apply()
    raise ValueError(f"Unknown backbone name {name!r}")


def _read_msgpack_variables(path: str):
    from flax.serialization import msgpack_restore

    with open(path, "rb") as fh:
        return msgpack_restore(fh.read())


def load_lpips(net_type: str, weights_dir: Optional[str] = None) -> Callable:
    """Resolve an LPIPS scorer ``(img1, img2, normalize=False) → (N,)`` for vgg/alex."""
    from metrics_tpu.models.lpips_nets import (
        build_lpips,
        convert_torch_backbone,
        convert_torch_lin,
    )

    weights_dir = resolve_weights_dir(weights_dir)
    if not weights_dir:
        raise _missing(f"{net_type}_lpips", f"{net_type}*.pth + lpips_{net_type}.pth")
    backbone_name = {"vgg": "vgg16", "alex": "alexnet", "squeeze": "squeezenet1_1"}[net_type]
    backbone_pth = _find(weights_dir, f"{backbone_name}*.pth", f"{net_type}_backbone.pth")
    lin_pth = _find(weights_dir, f"lpips_{net_type}.pth", f"{net_type}_lin.pth", f"{net_type}.pth")
    if not backbone_pth or not lin_pth:
        raise _missing(f"{net_type}_lpips", f"{net_type} backbone .pth + lin .pth")
    variables = convert_torch_backbone(_load_torch_sd(backbone_pth), net_type)
    lin = convert_torch_lin(_load_torch_sd(lin_pth))
    return build_lpips(net_type, variables, lin)


def load_clip(
    model_name_or_path: str, weights_dir: Optional[str] = None
) -> Tuple[Callable, Callable]:
    """Resolve a local HF CLIP checkpoint into (image_encoder, text_encoder) callables.

    Uses the transformers Flax CLIP classes against a LOCAL directory only —
    ``<weights_dir>/<basename>`` or an absolute path (reference call site:
    ``multimodal/clip_score.py:30``).
    """
    path = model_name_or_path
    if not os.path.isdir(path):
        weights_dir = resolve_weights_dir(weights_dir)
        candidate = os.path.join(weights_dir, os.path.basename(model_name_or_path)) if weights_dir else None
        if candidate and os.path.isdir(candidate):
            path = candidate
        else:
            raise _missing(model_name_or_path, "a local HF CLIP checkpoint directory")
    import jax.numpy as jnp
    from transformers import AutoProcessor, FlaxCLIPModel

    model = FlaxCLIPModel.from_pretrained(path, local_files_only=True)
    processor = AutoProcessor.from_pretrained(path, local_files_only=True)

    def image_encoder(images):
        import numpy as np

        arr = [np.asarray(i) for i in images] if isinstance(images, (list, tuple)) else np.asarray(images)
        inputs = processor(images=list(arr) if isinstance(arr, list) else [a for a in arr], return_tensors="np")
        return jnp.asarray(model.get_image_features(pixel_values=jnp.asarray(inputs["pixel_values"])))

    def text_encoder(texts):
        inputs = processor(text=list(texts), return_tensors="np", padding=True, truncation=True)
        return jnp.asarray(
            model.get_text_features(
                input_ids=jnp.asarray(inputs["input_ids"]),
                attention_mask=jnp.asarray(inputs["attention_mask"]),
            )
        )

    return image_encoder, text_encoder


def load_text_encoder(model_name_or_path: str, weights_dir: Optional[str] = None) -> Callable:
    """Resolve a local HF encoder checkpoint into a ``texts → list[(L_i, D)]`` callable.

    The BERTScore default path (reference ``text/bert.py:55``) via Flax AutoModel;
    per-text embeddings are trimmed to real (non-padding) tokens.
    """
    path = model_name_or_path
    if not os.path.isdir(path):
        weights_dir = resolve_weights_dir(weights_dir)
        candidate = os.path.join(weights_dir, os.path.basename(model_name_or_path)) if weights_dir else None
        if candidate and os.path.isdir(candidate):
            path = candidate
        else:
            raise _missing(model_name_or_path, "a local HF encoder checkpoint directory")
    import numpy as np
    from transformers import AutoTokenizer, FlaxAutoModel

    model = FlaxAutoModel.from_pretrained(path, local_files_only=True)
    tokenizer = AutoTokenizer.from_pretrained(path, local_files_only=True)

    def encoder(texts):
        batch = tokenizer(list(texts), return_tensors="np", padding=True, truncation=True)
        out = model(**{k: batch[k] for k in ("input_ids", "attention_mask")}).last_hidden_state
        out = np.asarray(out)
        return [out[i, batch["attention_mask"][i].astype(bool)] for i in range(out.shape[0])]

    return encoder
