"""Model backbones for model-based metrics.

The reference reaches its backbones through torch-fidelity / torchvision /
transformers downloads (SURVEY §2.9); this build keeps backbones **injectable**
(every model-based metric takes a callable) and ships a small flax feature CNN
for testing the injection path end-to-end. Pretrained flax ports (InceptionV3
for FID/KID/IS, VGG/Alex for LPIPS, CLIP for CLIPScore) slot in here when their
weights are present locally — see ``load_feature_extractor``.
"""

from metrics_tpu.models.simple_cnn import SimpleFeatureCNN, load_feature_extractor

__all__ = ["SimpleFeatureCNN", "load_feature_extractor"]
