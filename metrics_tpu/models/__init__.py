"""Model backbones for model-based metrics — native flax, local weights only.

The reference reaches its backbones through torch-fidelity / torchvision /
transformers downloads (SURVEY §2.9); this build ships native flax ports and a
zero-egress loader hub:

* :class:`InceptionV3FID` — full FID InceptionV3 (taps 64/192/768/2048/logits)
  for FID/KID/IS/MiFID, with a torch-state-dict converter;
* :class:`VGG16Features` / :class:`AlexNetFeatures` + LPIPS lin heads;
* HF Flax CLIP / text encoders resolved from local checkpoint directories;
* :func:`load_feature_extractor` / :func:`load_lpips` / :func:`load_clip` /
  :func:`load_text_encoder` — the local-weights resolution layer.

Every model-based metric also accepts injected callables, so the metric math is
usable with any user model.
"""

from metrics_tpu.models.hub import (
    load_clip,
    load_feature_extractor,
    load_lpips,
    load_text_encoder,
)
from metrics_tpu.models.inception_v3 import (
    InceptionV3FID,
    convert_torch_state_dict,
    init_inception_params,
    make_feature_extractor,
)
from metrics_tpu.models.lpips_nets import (
    AlexNetFeatures,
    VGG16Features,
    build_lpips,
    init_lpips,
)
from metrics_tpu.models.simple_cnn import SimpleFeatureCNN

__all__ = [
    "AlexNetFeatures",
    "InceptionV3FID",
    "SimpleFeatureCNN",
    "VGG16Features",
    "build_lpips",
    "convert_torch_state_dict",
    "init_inception_params",
    "init_lpips",
    "load_clip",
    "load_feature_extractor",
    "load_lpips",
    "load_text_encoder",
    "make_feature_extractor",
]
