"""Flax VGG16 / AlexNet feature towers + LPIPS linear heads.

The reference builds these from torchvision checkpoints plus vendored 1×1 "lin"
head weights (``/root/reference/src/torchmetrics/functional/image/lpips.py:63-150``,
``lpips_models/{alex,vgg}.pth``). Here both towers are native flax with the five
canonical LPIPS tap points; :func:`convert_torch_backbone` /
:func:`convert_torch_lin` turn locally-available torch state dicts (torchvision
layout / LPIPS lin layout) into flax params — no downloads.

LPIPS pipeline (as published): normalize input with the fixed shift/scale,
run the tower, unit-normalize each tap across channels, square the difference,
apply the 1×1 lin head (non-negative weights), average spatially, sum taps.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

import flax.linen as nn

# fixed input normalization constants from the published LPIPS implementation
_SHIFT = np.asarray([-0.030, -0.088, -0.188], dtype=np.float32)
_SCALE = np.asarray([0.458, 0.448, 0.450], dtype=np.float32)

VGG16_TAPS = (64, 128, 256, 512, 512)
ALEX_TAPS = (64, 192, 384, 256, 256)
SQUEEZE_TAPS = (64, 128, 256, 384, 384, 512, 512)


class VGG16Features(nn.Module):
    """torchvision-layout VGG16 ``features`` trunk, taps after relu{1_2,2_2,3_3,4_3,5_3}.

    Layer indices in the torchvision Sequential (0-30) are used as flax module
    names (``conv_<idx>``) so weight conversion is mechanical.
    """

    @nn.compact
    def __call__(self, x: Array) -> List[Array]:
        cfg = [  # (sequential_idx, out_channels) per conv; 'M' = maxpool
            (0, 64), (2, 64), "M",
            (5, 128), (7, 128), "M",
            (10, 256), (12, 256), (14, 256), "M",
            (17, 512), (19, 512), (21, 512), "M",
            (24, 512), (26, 512), (28, 512),
        ]
        tap_after = {2, 7, 14, 21, 28}
        taps: List[Array] = []
        for item in cfg:
            if item == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
                continue
            idx, ch = item
            x = nn.Conv(ch, (3, 3), padding=[(1, 1), (1, 1)], name=f"conv_{idx}")(x)
            x = nn.relu(x)
            if idx in tap_after:
                taps.append(x)
        return taps


class AlexNetFeatures(nn.Module):
    """torchvision-layout AlexNet ``features`` trunk, taps after each of the 5 ReLUs."""

    @nn.compact
    def __call__(self, x: Array) -> List[Array]:
        taps: List[Array] = []
        x = nn.Conv(64, (11, 11), strides=(4, 4), padding=[(2, 2), (2, 2)], name="conv_0")(x)
        x = nn.relu(x)
        taps.append(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(192, (5, 5), padding=[(2, 2), (2, 2)], name="conv_3")(x)
        x = nn.relu(x)
        taps.append(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(384, (3, 3), padding=[(1, 1), (1, 1)], name="conv_6")(x)
        x = nn.relu(x)
        taps.append(x)
        x = nn.Conv(256, (3, 3), padding=[(1, 1), (1, 1)], name="conv_8")(x)
        x = nn.relu(x)
        taps.append(x)
        x = nn.Conv(256, (3, 3), padding=[(1, 1), (1, 1)], name="conv_10")(x)
        x = nn.relu(x)
        taps.append(x)
        return taps


class _Fire(nn.Module):
    """SqueezeNet fire module: 1×1 squeeze → relu → (1×1 ∥ 3×3) expand → relu."""

    squeeze: int
    expand: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = nn.relu(nn.Conv(self.squeeze, (1, 1), name="squeeze")(x))
        e1 = nn.relu(nn.Conv(self.expand, (1, 1), name="expand1x1")(x))
        e3 = nn.relu(nn.Conv(self.expand, (3, 3), padding=[(1, 1), (1, 1)], name="expand3x3")(x))
        return jnp.concatenate([e1, e3], axis=-1)


def _max_pool_ceil(x: Array) -> Array:
    """3×3 stride-2 max pool with torch ``ceil_mode=True`` semantics.

    torchvision SqueezeNet pools with ceil_mode; shapes are static under jit, so
    the required right/bottom -inf padding is computed from the traced shape.
    """
    pads = [(0, (d - 3) % 2) for d in x.shape[1:3]]
    return nn.max_pool(x, (3, 3), strides=(2, 2), padding=pads)


class SqueezeNetFeatures(nn.Module):
    """torchvision SqueezeNet-1.1 ``features`` trunk with the 7 LPIPS tap points."""

    @nn.compact
    def __call__(self, x: Array) -> List[Array]:
        taps: List[Array] = []
        x = nn.relu(nn.Conv(64, (3, 3), strides=(2, 2), padding="VALID", name="conv_0")(x))
        taps.append(x)  # 64
        x = _max_pool_ceil(x)
        x = _Fire(16, 64, name="fire_3")(x)
        x = _Fire(16, 64, name="fire_4")(x)
        taps.append(x)  # 128
        x = _max_pool_ceil(x)
        x = _Fire(32, 128, name="fire_6")(x)
        x = _Fire(32, 128, name="fire_7")(x)
        taps.append(x)  # 256
        x = _max_pool_ceil(x)
        x = _Fire(48, 192, name="fire_9")(x)
        taps.append(x)  # 384
        x = _Fire(48, 192, name="fire_10")(x)
        taps.append(x)  # 384
        x = _Fire(64, 256, name="fire_11")(x)
        taps.append(x)  # 512
        x = _Fire(64, 256, name="fire_12")(x)
        taps.append(x)  # 512
        return taps


def _net_for(net_type: str) -> nn.Module:
    if net_type == "vgg":
        return VGG16Features()
    if net_type == "squeeze":
        return SqueezeNetFeatures()
    return AlexNetFeatures()


def _unit_normalize(x: Array, eps: float = 1e-10) -> Array:
    norm = jnp.sqrt(jnp.sum(x**2, axis=-1, keepdims=True))
    return x / (norm + eps)


def lpips_score(
    net_apply: Callable[[Array], List[Array]],
    lin_weights: Sequence[Array],
    img1: Array,
    img2: Array,
    normalize: bool = False,
) -> Array:
    """Per-pair LPIPS distance from a tower and its lin-head weights.

    ``img*``: (N, 3, H, W); [-1, 1] by default, [0, 1] with ``normalize=True``.
    ``lin_weights[i]``: (C_i,) non-negative 1×1 head for tap i.
    """
    if normalize:
        img1 = 2 * img1 - 1
        img2 = 2 * img2 - 1
    shift = jnp.asarray(_SHIFT).reshape(1, 3, 1, 1)
    scale = jnp.asarray(_SCALE).reshape(1, 3, 1, 1)
    a = jnp.transpose((img1 - shift) / scale, (0, 2, 3, 1))  # NHWC
    b = jnp.transpose((img2 - shift) / scale, (0, 2, 3, 1))
    feats_a = net_apply(a)
    feats_b = net_apply(b)
    total = 0.0
    for fa, fb, w in zip(feats_a, feats_b, lin_weights):
        diff = (_unit_normalize(fa) - _unit_normalize(fb)) ** 2
        weighted = (diff * w.reshape(1, 1, 1, -1)).sum(-1)  # 1x1 conv, no bias
        total = total + weighted.mean(axis=(1, 2))
    return total


def build_lpips(net_type: str, variables: Dict, lin_weights: Sequence[Array]) -> Callable:
    """Jitted ``(img1, img2, normalize) → (N,) distances`` for a tower + heads."""
    net = _net_for(net_type)

    def apply_tower(x: Array) -> List[Array]:
        return net.apply(variables, x)

    from functools import partial

    @partial(jax.jit, static_argnums=2)
    def score(img1: Array, img2: Array, normalize: bool = False) -> Array:
        return lpips_score(apply_tower, lin_weights, img1, img2, normalize)

    return score


def init_lpips(net_type: str, rng_seed: int = 0) -> Tuple[Dict, List[Array]]:
    """Random-init tower + uniform lin heads (offline testing; real weights via converters)."""
    net = _net_for(net_type)
    taps = {"vgg": VGG16_TAPS, "squeeze": SQUEEZE_TAPS}.get(net_type, ALEX_TAPS)
    variables = net.init(jax.random.PRNGKey(rng_seed), jnp.zeros((1, 64, 64, 3)))
    lin = [jnp.ones(c) / c for c in taps]
    return variables, lin


def convert_torch_backbone(state_dict: Dict[str, "np.ndarray"], net_type: str) -> Dict:
    """torchvision ``features.*`` state dicts → flax params.

    vgg/alex: ``features.<idx>.weight/bias`` → ``conv_<idx>/kernel|bias``;
    squeeze (SqueezeNet-1.1): ``features.<idx>.<sub>.weight`` →
    ``fire_<idx>/<sub>/kernel`` (sub ∈ squeeze|expand1x1|expand3x3), plus the
    stem ``features.0`` → ``conv_0``.
    """
    params: Dict = {}

    def _np(v):
        return v.numpy() if hasattr(v, "numpy") else np.asarray(v)

    for name, value in state_dict.items():
        parts = name.split(".")
        if parts[0] == "features":
            parts = parts[1:]
        if parts[-1] not in ("weight", "bias"):
            continue
        arr = _np(value)
        leaf = "kernel" if parts[-1] == "weight" else "bias"
        val = jnp.asarray(np.transpose(arr, (2, 3, 1, 0)) if arr.ndim == 4 else arr)
        if len(parts) == 2:
            params.setdefault(f"conv_{parts[0]}", {})[leaf] = val
        elif len(parts) == 3:  # squeeze fire module
            params.setdefault(f"fire_{parts[0]}", {}).setdefault(parts[1], {})[leaf] = val
    return {"params": params}


def convert_torch_lin(state_dict: Dict[str, "np.ndarray"]) -> List[Array]:
    """LPIPS lin-head state dict (``lin<i>.model.1.weight`` (1,C,1,1)) → list of (C,) arrays."""

    def _np(v):
        return v.numpy() if hasattr(v, "numpy") else np.asarray(v)

    out = []
    for i in range(len([k for k in state_dict if ".weight" in k])):
        key = next(k for k in state_dict if k.startswith(f"lin{i}.") and k.endswith("weight"))
        out.append(jnp.asarray(_np(state_dict[key]).reshape(-1)))
    return out
