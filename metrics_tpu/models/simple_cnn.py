"""A small flax feature extractor + the local-weights loader hook.

Stands in for the reference's downloaded InceptionV3/VGG backbones so the
FID/KID/IS/LPIPS injection path can be exercised end-to-end offline; the loader
resolves named pretrained backbones from a local directory when available.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import Array

try:
    import flax.linen as nn

    _FLAX_AVAILABLE = True
except Exception:  # pragma: no cover
    _FLAX_AVAILABLE = False


if _FLAX_AVAILABLE:

    class SimpleFeatureCNN(nn.Module):
        """Tiny conv tower producing (N, features) embeddings from NCHW images."""

        features: int = 64
        widths: Sequence[int] = (16, 32)

        @nn.compact
        def __call__(self, x: Array) -> Array:
            x = jnp.transpose(x, (0, 2, 3, 1))  # NCHW → NHWC
            for w in self.widths:
                x = nn.Conv(w, (3, 3), strides=(2, 2))(x)
                x = nn.relu(x)
            x = x.mean(axis=(1, 2))
            return nn.Dense(self.features)(x)

        def bind_apply(self, rng_seed: int = 0, image_shape=(1, 3, 32, 32)) -> Callable:
            """Initialize params and return a pure ``images -> features`` callable."""
            params = self.init(jax.random.PRNGKey(rng_seed), jnp.zeros(image_shape))
            apply = jax.jit(lambda imgs: self.apply(params, imgs))
            return apply

else:  # pragma: no cover

    class SimpleFeatureCNN:  # type: ignore[no-redef]
        def __init__(self, *a, **k):
            raise ModuleNotFoundError("SimpleFeatureCNN requires flax to be installed.")


# load_feature_extractor moved to metrics_tpu.models.hub (real architecture ports)
