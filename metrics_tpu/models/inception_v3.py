"""Flax InceptionV3, FID variant — the default backbone for FID/KID/IS/MiFID.

The reference obtains this network from the ``torch-fidelity`` package
(``/root/reference/src/torchmetrics/image/fid.py:30-45``: ``NoTrainInceptionV3``
with feature taps ``64 | 192 | 768 | 2048 | logits_unbiased``). Here the
architecture is implemented natively in flax from the published FID network
definition (TF-slim InceptionV3 with the FID-specific pooling quirks):

* all convolutions are bias-free and followed by BatchNorm(eps=1e-3) + ReLU;
* InceptionA/C use average pooling that EXCLUDES padding from the divisor
  (``count_include_pad=False`` semantics);
* the two InceptionE blocks differ: Mixed_7b pools with the padding-excluding
  average, Mixed_7c uses MAX pooling — the known quirk of the original FID
  weights;
* inputs are uint8-range images resized to 299×299 (bilinear) and scaled to
  roughly [-1, 1] with the FID normalization ``(x - 128) / 128``.

Module names mirror the torch-fidelity state-dict layout 1:1 so that
:func:`convert_torch_state_dict` is a mechanical rename — point it at a local
``pt_inception-2015-12-05`` checkpoint and the port runs with the real FID
weights (no downloads happen here; SURVEY §2.9's zero-egress constraint).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

import flax.linen as nn

FEATURE_DIMS = {64: 64, 192: 192, 768: 768, 2048: 2048}


class BasicConv2d(nn.Module):
    """Conv (no bias) + BatchNorm(eps=1e-3) + ReLU, NHWC."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = (0, 0)

    @nn.compact
    def __call__(self, x: Array) -> Array:
        pad = self.padding
        if isinstance(pad, tuple) and isinstance(pad[0], int):
            pad = [(pad[0], pad[0]), (pad[1], pad[1])]
        x = nn.Conv(self.features, self.kernel, strides=self.strides, padding=pad, use_bias=False, name="conv")(x)
        x = nn.BatchNorm(use_running_average=True, epsilon=1e-3, momentum=0.9, name="bn")(x)
        return nn.relu(x)


def _avg_pool_nopad(x: Array, window: int = 3) -> Array:
    """3×3 stride-1 average pool with pad excluded from the divisor (FID quirk)."""
    ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
    summed = nn.avg_pool(x, (window, window), strides=(1, 1), padding=[(1, 1), (1, 1)], count_include_pad=True)
    counts = nn.avg_pool(ones, (window, window), strides=(1, 1), padding=[(1, 1), (1, 1)], count_include_pad=True)
    return summed / counts


class FIDInceptionA(nn.Module):
    pool_features: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b1 = BasicConv2d(64, (1, 1), name="branch1x1")(x)
        b5 = BasicConv2d(48, (1, 1), name="branch5x5_1")(x)
        b5 = BasicConv2d(64, (5, 5), padding=(2, 2), name="branch5x5_2")(b5)
        b3 = BasicConv2d(64, (1, 1), name="branch3x3dbl_1")(x)
        b3 = BasicConv2d(96, (3, 3), padding=(1, 1), name="branch3x3dbl_2")(b3)
        b3 = BasicConv2d(96, (3, 3), padding=(1, 1), name="branch3x3dbl_3")(b3)
        bp = _avg_pool_nopad(x)
        bp = BasicConv2d(self.pool_features, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class FIDInceptionB(nn.Module):
    @nn.compact
    def __call__(self, x: Array) -> Array:
        b3 = BasicConv2d(384, (3, 3), strides=(2, 2), name="branch3x3")(x)
        bd = BasicConv2d(64, (1, 1), name="branch3x3dbl_1")(x)
        bd = BasicConv2d(96, (3, 3), padding=(1, 1), name="branch3x3dbl_2")(bd)
        bd = BasicConv2d(96, (3, 3), strides=(2, 2), name="branch3x3dbl_3")(bd)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class FIDInceptionC(nn.Module):
    channels_7x7: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        c7 = self.channels_7x7
        b1 = BasicConv2d(192, (1, 1), name="branch1x1")(x)
        b7 = BasicConv2d(c7, (1, 1), name="branch7x7_1")(x)
        b7 = BasicConv2d(c7, (1, 7), padding=(0, 3), name="branch7x7_2")(b7)
        b7 = BasicConv2d(192, (7, 1), padding=(3, 0), name="branch7x7_3")(b7)
        bd = BasicConv2d(c7, (1, 1), name="branch7x7dbl_1")(x)
        bd = BasicConv2d(c7, (7, 1), padding=(3, 0), name="branch7x7dbl_2")(bd)
        bd = BasicConv2d(c7, (1, 7), padding=(0, 3), name="branch7x7dbl_3")(bd)
        bd = BasicConv2d(c7, (7, 1), padding=(3, 0), name="branch7x7dbl_4")(bd)
        bd = BasicConv2d(192, (1, 7), padding=(0, 3), name="branch7x7dbl_5")(bd)
        bp = _avg_pool_nopad(x)
        bp = BasicConv2d(192, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class FIDInceptionD(nn.Module):
    @nn.compact
    def __call__(self, x: Array) -> Array:
        b3 = BasicConv2d(192, (1, 1), name="branch3x3_1")(x)
        b3 = BasicConv2d(320, (3, 3), strides=(2, 2), name="branch3x3_2")(b3)
        b7 = BasicConv2d(192, (1, 1), name="branch7x7x3_1")(x)
        b7 = BasicConv2d(192, (1, 7), padding=(0, 3), name="branch7x7x3_2")(b7)
        b7 = BasicConv2d(192, (7, 1), padding=(3, 0), name="branch7x7x3_3")(b7)
        b7 = BasicConv2d(192, (3, 3), strides=(2, 2), name="branch7x7x3_4")(b7)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class FIDInceptionE(nn.Module):
    """Mixed_7b (pool="avg", padding-excluding) / Mixed_7c (pool="max")."""

    pool: str = "avg"

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b1 = BasicConv2d(320, (1, 1), name="branch1x1")(x)
        b3 = BasicConv2d(384, (1, 1), name="branch3x3_1")(x)
        b3a = BasicConv2d(384, (1, 3), padding=(0, 1), name="branch3x3_2a")(b3)
        b3b = BasicConv2d(384, (3, 1), padding=(1, 0), name="branch3x3_2b")(b3)
        b3 = jnp.concatenate([b3a, b3b], axis=-1)
        bd = BasicConv2d(448, (1, 1), name="branch3x3dbl_1")(x)
        bd = BasicConv2d(384, (3, 3), padding=(1, 1), name="branch3x3dbl_2")(bd)
        bda = BasicConv2d(384, (1, 3), padding=(0, 1), name="branch3x3dbl_3a")(bd)
        bdb = BasicConv2d(384, (3, 1), padding=(1, 0), name="branch3x3dbl_3b")(bd)
        bd = jnp.concatenate([bda, bdb], axis=-1)
        if self.pool == "avg":
            bp = _avg_pool_nopad(x)
        else:
            bp = nn.max_pool(x, (3, 3), strides=(1, 1), padding=[(1, 1), (1, 1)])
        bp = BasicConv2d(192, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class _FC(nn.Module):
    """Final classifier exposing bias-free logits (torch-fidelity 'logits_unbiased')."""

    num_classes: int

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, Array]:
        kernel = self.param("kernel", nn.initializers.lecun_normal(), (x.shape[-1], self.num_classes))
        bias = self.param("bias", nn.initializers.zeros, (self.num_classes,))
        unbiased = x @ kernel
        return unbiased, unbiased + bias


class InceptionV3FID(nn.Module):
    """Full FID InceptionV3; ``__call__`` returns the requested feature taps.

    Taps (torch-fidelity names): ``64`` after the first maxpool, ``192`` after
    the second, ``768`` after Mixed_6e, ``2048`` after global average pooling,
    ``"logits_unbiased"`` = final fc without bias.
    """

    num_classes: int = 1008

    @nn.compact
    def __call__(self, x: Array, features: Sequence[Any] = (2048,)) -> Dict[Any, Array]:
        # x: (N, 3, H, W) in [0, 255]; resize + FID normalization
        x = jnp.transpose(x.astype(jnp.float32), (0, 2, 3, 1))
        # antialias=False: torch-fidelity resizes with F.interpolate(bilinear,
        # align_corners=False), which never antialiases — keep downsampling identical
        x = jax.image.resize(x, (x.shape[0], 299, 299, x.shape[3]), method="bilinear", antialias=False)
        x = (x - 128.0) / 128.0

        out: Dict[Any, Array] = {}
        x = BasicConv2d(32, (3, 3), strides=(2, 2), name="Conv2d_1a_3x3")(x)
        x = BasicConv2d(32, (3, 3), name="Conv2d_2a_3x3")(x)
        x = BasicConv2d(64, (3, 3), padding=(1, 1), name="Conv2d_2b_3x3")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        if 64 in features:
            out[64] = x.transpose(0, 3, 1, 2)
        x = BasicConv2d(80, (1, 1), name="Conv2d_3b_1x1")(x)
        x = BasicConv2d(192, (3, 3), name="Conv2d_4a_3x3")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        if 192 in features:
            out[192] = x.transpose(0, 3, 1, 2)
        x = FIDInceptionA(32, name="Mixed_5b")(x)
        x = FIDInceptionA(64, name="Mixed_5c")(x)
        x = FIDInceptionA(64, name="Mixed_5d")(x)
        x = FIDInceptionB(name="Mixed_6a")(x)
        x = FIDInceptionC(128, name="Mixed_6b")(x)
        x = FIDInceptionC(160, name="Mixed_6c")(x)
        x = FIDInceptionC(160, name="Mixed_6d")(x)
        x = FIDInceptionC(192, name="Mixed_6e")(x)
        if 768 in features:
            out[768] = x.transpose(0, 3, 1, 2)
        x = FIDInceptionD(name="Mixed_7a")(x)
        x = FIDInceptionE(pool="avg", name="Mixed_7b")(x)
        x = FIDInceptionE(pool="max", name="Mixed_7c")(x)
        x = x.mean(axis=(1, 2))  # global average pool → (N, 2048)
        if 2048 in features:
            out[2048] = x
        if "logits_unbiased" in features or "logits" in features:
            unbiased, logits = _FC(self.num_classes, name="fc")(x)
            if "logits" in features:
                out["logits"] = logits
            if "logits_unbiased" in features:
                out["logits_unbiased"] = unbiased
        return out


def init_inception_params(rng_seed: int = 0) -> Dict:
    """Random-init parameter tree (for offline testing; real weights via converter)."""
    model = InceptionV3FID()
    variables = model.init(
        jax.random.PRNGKey(rng_seed), jnp.zeros((1, 3, 299, 299)), features=(64, 192, 768, 2048, "logits_unbiased")
    )
    return variables


def make_feature_extractor(variables: Dict, feature: Any = 2048):
    """Pure jitted ``images (N,3,H,W) → features`` callable for one tap."""
    model = InceptionV3FID()

    @jax.jit
    def extract(imgs: Array) -> Array:
        feats = model.apply(variables, imgs, features=(feature,))
        f = feats[feature]
        if f.ndim == 4:  # spatial taps → global average pool like torch-fidelity
            f = f.mean(axis=(2, 3))
        return f

    return extract


def convert_torch_state_dict(state_dict: Dict[str, "np.ndarray"]) -> Dict:
    """Convert a torch-fidelity/pytorch-fid InceptionV3 state dict to flax variables.

    Accepts ``{name: ndarray}`` (call ``.numpy()`` on torch tensors first, or pass
    a ``torch.load(..., map_location='cpu')`` result — tensors are converted).
    Layout mapping: ``<block>.<branch>.conv.weight`` (O,I,kH,kW) → flax
    ``params/<block>/<branch>/conv/kernel`` (kH,kW,I,O); BatchNorm
    weight/bias/running_mean/running_var → scale/bias + batch_stats mean/var;
    ``fc.weight`` (O,I) → ``fc/kernel`` (I,O).
    """
    params: Dict = {}
    batch_stats: Dict = {}

    def _np(v):
        return v.numpy() if hasattr(v, "numpy") else np.asarray(v)

    def _set(tree, path, value):
        node = tree
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = jnp.asarray(value)

    for name, value in state_dict.items():
        arr = _np(value)
        parts = name.split(".")
        if parts[-2:] == ["conv", "weight"]:
            _set(params, parts[:-1] + ["kernel"], np.transpose(arr, (2, 3, 1, 0)))
        elif parts[-2] == "bn":
            leaf = parts[-1]
            if leaf == "weight":
                _set(params, parts[:-1] + ["scale"], arr)
            elif leaf == "bias":
                _set(params, parts[:-1] + ["bias"], arr)
            elif leaf == "running_mean":
                _set(batch_stats, parts[:-1] + ["mean"], arr)
            elif leaf == "running_var":
                _set(batch_stats, parts[:-1] + ["var"], arr)
        elif parts == ["fc", "weight"]:
            _set(params, ["fc", "kernel"], arr.T)
        elif parts == ["fc", "bias"]:
            _set(params, ["fc", "bias"], arr)
        # num_batches_tracked and aux-logits entries are dropped
    return {"params": params, "batch_stats": batch_stats}
