"""MetricCollection with on-device compute-group state sharing.

Capability parity with reference ``torchmetrics/collections.py`` (``MetricCollection
:59``, ``update :231``, ``_merge_compute_groups :264-298``, ``_equal_metric_states
:300-323``, ``_compute_groups_create_state_ref :325-343``, ``compute :345``,
``_compute_and_reduce :349-394``).

TPU redesign (SURVEY §7.1-4): the reference shares states *by Python reference* and
must copy on ``.items()`` to protect against user mutation (``collections.py:551-574``).
JAX arrays are immutable, so group members simply hold the same array objects as the
leader — sharing is free AND safe; no copy-on-read is needed. Group detection keeps
the reference's behavior: after the first update, metrics whose states compare equal
are merged, and later updates run only once per group.
"""

from __future__ import annotations

from collections import OrderedDict
from copy import deepcopy
from typing import Any, Dict, Hashable, Iterable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

import weakref

from metrics_tpu.metric import Metric
from jax import Array

from metrics_tpu.observe import recorder as _observe
from metrics_tpu.observe import tracing as _tracing
from metrics_tpu.utils.data import _flatten_dict
from metrics_tpu.utils.prints import rank_zero_warn

# Fused leader-update programs. Primary cache: keyed by the tuple of the leaders'
# static-config keys, so config-equal collections (even short-lived ones) share
# ONE compilation — the same economics as Metric's shared jit cache. Fallback for
# unhashable configs: weakly keyed per collection (deepcopy/pickle never see a
# compiled closure either way).
_FUSED_SHARED_CACHE: Dict[Any, Any] = {}
_FUSED_UPDATE_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

__all__ = ["CollectionFunctions", "MetricCollection"]


class CollectionFunctions:
    """Pure ``(init, update, compute)`` triple for a whole :class:`MetricCollection`.

    Mirrors :class:`metrics_tpu.metric.MetricFunctions` at collection scope;
    state is a ``{leader_name: state_pytree}`` dict, so the triple composes with
    ``jax.jit`` / ``lax.scan`` / ``shard_map`` like any other pytree program.
    """

    def __init__(self, init, update, compute, reductions=None):
        self.init = init
        self.update = update
        self.compute = compute
        #: per-leader ``{state_name: dist_reduce_fx}`` dicts, for cross-mesh sync
        self.reductions = reductions or {}

    def sync(self, state, axis_name):
        """Reduce every leader's state across a mesh axis — call INSIDE ``shard_map``.

        Collection-scope analog of :func:`metrics_tpu.parallel.sync_states`: each
        leader state syncs with its own per-state ``dist_reduce_fx``, so one call
        reduces the whole collection in the same compiled program.
        """
        from metrics_tpu.parallel.sync import sync_states

        return {n: sync_states(st, self.reductions[n], axis_name) for n, st in state.items()}


class MetricCollection:
    """Collection of metrics updated from the same inputs (reference ``collections.py:59-170``).

    Args:
        metrics: a single metric, a sequence of metrics, or a dict mapping names to metrics.
        *additional_metrics: more metrics (when ``metrics`` is a single metric or sequence).
        prefix: string prepended to every result key.
        postfix: string appended to every result key.
        compute_groups: share state between metrics with identical update behavior
            (auto-detected after the first update), or an explicit list of name groups.

    >>> import jax.numpy as jnp
    >>> from metrics_tpu.classification import MulticlassAccuracy, MulticlassPrecision, MulticlassRecall
    >>> target = jnp.array([0, 2, 0, 2, 0, 1, 0, 2])
    >>> preds = jnp.array([2, 1, 2, 0, 1, 2, 2, 2])
    >>> metrics = MetricCollection([MulticlassAccuracy(num_classes=3, average='micro'),
    ...                             MulticlassPrecision(num_classes=3, average='macro'),
    ...                             MulticlassRecall(num_classes=3, average='macro')])
    >>> metrics.update(preds, target)
    >>> sorted(metrics.compute())
    ['MulticlassAccuracy', 'MulticlassPrecision', 'MulticlassRecall']
    """

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        compute_groups: Union[bool, List[List[str]]] = True,
    ) -> None:
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        self._enable_compute_groups = compute_groups
        self._groups_checked = False
        self._state_is_copy = False
        self._modules: "OrderedDict[str, Metric]" = OrderedDict()
        self.add_metrics(metrics, *additional_metrics)

    # ------------------------------------------------------------------ container protocol
    def __getitem__(self, key: str) -> Metric:
        return self._modules[key]

    def __setitem__(self, key: str, value: Metric) -> None:
        if not isinstance(value, Metric):
            raise ValueError(f"Value for key {key!r} should be a Metric but got {type(value)}")
        self._modules[key] = value
        self._groups_checked = False
        if isinstance(self._enable_compute_groups, list):
            if not any(key in group for group in self._groups.values()):
                self._groups[len(self._groups)] = [key]
        else:
            # re-seed singleton groups over ALL current members; they re-merge on next update
            self._groups = {i: [name] for i, name in enumerate(self._modules)}

    def __iter__(self):
        return iter(self._modules)

    def __len__(self) -> int:
        return len(self._modules)

    def __contains__(self, key: str) -> bool:
        return key in self._modules

    def keys(self, keep_base: bool = False):
        """Return metric names; adorned with prefix/postfix unless ``keep_base``."""
        if keep_base:
            return self._modules.keys()
        return [self._set_name(k) for k in self._modules]

    def values(self):
        """Return the metric instances."""
        return self._modules.values()

    def items(self, keep_base: bool = False):
        """Return (name, metric) pairs."""
        if keep_base:
            return self._modules.items()
        return [(self._set_name(k), v) for k, v in self._modules.items()]

    # ------------------------------------------------------------------ construction
    def add_metrics(
        self, metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]], *additional_metrics: Metric
    ) -> None:
        """Add new metrics to the collection (reference ``collections.py:576-648``)."""
        if isinstance(metrics, str):
            raise ValueError(
                "Unknown input to MetricCollection. Expected a Metric, a sequence of Metrics or a dict,"
                f" but got a string: {metrics!r}"
            )
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence) and not isinstance(metrics, dict):
            metrics = list(metrics)
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                rank_zero_warn(
                    f"You have passes extra arguments {remain} which are not Metrics so they will be ignored."
                )
        elif additional_metrics:
            raise ValueError(
                f"You have passes extra arguments {additional_metrics} which are not compatible"
                f" with first passed dictionary."
            )
        if isinstance(metrics, dict):
            for name in sorted(metrics.keys()):
                metric = metrics[name]
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Value {metric} belonging to key {name} is not an instance of"
                        " `metrics_tpu.Metric` or `metrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    self._modules[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self._modules[f"{name}_{k}"] = v
        elif isinstance(metrics, Sequence):
            for metric in metrics:
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Input {metric} to `MetricCollection` is not a instance of"
                        " `metrics_tpu.Metric` or `metrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    name = metric.__class__.__name__
                    if name in self._modules:
                        raise ValueError(f"Encountered two metrics both named {name}")
                    self._modules[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self._modules[k] = v
        else:
            raise ValueError("Unknown input to MetricCollection.")
        self._groups_checked = False
        if self._enable_compute_groups:
            self._init_compute_groups()
        else:
            self._groups = {i: [name] for i, name in enumerate(self._modules)}

    def _init_compute_groups(self) -> None:
        """Initialize compute groups (reference ``collections.py:250-262``)."""
        if isinstance(self._enable_compute_groups, list):
            self._groups = dict(enumerate(self._enable_compute_groups))
            for v in self._groups.values():
                for metric in v:
                    if metric not in self._modules:
                        raise ValueError(
                            f"Input {metric} in `compute_groups` argument does not match a metric in the collection."
                        )
            self._groups_checked = True
        else:
            self._groups = {i: [name] for i, name in enumerate(self._modules)}

    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(f"Expected input `{name}` to be a string, but got {type(arg)}")

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        return name if self.postfix is None else name + self.postfix

    # ------------------------------------------------------------------ lifecycle
    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update each metric (once per compute group after groups stabilize; reference ``collections.py:231-248``)."""
        if self._state_is_copy:
            self._groups_checked = False
            self._state_is_copy = False
        if self._groups_checked:
            if not self._fused_group_update(args, kwargs):
                for cg in self._groups.values():
                    mi = self._modules[cg[0]]
                    mi.update(*args, **mi._filter_kwargs(**kwargs))
            # members share the leader's (immutable) state arrays — zero-copy
            for cg in self._groups.values():
                leader = self._modules[cg[0]]
                for name in cg[1:]:
                    member = self._modules[name]
                    # arrays are immutable → share; list containers are mutable → shallow-copy
                    # the container (elements still shared) so a later independent update
                    # cannot append into both metrics at once
                    member.__dict__["_state"].update({
                        k: (list(leader._state[k]) if isinstance(leader._state[k], list) else leader._state[k])
                        for k in member._defaults
                    })
                    member._update_count = leader._update_count
                    member._computed = None
                    # members alias the leader's arrays: the leader must copy before
                    # its next donated dispatch, and so must the members themselves
                    member.__dict__["_group_shared"] = True
                    leader.__dict__["_group_shared"] = True
        else:
            for m in self._modules.values():
                m.update(*args, **m._filter_kwargs(**kwargs))
            # only auto-detected groups are re-derived; explicit user groups are never merged
            if self._enable_compute_groups is True:
                self._merge_compute_groups()
            self._groups_checked = True
            _FUSED_UPDATE_CACHE.pop(self, None)  # leader set may have changed

    def _fused_group_update(self, args: Tuple, kwargs: Dict) -> bool:
        """Run ALL group leaders' updates as ONE jitted program (one dispatch, not L).

        Only for the homogeneous hot path: positional array args, every leader
        jit-eligible with pure-array fixed-shape states. Returns False to fall
        back to the per-leader loop.
        """
        if kwargs or not args:
            return False
        leaders = [self._modules[cg[0]] for cg in self._groups.values()]
        if len(leaders) < 2:
            return False
        if any(lm._is_synced for lm in leaders):
            return False  # the per-leader loop raises the proper synced-state error
        if any(not lm._jit_eligible(args, {}) for lm in leaders):
            return False
        shared_key = tuple(lm._jit_cache_key() for lm in leaders)
        shareable = all(k is not None for k in shared_key)
        rec = _observe.RECORDER if _observe.ENABLED else None
        t0 = _observe.clock() if rec is not None else 0.0
        from metrics_tpu.metric import _CompiledUpdate, _named_for_profiler, _probation_dispatch

        donate = all(lm._donation_eligible() for lm in leaders)
        entry = _FUSED_SHARED_CACHE.get((shared_key, donate)) if shareable else _FUSED_UPDATE_CACHE.get(self)
        if entry is None:
            if rec is not None and shareable:
                # cause attribution (DESIGN §22): per-leader config components
                # are index-namespaced so "leader 0's num_classes changed" and
                # "the leader set itself changed" stay distinguishable
                comps = [("leaders", tuple(type(lm).__name__ for lm in leaders))]
                for i, leader_key in enumerate(shared_key):
                    comps.extend(
                        (f"config[{i}]:{ck.lstrip('_')}", cv) for ck, cv in leader_key[1]
                    )
                comps.append(("donation", bool(donate)))
                comps.append(("x64", bool(jax.config.jax_enable_x64)))
                _observe.note_compile_miss("fused", f"fused[{len(leaders)}]", tuple(comps))
            # representatives are pristine clones so no live collection is pinned
            reps = [lm.clone() for lm in leaders] if shareable else leaders
            for r in (reps if shareable else []):
                r.reset()
            # per-leader profiler names so the fused program's trace still
            # attributes time to each metric (metric.py:_named_for_profiler)
            fns = [
                _named_for_profiler(r._functional_update, f"{type(r).__name__}_update") for r in reps
            ]

            def _fused(states, *a):
                return tuple(fn(s, *a) for fn, s in zip(fns, states))

            entry = _CompiledUpdate(_fused, donate)
            if shareable:
                _FUSED_SHARED_CACHE[(shared_key, donate)] = entry
                if len(_FUSED_SHARED_CACHE) > 64:
                    _FUSED_SHARED_CACHE.pop(next(iter(_FUSED_SHARED_CACHE)))
            else:
                _FUSED_UPDATE_CACHE[self] = entry
            _observe.note_fused_compile(len(leaders), shareable)
        elif rec is not None:
            rec.add_count("fused_hit", str(len(leaders)))
        if entry.donate:
            # copy any leader state with live outside references, and dedup aliases
            # across the WHOLE donated pytree — one buffer must not be donated twice.
            # While the entry is on probation the dispatch is not yet known-good, so
            # every leader donates copies and keeps its live state as the rescue
            # reference a mid-dispatch death cannot consume (DESIGN §14).
            seen: set = set()
            probation = entry.probation

            def _donatable(lm: Metric) -> Dict[str, Any]:
                force = probation or lm._state_escaped or lm._group_shared
                out: Dict[str, Any] = {}
                for k in lm._defaults:
                    v = lm._state[k]
                    if isinstance(v, jax.Array):
                        if force or id(v) in seen:
                            v = jnp.copy(v)
                        seen.add(id(v))
                    out[k] = v
                return out

            states = tuple(_donatable(lm) for lm in leaders)
        else:
            states = tuple({k: lm._state[k] for k in lm._defaults} for lm in leaders)
        try:
            if entry.probation:
                new_states = _probation_dispatch(entry, f"fused[{len(leaders)}]", (states,) + args, {})
            else:
                new_states = entry(states, *args)
        except (jax.errors.TracerBoolConversionError, jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError, jax.errors.UnexpectedTracerError,
                jax.errors.TracerIntegerConversionError) as exc:
            _FUSED_UPDATE_CACHE.pop(self, None)
            _observe.note_fused_fallback(len(leaders), exc)
            return False
        except BaseException as exc:
            # fused dispatch died: no leader state/count was assigned yet, so the
            # whole group is untouched — the fused path is atomic as one unit
            _observe.note_update_rollback(f"fused[{len(leaders)}]", exc)
            raise
        for lm, ns in zip(leaders, new_states):
            lm.__dict__["_state"].update(ns)
            lm._computed = None
            lm._update_count += 1
            # fresh executable-owned buffers; the sharing loop in update() re-marks
            # the leader once members re-alias them
            lm.__dict__["_state_escaped"] = False
            lm.__dict__["_group_shared"] = False
        if rec is not None:
            t1 = _observe.clock()
            rec.add_time("fused_update", str(len(leaders)), t1 - t0)
            _tracing.record_complete("fused_update", str(len(leaders)), t0, t1)
            rec.add_count("fused_dispatch", str(len(leaders)))
            if entry.donate:
                rec.add_count("fused_donated", str(len(leaders)))
        return True

    def _merge_compute_groups(self) -> None:
        """Merge metrics with identical post-update states (reference ``collections.py:264-298``).

        Merging never mutates the leaders' states, so the pairwise equality
        matrix over the current leader set is computed up front — all value
        comparisons run as async device ops and ONE host fetch resolves every
        pair. On high-latency devices (a tunneled TPU) this replaces a
        ~70 ms device→host sync per comparison with a single sync total.
        """
        keys = list(self._groups.keys())
        leaders = {k: self._modules[self._groups[k][0]] for k in keys}
        equal = self._pairwise_equal_states(keys, leaders)
        num_groups = len(self._groups)
        while True:
            for cg_idx1 in list(self._groups):
                for cg_idx2 in list(self._groups):
                    if cg_idx1 == cg_idx2:
                        continue
                    if equal[(cg_idx1, cg_idx2)]:
                        self._groups[cg_idx1].extend(self._groups.pop(cg_idx2))
                        break
                else:
                    continue
                break
            else:
                break
            if len(self._groups) == num_groups:
                break
            num_groups = len(self._groups)
        self._groups = {i: v for i, v in enumerate(self._groups.values())}

    @classmethod
    def _pairwise_equal_states(cls, keys: List, leaders: Dict) -> Dict:
        """Equality over all leader pairs with at most one device→host sync."""
        equal: Dict = {}
        pending: List = []  # (key-pair, 0-d bool device array)
        for i, k1 in enumerate(keys):
            for k2 in keys[i + 1 :]:
                verdict = cls._structural_equal_states(leaders[k1], leaders[k2])
                if verdict is None:
                    pending.append(((k1, k2), cls._value_equal_device(leaders[k1], leaders[k2])))
                    continue
                equal[(k1, k2)] = equal[(k2, k1)] = verdict
        if pending:
            # hotlint: intentional-transfer — ONE batched d2h resolves every pair
            flat = np.asarray(jax.device_get(jnp.stack([arr for _, arr in pending])))
            _observe.note_explicit_transfer("collection_state_equal")
            for ((k1, k2), _), ok in zip(pending, flat):
                equal[(k1, k2)] = equal[(k2, k1)] = bool(ok)
        return equal

    @staticmethod
    def _structural_equal_states(metric1: Metric, metric2: Metric) -> Optional[bool]:
        """Host-side part of the state equality check (reference ``collections.py:300-323``).

        Returns False on any structural mismatch, True when states are the very
        same arrays, and None when a device value comparison is still needed.
        """
        if len(metric1._defaults) == 0 or len(metric2._defaults) == 0:
            return False
        if metric1._defaults.keys() != metric2._defaults.keys():
            return False
        all_shared = True
        for key in metric1._defaults:
            s1, s2 = metric1._state[key], metric2._state[key]
            if type(s1) != type(s2):  # noqa: E721
                return False
            if isinstance(s1, list):
                if len(s1) != len(s2) or any(x.shape != y.shape for x, y in zip(s1, s2)):
                    return False
                all_shared = all_shared and all(x is y for x, y in zip(s1, s2))
            else:
                if s1.shape != s2.shape:
                    return False
                all_shared = all_shared and s1 is s2
        return True if all_shared else None

    @staticmethod
    def _value_equal_device(metric1: Metric, metric2: Metric) -> Array:
        """0-d bool array: all states allclose (async — caller batches the fetch)."""
        checks = []
        for key in metric1._defaults:
            s1, s2 = metric1._state[key], metric2._state[key]
            pairs = zip(s1, s2) if isinstance(s1, list) else [(s1, s2)]
            for x, y in pairs:
                if x.dtype != y.dtype:
                    y = y.astype(x.dtype)
                checks.append(jnp.allclose(x, y))
        if not checks:
            return jnp.asarray(True)
        return jnp.stack(checks).all()

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Call forward on each metric, returning batch values (reference ``collections.py:222-229``)."""
        res = {k: m(*args, **m._filter_kwargs(**kwargs)) for k, m in self._modules.items()}
        # forward mutates states independently, so group sharing must be re-derived
        self._groups_checked = False
        res, duplicates = _flatten_dict(res)
        if duplicates:
            rank_zero_warn("Metric output keys overlap after flattening; some results were overwritten.")
        return {self._set_name(k): v for k, v in res.items()}

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def compute(self) -> Dict[str, Any]:
        """Compute the result for each metric (reference ``collections.py:345-347``)."""
        return self._compute_and_reduce("compute")

    def plot(self, val: Any = None, ax: Any = None, together: bool = False):
        """Plot each metric's value — one figure per metric, or all on one axis (reference ``collections.py:656-741``).

        Args:
            val: a ``compute()``/``forward()`` result dict, or a list of them (one per step);
                defaults to ``compute()``.
            ax: with ``together=True`` a single matplotlib axis; otherwise a sequence of
                axes, one per metric.
            together: plot all metrics onto one shared axis instead of one figure each.

        Returns:
            ``(fig, ax)`` when ``together`` else a list of per-metric ``(fig, ax)`` pairs.
        """
        from metrics_tpu.utils.plot import plot_single_or_multi_val

        if not isinstance(together, bool):
            raise ValueError(f"Expected argument `together` to be a boolean, but got {type(together)}")
        if ax is not None:
            import matplotlib.axes

            if together and not isinstance(ax, matplotlib.axes.Axes):
                raise ValueError(
                    f"Expected argument `ax` to be a matplotlib axis object, but got {type(ax)} when `together=True`"
                )
            if not together and not (isinstance(ax, Sequence) and len(ax) == len(self)):
                raise ValueError(
                    "Expected argument `ax` to be a sequence of matplotlib axis objects of the same "
                    f"length as the number of metrics in the collection, but got {type(ax)} when `together=False`"
                )
        val = val if val is not None else self.compute()
        if together:
            return plot_single_or_multi_val(val, ax=ax)
        fig_axs = []
        for i, (k, m) in enumerate(self.items()):
            if isinstance(val, dict):
                f, a = m.plot(val[k], ax=ax[i] if ax is not None else None)
            elif isinstance(val, Sequence):
                f, a = m.plot([v[k] for v in val], ax=ax[i] if ax is not None else None)
            else:
                raise TypeError(f"Expected argument `val` to be None, a dict, or a sequence of dicts, got {type(val)}")
            fig_axs.append((f, a))
        return fig_axs

    def functional(self) -> "CollectionFunctions":
        """Pure ``(init, update, compute)`` over the whole collection for jit/scan use.

        The TPU-native deployment of a collection: embed ``update`` in a jitted
        eval step (or ``lax.scan`` over a batch stream) and carry one state
        pytree. When compute groups have been detected (after the first eager
        ``update``) only one state per group is carried and updated; before
        detection every metric carries its own state — XLA's CSE then dedupes
        the identical group-mate updates inside the compiled program, which is
        the compiler-native form of the reference's compute-group sharing
        (reference ``collections.py:231-298``).
        """
        names = list(self._modules)
        if self._groups_checked:
            leader_of = {n: cg[0] for cg in self._groups.values() for n in cg}
        else:
            leader_of = {n: n for n in names}
        leaders = sorted({leader_of[n] for n in names}, key=names.index)
        lead_fns = {n: self._modules[n].functional() for n in leaders}
        member_fns = {n: (self._modules[n].functional() if n not in lead_fns else lead_fns[n]) for n in names}
        filters = {n: self._modules[n]._filter_kwargs for n in leaders}

        def init() -> Dict[str, Any]:
            return {n: lead_fns[n].init() for n in leaders}

        def update(state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
            return {n: lead_fns[n].update(state[n], *args, **filters[n](**kwargs)) for n in leaders}

        def compute(state: Dict[str, Any]) -> Dict[str, Any]:
            result = {n: member_fns[n].compute(state[leader_of[n]]) for n in names}
            return self._flatten_results(result)

        return CollectionFunctions(
            init=init,
            update=update,
            compute=compute,
            reductions={n: lead_fns[n].reductions for n in leaders},
        )

    def _compute_and_reduce(self, method_name: str, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Run compute/forward per metric and flatten outputs (reference ``collections.py:349-394``)."""
        result = {}
        for k, m in self._modules.items():
            if method_name == "compute":
                res = m.compute()
            else:
                res = m(*args, **m._filter_kwargs(**kwargs))
            result[k] = res
        return self._flatten_results(result)

    def _flatten_results(self, result: Dict[str, Any]) -> Dict[str, Any]:
        """Flatten per-metric results into one dict — shared by the eager and
        functional compute paths so both emit identical key sets."""
        _, duplicates = _flatten_dict(result)
        flat_result = {}
        for k, res in result.items():
            if isinstance(res, dict):
                for key, v in res.items():
                    if duplicates:
                        stripped = key.replace(self.prefix, "") if self.prefix else key
                        stripped = stripped.replace(self.postfix, "") if self.postfix else stripped
                        key = f"{k}_{stripped}"
                    flat_result[key] = v
            else:
                flat_result[k] = res
        return {self._set_name(k): v for k, v in flat_result.items()}

    def reset(self) -> None:
        """Call reset for each metric (reference ``collections.py:396-402``)."""
        for m in self._modules.values():
            m.reset()
        if self._enable_compute_groups and self._groups_checked:
            self._init_compute_groups()
            # explicit user-specified groups survive reset; auto-detected ones re-derive
            self._groups_checked = isinstance(self._enable_compute_groups, list)

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        """Make a copy of the collection (reference ``collections.py:404-419``)."""
        mc = deepcopy(self)
        if prefix:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def persistent(self, mode: bool = True) -> None:
        """Change if metric states should be saved to state_dict (reference ``collections.py:421-424``)."""
        for m in self._modules.values():
            m.persistent(mode)

    def state_dict(self) -> Dict[str, Any]:
        """Export all member state dicts keyed by metric name."""
        return {name: m.state_dict() for name, m in self._modules.items()}

    def load_state_dict(self, state_dict: Dict[str, Any], strict: bool = True) -> None:
        """Load member state dicts.

        ``strict`` is forwarded to every member (so a partial per-metric dict is
        loadable with ``strict=False``) and additionally checks the member names
        themselves: unknown or missing metric names raise under ``strict=True``
        and are skipped otherwise.
        """
        if strict:
            unexpected = sorted(set(state_dict) - set(self._modules))
            missing = sorted(set(self._modules) - set(state_dict))
            if unexpected or missing:
                raise RuntimeError(
                    f"MetricCollection.load_state_dict: state_dict does not match collection members "
                    f"(missing: {missing or 'none'}, unexpected: {unexpected or 'none'}). "
                    "Pass strict=False to load the intersection."
                )
        for name, sd in state_dict.items():
            if name in self._modules:
                self._modules[name].load_state_dict(sd, strict=strict)

    def set_dtype(self, dst_type) -> "MetricCollection":
        """Transfer all metric states to ``dst_type``."""
        for m in self._modules.values():
            m.set_dtype(dst_type)
        return self

    @property
    def compute_groups(self) -> Dict[int, List[str]]:
        """Return the current compute groups."""
        return self._groups

    @property
    def metric_state(self) -> Dict[str, Dict[str, Any]]:
        """Return the state of each metric in the collection."""
        return {name: m.metric_state for name, m in self._modules.items()}

    def __repr__(self) -> str:
        repr_str = self.__class__.__name__ + "("
        for name, m in self._modules.items():
            repr_str += f"\n  {name}: {m!r}"
        if self.prefix:
            repr_str += f"\n  prefix={self.prefix}"
        if self.postfix:
            repr_str += f"\n  postfix={self.postfix}"
        return repr_str + "\n)"
