"""metrics_tpu: a TPU-native (JAX/XLA/pjit/Pallas) metrics framework.

Capability parity with TorchMetrics (reference at ``/root/reference``, see SURVEY.md)
built from scratch TPU-first: metric state is a pytree, update/compute are pure
jit-compiled XLA functions, and distributed sync lowers to XLA collectives over a
``jax.sharding.Mesh``.
"""

from metrics_tpu import (
    audio,
    integration,
    classification,
    clustering,
    detection,
    functional,
    image,
    models,
    multimodal,
    nominal,
    ops,
    parallel,
    regression,
    retrieval,
    segmentation,
    shape,
    text,
    utils,
    wrappers,
)
from metrics_tpu.integration import MetricLogbook
from metrics_tpu.aggregation import (
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    RunningMean,
    RunningSum,
    SumMetric,
)
from metrics_tpu.collections import MetricCollection
from metrics_tpu.metric import CompositionalMetric, Metric

__version__ = "0.1.0"

__all__ = [
    "audio",
    "CatMetric",
    "CompositionalMetric",
    "MaxMetric",
    "MeanMetric",
    "Metric",
    "MetricCollection",
    "MinMetric",
    "RunningMean",
    "RunningSum",
    "SumMetric",
    "__version__",
    "classification",
    "clustering",
    "detection",
    "functional",
    "image",
    "models",
    "multimodal",
    "nominal",
    "ops",
    "parallel",
    "regression",
    "retrieval",
    "segmentation",
    "shape",
    "text",
    "utils",
    "wrappers",
]
