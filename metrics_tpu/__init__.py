"""metrics_tpu: a TPU-native (JAX/XLA/pjit/Pallas) metrics framework.

Capability parity with TorchMetrics (reference at ``/root/reference``, see SURVEY.md)
built from scratch TPU-first: metric state is a pytree, update/compute are pure
jit-compiled XLA functions, and distributed sync lowers to XLA collectives over a
``jax.sharding.Mesh``.

Root namespace parity: every metric class the reference exports from its root
(``/root/reference/src/torchmetrics/__init__.py``, 106 names) is importable from
``metrics_tpu`` directly.  Resolution is lazy (PEP 562) so ``import metrics_tpu``
stays light; subpackages load on first attribute access.
"""

from metrics_tpu.aggregation import (
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    RunningMean,
    RunningSum,
    SumMetric,
)
from metrics_tpu.collections import MetricCollection
from metrics_tpu.metric import CompositionalMetric, Metric

__version__ = "1.0.0"

# name -> defining module, for every reference root export not imported above
_LAZY_EXPORTS = {
    "PermutationInvariantTraining": "metrics_tpu.audio",
    "ScaleInvariantSignalDistortionRatio": "metrics_tpu.audio",
    "ScaleInvariantSignalNoiseRatio": "metrics_tpu.audio",
    "SignalDistortionRatio": "metrics_tpu.audio",
    "SignalNoiseRatio": "metrics_tpu.audio",
    "AUROC": "metrics_tpu.classification",
    "Accuracy": "metrics_tpu.classification",
    "AveragePrecision": "metrics_tpu.classification",
    "CalibrationError": "metrics_tpu.classification",
    "CohenKappa": "metrics_tpu.classification",
    "ConfusionMatrix": "metrics_tpu.classification",
    "Dice": "metrics_tpu.classification",
    "ExactMatch": "metrics_tpu.classification",
    "F1Score": "metrics_tpu.classification",
    "FBetaScore": "metrics_tpu.classification",
    "HammingDistance": "metrics_tpu.classification",
    "HingeLoss": "metrics_tpu.classification",
    "JaccardIndex": "metrics_tpu.classification",
    "LogAUC": "metrics_tpu.classification",
    "MatthewsCorrCoef": "metrics_tpu.classification",
    "NegativePredictiveValue": "metrics_tpu.classification",
    "Precision": "metrics_tpu.classification",
    "PrecisionAtFixedRecall": "metrics_tpu.classification",
    "PrecisionRecallCurve": "metrics_tpu.classification",
    "ROC": "metrics_tpu.classification",
    "Recall": "metrics_tpu.classification",
    "RecallAtFixedPrecision": "metrics_tpu.classification",
    "SensitivityAtSpecificity": "metrics_tpu.classification",
    "Specificity": "metrics_tpu.classification",
    "SpecificityAtSensitivity": "metrics_tpu.classification",
    "StatScores": "metrics_tpu.classification",
    "MetricLogbook": "metrics_tpu.integration",
    "ModifiedPanopticQuality": "metrics_tpu.detection",
    "PanopticQuality": "metrics_tpu.detection",
    "ErrorRelativeGlobalDimensionlessSynthesis": "metrics_tpu.image",
    "MultiScaleStructuralSimilarityIndexMeasure": "metrics_tpu.image",
    "PeakSignalNoiseRatio": "metrics_tpu.image",
    "RelativeAverageSpectralError": "metrics_tpu.image",
    "RootMeanSquaredErrorUsingSlidingWindow": "metrics_tpu.image",
    "SpectralAngleMapper": "metrics_tpu.image",
    "SpectralDistortionIndex": "metrics_tpu.image",
    "StructuralSimilarityIndexMeasure": "metrics_tpu.image",
    "TotalVariation": "metrics_tpu.image",
    "UniversalImageQualityIndex": "metrics_tpu.image",
    "CramersV": "metrics_tpu.nominal",
    "FleissKappa": "metrics_tpu.nominal",
    "PearsonsContingencyCoefficient": "metrics_tpu.nominal",
    "TheilsU": "metrics_tpu.nominal",
    "TschuprowsT": "metrics_tpu.nominal",
    "ConcordanceCorrCoef": "metrics_tpu.regression",
    "CosineSimilarity": "metrics_tpu.regression",
    "CriticalSuccessIndex": "metrics_tpu.regression",
    "ExplainedVariance": "metrics_tpu.regression",
    "KLDivergence": "metrics_tpu.regression",
    "KendallRankCorrCoef": "metrics_tpu.regression",
    "LogCoshError": "metrics_tpu.regression",
    "MeanAbsoluteError": "metrics_tpu.regression",
    "MeanAbsolutePercentageError": "metrics_tpu.regression",
    "MeanSquaredError": "metrics_tpu.regression",
    "MeanSquaredLogError": "metrics_tpu.regression",
    "MinkowskiDistance": "metrics_tpu.regression",
    "NormalizedRootMeanSquaredError": "metrics_tpu.regression",
    "PearsonCorrCoef": "metrics_tpu.regression",
    "R2Score": "metrics_tpu.regression",
    "RelativeSquaredError": "metrics_tpu.regression",
    "SpearmanCorrCoef": "metrics_tpu.regression",
    "SymmetricMeanAbsolutePercentageError": "metrics_tpu.regression",
    "TweedieDevianceScore": "metrics_tpu.regression",
    "WeightedMeanAbsolutePercentageError": "metrics_tpu.regression",
    "RetrievalFallOut": "metrics_tpu.retrieval",
    "RetrievalHitRate": "metrics_tpu.retrieval",
    "RetrievalMAP": "metrics_tpu.retrieval",
    "RetrievalMRR": "metrics_tpu.retrieval",
    "RetrievalNormalizedDCG": "metrics_tpu.retrieval",
    "RetrievalPrecision": "metrics_tpu.retrieval",
    "RetrievalPrecisionRecallCurve": "metrics_tpu.retrieval",
    "RetrievalRPrecision": "metrics_tpu.retrieval",
    "RetrievalRecall": "metrics_tpu.retrieval",
    "RetrievalRecallAtFixedPrecision": "metrics_tpu.retrieval",
    "BLEUScore": "metrics_tpu.text",
    "CHRFScore": "metrics_tpu.text",
    "CharErrorRate": "metrics_tpu.text",
    "ExtendedEditDistance": "metrics_tpu.text",
    "MatchErrorRate": "metrics_tpu.text",
    "Perplexity": "metrics_tpu.text",
    "SQuAD": "metrics_tpu.text",
    "SacreBLEUScore": "metrics_tpu.text",
    "TranslationEditRate": "metrics_tpu.text",
    "WordErrorRate": "metrics_tpu.text",
    "WordInfoLost": "metrics_tpu.text",
    "WordInfoPreserved": "metrics_tpu.text",
    "ShardedStreamEngine": "metrics_tpu.engine",
    "StreamEngine": "metrics_tpu.engine",
    "DecayedDDSketch": "metrics_tpu.windows",
    "DecayedHLL": "metrics_tpu.windows",
    "TimeDecayed": "metrics_tpu.windows",
    "TumblingWindow": "metrics_tpu.windows",
    "CUSUM": "metrics_tpu.drift",
    "KSDistance": "metrics_tpu.drift",
    "PSI": "metrics_tpu.drift",
    "DDSketch": "metrics_tpu.sketches",
    "HyperLogLog": "metrics_tpu.sketches",
    "ReservoirSample": "metrics_tpu.sketches",
    "StreamingAUROC": "metrics_tpu.sketches",
    "StreamingCalibrationError": "metrics_tpu.sketches",
    "BootStrapper": "metrics_tpu.wrappers",
    "ClasswiseWrapper": "metrics_tpu.wrappers",
    "MetricTracker": "metrics_tpu.wrappers",
    "MinMaxMetric": "metrics_tpu.wrappers",
    "MultioutputWrapper": "metrics_tpu.wrappers",
    "MultitaskWrapper": "metrics_tpu.wrappers",
}

_LAZY_SUBPACKAGES = (
    "aot", "audio", "classification", "clustering", "detection", "drift", "engine", "functional",
    "image", "integration", "models", "multimodal", "nominal", "observe", "ops", "parallel",
    "regression", "resilience", "retrieval", "segmentation", "shape", "sketches", "text",
    "utils", "windows", "wrappers",
)


def __getattr__(name):
    """Lazily resolve root metric exports and subpackages (PEP 562)."""
    import importlib

    if name in _LAZY_EXPORTS:
        value = getattr(importlib.import_module(_LAZY_EXPORTS[name]), name)
    elif name in _LAZY_SUBPACKAGES:
        value = importlib.import_module(f"metrics_tpu.{name}")
    else:
        raise AttributeError(f"module 'metrics_tpu' has no attribute {name!r}")
    globals()[name] = value  # cache: subsequent accesses skip __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY_EXPORTS) | set(_LAZY_SUBPACKAGES))


__all__ = sorted(set(_LAZY_EXPORTS) | set(_LAZY_SUBPACKAGES) | {
    "CatMetric", "CompositionalMetric", "MaxMetric", "MeanMetric", "Metric",
    "MetricCollection", "MinMetric", "RunningMean", "RunningSum",
    "SumMetric", "__version__",
})
