"""Modular text metrics.

Parity with reference ``torchmetrics/text/``: ``wer.py``, ``cer.py``, ``mer.py``,
``wil.py``, ``wip.py``, ``edit.py:113-116``, ``perplexity.py:78-79``, ``bleu.py``,
``sacre_bleu.py``, ``chrf.py``, ``rouge.py:144``, ``ter.py``, ``eed.py``,
``squad.py``. Text metrics keep sum-counter states (mesh-reducible); strings are
processed host-side at update.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update, _get_tokenizer
from metrics_tpu.functional.text.chrf import _chrf_counters
from metrics_tpu.functional.text.error_rates import (
    _as_list,
    _cer_update,
    _mer_wil_update,
    _wer_update,
    edit_distance as _edit_distance_fn,
)
from metrics_tpu.functional.text.helper import _tokenize_words
from metrics_tpu.functional.text.misc import extended_edit_distance, squad, translation_edit_rate
from metrics_tpu.functional.text.perplexity import _perplexity_compute, _perplexity_update
from metrics_tpu.functional.text.rouge import rouge_score
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.compute import count_dtype

_TEXT_KW = {"__jit_ineligible__": True}


class _ErrorRateMetric(Metric):
    """Shared plumbing: errors/total sum states over host-side token DP."""

    __jit_ineligible__ = True  # string inputs are host data
    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    errors: Array
    total: Array

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def compute(self) -> Array:
        """Compute metric."""
        return (self.errors / self.total).astype(jnp.float32)


class WordErrorRate(_ErrorRateMetric):
    """Word error rate (reference ``text/wer.py:27``).

    >>> preds = ["this is the prediction", "there is an other sample"]
    >>> target = ["this is the reference", "there is another one"]
    >>> wer = WordErrorRate()
    >>> wer.update(preds, target)
    >>> wer.compute()
    Array(0.5, dtype=float32)
    """

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Update state with predictions and targets."""
        errors, total = _wer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total


class CharErrorRate(_ErrorRateMetric):
    """Character error rate (reference ``text/cer.py:27``)."""

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Update state with predictions and targets."""
        errors, total = _cer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total


class MatchErrorRate(_ErrorRateMetric):
    """Match error rate (reference ``text/mer.py:27``)."""

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Update state with predictions and targets."""
        errors, total, _, _ = _mer_wil_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total


class WordInfoPreserved(Metric):
    """Word information preserved (reference ``text/wip.py:27``)."""

    __jit_ineligible__ = True
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("total_hits", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("target_total", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("preds_total", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Update state with predictions and targets."""
        _, _, hits, lens = _mer_wil_update(preds, target)
        self.total_hits = self.total_hits + hits
        self.target_total = self.target_total + lens[0]
        self.preds_total = self.preds_total + lens[1]

    def compute(self) -> Array:
        """Compute metric."""
        return (self.total_hits / self.target_total * self.total_hits / self.preds_total).astype(jnp.float32)


class WordInfoLost(WordInfoPreserved):
    """Word information lost (reference ``text/wil.py:27``)."""

    higher_is_better = False

    def compute(self) -> Array:
        """Compute metric."""
        return (1 - super().compute()).astype(jnp.float32)


class EditDistance(Metric):
    """Character edit distance (reference ``text/edit.py:26``, states ``:113-116``).

    >>> metric = EditDistance()
    >>> metric.update(["rain"], ["shine"])
    >>> metric.compute()
    Array(3., dtype=float32)
    """

    __jit_ineligible__ = True
    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, substitution_cost: int = 1, reduction: Optional[str] = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(substitution_cost, int) and substitution_cost >= 0):
            raise ValueError("Expected argument `substitution_cost` to be a positive integer")
        self.substitution_cost = substitution_cost
        if reduction not in ("mean", "sum", "none", None):
            raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")
        self.reduction = reduction
        if reduction in ("mean", "sum"):
            self.add_state("edit_scores_list", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("num_elements", jnp.zeros((), dtype=count_dtype()), dist_reduce_fx="sum")
        else:
            self.add_state("edit_scores", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Update state with predictions and targets."""
        dists = _edit_distance_fn(preds, target, self.substitution_cost, reduction="none")
        if self.reduction in ("mean", "sum"):
            self.edit_scores_list = self.edit_scores_list + dists.sum()
            self.num_elements = self.num_elements + dists.shape[0]
        else:
            self.edit_scores.append(dists)

    def compute(self) -> Array:
        """Compute metric."""
        if self.reduction == "mean":
            return self.edit_scores_list / self.num_elements
        if self.reduction == "sum":
            return self.edit_scores_list
        return dim_zero_cat(self.edit_scores)


class Perplexity(Metric):
    """Perplexity (reference ``text/perplexity.py:27``, states ``:78-79``).

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(22)
    >>> metric = Perplexity()
    >>> metric.update(jnp.asarray(rng.rand(2, 8, 5).astype(np.float32) * 10), jnp.asarray(rng.randint(5, size=(2, 8))))
    >>> float(metric.compute()) > 1
    True
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError(f"Argument `ignore_index` expected to either be `None` or an `int` but got {ignore_index}")
        self.ignore_index = ignore_index
        self.add_state("total_log_probs", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("count", jnp.zeros((), dtype=count_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Update state with log-probs/logits and targets."""
        total, count = _perplexity_update(preds, target, self.ignore_index)
        self.total_log_probs = self.total_log_probs + total
        self.count = self.count + count

    def compute(self) -> Array:
        """Compute metric."""
        return _perplexity_compute(self.total_log_probs, self.count)


class BLEUScore(Metric):
    """BLEU score (reference ``text/bleu.py:30``).

    >>> preds = ['the cat is on the mat']
    >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
    >>> bleu = BLEUScore()
    >>> bleu.update(preds, target)
    >>> bleu.compute()
    Array(0.75983566, dtype=float32)
    """

    __jit_ineligible__ = True
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        if weights is not None and len(weights) != n_gram:
            raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
        self.weights = weights if weights is not None else [1.0 / n_gram] * n_gram
        self._tokenizer = _tokenize_words
        self.add_state("preds_len", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("target_len", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("numerator", jnp.zeros(n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", jnp.zeros(n_gram), dist_reduce_fx="sum")

    def update(self, preds: Union[str, Sequence[str]], target: Union[Sequence[str], Sequence[Sequence[str]]]) -> None:
        """Update state with predictions and reference corpora."""
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        target_ = [[t] if isinstance(t, str) else list(t) for t in target]
        numerator = np.zeros(self.n_gram)
        denominator = np.zeros(self.n_gram)
        numerator, denominator, preds_len, target_len = _bleu_score_update(
            preds_, target_, numerator, denominator, 0.0, 0.0, self.n_gram, self._tokenizer
        )
        self.numerator = self.numerator + jnp.asarray(numerator)
        self.denominator = self.denominator + jnp.asarray(denominator)
        self.preds_len = self.preds_len + preds_len
        self.target_len = self.target_len + target_len

    def compute(self) -> Array:
        """Compute metric."""
        return _bleu_score_compute(
            self.preds_len, self.target_len, self.numerator, self.denominator, self.n_gram, self.weights, self.smooth
        )


class SacreBLEUScore(BLEUScore):
    """SacreBLEU score (reference ``text/sacre_bleu.py:38``)."""

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        self._tokenizer = _get_tokenizer(tokenize)
        self.lowercase = lowercase

    def update(self, preds: Union[str, Sequence[str]], target: Union[Sequence[str], Sequence[Sequence[str]]]) -> None:
        """Update state with predictions and reference corpora."""
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        target_ = [[t] if isinstance(t, str) else list(t) for t in target]
        if self.lowercase:
            preds_ = [p.lower() for p in preds_]
            target_ = [[t.lower() for t in refs] for refs in target_]
        super().update(preds_, target_)


class CHRFScore(Metric):
    """chrF / chrF++ score (reference ``text/chrf.py:32``).

    >>> preds = ['the cat is on the mat']
    >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
    >>> chrf = CHRFScore()
    >>> chrf.update(preds, target)
    >>> round(float(chrf.compute()), 4)
    0.864
    """

    __jit_ineligible__ = True
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(n_char_order, int) or n_char_order < 1:
            raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
        if not isinstance(n_word_order, int) or n_word_order < 0:
            raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
        if beta < 0:
            raise ValueError("Expected argument `beta` to be greater than 0.")
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score
        total = n_char_order + n_word_order
        self.add_state("matches", jnp.zeros(total), dist_reduce_fx="sum")
        self.add_state("preds_totals", jnp.zeros(total), dist_reduce_fx="sum")
        self.add_state("target_totals", jnp.zeros(total), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_chrf", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Union[Sequence[str], Sequence[Sequence[str]]]) -> None:
        """Update state with predictions and reference corpora."""
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        target_ = [[t] if isinstance(t, str) else list(t) for t in target]
        matches, pred_totals, target_totals = _chrf_counters(
            preds_, target_, self.n_char_order, self.n_word_order, self.lowercase, self.whitespace
        )
        self.matches = self.matches + jnp.asarray(matches)
        self.preds_totals = self.preds_totals + jnp.asarray(pred_totals)
        self.target_totals = self.target_totals + jnp.asarray(target_totals)
        if self.return_sentence_level_score:
            from metrics_tpu.functional.text.chrf import chrf_score

            _, sentence = chrf_score(
                preds_, target_, self.n_char_order, self.n_word_order, self.beta, self.lowercase,
                self.whitespace, return_sentence_level_score=True,
            )
            self.sentence_chrf.append(sentence)

    def compute(self) -> Array:
        """Compute metric."""
        p_vec = jnp.where(self.preds_totals > 0, self.matches / jnp.maximum(self.preds_totals, 1), 0.0)
        r_vec = jnp.where(self.target_totals > 0, self.matches / jnp.maximum(self.target_totals, 1), 0.0)
        b2 = self.beta**2
        denom = b2 * p_vec + r_vec
        f_vec = jnp.where(denom > 0, (1 + b2) * p_vec * r_vec / jnp.where(denom > 0, denom, 1.0), 0.0)
        corpus = f_vec.mean().astype(jnp.float32)
        if self.return_sentence_level_score:
            return corpus, dim_zero_cat(self.sentence_chrf)
        return corpus


class _StringStoreMetric(Metric):
    """Shared plumbing for text metrics whose compute runs on the raw strings."""

    __jit_ineligible__ = True
    is_differentiable = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        # string payloads live outside the array-state system
        self._preds_store: List = []
        self._target_store: List = []

    def update(self, preds, target) -> None:
        """Store inputs for compute."""
        self._preds_store.extend([preds] if isinstance(preds, str) else list(preds))
        if isinstance(target, str):
            self._target_store.append(target)
        else:
            self._target_store.extend(list(target))

    def forward(self, *args: Any, **kwargs: Any):
        """Batch-local value + accumulation (reference ``metric.py:287-317``).

        The string payloads live outside the array-state system, so the generic
        reduce-state forward (which resets only ``_state``) would leak the
        running store into the batch value; swap a fresh store in for the batch
        compute, then splice the histories back together.
        """
        prev_preds, prev_target = self._preds_store, self._target_store
        prev_count = self._update_count
        self._preds_store, self._target_store = [], []
        try:
            self.update(*args, **kwargs)
            batch_val = self.compute()
        except Exception:
            # all-or-nothing: a half-appended batch (e.g. preds stored, targets
            # invalid) would misalign every later compute
            self._preds_store, self._target_store = prev_preds, prev_target
            self._update_count = prev_count
            self._computed = None
            raise
        self._preds_store = prev_preds + self._preds_store
        self._target_store = prev_target + self._target_store
        self._computed = None  # running compute must not reuse the batch value
        return batch_val

    def merge_state(self, incoming_state) -> None:
        """Merge the string stores too — they live outside ``_state``.

        The generic ``merge_state`` only folds registered array states; with an
        empty ``_defaults`` it would silently drop every stored string of the
        incoming shard (distlint DL005 failure mode). Incoming strings go first,
        matching the base merge's incoming-first "cat" convention.
        """
        if not isinstance(incoming_state, _StringStoreMetric):
            raise ValueError(
                f"Expected incoming state to be a {self.__class__.__name__} holding its string "
                f"stores but got {type(incoming_state)}"
            )
        in_preds = list(incoming_state._preds_store)
        in_target = list(incoming_state._target_store)
        super().merge_state(incoming_state)
        self._preds_store = in_preds + self._preds_store
        self._target_store = in_target + self._target_store
        self._computed = None

    def reset(self) -> None:
        """Reset stored strings too."""
        super().reset()
        self._preds_store = []
        self._target_store = []


class ROUGEScore(_StringStoreMetric):
    """ROUGE score (reference ``text/rouge.py:31``, list states ``:144``).

    >>> rouge = ROUGEScore()
    >>> rouge.update("My name is John", "Is your name John")
    >>> sorted(rouge.compute())[:2]
    ['rouge1_fmeasure', 'rouge1_precision']
    """

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, use_stemmer: bool = False, accumulate: str = "best",
                 rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.rouge_keys = rouge_keys
        self.accumulate = accumulate
        self.use_stemmer = use_stemmer  # stemming requires nltk; plain tokenization otherwise

    def compute(self) -> Dict[str, Array]:
        """Compute metric."""
        return rouge_score(
            self._preds_store, self._target_store, self.accumulate, self.use_stemmer, self.rouge_keys
        )


class TranslationEditRate(_StringStoreMetric):
    """Translation edit rate (reference ``text/ter.py:30``)."""

    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(self, normalize: bool = False, no_punctuation: bool = False, lowercase: bool = True,
                 asian_support: bool = False, return_sentence_level_score: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.normalize = normalize
        self.no_punctuation = no_punctuation
        self.lowercase = lowercase
        self.asian_support = asian_support
        self.return_sentence_level_score = return_sentence_level_score

    def compute(self):
        """Compute metric."""
        return translation_edit_rate(
            self._preds_store, self._target_store, self.normalize, self.no_punctuation, self.lowercase,
            self.asian_support, self.return_sentence_level_score,
        )


class ExtendedEditDistance(_StringStoreMetric):
    """Extended edit distance (reference ``text/eed.py:30``)."""

    higher_is_better = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, language: str = "en", return_sentence_level_score: bool = False, alpha: float = 2.0,
                 rho: float = 0.3, deletion: float = 0.2, insertion: float = 1.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        self.alpha = alpha
        self.rho = rho
        self.deletion = deletion
        self.insertion = insertion

    def compute(self):
        """Compute metric."""
        return extended_edit_distance(
            self._preds_store, self._target_store, self.language, self.return_sentence_level_score,
            self.alpha, self.rho, self.deletion, self.insertion,
        )


class SQuAD(_StringStoreMetric):
    """SQuAD EM/F1 (reference ``text/squad.py:27``).

    Shares the string-store plumbing (stores, batch-local ``forward``, reset)
    with the other raw-payload text metrics; only the payloads are QA dicts.

    >>> preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"}]
    >>> target = [{"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"}]
    >>> metric = SQuAD()
    >>> metric.update(preds, target)
    >>> {k: float(v) for k, v in sorted(metric.compute().items())}
    {'exact_match': 100.0, 'f1': 100.0}
    """

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0

    def update(self, preds, target) -> None:
        """Store QA predictions/targets for compute."""
        self._preds_store.extend([preds] if isinstance(preds, dict) else list(preds))
        self._target_store.extend([target] if isinstance(target, dict) else list(target))

    def compute(self) -> Dict[str, Array]:
        """Compute metric."""
        return squad(self._preds_store, self._target_store)
