"""Modular text metrics (reference ``torchmetrics/text/__init__.py``)."""

from metrics_tpu.text.model_based import BERTScore, InfoLM
from metrics_tpu.text.metrics import (
    BLEUScore,
    CharErrorRate,
    CHRFScore,
    EditDistance,
    ExtendedEditDistance,
    MatchErrorRate,
    Perplexity,
    ROUGEScore,
    SacreBLEUScore,
    SQuAD,
    TranslationEditRate,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)

__all__ = [
    "BERTScore",
    "BLEUScore",
    "CHRFScore",
    "CharErrorRate",
    "EditDistance",
    "ExtendedEditDistance",
    "InfoLM",
    "MatchErrorRate",
    "Perplexity",
    "ROUGEScore",
    "SQuAD",
    "SacreBLEUScore",
    "TranslationEditRate",
    "WordErrorRate",
    "WordInfoLost",
    "WordInfoPreserved",
]
