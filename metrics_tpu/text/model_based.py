"""Model-based text metrics: BERTScore and InfoLM with injectable encoders.

Parity with reference ``text/bert.py:55`` and ``text/infolm.py`` (which download HF
transformers checkpoints — SURVEY §2.9). Offline build: inject an ``encoder``
callable mapping a list of strings to per-token embedding arrays (list of (T_i, D));
the metric owns the greedy cosine-matching P/R/F math (BERTScore) and the
information-measure aggregation (InfoLM, given a token-distribution callable).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.metric import Metric


class BERTScore(Metric):
    """BERTScore (reference ``text/bert.py:55``): greedy cosine matching of token embeddings.

    >>> import numpy as np
    >>> rng = np.random.RandomState(42)
    >>> vocab = {w: rng.rand(8) for w in "the cat sat on mat a dog ran".split()}
    >>> encoder = lambda texts: [np.stack([vocab[w] for w in t.split()]) for t in texts]
    >>> metric = BERTScore(encoder=encoder)
    >>> metric.update(["the cat sat"], ["the cat sat"])
    >>> round(float(metric.compute()["f1"]), 4)
    1.0
    """

    __jit_ineligible__ = True
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        encoder: Optional[Callable] = None,
        idf: bool = False,
        rescale_with_baseline: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if encoder is None:
            # default path = local HF Flax encoder checkpoint (reference downloads
            # roberta-large, text/bert.py:55); raises a clear error if absent on disk
            from metrics_tpu.models.hub import load_text_encoder

            encoder = load_text_encoder(model_name_or_path or "roberta-large")
        self.encoder = encoder
        self.idf = idf
        self.rescale_with_baseline = rescale_with_baseline
        self._pairs: List = []

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        """Store prediction/reference pairs."""
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        target_ = [target] if isinstance(target, str) else list(target)
        self._pairs.extend(zip(preds_, target_))

    def compute(self) -> Dict[str, Array]:
        """Greedy-match P/R/F1 per pair, averaged."""
        ps, rs, fs = [], [], []
        pred_embs = self.encoder([p for p, _ in self._pairs])
        tgt_embs = self.encoder([t for _, t in self._pairs])
        for pe, te in zip(pred_embs, tgt_embs):
            pe = np.asarray(pe, dtype=np.float64)
            te = np.asarray(te, dtype=np.float64)
            pe = pe / np.clip(np.linalg.norm(pe, axis=-1, keepdims=True), 1e-12, None)
            te = te / np.clip(np.linalg.norm(te, axis=-1, keepdims=True), 1e-12, None)
            sim = pe @ te.T  # (Tp, Tt)
            p = sim.max(axis=1).mean() if sim.size else 0.0
            r = sim.max(axis=0).mean() if sim.size else 0.0
            f = 2 * p * r / (p + r) if (p + r) else 0.0
            ps.append(p)
            rs.append(r)
            fs.append(f)
        return {
            "precision": jnp.asarray(np.mean(ps) if ps else 0.0, dtype=jnp.float32),
            "recall": jnp.asarray(np.mean(rs) if rs else 0.0, dtype=jnp.float32),
            "f1": jnp.asarray(np.mean(fs) if fs else 0.0, dtype=jnp.float32),
        }

    def reset(self) -> None:
        """Reset stored pairs too."""
        super().reset()
        self._pairs = []


class InfoLM(Metric):
    """InfoLM (reference ``text/infolm.py:40``): information measures between masked-LM
    token distributions of prediction and reference.

    Requires a ``distribution_fn`` mapping a list of strings to per-text token
    probability arrays (T_i, V) — e.g. a masked-LM apply fn.
    """

    __jit_ineligible__ = True
    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    _MEASURES = ("kl_divergence", "alpha_divergence", "beta_divergence", "ab_divergence",
                 "renyi_divergence", "l1_distance", "l2_distance", "l_infinity_distance",
                 "fisher_rao_distance")

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        distribution_fn: Optional[Callable] = None,
        information_measure: str = "kl_divergence",
        idf: bool = False,
        alpha: float = 0.25,
        beta: float = 0.25,
        temperature: float = 0.25,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if distribution_fn is None:
            raise ModuleNotFoundError(
                f"The pretrained checkpoint {model_name_or_path!r} requires downloaded transformers weights,"
                " unavailable offline. Pass `distribution_fn=` returning per-token distributions."
            )
        if information_measure not in self._MEASURES:
            raise ValueError(f"Expected `information_measure` to be one of {self._MEASURES}")
        if not (isinstance(temperature, (int, float)) and temperature > 0):
            raise ValueError(f"Expected `temperature` to be a positive number but got {temperature}")
        self.distribution_fn = distribution_fn
        self.information_measure = information_measure
        self.idf = idf
        self.alpha = alpha
        self.beta = beta
        # Re-tempering exponent: softmax(z/T) == softmax(z)^(1/T) renormalized, so
        # applying p^(1/T) per token to the injected distributions reproduces the
        # reference's temperature semantics (infolm.py:546 applies T inside the
        # MLM softmax). Default 0.25 matches the reference; pass 1.0 to use
        # distribution_fn's outputs untouched.
        self.temperature = float(temperature)
        self._pairs: List = []

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        """Store prediction/reference pairs."""
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        target_ = [target] if isinstance(target, str) else list(target)
        self._pairs.extend(zip(preds_, target_))

    def _measure(self, p: np.ndarray, q: np.ndarray) -> float:
        eps = 1e-12
        p = np.clip(p, eps, None)
        q = np.clip(q, eps, None)
        m = self.information_measure
        if m == "kl_divergence":
            return float(np.sum(p * np.log(p / q)))
        if m == "l1_distance":
            return float(np.abs(p - q).sum())
        if m == "l2_distance":
            return float(np.sqrt(((p - q) ** 2).sum()))
        if m == "l_infinity_distance":
            return float(np.abs(p - q).max())
        if m == "fisher_rao_distance":
            return float(2 * np.arccos(np.clip(np.sum(np.sqrt(p * q)), 0, 1)))
        if m == "alpha_divergence":
            a = self.alpha
            return float((1 - np.sum(p**a * q ** (1 - a))) / (a * (1 - a)))
        if m == "renyi_divergence":
            a = self.alpha
            return float(np.log(np.sum(p**a * q ** (1 - a))) / (a - 1))
        if m == "beta_divergence":
            b = self.beta
            return float(
                np.sum(p ** (b + 1)) / (b * (b + 1)) + np.sum(q ** (b + 1)) / (b + 1) - np.sum(p * q**b) / b
            )
        # ab_divergence
        a, b = self.alpha, self.beta
        return float(
            np.log(np.sum(p ** (a + b))) / (b * (a + b)) + np.log(np.sum(q ** (a + b))) / (a * (a + b))
            - np.log(np.sum(p**a * q**b)) / (a * b)
        )

    def _temper(self, dist: np.ndarray) -> np.ndarray:
        """Per-token ``p^(1/T)`` renormalized — identity at T=1."""
        if self.temperature == 1.0:
            return dist
        t = np.clip(dist, 1e-12, None) ** (1.0 / self.temperature)
        return t / t.sum(axis=-1, keepdims=True)

    def _pair_scores(self) -> List[float]:
        pred_dists = self.distribution_fn([p for p, _ in self._pairs])
        tgt_dists = self.distribution_fn([t for _, t in self._pairs])
        vals = []
        for pd, td in zip(pred_dists, tgt_dists):
            p = self._temper(np.asarray(pd, dtype=np.float64)).mean(0)
            q = self._temper(np.asarray(td, dtype=np.float64)).mean(0)
            p = p / p.sum()
            q = q / q.sum()
            vals.append(self._measure(p, q))
        return vals

    def compute(self) -> Array:
        """Average information measure over pairs (mean-pooled token distributions)."""
        vals = self._pair_scores()
        return jnp.asarray(np.mean(vals) if vals else 0.0, dtype=jnp.float32)

    def compute_sentence_scores(self) -> Array:
        """Per-pair scores (the reference's ``return_sentence_level_score`` payload,
        ``functional/text/infolm.py:560``)."""
        return jnp.asarray(np.asarray(self._pair_scores(), dtype=np.float32))

    def reset(self) -> None:
        """Reset stored pairs too."""
        super().reset()
        self._pairs = []
