"""Drift detection & canary analysis as ordinary fleet metrics (DESIGN §20).

The scenario layer the windowed machinery unlocks: "is live traffic still
distributed like the reference?" and "did the monitored statistic shift?" as
registered :class:`~metrics_tpu.Metric` subclasses with fixed-shape states —
fleet-bucketable, donation-eligible, checkpointable via MTCKPT and
WAL-replayable with zero new engine code.

* :class:`PSI` — Population Stability Index from paired binned-histogram
  states (reference vs. live), the canonical feature-drift score.
* :class:`KSDistance` — Kolmogorov–Smirnov distance ``max |CDF_ref − CDF_live|``
  from the same paired-histogram state.
* :class:`CUSUM` — two-sided cumulative-sum change detector with a fixed
  (4,)-per-side segment state that composes associatively across shards.
"""

from metrics_tpu.drift.cusum import CUSUM
from metrics_tpu.drift.histogram import KSDistance, PSI

__all__ = ["CUSUM", "KSDistance", "PSI"]
