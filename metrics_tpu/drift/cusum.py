"""Two-sided CUSUM change detection with a fixed-shape composable state."""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.ops.decay import cusum_compose, cusum_segment

__all__ = ["CUSUM"]


class CUSUM(Metric):
    """Page's two-sided cumulative-sum change detector as a fleet metric.

    Tracks the classic recursions over the monitored statistic ``x``::

        S⁺ ← max(0, S⁺ + (x − target − k))      # upward shift
        S⁻ ← max(0, S⁻ + (target − x − k))      # downward shift

    and alarms when either side's *watermark* (the highest the statistic got
    anywhere in the stream, not just its current value — so an excursion
    inside a batch cannot be missed) exceeds the threshold ``h``.

    The state per side is a fixed (4,) float32 segment summary ``(total,
    statistic, max-prefix, watermark)`` that composes exactly across stream
    segments (:func:`metrics_tpu.ops.decay.cusum_compose`): a whole batch
    folds in one prefix-sum pass, and per-shard partials merge to the
    single-pass trajectory bit-for-bit. The composition is associative but
    NOT commutative — a CUSUM trajectory is an order statistic — so the merge
    harness classifies it CAT_ORDER_SENSITIVE: shard-order-respecting folds
    (checkpoint restore + WAL replay, ``merge_state`` chains) are exact, while
    order-oblivious collectives are refused by the declared
    ``merge_associative=False``.

    ``compute()`` returns (3,) float32: ``[S⁺, S⁻, alarm]`` with alarm 1.0
    when ``max(watermark⁺, watermark⁻) > h``.

    Args:
        target: in-control mean of the monitored statistic.
        k: slack (allowance) per observation, typically half the shift to
            detect, in the statistic's units (≥ 0).
        h: decision threshold on the CUSUM statistic (> 0).
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(self, target: float, k: float = 0.5, h: float = 5.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not float(k) >= 0.0:
            raise ValueError(f"`k` must be >= 0, got {k}")
        if not float(h) > 0.0:
            raise ValueError(f"`h` must be > 0, got {h}")
        self.target = float(target)
        self.k = float(k)
        self.h = float(h)
        # dist_reduce_fx=None: no order-oblivious reduction exists for an order
        # statistic; merges must route through the override below, and the
        # explicit merge_associative=False lets the sync layer refuse folds
        # with no well-defined cross-shard answer.
        self.add_state(
            "pos", default=jnp.zeros((4,), jnp.float32), dist_reduce_fx=None, merge_associative=False
        )
        self.add_state(
            "neg", default=jnp.zeros((4,), jnp.float32), dist_reduce_fx=None, merge_associative=False
        )

    def update(self, value: Array) -> None:
        v = jnp.asarray(value, jnp.float32).reshape(-1)
        ok = jnp.isfinite(v)
        self.pos = cusum_compose(self.pos, cusum_segment(v - (self.target + self.k), ok))
        self.neg = cusum_compose(self.neg, cusum_segment((self.target - self.k) - v, ok))

    def compute(self) -> Array:
        state = self.__dict__["_state"]
        pos, neg = state["pos"], state["neg"]
        alarm = jnp.maximum(pos[3], neg[3]) > self.h
        return jnp.stack([pos[1], neg[1], alarm.astype(jnp.float32)])

    def _merge_state_dicts(
        self, state_a: Dict[str, Any], state_b: Dict[str, Any], count_a: int, count_b: int
    ) -> Dict[str, Any]:
        # `state_a` is the incoming (stream-earlier) side everywhere this is
        # called: merge_state folds incoming-first, forward-reduce passes the
        # running global state first, and the merge harness folds shards in
        # stream order — exactly the order cusum_compose requires.
        return {
            "pos": cusum_compose(state_a["pos"], state_b["pos"]),
            "neg": cusum_compose(state_a["neg"], state_b["neg"]),
        }
