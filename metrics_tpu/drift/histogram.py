"""Distribution-drift scores from paired binned-histogram states."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import bincount
from metrics_tpu.utils.compute import acc_dtype

__all__ = ["KSDistance", "PSI"]

_EPS = 1e-6


def _drift_histogram_delta(values: Array, *, lo: float, hi: float, num_bins: int) -> Array:
    """One batch binned into (num_bins + 2,) float32 counts.

    Bin 0 is underflow (v < lo), bin num_bins + 1 overflow (v ≥ hi), interior
    bins split [lo, hi) evenly. Non-finite values are dropped into a discarded
    dead bin — branch-free, so the kernel jits and vmaps cleanly.
    """
    v = jnp.asarray(values, jnp.float32).reshape(-1)
    ok = jnp.isfinite(v)
    scaled = (v - jnp.float32(lo)) / jnp.float32(hi - lo) * num_bins
    idx = jnp.clip(jnp.floor(scaled).astype(jnp.int32) + 1, 0, num_bins + 1)
    dead = num_bins + 2
    return bincount(jnp.where(ok, idx, dead), dead + 1)[:dead].astype(jnp.float32)


class _PairedHistogram(Metric):
    """Shared state layout for histogram-based drift scores.

    Two fixed-shape ``(num_bins + 2,)`` float32 count states over identical
    bin edges — ``ref_counts`` for the reference distribution, ``live_counts``
    for production traffic — both plain ``sum`` algebra, so shard merges are
    exact elementwise adds and the metric keeps the full fleet contract with
    no merge override. The +2 are explicit under/overflow bins, so mass
    outside ``[lo, hi)`` still counts toward the score instead of vanishing.

    ``update(live, reference)`` feeds both sides; either may be an empty
    ``(0,)`` array when only one stream has data this batch (e.g. the
    reference was loaded once up front).
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(self, lo: float, hi: float, num_bins: int = 64, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not float(hi) > float(lo):
            raise ValueError(f"need `hi` > `lo`, got lo={lo}, hi={hi}")
        if int(num_bins) < 1:
            raise ValueError(f"`num_bins` must be >= 1, got {num_bins}")
        self.lo = float(lo)
        self.hi = float(hi)
        self.num_bins = int(num_bins)
        shape = (self.num_bins + 2,)
        self.add_state("ref_counts", default=jnp.zeros(shape, acc_dtype()), dist_reduce_fx="sum")
        self.add_state("live_counts", default=jnp.zeros(shape, acc_dtype()), dist_reduce_fx="sum")

    def update(self, live: Array, reference: Array) -> None:
        self.live_counts = self.live_counts + _drift_histogram_delta(
            live, lo=self.lo, hi=self.hi, num_bins=self.num_bins
        )
        self.ref_counts = self.ref_counts + _drift_histogram_delta(
            reference, lo=self.lo, hi=self.hi, num_bins=self.num_bins
        )

    def _proportions(self) -> tuple:
        state = self.__dict__["_state"]
        ref, live = state["ref_counts"], state["live_counts"]
        p_ref = ref / jnp.maximum(jnp.sum(ref), 1.0)
        p_live = live / jnp.maximum(jnp.sum(live), 1.0)
        return p_ref, p_live


class PSI(_PairedHistogram):
    """Population Stability Index between reference and live distributions.

    ``PSI = Σ_b (p_live[b] − p_ref[b]) · ln(p_live[b] / p_ref[b])`` over the
    shared bins (proportions clipped to 1e-6 before the log, the standard
    zero-bin smoothing). PSI ≥ 0 always; the usual reading is < 0.1 stable,
    0.1–0.25 moderate shift, > 0.25 action. An empty side contributes uniform
    epsilon proportions, so a never-updated metric scores 0.0, not NaN.

    Args:
        lo / hi: value range split into equal-width bins (plus explicit
            under/overflow bins, so out-of-range mass still drives the score).
        num_bins: interior bin count over ``[lo, hi)``.
    """

    def compute(self) -> Array:
        p_ref, p_live = self._proportions()
        p_ref = jnp.clip(p_ref, _EPS, 1.0)
        p_live = jnp.clip(p_live, _EPS, 1.0)
        return jnp.sum((p_live - p_ref) * jnp.log(p_live / p_ref))


class KSDistance(_PairedHistogram):
    """Kolmogorov–Smirnov distance between reference and live distributions.

    ``D = max_b |CDF_ref[b] − CDF_live[b]|`` evaluated at the shared bin
    edges — the exact two-sample KS statistic of the binned distributions
    (a lower bound on the unbinned statistic, tightening as ``num_bins``
    grows). D ∈ [0, 1]; an empty metric scores 0.0.

    Args: as :class:`PSI`.
    """

    def compute(self) -> Array:
        p_ref, p_live = self._proportions()
        return jnp.max(jnp.abs(jnp.cumsum(p_ref) - jnp.cumsum(p_live)))
