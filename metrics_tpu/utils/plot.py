"""Plotting helpers (reference ``torchmetrics/utilities/plot.py``).

Host-side matplotlib (gated like the reference): ``plot_single_or_multi_val :65``,
``plot_confusion_matrix :221``, ``plot_curve :297``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from metrics_tpu.utils.imports import _MATPLOTLIB_AVAILABLE


def _error_on_missing_matplotlib() -> None:
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(
            "Plot function expects `matplotlib` to be installed. Please install with `pip install matplotlib`"
        )


def plot_single_or_multi_val(
    val,
    ax=None,
    higher_is_better: Optional[bool] = None,
    lower_bound: Optional[float] = None,
    upper_bound: Optional[float] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
):
    """Plot a single scalar, a vector of per-class values, or a sequence over steps
    (reference ``plot.py:65-218``)."""
    _error_on_missing_matplotlib()
    import matplotlib.pyplot as plt

    fig, ax = (ax.get_figure(), ax) if ax is not None else plt.subplots()
    if isinstance(val, (list, tuple)) and val and isinstance(val[0], dict):
        # a time series of result dicts → one series per key (reference plot.py:117-121)
        val = {k: np.stack([np.asarray(v[k]) for v in val]) for k in val[0]}
    if isinstance(val, dict):
        for key, item in val.items():
            arr = np.atleast_1d(np.asarray(item))
            ax.plot(np.arange(len(arr)), arr, marker="o", label=key)
        ax.legend()
    elif isinstance(val, (list, tuple)) or (hasattr(val, "ndim") and np.asarray(val).ndim > 0 and np.asarray(val).size > 1):
        arr = np.asarray([np.asarray(v) for v in val]) if isinstance(val, (list, tuple)) else np.asarray(val)
        if arr.ndim == 1:
            ax.plot(np.arange(len(arr)), arr, marker="o", label=legend_name)
        else:
            for ci in range(arr.shape[-1]):
                ax.plot(np.arange(arr.shape[0]), arr[:, ci], marker="o",
                        label=f"{legend_name or 'series'} {ci}")
        if legend_name:
            ax.legend()
    else:
        ax.bar(0, float(np.asarray(val)), width=0.4)
        ax.set_xticks([])
    if lower_bound is not None or upper_bound is not None:
        ax.set_ylim(bottom=lower_bound, top=upper_bound)
    if name:
        ax.set_title(name)
    ax.grid(True, alpha=0.3)
    return fig, ax


def plot_confusion_matrix(
    confmat,
    ax=None,
    add_text: bool = True,
    labels: Optional[Sequence[str]] = None,
    cmap: Optional[str] = None,
):
    """Plot a (C, C) or (L, 2, 2) confusion matrix (reference ``plot.py:221-294``)."""
    _error_on_missing_matplotlib()
    import matplotlib.pyplot as plt

    confmat = np.asarray(confmat)
    if confmat.ndim == 3:
        nb, fig_label = confmat.shape[0], labels or [str(i) for i in range(confmat.shape[0])]
        if ax is not None:
            axs = np.atleast_1d(np.asarray(ax, dtype=object))
            if len(axs) != nb:
                raise ValueError(f"Expected {nb} axes for a ({nb}, 2, 2) confusion matrix, got {len(axs)}")
            fig = axs[0].get_figure()
        else:
            fig, axs = plt.subplots(nrows=1, ncols=nb, figsize=(4 * nb, 4))
            axs = np.atleast_1d(axs)
        for i in range(nb):
            ax_i = axs[i]
            ax_i.imshow(confmat[i], cmap=cmap)
            ax_i.set_title(f"Label {fig_label[i]}")
            if add_text:
                for r in range(2):
                    for c in range(2):
                        ax_i.text(c, r, str(round(confmat[i, r, c].item(), 2)), ha="center", va="center")
        return fig, axs
    fig, ax = (ax.get_figure(), ax) if ax is not None else plt.subplots()
    im = ax.imshow(confmat, cmap=cmap)
    fig.colorbar(im, ax=ax)
    n = confmat.shape[0]
    tick_labels = labels or [str(i) for i in range(n)]
    ax.set_xticks(range(n), tick_labels)
    ax.set_yticks(range(n), tick_labels)
    ax.set_xlabel("Predicted")
    ax.set_ylabel("True")
    if add_text:
        for r in range(n):
            for c in range(n):
                # reference plot.py:291 renders round(val, 2): ints stay ints, normalized floats keep 2 dp
                ax.text(c, r, str(round(confmat[r, c].item(), 2)), ha="center", va="center")
    return fig, ax


def plot_curve(
    curve: Tuple,
    score=None,
    ax=None,
    label_names: Optional[Tuple[str, str]] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
):
    """Plot an (x, y[, thresholds]) curve, e.g. ROC/PR (reference ``plot.py:297-366``)."""
    _error_on_missing_matplotlib()
    import matplotlib.pyplot as plt

    fig, ax = (ax.get_figure(), ax) if ax is not None else plt.subplots()
    if isinstance(curve[0], (list, tuple)) and not hasattr(curve[0], "ndim"):
        # exact-path multiclass/multilabel curves are ragged: one array per class,
        # potentially different lengths — never stack, plot per class
        for i, (xi, yi) in enumerate(zip(curve[0], curve[1])):
            ax.plot(np.asarray(xi), np.asarray(yi), label=f"{legend_name or 'class'} {i}")
        ax.legend()
    else:
        x, y = np.asarray(curve[0]), np.asarray(curve[1])
        if x.ndim == 2:
            for i in range(x.shape[0]):
                ax.plot(x[i], y[i], label=f"{legend_name or 'class'} {i}")
            ax.legend()
        else:
            lbl = None
            if score is not None:
                lbl = f"AUC={float(np.asarray(score)):.3f}"
            ax.plot(x, y, label=lbl)
            if lbl:
                ax.legend()
    if label_names:
        ax.set_xlabel(label_names[0])
        ax.set_ylabel(label_names[1])
    if name:
        ax.set_title(name)
    ax.grid(True, alpha=0.3)
    return fig, ax
