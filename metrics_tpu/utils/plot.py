"""Plotting helpers (reference ``torchmetrics/utilities/plot.py``).

Host-side matplotlib (gated like the reference): ``plot_single_or_multi_val :65``,
``plot_confusion_matrix :221``, ``plot_curve :297``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from metrics_tpu.utils.imports import _MATPLOTLIB_AVAILABLE


def _error_on_missing_matplotlib() -> None:
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(
            "Plot function expects `matplotlib` to be installed. Please install with `pip install matplotlib`"
        )


def plot_single_or_multi_val(
    val,
    ax=None,
    higher_is_better: Optional[bool] = None,
    lower_bound: Optional[float] = None,
    upper_bound: Optional[float] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
):
    """Plot a single scalar, a vector of per-class values, or a sequence over steps.

    Reference semantics (``plot.py:65-218``): scalars and per-class vectors are
    marker POINTS; lists are time series over a visible "Step" axis; known bounds
    draw dashed lines with an "Optimal value" annotation on the better one; the
    metric name labels the y-axis.
    """
    _error_on_missing_matplotlib()
    import matplotlib.pyplot as plt

    fig, ax = (ax.get_figure(), ax) if ax is not None else plt.subplots()
    ax.get_xaxis().set_visible(False)

    def _series_axis(n_steps: int) -> None:
        ax.get_xaxis().set_visible(True)
        ax.set_xlabel("Step")
        ax.set_xticks(np.arange(n_steps))

    if isinstance(val, (list, tuple)) and val and isinstance(val[0], dict):
        # a time series of result dicts → one series per key (reference plot.py:117-121)
        val = {k: np.stack([np.asarray(v[k]) for v in val]) for k in val[0]}
    if isinstance(val, dict):
        for i, (key, item) in enumerate(val.items()):
            arr = np.atleast_1d(np.asarray(item))
            if arr.size == 1:
                ax.plot(i, arr.item(), marker="o", markersize=10, label=key)
            else:
                ax.plot(np.arange(len(arr)), arr, marker="o", markersize=10, linestyle="-", label=key)
                _series_axis(len(arr))
    elif isinstance(val, (list, tuple)):
        arr = np.asarray([np.asarray(v) for v in val])
        if arr.ndim == 1:
            ax.plot(np.arange(len(arr)), arr, marker="o", markersize=10, linestyle="-", label=legend_name or "")
        else:  # per-step multi-value results → one series per component
            for ci in range(arr.shape[-1]):
                ax.plot(np.arange(arr.shape[0]), arr[:, ci], marker="o", markersize=10, linestyle="-",
                        label=f"{legend_name} {ci}" if legend_name else f"{ci}")
        _series_axis(arr.shape[0])
    elif hasattr(val, "ndim") and np.asarray(val).ndim > 0 and np.asarray(val).size > 1:
        # ONE multi-element result (per-class/per-output): separate marker points
        arr = np.asarray(val).reshape(-1)
        for i, v in enumerate(arr):
            ax.plot(i, v, marker="o", markersize=10, linestyle="None",
                    label=f"{legend_name} {i}" if legend_name else f"{i}")
    else:
        ax.plot([np.asarray(val).item()], marker="o", markersize=10)

    ylim = ax.get_ylim()
    if lower_bound is not None and upper_bound is not None:
        factor = 0.1 * (upper_bound - lower_bound)
    else:
        factor = 0.1 * (ylim[1] - ylim[0])
    ax.set_ylim(
        bottom=lower_bound - factor if lower_bound is not None else ylim[0] - factor,
        top=upper_bound + factor if upper_bound is not None else ylim[1] + factor,
    )
    ax.grid(True)
    if name:
        ax.set_ylabel(name)

    xlim = ax.get_xlim()
    xfactor = 0.1 * (xlim[1] - xlim[0])
    y_lines = [b for b in (lower_bound, upper_bound) if b is not None]
    if y_lines:
        ax.hlines(y_lines, xlim[0], xlim[1], linestyles="dashed", colors="k")
    if higher_is_better is not None:
        if lower_bound is not None and not higher_is_better:
            ax.set_xlim(xlim[0] - xfactor, xlim[1])
            ax.text(xlim[0], lower_bound, s="Optimal \n value", horizontalalignment="center",
                    verticalalignment="center")
        if upper_bound is not None and higher_is_better:
            ax.set_xlim(xlim[0] - xfactor, xlim[1])
            ax.text(xlim[0], upper_bound, s="Optimal \n value", horizontalalignment="center",
                    verticalalignment="center")

    handles, labels = ax.get_legend_handles_labels()
    if handles and any(labels):
        ax.legend(handles, labels, loc="upper center", bbox_to_anchor=(0.5, 1.15), ncol=3,
                  fancybox=True, shadow=True)
    return fig, ax


def plot_confusion_matrix(
    confmat,
    ax=None,
    add_text: bool = True,
    labels: Optional[Sequence[str]] = None,
    cmap: Optional[str] = None,
):
    """Plot a (C, C) or (L, 2, 2) confusion matrix (reference ``plot.py:221-294``)."""
    _error_on_missing_matplotlib()
    import matplotlib.pyplot as plt

    confmat = np.asarray(confmat)
    if confmat.ndim == 3:
        nb, fig_label = confmat.shape[0], labels or [str(i) for i in range(confmat.shape[0])]
        if ax is not None:
            axs = np.atleast_1d(np.asarray(ax, dtype=object))
            if len(axs) != nb:
                raise ValueError(f"Expected {nb} axes for a ({nb}, 2, 2) confusion matrix, got {len(axs)}")
            fig = axs[0].get_figure()
        else:
            fig, axs = plt.subplots(nrows=1, ncols=nb, figsize=(4 * nb, 4))
            axs = np.atleast_1d(axs)
        for i in range(nb):
            ax_i = axs[i]
            ax_i.imshow(confmat[i], cmap=cmap)
            ax_i.set_title(f"Label {fig_label[i]}")
            if add_text:
                for r in range(2):
                    for c in range(2):
                        ax_i.text(c, r, str(round(confmat[i, r, c].item(), 2)), ha="center", va="center")
        return fig, axs
    fig, ax = (ax.get_figure(), ax) if ax is not None else plt.subplots()
    im = ax.imshow(confmat, cmap=cmap)
    fig.colorbar(im, ax=ax)
    n = confmat.shape[0]
    tick_labels = labels or [str(i) for i in range(n)]
    ax.set_xticks(range(n), tick_labels)
    ax.set_yticks(range(n), tick_labels)
    ax.set_xlabel("Predicted")
    ax.set_ylabel("True")
    if add_text:
        for r in range(n):
            for c in range(n):
                # reference plot.py:291 renders round(val, 2): ints stay ints, normalized floats keep 2 dp
                ax.text(c, r, str(round(confmat[r, c].item(), 2)), ha="center", va="center")
    return fig, ax


def plot_curve(
    curve: Tuple,
    score=None,
    ax=None,
    label_names: Optional[Tuple[str, str]] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
):
    """Plot an (x, y[, thresholds]) curve, e.g. ROC/PR (reference ``plot.py:297-366``)."""
    _error_on_missing_matplotlib()
    import matplotlib.pyplot as plt

    fig, ax = (ax.get_figure(), ax) if ax is not None else plt.subplots()
    if isinstance(curve[0], (list, tuple)) and not hasattr(curve[0], "ndim"):
        # exact-path multiclass/multilabel curves are ragged: one array per class,
        # potentially different lengths — never stack, plot per class
        for i, (xi, yi) in enumerate(zip(curve[0], curve[1])):
            ax.plot(np.asarray(xi), np.asarray(yi), label=f"{legend_name or 'class'} {i}")
        ax.legend()
    else:
        x, y = np.asarray(curve[0]), np.asarray(curve[1])
        if x.ndim == 2:
            for i in range(x.shape[0]):
                ax.plot(x[i], y[i], label=f"{legend_name or 'class'} {i}")
            ax.legend()
        else:
            lbl = None
            if score is not None:
                lbl = f"AUC={float(np.asarray(score)):.3f}"
            ax.plot(x, y, label=lbl)
            if lbl:
                ax.legend()
    if label_names:
        ax.set_xlabel(label_names[0])
        ax.set_ylabel(label_names[1])
    if name:
        ax.set_title(name)
    ax.grid(True, alpha=0.3)
    return fig, ax
