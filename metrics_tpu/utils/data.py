"""Core data-movement kernels, designed XLA-first.

Capability parity with reference ``torchmetrics/utilities/data.py`` (dim_zero reductions
``:29-56``, ``to_onehot :81``, ``select_topk :124``, ``to_categorical :151``,
``_bincount :178``, ``_cumsum :209``, ``_flexible_bincount :223``, ``interp :249``)
— but implemented as static-shape jnp ops:

* ``bincount`` takes a **static** ``minlength`` so it lowers to one scatter-add /
  one-hot contraction (the reference's deterministic fallback is already this form);
  no data-dependent output shape ever reaches XLA.
* list-state concatenation (``dim_zero_cat``) accepts Python lists of arrays and is
  host-side glue — it only runs at ``compute()`` boundaries, never inside the jitted
  update hot loop.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import Array


def dim_zero_cat(x: Union[Array, List[Array]]) -> Array:
    """Concatenation along the zero dimension (reference ``data.py:29``)."""
    if isinstance(x, (jnp.ndarray, jax.Array)) and not isinstance(x, (list, tuple)):
        return x
    x = [y if y.ndim else y.reshape(1) for y in x]
    if not x:
        raise ValueError("No samples to concatenate")
    return jnp.concatenate(x, axis=0)


def dim_zero_sum(x: Array) -> Array:
    """Summation along the zero dimension (reference ``data.py:40``)."""
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    """Average along the zero dimension (reference ``data.py:45``)."""
    return jnp.mean(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    """Max along the zero dimension (reference ``data.py:50``)."""
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    """Min along the zero dimension (reference ``data.py:55``)."""
    return jnp.min(x, axis=0)


def _flatten(x: Sequence) -> list:
    """Flatten list of lists into single list (reference ``data.py:59``)."""
    return [item for sublist in x for item in sublist]


def _flatten_dict(x: Dict) -> tuple[Dict, bool]:
    """Flatten dict of dicts into single dict; returns (flat, duplicates_found) (reference ``data.py:63``)."""
    new_dict = {}
    duplicates = False
    for key, value in x.items():
        if isinstance(value, dict):
            for k, v in value.items():
                if k in new_dict:
                    duplicates = True
                new_dict[k] = v
        else:
            if key in new_dict:
                duplicates = True
            new_dict[key] = value
    return new_dict, duplicates


def to_onehot(label_tensor: Array, num_classes: int) -> Array:
    """Convert a dense label tensor to one-hot format (reference ``data.py:81-121``).

    Output has the class dim inserted at axis 1 (N, C, ...), matching the reference's
    scatter layout; implemented as a comparison against an iota so XLA fuses it.

    >>> import jax.numpy as jnp
    >>> to_onehot(jnp.array([0, 1, 2]), num_classes=3)
    Array([[1, 0, 0],
           [0, 1, 0],
           [0, 0, 1]], dtype=int32)
    """
    classes = jnp.arange(num_classes, dtype=label_tensor.dtype)
    shape = (label_tensor.shape[0], num_classes) + tuple(label_tensor.shape[1:])
    onehot = label_tensor[:, None] == classes.reshape((1, num_classes) + (1,) * (label_tensor.ndim - 1))
    return onehot.astype(jnp.int32).reshape(shape)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """One-hot mask of the top-k entries along ``dim`` (reference ``data.py:124-148``).

    >>> import jax.numpy as jnp
    >>> select_topk(jnp.array([[1.1, 2.0, 3.0], [2.0, 1.0, 0.5]]), topk=2)
    Array([[0, 1, 1],
           [1, 1, 0]], dtype=int32)
    """
    if topk == 1:  # cheap argmax path, no sort
        idx = jnp.argmax(prob_tensor, axis=dim, keepdims=True)
        mask = jnp.zeros_like(prob_tensor, dtype=jnp.int32)
        return jnp.put_along_axis(mask, idx, 1, axis=dim, inplace=False)
    moved = jnp.moveaxis(prob_tensor, dim, -1)
    _, idx = jax.lax.top_k(moved, topk)
    mask = jnp.zeros_like(moved, dtype=jnp.int32)
    mask = jnp.put_along_axis(mask, idx, 1, axis=-1, inplace=False)
    return jnp.moveaxis(mask, -1, dim)


def to_categorical(x: Array, argmax_dim: int = 1) -> Array:
    """Convert probability-like tensor to categorical labels (reference ``data.py:151-175``)."""
    return jnp.argmax(x, axis=argmax_dim)


# matmul-bincount guard rails: counts stay exact in f32 while every bin count is
# < 2^24; the (N, minlength) one-hot must stay fusable/tileable on the MXU, and
# its total element count is capped so the materialized operand cannot approach
# HBM capacity (2^27 bf16 elements = 256 MB)
_BINCOUNT_MATMUL_MAX_SIZE = 1 << 24
_BINCOUNT_MATMUL_MAX_BINS = 2048
_BINCOUNT_MATMUL_MAX_ELEMS = 1 << 27


def _bincount_matmul_ok(size: int, minlength: int) -> bool:
    if not (
        0 < size < _BINCOUNT_MATMUL_MAX_SIZE
        and minlength <= _BINCOUNT_MATMUL_MAX_BINS
        and size * minlength <= _BINCOUNT_MATMUL_MAX_ELEMS
    ):
        return False
    # the one-hot dot wins only where there's an MXU; CPU XLA runs the scatter
    # far faster than a materialized (N, bins) matmul (measured: 200-step
    # collection scan 0.8s scatter vs 19s matmul on host, and the reverse —
    # 0.52s matmul vs 8.1s scatter — on TPU v5e)
    choice = os.environ.get("METRICS_TPU_BINCOUNT", "auto").lower()
    if choice == "matmul":
        return True
    if choice == "scatter":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # backend probe failed — keep the portable path
        return False


def bincount(x: Array, minlength: int) -> Array:
    """Static-shape bincount (reference ``data.py:178-206`` ``_bincount``).

    TPU-first formulation: a bincount is ``ones @ one_hot(x)`` — one bf16
    matmul on the MXU with f32 accumulation (exact: one-hot entries are 0/1 and
    per-bin counts stay below 2^24). Scatter-add ``jnp.bincount`` serializes
    badly on TPU inside batched/vmapped programs, so it remains only as the
    fallback for huge inputs or bin counts where the one-hot would not fuse.

    >>> import jax.numpy as jnp
    >>> bincount(jnp.array([0, 2, 2, 5]), minlength=6)
    Array([1, 0, 2, 0, 0, 1], dtype=int32)
    """
    x = x.reshape(-1)
    if _bincount_matmul_ok(x.size, minlength):
        xi = x.astype(jnp.int32)
        one_hot = (xi[:, None] == jnp.arange(minlength, dtype=jnp.int32)).astype(jnp.bfloat16)
        counts = jax.lax.dot_general(
            jnp.ones((x.size,), jnp.bfloat16),
            one_hot,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return counts.astype(jnp.int32)
    return jnp.bincount(x, length=minlength).astype(jnp.int32)


def bincount_weighted(x: Array, weights: Array, minlength: int) -> Array:
    """Weighted static-shape bincount (no reference equivalent; used by calibration).

    Same MXU formulation as :func:`bincount` — ``weights @ one_hot(x)`` in f32
    (weights are floats, so the usual sum-reordering rounding applies either
    way); scatter ``segment_sum`` only for sizes where the one-hot won't fuse.
    """
    x = x.reshape(-1)
    weights = weights.reshape(-1)
    if _bincount_matmul_ok(x.size, minlength):
        # accumulate in the weights' own float dtype (f64 under jax_enable_x64
        # keeps the precision the segment-sum path had)
        acc = weights.dtype if jnp.issubdtype(weights.dtype, jnp.floating) else jnp.float32
        one_hot = (x.astype(jnp.int32)[:, None] == jnp.arange(minlength, dtype=jnp.int32)).astype(acc)
        return jax.lax.dot_general(
            weights.astype(acc),
            one_hot,
            (((0,), (0,)), ((), ())),
            preferred_element_type=acc,
        ).astype(weights.dtype)
    return jax.ops.segment_sum(weights, x, num_segments=minlength)


def _cumsum(x: Array, axis: Optional[int] = 0) -> Array:
    """Cumulative sum (reference ``data.py:209-220``); XLA's associative scan is deterministic on TPU."""
    return jnp.cumsum(x, axis=axis)


def _flexible_bincount(x: Array) -> Array:
    """Count occurrences of each unique value (reference ``data.py:223-246``).

    Data-dependent output shape — host-side / compute-boundary only, never jitted.
    """
    x = x - jnp.min(x)
    unique_x = jnp.unique(x)
    output = bincount(x, minlength=int(jnp.max(x)) + 1)
    return output[unique_x]


def interp(x: Array, xp: Array, fp: Array) -> Array:
    """One-dimensional linear interpolation (reference ``data.py:249-271``)."""
    return jnp.interp(x, xp, fp)


def allclose(tensor1: Array, tensor2: Array) -> bool:
    """Wrapper of jnp.allclose that is robust towards dtype difference (reference ``data.py:274``)."""
    if tensor1.dtype != tensor2.dtype:
        tensor2 = tensor2.astype(tensor1.dtype)
    return bool(jnp.allclose(tensor1, tensor2))
