"""Input-validation helpers.

Parity with reference ``torchmetrics/utilities/checks.py`` (``_check_same_shape :38``,
retrieval checks ``:508-618``). TPU design note (SURVEY §7.1-3): validation that
branches on data *values* cannot live under ``jit``; these helpers therefore run
eagerly in the public API layer (gated by ``validate_args``) BEFORE the jitted
update kernel is entered. Shape/dtype checks are trace-safe (shapes are static).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array, core


def _is_traced(*xs) -> bool:
    """True if any input is an abstract tracer (inside jit/vmap) — skip value checks then."""
    return any(isinstance(x, core.Tracer) for x in xs)


def _check_same_shape(preds: Array, target: Array) -> None:
    """Check that predictions and target have the same shape, else raise (reference ``checks.py:38``)."""
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, but got {preds.shape} and {target.shape}."
        )


def _check_retrieval_shape(indexes: Array, preds: Array, target: Array) -> None:
    """Check retrieval input shapes match (reference ``checks.py:508``)."""
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise IndexError("`indexes`, `preds` and `target` must be of the same shape")


def _check_retrieval_inputs(
    indexes: Array,
    preds: Array,
    target: Array,
    allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    """Check ``indexes``, ``preds`` and ``target`` for retrieval metrics (reference ``checks.py:508-575``).

    Flattens all inputs; validates dtypes eagerly (never under jit).
    """
    _check_retrieval_shape(indexes, preds, target)
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of integers")
    if not (jnp.issubdtype(target.dtype, jnp.integer) or jnp.issubdtype(target.dtype, jnp.bool_)):
        raise ValueError("`target` must be a tensor of booleans or integers")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    indexes, preds, target = indexes.reshape(-1), preds.reshape(-1), target.reshape(-1)
    if ignore_index is not None:
        valid = target != ignore_index
        # dynamic filter: host-side only (retrieval states are list states, never jitted)
        import numpy as np

        mask = np.asarray(valid)
        indexes, preds, target = indexes[mask], preds[mask], target[mask]
    if not _is_traced(target) and not allow_non_binary_target and target.size:
        # one fused device predicate → one host sync (not one per bound)
        if bool((jnp.max(target) > 1) | (jnp.min(target) < 0)):
            raise ValueError("`target` must contain binary values")
    return indexes.astype(jnp.int32), preds.astype(jnp.float32), target

def _check_data_range(x: Array, lower: float, upper: float, name: str) -> None:
    """Eagerly validate value range; silently skipped under tracing."""
    if _is_traced(x):
        return
    if x.size and bool((jnp.min(x) < lower) | (jnp.max(x) > upper)):
        raise ValueError(f"Expected `{name}` to be in range [{lower}, {upper}].")


def _allclose_recursive(res1, res2, atol: float = 1e-6) -> bool:
    """Recursively check two metric results for closeness (reference ``checks.py:620-632``)."""
    import numpy as np

    if isinstance(res1, str):
        return res1 == res2
    if isinstance(res1, dict):
        return set(res1) == set(res2) and all(_allclose_recursive(res1[k], res2[k], atol) for k in res1)
    if isinstance(res1, (list, tuple)):
        return len(res1) == len(res2) and all(_allclose_recursive(a, b, atol) for a, b in zip(res1, res2))
    if isinstance(res1, (jnp.ndarray, np.ndarray, int, float, bool)):
        return bool(jnp.allclose(jnp.asarray(res1), jnp.asarray(res2), atol=atol))
    return res1 == res2


def check_forward_full_state_property(
    metric_class,
    init_args: Optional[dict] = None,
    input_args: Optional[dict] = None,
    num_update_to_compare=(10, 100, 1000),
    reps: int = 5,
) -> bool:
    """Empirically validate whether ``full_state_update=False`` is safe for a metric.

    Parity with reference ``utilities/checks.py:635-737``: runs the metric's
    ``forward`` both ways — the two-update full-state path and the single-update
    reduce-state path — over identical inputs, compares every batch value and the
    final ``compute``, and (when both agree) times the two variants. Returns
    ``True`` when ``full_state_update=False`` is both correct and not slower
    (the reference prints a recommendation; here the recommendation is also the
    return value so tests can assert on it).

    TPU note: both paths run eagerly through the shared jit-cached update, so the
    timing comparison reflects the number of compiled-update launches per
    ``forward`` (2 for full, 1+merge for reduce), which is the quantity that
    matters on an accelerator with nontrivial dispatch latency.
    """
    from time import perf_counter

    import jax

    init_args = init_args or {}
    input_args = input_args or {}

    class _FullState(metric_class):
        full_state_update = True

    class _PartState(metric_class):
        full_state_update = False

    fullstate = _FullState(**init_args)
    partstate = _PartState(**init_args)

    equal = True
    try:  # a failure here means update depends on the accumulated global state
        for _ in range(num_update_to_compare[0]):
            equal = equal and _allclose_recursive(fullstate(**input_args), partstate(**input_args))
        res1 = fullstate.compute()
        res2 = partstate.compute()
        equal = equal and _allclose_recursive(res1, res2)
    except (RuntimeError, ValueError, TypeError):
        # covers jax runtime failures too: XlaRuntimeError subclasses RuntimeError and
        # ConcretizationTypeError subclasses TypeError. Anything else (AttributeError,
        # KeyError, …) is a genuine metric bug and should propagate with its traceback.
        equal = False

    if not equal:
        print("Recommended setting `full_state_update=True`")
        return False

    timings = [[0.0] * len(num_update_to_compare) for _ in range(2)]
    for i, metric in enumerate((fullstate, partstate)):
        for j, steps in enumerate(num_update_to_compare):
            best = float("inf")
            for _ in range(reps):
                metric.reset()
                start = perf_counter()
                for _ in range(steps):
                    out = metric(**input_args)
                jax.block_until_ready(out)
                best = min(best, perf_counter() - start)
            timings[i][j] = best

    for j, steps in enumerate(num_update_to_compare):
        print(f"Full state for {steps} steps took: {timings[0][j]:0.4f}s")
        print(f"Partial state for {steps} steps took: {timings[1][j]:0.4f}s")

    faster = timings[1][-1] < timings[0][-1]
    print(f"Recommended setting `full_state_update={not faster}`")
    return faster
