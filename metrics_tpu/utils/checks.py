"""Input-validation helpers.

Parity with reference ``torchmetrics/utilities/checks.py`` (``_check_same_shape :38``,
retrieval checks ``:508-618``). TPU design note (SURVEY §7.1-3): validation that
branches on data *values* cannot live under ``jit``; these helpers therefore run
eagerly in the public API layer (gated by ``validate_args``) BEFORE the jitted
update kernel is entered. Shape/dtype checks are trace-safe (shapes are static).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array, core


def _is_traced(*xs) -> bool:
    """True if any input is an abstract tracer (inside jit/vmap) — skip value checks then."""
    return any(isinstance(x, core.Tracer) for x in xs)


def _check_same_shape(preds: Array, target: Array) -> None:
    """Check that predictions and target have the same shape, else raise (reference ``checks.py:38``)."""
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, but got {preds.shape} and {target.shape}."
        )


def _check_retrieval_shape(indexes: Array, preds: Array, target: Array) -> None:
    """Check retrieval input shapes match (reference ``checks.py:508``)."""
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise IndexError("`indexes`, `preds` and `target` must be of the same shape")


def _check_retrieval_inputs(
    indexes: Array,
    preds: Array,
    target: Array,
    allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    """Check ``indexes``, ``preds`` and ``target`` for retrieval metrics (reference ``checks.py:508-575``).

    Flattens all inputs; validates dtypes eagerly (never under jit).
    """
    _check_retrieval_shape(indexes, preds, target)
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of integers")
    if not (jnp.issubdtype(target.dtype, jnp.integer) or jnp.issubdtype(target.dtype, jnp.bool_)):
        raise ValueError("`target` must be a tensor of booleans or integers")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    indexes, preds, target = indexes.reshape(-1), preds.reshape(-1), target.reshape(-1)
    if ignore_index is not None:
        valid = target != ignore_index
        # dynamic filter: host-side only (retrieval states are list states, never jitted)
        import numpy as np

        mask = np.asarray(valid)
        indexes, preds, target = indexes[mask], preds[mask], target[mask]
    if not _is_traced(target) and not allow_non_binary_target and target.size:
        # one fused device predicate → one host sync (not one per bound)
        if bool((jnp.max(target) > 1) | (jnp.min(target) < 0)):
            raise ValueError("`target` must contain binary values")
    return indexes.astype(jnp.int32), preds.astype(jnp.float32), target

def _check_data_range(x: Array, lower: float, upper: float, name: str) -> None:
    """Eagerly validate value range; silently skipped under tracing."""
    if _is_traced(x):
        return
    if x.size and bool((jnp.min(x) < lower) | (jnp.max(x) > upper)):
        raise ValueError(f"Expected `{name}` to be in range [{lower}, {upper}].")
