"""Numeric helper kernels.

Parity with reference ``torchmetrics/utilities/compute.py`` (``_safe_matmul :21``,
``_safe_xlogy :32``, ``_safe_divide :47``, ``_adjust_weights_safe_divide :71``,
``_auc_compute :101-138``, ``interp :157``, ``normalize_logits_if_needed :190``).
All are branch-free jnp formulations safe under ``jit`` — the reference's in-place
masking becomes ``jnp.where``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array


def _safe_matmul(x: Array, y: Array) -> Array:
    """Matmul; on TPU there is no fp16 overflow cliff to work around (reference ``compute.py:21``)."""
    return x @ y.T


def _safe_xlogy(x: Array, y: Array) -> Array:
    """x * log(y) with 0*log(0) := 0 (reference ``compute.py:32``)."""
    res = jax.scipy.special.xlogy(x, y)
    return res


def _safe_log(x: Array) -> Array:
    """log with log(0) clamped to a large negative finite value instead of -inf."""
    return jnp.log(jnp.clip(x, a_min=jnp.finfo(jnp.result_type(x, jnp.float32)).tiny))


def _safe_divide(num: Array, denom: Array, zero_division: float = 0.0) -> Array:
    """Element-wise division, 0 (or ``zero_division``) where denominator is 0 (reference ``compute.py:47``).

    >>> import jax.numpy as jnp
    >>> _safe_divide(jnp.array([1.0, 2.0]), jnp.array([2.0, 0.0]))
    Array([0.5, 0. ], dtype=float32)
    """
    num = num if jnp.issubdtype(jnp.asarray(num).dtype, jnp.floating) else jnp.asarray(num, jnp.float32)
    denom = denom if jnp.issubdtype(jnp.asarray(denom).dtype, jnp.floating) else jnp.asarray(denom, jnp.float32)
    zero_mask = denom == 0
    safe_denom = jnp.where(zero_mask, 1.0, denom)
    return jnp.where(zero_mask, jnp.asarray(zero_division, dtype=safe_denom.dtype), num / safe_denom)


def _adjust_weights_safe_divide(
    score: Array, average: Optional[str], multilabel: bool, tp: Array, fp: Array, fn: Array, top_k: int = 1
) -> Array:
    """Apply micro/macro/weighted averaging to per-class scores (reference ``compute.py:71-98``)."""
    if average is None or average == "none":
        return score
    if average == "weighted":
        weights = tp + fn
    else:
        weights = jnp.ones_like(score)
        if not multilabel:
            present = ((tp + fp + fn) > 0) if top_k == 1 else ((tp + fn) > 0)
            weights = weights * present
    return _safe_divide(weights * score, jnp.sum(weights, axis=-1, keepdims=True)).sum(-1)


def _auc_compute_without_check(x: Array, y: Array, direction: float, axis: int = -1) -> Array:
    """Trapezoidal AUC given monotone x (reference ``compute.py:118-137``)."""
    dx = jnp.diff(x, axis=axis)
    y_avg = (jax.lax.slice_in_dim(y, 1, None, axis=axis) + jax.lax.slice_in_dim(y, 0, -1, axis=axis)) / 2.0
    return jnp.sum(dx * y_avg, axis=axis) * direction


def _auc_compute(x: Array, y: Array, reorder: bool = False) -> Array:
    """Trapezoidal AUC with optional reorder by x (reference ``compute.py:101-116``).

    The reference raises on non-monotone x; under jit we cannot branch on data, so the
    direction is derived from the sign of the total x-span (matching behavior for
    monotone inputs, which is the library-internal contract).
    """
    if reorder:
        order = jnp.argsort(x)
        x, y = x[order], y[order]
    direction = jnp.where(x[-1] >= x[0], 1.0, -1.0)
    return _auc_compute_without_check(x, y, 1.0) * direction


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Area under the curve using the trapezoidal rule (public functional; reference ``functional/classification/auc``)."""
    return _auc_compute(x, y, reorder=reorder)


def interp(x: Array, xp: Array, fp: Array) -> Array:
    """One-dimensional linear interpolation (reference ``compute.py:157-187``)."""
    return jnp.interp(x, xp, fp)


def normalize_logits_if_needed(tensor: Array, normalization: str) -> Array:
    """Sigmoid/softmax the input iff values fall outside [0,1] (reference ``compute.py:190-229``).

    The reference's data-dependent Python branch becomes a ``jnp.where`` on a traced
    predicate so the op stays inside one XLA program (no host sync).

    >>> import jax.numpy as jnp
    >>> normalize_logits_if_needed(jnp.array([0.1, 0.5, 0.9]), "sigmoid")
    Array([0.1, 0.5, 0.9], dtype=float32)
    """
    if normalization not in ("sigmoid", "softmax"):
        raise ValueError(f"Unknown normalization: {normalization}")
    out_of_bounds = jnp.logical_or(jnp.min(tensor) < 0, jnp.max(tensor) > 1)
    if normalization == "sigmoid":
        normed = jax.nn.sigmoid(tensor)
    else:
        normed = jax.nn.softmax(tensor, axis=-1)
    return jnp.where(out_of_bounds, normed, tensor)
