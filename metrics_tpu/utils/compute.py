"""Numeric helper kernels.

Parity with reference ``torchmetrics/utilities/compute.py`` (``_safe_matmul :21``,
``_safe_xlogy :32``, ``_safe_divide :47``, ``_adjust_weights_safe_divide :71``,
``_auc_compute :101-138``, ``interp :157``, ``normalize_logits_if_needed :190``).
All are branch-free jnp formulations safe under ``jit`` — the reference's in-place
masking becomes ``jnp.where``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array


def _safe_matmul(x: Array, y: Array) -> Array:
    """Matmul; on TPU there is no fp16 overflow cliff to work around (reference ``compute.py:21``)."""
    return x @ y.T


def _safe_xlogy(x: Array, y: Array) -> Array:
    """x * log(y) with 0*log(0) := 0 (reference ``compute.py:32``)."""
    res = jax.scipy.special.xlogy(x, y)
    return res


def _safe_log(x: Array) -> Array:
    """log with log(0) clamped to a large negative finite value instead of -inf."""
    return jnp.log(jnp.clip(x, a_min=jnp.finfo(jnp.result_type(x, jnp.float32)).tiny))


def count_dtype() -> jnp.dtype:
    """Widest *available* integer dtype for long-horizon counters.

    ``canonicalize_dtype(int64)``: int64 when ``jax_enable_x64`` is on, int32
    otherwise. Under the default x32 regime this is bit-identical to a pinned
    ``jnp.int32`` (same avals — donation/AOT signatures unchanged); flipping
    x64 widens every counter that uses it past the 2^31 wrap in one move.
    """
    return jax.dtypes.canonicalize_dtype(jnp.int64)


def acc_dtype() -> jnp.dtype:
    """Widest available float dtype for long-horizon accumulators (x64-aware twin of :func:`count_dtype`)."""
    return jax.dtypes.canonicalize_dtype(jnp.float64)


def neumaier_add(total: Array, comp: Array, value: Array) -> tuple:
    """One Neumaier (improved-Kahan) compensated accumulation step.

    Returns the new ``(total, comp)`` pair; the exact running sum is
    ``total + comp`` (fold via :func:`neumaier_value` at read-out). Unlike
    classic Kahan this stays correct when ``|value| > |total|``, so it is safe
    for adversarial orderings. Both branches of the ``where`` are finite, so
    the step is jit- and grad-safe.
    """
    t = total + value
    comp = comp + jnp.where(jnp.abs(total) >= jnp.abs(value), (total - t) + value, (value - t) + total)
    return t, comp


def neumaier_value(total: Array, comp: Array) -> Array:
    """Read-out of a compensated pair: the corrected sum ``total + comp``."""
    return total + comp


def _safe_divide(num: Array, denom: Array, zero_division: float = 0.0) -> Array:
    """Element-wise division with pinned zero-denominator semantics (reference ``compute.py:47``).

    Contract (identical under eager, ``jit``, and x64 — pinned by
    ``tests/test_safe_divide_contract.py``):

    * ``x / 0 -> zero_division`` (default ``0.0``) for every ``x``, including
      ``0 / 0`` — never ``nan``/``inf`` from a zero denominator;
    * the masked lane divides by 1, so gradients through it stay finite;
    * dtype is ``result_type(num, denom, float32)`` — float32 for integer or
      float32 inputs under x32, float64 once either side is a 64-bit type
      under x64 (integers are never truncated through float32).

    >>> import jax.numpy as jnp
    >>> _safe_divide(jnp.array([1.0, 2.0]), jnp.array([2.0, 0.0]))
    Array([0.5, 0. ], dtype=float32)
    """
    num = jnp.asarray(num)
    denom = jnp.asarray(denom)

    def _as_float(dt: jnp.dtype) -> jnp.dtype:
        # JAX's lattice promotes i64 & f32 -> f32, which would silently round
        # 64-bit counters; widen integer inputs to their natural float first.
        # 64-bit integers only exist under x64, where float64 is available.
        if jnp.issubdtype(dt, jnp.integer) or jnp.issubdtype(dt, jnp.bool_):
            return jnp.dtype(jnp.float64) if jnp.dtype(dt).itemsize >= 8 else jnp.dtype(jnp.float32)
        return jnp.dtype(dt)

    dtype = jnp.result_type(_as_float(num.dtype), _as_float(denom.dtype), jnp.float32)
    num = num.astype(dtype)
    denom = denom.astype(dtype)
    zero_mask = denom == 0
    safe_denom = jnp.where(zero_mask, jnp.ones((), dtype), denom)
    return jnp.where(zero_mask, jnp.asarray(zero_division, dtype=dtype), num / safe_denom)


def _adjust_weights_safe_divide(
    score: Array, average: Optional[str], multilabel: bool, tp: Array, fp: Array, fn: Array, top_k: int = 1
) -> Array:
    """Apply micro/macro/weighted averaging to per-class scores (reference ``compute.py:71-98``)."""
    if average is None or average == "none":
        return score
    if average == "weighted":
        weights = tp + fn
    else:
        weights = jnp.ones_like(score)
        if not multilabel:
            present = ((tp + fp + fn) > 0) if top_k == 1 else ((tp + fn) > 0)
            weights = weights * present
    return _safe_divide(weights * score, jnp.sum(weights, axis=-1, keepdims=True)).sum(-1)


def _auc_compute_without_check(x: Array, y: Array, direction: float, axis: int = -1) -> Array:
    """Trapezoidal AUC given monotone x (reference ``compute.py:118-137``)."""
    dx = jnp.diff(x, axis=axis)
    y_avg = (jax.lax.slice_in_dim(y, 1, None, axis=axis) + jax.lax.slice_in_dim(y, 0, -1, axis=axis)) / 2.0
    return jnp.sum(dx * y_avg, axis=axis) * direction


def _auc_compute(x: Array, y: Array, reorder: bool = False) -> Array:
    """Trapezoidal AUC with optional reorder by x (reference ``compute.py:101-116``).

    The reference raises on non-monotone x; under jit we cannot branch on data, so the
    direction is derived from the sign of the total x-span (matching behavior for
    monotone inputs, which is the library-internal contract).
    """
    if reorder:
        order = jnp.argsort(x)
        x, y = x[order], y[order]
    direction = jnp.where(x[-1] >= x[0], 1.0, -1.0)
    return _auc_compute_without_check(x, y, 1.0) * direction


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Area under the curve using the trapezoidal rule (public functional; reference ``functional/classification/auc``)."""
    return _auc_compute(x, y, reorder=reorder)


def interp(x: Array, xp: Array, fp: Array) -> Array:
    """One-dimensional linear interpolation (reference ``compute.py:157-187``)."""
    return jnp.interp(x, xp, fp)


def normalize_logits_if_needed(tensor: Array, normalization: str) -> Array:
    """Sigmoid/softmax the input iff values fall outside [0,1] (reference ``compute.py:190-229``).

    The reference's data-dependent Python branch becomes a ``jnp.where`` on a traced
    predicate so the op stays inside one XLA program (no host sync).

    >>> import jax.numpy as jnp
    >>> normalize_logits_if_needed(jnp.array([0.1, 0.5, 0.9]), "sigmoid")
    Array([0.1, 0.5, 0.9], dtype=float32)
    """
    if normalization not in ("sigmoid", "softmax"):
        raise ValueError(f"Unknown normalization: {normalization}")
    out_of_bounds = jnp.logical_or(jnp.min(tensor) < 0, jnp.max(tensor) > 1)
    if normalization == "sigmoid":
        normed = jax.nn.sigmoid(tensor)
    else:
        normed = jax.nn.softmax(tensor, axis=-1)
    return jnp.where(out_of_bounds, normed, tensor)
