"""Single-rank emission helpers.

Parity with reference ``torchmetrics/utilities/prints.py:1-73`` (``rank_zero_warn/info/debug``).
TPU-native: rank is ``jax.process_index()`` (one JAX process per host) instead of
``torch.distributed.get_rank``. The probe is lazy so importing this module never
initialises a JAX backend.
"""

from __future__ import annotations

import logging
import warnings
from functools import partial, wraps
from typing import Any, Callable

log = logging.getLogger("metrics_tpu")


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # backend not initialised / single process
        return 0


def rank_zero_only(fn: Callable) -> Callable:
    """Run ``fn`` only on process 0 of a multi-host setup."""

    @wraps(fn)
    def wrapped_fn(*args: Any, **kwargs: Any) -> Any:
        if _process_index() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped_fn


@rank_zero_only
def rank_zero_warn(message: str, category: type = UserWarning, stacklevel: int = 3, **kwargs: Any) -> None:
    warnings.warn(message, category=category, stacklevel=stacklevel, **kwargs)


@rank_zero_only
def rank_zero_info(message: str, **kwargs: Any) -> None:
    log.info(message, **kwargs)


@rank_zero_only
def rank_zero_debug(message: str, **kwargs: Any) -> None:
    log.debug(message, **kwargs)


_future_warning = partial(warnings.warn, category=FutureWarning)
