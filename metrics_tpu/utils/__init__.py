"""Utility layer (L0): reductions, kernels, checks, enums.

Parity with reference ``torchmetrics/utilities/`` (SURVEY §2.3).
"""

from metrics_tpu.utils import enums, imports, plot  # noqa: F401  (submodule surface parity)
from metrics_tpu.utils.checks import _check_same_shape, check_forward_full_state_property
from metrics_tpu.utils.compute import _safe_divide, _safe_xlogy, auc, interp
from metrics_tpu.utils.distributed import class_reduce, reduce
from metrics_tpu.utils.data import (
    bincount,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
    select_topk,
    to_categorical,
    to_onehot,
)
from metrics_tpu.utils.exceptions import TPUMetricsUserError, TPUMetricsUserWarning
from metrics_tpu.utils.prints import rank_zero_debug, rank_zero_info, rank_zero_warn

__all__ = [
    "reduce",
    "class_reduce",
    "TPUMetricsUserError",
    "TPUMetricsUserWarning",
    "_check_same_shape",
    "_safe_divide",
    "_safe_xlogy",
    "auc",
    "bincount",
    "check_forward_full_state_property",
    "dim_zero_cat",
    "dim_zero_max",
    "dim_zero_mean",
    "dim_zero_min",
    "dim_zero_sum",
    "enums",
    "imports",
    "interp",
    "plot",
    "rank_zero_debug",
    "rank_zero_info",
    "rank_zero_warn",
    "select_topk",
    "to_categorical",
    "to_onehot",
]
