"""String enums used across the framework.

Parity with reference ``torchmetrics/utilities/enums.py:19-153`` (EnumStr, DataType,
AverageMethod, MDMCAverageMethod, ClassificationTask and variants). Pure Python —
identical semantics are fine on TPU since enums are static config, never traced.
"""

from __future__ import annotations

from enum import Enum
from typing import Optional, Union


class EnumStr(str, Enum):
    """Type of any enumerator with allowed comparison to string invariant to cases.

    >>> ClassificationTask.from_str("Binary") == ClassificationTask.BINARY
    True
    """

    @staticmethod
    def _name() -> str:
        return "Task"

    @classmethod
    def from_str(cls, value: str, source: str = "Key") -> "EnumStr":
        try:
            return cls[value.replace("-", "_").upper()]
        except KeyError as err:
            _allowed = [m.lower() for m in cls._member_names_]
            raise ValueError(f"Invalid {cls._name()}: expected one of {_allowed}, but got {value}.") from err

    @classmethod
    def try_from_str(cls, value: str, source: str = "Key") -> Optional["EnumStr"]:
        try:
            return cls.from_str(value, source)
        except ValueError:
            return None

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Enum):
            other = other.value
        return self.value.lower() == str(other).lower()

    def __hash__(self) -> int:
        return hash(self.value.lower())


class DataType(EnumStr):
    """Enum to represent data type of inputs (reference ``enums.py:55``)."""

    @staticmethod
    def _name() -> str:
        return "Data type"

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"


class AverageMethod(EnumStr):
    """Enum to represent average method (reference ``enums.py:73``)."""

    @staticmethod
    def _name() -> str:
        return "Average method"

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    """Enum to represent multi-dim multi-class average method."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"


class ClassificationTask(EnumStr):
    """Enum to represent the different tasks in classification metrics (reference ``enums.py:107``).

    >>> "binary" in list(ClassificationTask)
    True
    """

    @staticmethod
    def _name() -> str:
        return "Classification"

    BINARY = "binary"
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoBinary(EnumStr):
    """Classification tasks excluding binary (reference ``enums.py:124``)."""

    @staticmethod
    def _name() -> str:
        return "Classification"

    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoMultilabel(EnumStr):
    """Classification tasks excluding multilabel (reference ``enums.py:140``)."""

    @staticmethod
    def _name() -> str:
        return "Classification"

    BINARY = "binary"
    MULTICLASS = "multiclass"
