"""Framework exceptions.

Capability parity with reference ``torchmetrics/utilities/exceptions.py:1-21``
(TorchMetricsUserError / TorchMetricsUserWarning), renamed for this framework.
"""

from __future__ import annotations


class TPUMetricsUserError(Exception):
    """Error raised when user-facing API contracts are violated."""


class TPUMetricsUserWarning(UserWarning):
    """Warning for recoverable user-facing issues (e.g. degraded precision paths)."""
