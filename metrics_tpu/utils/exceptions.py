"""Framework exceptions.

Capability parity with reference ``torchmetrics/utilities/exceptions.py:1-21``
(TorchMetricsUserError / TorchMetricsUserWarning), renamed for this framework.
"""

from __future__ import annotations


class TPUMetricsUserError(Exception):
    """Error raised when user-facing API contracts are violated."""


class TPUMetricsUserWarning(UserWarning):
    """Warning for recoverable user-facing issues (e.g. degraded precision paths)."""


class TraceIneligibleError(RuntimeError):
    """A kernel refused to run under tracing (data-dependent shapes or host math).

    Raised by ``_is_traced`` guards in functional kernels whose reference
    semantics cannot be expressed as a fixed-shape jaxpr (class-count
    inference, data-dependent slicing, host-side group partitioning).
    ``Metric._wrapped_update`` treats it like a tracer error: the metric
    latches eager mode and re-runs the update outside ``jax.jit``.
    """
