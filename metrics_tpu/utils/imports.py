"""Optional-dependency capability flags.

Parity with reference ``torchmetrics/utilities/imports.py:22-66`` (RequirementCache
gates). Here the flag system gates host-side optional features (matplotlib plotting,
transformers-backed text metrics, scipy test oracles); the TPU compute path has no
optional native deps — everything is jnp/Pallas in-tree.
"""

from __future__ import annotations

import importlib.util


def _package_available(name: str) -> bool:
    try:
        return importlib.util.find_spec(name) is not None
    except (ModuleNotFoundError, ValueError):
        return False


_MATPLOTLIB_AVAILABLE = _package_available("matplotlib")
_SCIPY_AVAILABLE = _package_available("scipy")
_TRANSFORMERS_AVAILABLE = _package_available("transformers")
_SKLEARN_AVAILABLE = _package_available("sklearn")
_REGEX_AVAILABLE = _package_available("regex")
_NLTK_AVAILABLE = _package_available("nltk")
_IPADIC_AVAILABLE = _package_available("ipadic")
_MECAB_AVAILABLE = _package_available("MeCab")
_SENTENCEPIECE_AVAILABLE = _package_available("sentencepiece")
_LIBROSA_AVAILABLE = _package_available("librosa")
_ONNXRUNTIME_AVAILABLE = _package_available("onnxruntime")
_GAMMATONE_AVAILABLE = _package_available("gammatone")
_PESQ_AVAILABLE = _package_available("pesq")
_PYSTOI_AVAILABLE = _package_available("pystoi")
