"""Accelerator-backend liveness probing and CPU fallback.

A TPU tunnel can wedge so that backend initialization blocks forever.  Any entry
point that must always make progress (benchmarks, driver dry-runs) probes the
default backend in a *separate, killable* process first; if the probe hangs or
fails — or the live backend has fewer devices than the caller needs — the current
process is pinned to the CPU platform, optionally with
``--xla_force_host_platform_device_count=N`` so multi-device sharding code still
exercises a real N-device mesh.

Must be called BEFORE the first JAX backend initialization in this process
(importing :mod:`jax` or :mod:`metrics_tpu` is fine; running a computation is not).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time

__all__ = ["ensure_backend"]

_PROBE_SRC = "import jax; print(len(jax.devices()), flush=True)"

# Device count reported by the one-per-process probe (None = not probed yet).
# A wedged tunnel stays wedged; re-probing would just re-pay the timeout.
_probe_result: "int | None" = None


def _probe_default_backend(timeout_s: float) -> int:
    """Initialize the default backend in a subprocess; return its device count.

    Returns ``-1`` if the probe crashed or had to be killed (wedged backend).
    The subprocess runs in its own session so the whole process group can be
    SIGKILLed without leaving a half-initialized client holding the tunnel.
    """
    with tempfile.TemporaryFile() as out, tempfile.TemporaryFile() as err:
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE_SRC],
            stdout=out,
            stderr=err,
            start_new_session=True,
        )
        deadline = time.monotonic() + timeout_s
        rc = None
        while time.monotonic() < deadline:
            rc = proc.poll()
            if rc is not None:
                break
            time.sleep(0.25)
        if rc is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            return -1
        if rc != 0:
            return -1
        out.seek(0)
        try:
            return int(out.read().split()[0])
        except (ValueError, IndexError):
            return -1


# Floor for virtual host devices when we fall back: the CPU client is created once
# per process and can never be widened afterwards, so a min_devices=1 fallback that
# provisioned a 1-wide client would silently starve a later 8-device dry-run in the
# same process. Virtual CPU devices are cheap (threads); always provision a mesh.
_VIRTUAL_DEVICE_FLOOR = 8

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def _set_host_device_count(n: int) -> None:
    """Set the host-platform device-count flag to at least ``n`` (rewriting any smaller value)."""
    flags = os.environ.get("XLA_FLAGS", "")
    parts = [f for f in flags.split() if f]
    for i, part in enumerate(parts):
        if part.startswith(_COUNT_FLAG):
            try:
                existing = int(part.split("=", 1)[1])
            except (IndexError, ValueError):
                existing = 0
            if existing >= n:
                return
            parts[i] = f"{_COUNT_FLAG}={n}"
            os.environ["XLA_FLAGS"] = " ".join(parts)
            return
    parts.append(f"{_COUNT_FLAG}={n}")
    os.environ["XLA_FLAGS"] = " ".join(parts)


def _force_cpu(min_devices: int) -> None:
    _set_host_device_count(max(min_devices, _VIRTUAL_DEVICE_FLOOR))
    import jax

    jax.config.update("jax_platforms", "cpu")


def ensure_backend(min_devices: int = 1, timeout_s: float = 60.0, quiet: bool = False) -> str:
    """Guarantee a usable JAX backend with at least ``min_devices`` devices.

    Returns ``"default"`` when the ambient backend is alive and large enough,
    else ``"cpu"`` after pinning this process to the (possibly virtualized)
    host platform.  Replaces the reference's implicit "torch.distributed is
    initialized or it isn't" probe (``/root/reference/src/torchmetrics/metric.py:47-49``)
    with an explicit liveness check suited to tunneled TPU backends.
    """
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # Caller already pinned CPU via env — but the env var alone does NOT stop a
        # wedged accelerator *plugin* from hanging during platform discovery (observed
        # with the tunneled TPU plugin); the config update below does. Apply both.
        _force_cpu(min_devices)
        return "cpu"

    # If this process already initialized a backend, honour it when possible.
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is not None and getattr(xb, "_backends", None):
        import jax

        if len(jax.devices()) >= min_devices:
            return "default"
        # Too few devices and too late to re-platform this process: widen the host
        # CPU client instead; callers reach it via ``jax.devices("cpu")``.  This
        # only helps if the CPU client itself has not been created yet — callers
        # must verify they actually got min_devices (and raise otherwise).
        _set_host_device_count(max(min_devices, _VIRTUAL_DEVICE_FLOOR))
        return "cpu"

    global _probe_result
    if _probe_result is None:
        _probe_result = _probe_default_backend(timeout_s)
    n = _probe_result
    if n >= min_devices:
        return "default"
    if not quiet:
        reason = "unreachable" if n < 0 else f"has only {n} device(s), need {min_devices}"
        print(f"# default jax backend {reason}; falling back to CPU platform", file=sys.stderr)
    _force_cpu(min_devices)
    return "cpu"
