"""Crash-consistent file writes shared by checkpointing and the lint baselines.

The contract (DESIGN §14): a reader at any instant sees either the complete old
file or the complete new file, never a truncated mix. Achieved the classic way —
write a sibling temp file, flush+fsync it, then atomically ``os.replace`` over
the destination, and fsync the directory so the rename itself is durable.
"""

from __future__ import annotations

import os
import tempfile
from typing import Iterable, Union

__all__ = ["atomic_write_bytes", "atomic_write_chunks", "atomic_write_text", "fsync_directory"]


def fsync_directory(directory: str) -> None:
    """fsync a directory so a just-completed rename inside it is durable."""
    try:
        dir_fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds: the data fsync already ran
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


def atomic_write_chunks(path: Union[str, os.PathLike], chunks: Iterable[bytes], fsync: bool = True) -> int:
    """Atomically replace ``path`` with the concatenation of ``chunks``.

    The streaming sibling of :func:`atomic_write_bytes`: chunks are written one
    by one, so a multi-part payload (checkpoint framing + per-bucket pickles)
    never has to be concatenated into one giant host buffer first. The temp
    file lives in the destination directory (``os.replace`` must not cross
    filesystems) and is unlinked on any failure, so a crashed writer never
    leaves a partial file under the real name. Returns the bytes written.
    """
    path = os.fspath(path)
    directory = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".", suffix=".tmp", dir=directory)
    written = 0
    try:
        with os.fdopen(fd, "wb") as fh:
            for chunk in chunks:
                fh.write(chunk)
                written += len(chunk)
            fh.flush()
            if fsync:
                os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if fsync:
        fsync_directory(directory)
    return written


def atomic_write_bytes(path: Union[str, os.PathLike], payload: bytes, fsync: bool = True) -> None:
    """Atomically replace ``path`` with ``payload`` (one-chunk convenience)."""
    atomic_write_chunks(path, (payload,), fsync=fsync)


def atomic_write_text(path: Union[str, os.PathLike], text: str, fsync: bool = True) -> None:
    atomic_write_bytes(path, text.encode("utf-8"), fsync=fsync)
