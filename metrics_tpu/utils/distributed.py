"""Reduction helpers + the multihost gather surface (reference ``utilities/distributed.py``).

The legacy public reducers ``reduce``/``class_reduce`` (``distributed.py:22-88``)
re-expressed over jnp, and the eager cross-process gather re-exported from the
mesh-native comm layer (:mod:`metrics_tpu.parallel.sync`) so user code porting
from the reference finds the same import surface.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.parallel.sync import gather_all_states  # noqa: F401  (re-export)

__all__ = ["class_reduce", "gather_all_states", "reduce"]


def reduce(x: Array, reduction: Optional[str]) -> Array:
    """Reduce a tensor by name: ``elementwise_mean`` | ``sum`` | ``none`` (reference ``distributed.py:22-42``).

    >>> import jax.numpy as jnp
    >>> reduce(jnp.asarray([1.0, 2.0, 3.0]), "sum")
    Array(6., dtype=float32)
    """
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "none" or reduction is None:
        return x
    if reduction == "sum":
        return jnp.sum(x)
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: Optional[str] = "none") -> Array:
    """Reduce per-class fractions ``num / denom`` (reference ``distributed.py:45-88``).

    ``micro`` divides the totals, ``macro`` means the per-class fractions,
    ``weighted`` weights them by ``weights``; 0/0 classes contribute 0.

    >>> import jax.numpy as jnp
    >>> tps = jnp.asarray([1.0, 2.0, 0.0])
    >>> sup = jnp.asarray([2.0, 2.0, 0.0])
    >>> class_reduce(tps, sup, sup, "macro")
    Array(0.5, dtype=float32)
    """
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    if class_reduction == "micro":
        fraction = jnp.sum(num) / jnp.sum(denom)
    else:
        fraction = num / denom
    fraction = jnp.where(jnp.isnan(fraction), 0.0, fraction)  # 0/0 → 0; x/0 keeps ±inf like the reference
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights.astype(fraction.dtype) / jnp.sum(weights)))
    if class_reduction == "none" or class_reduction is None:
        return fraction
    raise ValueError(
        f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}"
    )
