"""Modular detection metrics (reference ``torchmetrics/detection/__init__.py``)."""

from metrics_tpu.detection.iou_metrics import (
    CompleteIntersectionOverUnion,
    DistanceIntersectionOverUnion,
    GeneralizedIntersectionOverUnion,
    IntersectionOverUnion,
)
from metrics_tpu.detection.mean_ap import MeanAveragePrecision
from metrics_tpu.detection.panoptic_quality import ModifiedPanopticQuality, PanopticQuality

__all__ = [
    "CompleteIntersectionOverUnion",
    "DistanceIntersectionOverUnion",
    "GeneralizedIntersectionOverUnion",
    "IntersectionOverUnion",
    "MeanAveragePrecision",
    "ModifiedPanopticQuality",
    "PanopticQuality",
]
