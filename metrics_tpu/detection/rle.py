"""COCO run-length-encoded (RLE) binary-mask codec, host-side numpy.

Implements the public COCO mask format from its specification (column-major
run lengths, alternating background/foreground, with the LEB128-style string
compression used for JSON transport). The reference reaches this functionality
through pycocotools' C extension (``/root/reference/src/torchmetrics/detection/
mean_ap.py:30-45``, ``_mean_ap.py:131-146``); here the codec is pure numpy.
Mask IoU for same-resolution unit groups runs on device as a batched matmul
(:func:`metrics_tpu.functional.detection.map_matching.batched_mask_iou`, wired
in ``MeanAveragePrecision._unit_ious``) — the TPU-native replacement for
pycocotools' run-intersection loops; :func:`rle_iou` below is the host fallback
used for small groups.

An RLE object is ``{"size": [h, w], "counts": bytes | list[int]}``:
``bytes`` = compressed string form, ``list`` = uncompressed run lengths.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Union

import numpy as np

__all__ = [
    "mask_to_rle",
    "rle_to_mask",
    "rle_area",
    "rle_iou",
    "compress_counts",
    "decompress_counts",
]

RLE = Dict[str, Union[bytes, List[int], Sequence[int]]]


def _runs_from_mask(mask: np.ndarray) -> np.ndarray:
    """Column-major run lengths, first run counting zeros (possibly length 0)."""
    flat = np.asarray(mask, dtype=np.uint8).flatten(order="F")
    if flat.size == 0:
        return np.zeros(0, dtype=np.int64)
    change = np.nonzero(np.diff(flat))[0] + 1
    boundaries = np.concatenate([[0], change, [flat.size]])
    runs = np.diff(boundaries)
    if flat[0] == 1:  # counts must start with a zero-run
        runs = np.concatenate([[0], runs])
    return runs.astype(np.int64)


def _native():
    from metrics_tpu.native import load_rle_codec

    return load_rle_codec()


def compress_counts(counts: Sequence[int]) -> bytes:
    """Encode run lengths into the COCO compressed string form.

    Each value (delta-coded against the count two positions back, from index 3
    on; the first three counts are absolute) is written as little-endian 5-bit
    groups with a continuation bit, offset
    into printable ASCII by 48. Byte-level loop runs in the native codec when
    available (``metrics_tpu/native/rle_codec.cpp``), pure Python otherwise.
    """
    lib = _native()
    if lib is not None:
        import ctypes

        arr = np.ascontiguousarray(counts, dtype=np.int64)
        # worst case 13 output bytes per value: ceil(64 data bits / 5 bits-per-group)
        out = np.empty(max(13 * len(arr), 16), dtype=np.uint8)
        n = lib.rle_compress_counts(
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
            len(arr),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        )
        return out[:n].tobytes()
    out = bytearray()
    counts = list(int(c) for c in counts)
    for i, c in enumerate(counts):
        x = c - counts[i - 2] if i > 2 else c
        more = True
        while more:
            bits = x & 0x1F
            x >>= 5
            # sign-aware termination: stop when remaining bits are pure sign-extension
            more = not (x == 0 and not (bits & 0x10)) and not (x == -1 and (bits & 0x10))
            if more:
                bits |= 0x20
            out.append(bits + 48)
    return bytes(out)


def decompress_counts(data: Union[bytes, str]) -> np.ndarray:
    """Decode the COCO compressed string form back into run lengths."""
    if isinstance(data, str):
        data = data.encode("ascii")
    if data and ((data[-1] - 48) & 0x20):
        # uniform behavior across native/Python paths for corrupt input
        raise ValueError("truncated RLE counts string: final byte has the continuation bit set")
    lib = _native()
    if lib is not None and data:
        import ctypes

        buf = np.frombuffer(data, dtype=np.uint8)
        out = np.empty(len(buf), dtype=np.int64)
        n = lib.rle_decompress_counts(
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            len(buf),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        )
        if n < 0:
            raise ValueError("malformed RLE counts string: value wider than 13 5-bit groups")
        return out[:n].copy()
    counts: List[int] = []
    pos = 0
    n = len(data)
    while pos < n:
        x = 0
        k = 0
        more = True
        while more:
            if k >= 13:  # int64 maximum — same bound as the native codec
                raise ValueError("malformed RLE counts string: value wider than 13 5-bit groups")
            byte = data[pos] - 48
            if 5 * k < 64:
                x |= (byte & 0x1F) << (5 * k)
            more = bool(byte & 0x20)
            pos += 1
            k += 1
            if not more and (byte & 0x10) and 5 * k < 64:
                x |= -1 << (5 * k)  # sign-extend
        # int64 wraparound semantics, matching the native codec exactly
        x &= (1 << 64) - 1
        if x >= 1 << 63:
            x -= 1 << 64
        if len(counts) > 2:
            x += counts[-2]
        counts.append(x)
    return np.asarray(counts, dtype=np.int64)


def mask_to_rle(mask: np.ndarray, compress: bool = True) -> RLE:
    """Encode a binary mask ``(h, w)`` into an RLE object.

    >>> import numpy as np
    >>> m = np.zeros((3, 3), dtype=np.uint8); m[1, 1] = 1
    >>> rle = mask_to_rle(m, compress=False)
    >>> rle["size"], list(rle["counts"])
    ([3, 3], [4, 1, 4])
    """
    mask = np.asarray(mask)
    if mask.ndim != 2:
        raise ValueError(f"Expected a 2d mask, got shape {mask.shape}")
    runs = _runs_from_mask(mask)
    counts: Union[bytes, List[int]] = compress_counts(runs) if compress else runs.tolist()
    return {"size": [int(mask.shape[0]), int(mask.shape[1])], "counts": counts}


def _counts_of(rle: RLE) -> np.ndarray:
    counts = rle["counts"]
    if isinstance(counts, (bytes, str)):
        return decompress_counts(counts)
    return np.asarray(counts, dtype=np.int64)


def rle_to_mask(rle: RLE) -> np.ndarray:
    """Decode an RLE object back into a ``(h, w)`` uint8 mask.

    >>> import numpy as np
    >>> m = (np.arange(12).reshape(3, 4) % 3 == 0).astype(np.uint8)
    >>> bool((rle_to_mask(mask_to_rle(m)) == m).all())
    True
    """
    h, w = (int(s) for s in rle["size"])
    counts = _counts_of(rle)
    lib = _native()
    if lib is not None:
        import ctypes

        c = np.ascontiguousarray(counts, dtype=np.int64)
        flat = np.empty(h * w, dtype=np.uint8)
        rc = lib.rle_expand(
            c.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
            len(c),
            h * w,
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        )
        if rc != 0:
            raise ValueError(f"RLE counts sum to {int(counts.sum())}, expected {h * w}")
        return flat.reshape((w, h)).T  # column-major layout
    vals = np.zeros(len(counts), dtype=np.uint8)
    vals[1::2] = 1
    flat = np.repeat(vals, counts)
    if flat.size != h * w:
        raise ValueError(f"RLE counts sum to {flat.size}, expected {h * w}")
    return flat.reshape((w, h)).T  # column-major layout


def rle_area(rles: Union[RLE, Sequence[RLE]]) -> np.ndarray:
    """Foreground pixel count per RLE (sum of the odd runs); always a 1-d array."""
    if isinstance(rles, dict):
        rles = [rles]
    return np.asarray([int(_counts_of(r)[1::2].sum()) for r in rles], dtype=np.float64)


def rle_iou(dt: Sequence[RLE], gt: Sequence[RLE], iscrowd: Sequence[bool]) -> np.ndarray:
    """Pairwise mask IoU with COCO crowd semantics, decoded-dense on host.

    For the device-resident path used by MeanAveragePrecision see
    :func:`metrics_tpu.functional.detection.map_matching.batched_mask_iou`.
    """
    if len(dt) == 0 or len(gt) == 0:
        return np.zeros((len(dt), len(gt)))
    d = np.stack([rle_to_mask(r).reshape(-1) for r in dt]).astype(np.float64)
    g = np.stack([rle_to_mask(r).reshape(-1) for r in gt]).astype(np.float64)
    inter = d @ g.T
    d_area = d.sum(1)
    g_area = g.sum(1)
    union = d_area[:, None] + g_area[None, :] - inter
    crowd = np.asarray(iscrowd, dtype=bool)
    union = np.where(crowd[None, :], d_area[:, None], union)
    with np.errstate(divide="ignore", invalid="ignore"):
        out = np.where(union > 0, inter / union, 0.0)
    return out
