"""Modular box-IoU metrics (reference ``detection/iou.py``, ``giou.py``, ``diou.py``, ``ciou.py``)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.detection.iou import (
    complete_intersection_over_union,
    distance_intersection_over_union,
    generalized_intersection_over_union,
    intersection_over_union,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.compute import count_dtype


class IntersectionOverUnion(Metric):
    """IoU for object detection (reference ``detection/iou.py:30``).

    Matches each prediction to ground truths of the same label (unless
    ``respect_labels=False``) and averages the pairwise scores above threshold.

    >>> import jax.numpy as jnp
    >>> preds = [{"boxes": jnp.array([[296.55, 93.96, 314.97, 152.79]]),
    ...           "scores": jnp.array([0.236]), "labels": jnp.array([4])}]
    >>> target = [{"boxes": jnp.array([[300.00, 100.0, 315.0, 150.0]]), "labels": jnp.array([4])}]
    >>> metric = IntersectionOverUnion()
    >>> metric.update(preds, target)
    >>> round(float(metric.compute()["iou"]), 4)
    0.6898
    """

    __jit_ineligible__ = True
    is_differentiable = True
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    _iou_fn = staticmethod(intersection_over_union)
    _iou_type: str = "iou"
    _invalid_val: float = -1.0

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_threshold: Optional[float] = None,
        class_metrics: bool = False,
        respect_labels: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if box_format not in ("xyxy", "xywh", "cxcywh"):
            raise ValueError(f"Expected argument `box_format` to be one of ('xyxy', 'xywh', 'cxcywh') but got {box_format}")
        self.box_format = box_format
        self.iou_threshold = iou_threshold
        self.class_metrics = class_metrics
        self.respect_labels = respect_labels
        self.add_state("iou_sum", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=count_dtype()), dist_reduce_fx="sum")
        self._class_sums: Dict[int, List[float]] = {}

    def _to_xyxy(self, boxes: Array) -> Array:
        if self.box_format == "xyxy" or boxes.size == 0:
            return boxes
        if self.box_format == "xywh":
            return jnp.concatenate([boxes[:, :2], boxes[:, :2] + boxes[:, 2:]], axis=1)
        return jnp.concatenate([boxes[:, :2] - boxes[:, 2:] / 2, boxes[:, :2] + boxes[:, 2:] / 2], axis=1)

    def update(self, preds: Sequence[Dict[str, Array]], target: Sequence[Dict[str, Array]]) -> None:
        """Update state with per-image box dicts."""
        for p, t in zip(preds, target):
            p_boxes = self._to_xyxy(jnp.asarray(p["boxes"]).reshape(-1, 4))
            t_boxes = self._to_xyxy(jnp.asarray(t["boxes"]).reshape(-1, 4))
            if p_boxes.shape[0] == 0 or t_boxes.shape[0] == 0:
                continue
            matrix = type(self)._iou_fn(p_boxes, t_boxes, None, self._invalid_val, aggregate=False)
            if self.respect_labels:
                p_lab = np.asarray(p["labels"]).reshape(-1)
                t_lab = np.asarray(t["labels"]).reshape(-1)
                mask = p_lab[:, None] == t_lab[None, :]
                matrix = jnp.where(jnp.asarray(mask), matrix, self._invalid_val)
            if self.iou_threshold is not None:
                matrix = jnp.where(matrix >= self.iou_threshold, matrix, self._invalid_val)
            valid = matrix > self._invalid_val
            self.iou_sum = self.iou_sum + jnp.where(valid, matrix, 0.0).sum()
            self.total = self.total + valid.sum()
            if self.class_metrics:
                p_lab = np.asarray(p["labels"]).reshape(-1)
                for ci, cls in enumerate(np.unique(p_lab)):
                    sel = jnp.asarray(p_lab == cls)
                    vals = jnp.where(valid & sel[:, None], matrix, jnp.nan)
                    arr = np.asarray(vals).reshape(-1)
                    arr = arr[~np.isnan(arr)]
                    self._class_sums.setdefault(int(cls), []).extend(arr.tolist())

    def compute(self) -> Dict[str, Array]:
        """Compute metric."""
        key = self._iou_type
        out = {key: jnp.where(self.total > 0, self.iou_sum / jnp.maximum(self.total, 1), 0.0).astype(jnp.float32)}
        if self.class_metrics:
            for cls, vals in sorted(self._class_sums.items()):
                out[f"{key}/cl_{cls}"] = jnp.asarray(float(np.mean(vals)) if vals else 0.0, dtype=jnp.float32)
        return out

    def reset(self) -> None:
        """Reset per-class accumulators too."""
        super().reset()
        self._class_sums = {}


class GeneralizedIntersectionOverUnion(IntersectionOverUnion):
    """GIoU for object detection (reference ``detection/giou.py:30``)."""

    _iou_fn = staticmethod(generalized_intersection_over_union)
    _iou_type = "giou"
    plot_lower_bound = -1.0


class DistanceIntersectionOverUnion(IntersectionOverUnion):
    """DIoU for object detection (reference ``detection/diou.py:30``)."""

    _iou_fn = staticmethod(distance_intersection_over_union)
    _iou_type = "diou"
    plot_lower_bound = -1.0


class CompleteIntersectionOverUnion(IntersectionOverUnion):
    """CIoU for object detection (reference ``detection/ciou.py:30``)."""

    _iou_fn = staticmethod(complete_intersection_over_union)
    _iou_type = "ciou"
    plot_lower_bound = -1.0
