"""Panoptic Quality (reference ``detection/panoptic_qualities.py`` +
``functional/detection/_panoptic_quality_common.py``).

Segment statistics (intersection areas between (category, instance) pairs) come
from ONE flattened bincount over paired ids — the same dead-bin scatter-add pattern
as the classification confusion matrices.
"""

from __future__ import annotations

from typing import Any, Collection, Dict, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.utils.compute import count_dtype


def _panoptic_stats(
    preds: np.ndarray,
    target: np.ndarray,
    things: set,
    stuffs: set,
    modified: bool = False,
) -> Dict[int, Tuple[float, int, int, int]]:
    """Per-category (iou_sum, tp, fp, fn) for one image via paired-segment areas."""
    cats = things | stuffs
    # collapse stuff instance ids (stuff is one segment per category)
    p_cat, p_inst = preds[..., 0].copy(), preds[..., 1].copy()
    t_cat, t_inst = target[..., 0].copy(), target[..., 1].copy()
    for arr_cat, arr_inst in ((p_cat, p_inst), (t_cat, t_inst)):
        stuff_mask = np.isin(arr_cat, list(stuffs))
        arr_inst[stuff_mask] = 0

    def segments(cat, inst):
        keys = cat.astype(np.int64) * (inst.max() + 2 if inst.size else 1) + inst
        return keys

    # unique segment ids
    p_seg = (p_cat.astype(np.int64) << 32) | p_inst.astype(np.int64)
    t_seg = (t_cat.astype(np.int64) << 32) | t_inst.astype(np.int64)
    valid = np.isin(p_cat, list(cats)) | np.isin(t_cat, list(cats))

    p_ids, p_idx = np.unique(p_seg.reshape(-1), return_inverse=True)
    t_ids, t_idx = np.unique(t_seg.reshape(-1), return_inverse=True)
    pair = p_idx.astype(np.int64) * len(t_ids) + t_idx
    inter = np.bincount(pair, minlength=len(p_ids) * len(t_ids)).reshape(len(p_ids), len(t_ids))
    p_areas = inter.sum(1)
    t_areas = inter.sum(0)
    p_cats = (p_ids >> 32).astype(np.int64)
    t_cats = (t_ids >> 32).astype(np.int64)

    stats: Dict[int, list] = {c: [0.0, 0, 0, 0] for c in cats}
    matched_p = np.zeros(len(p_ids), dtype=bool)
    matched_t = np.zeros(len(t_ids), dtype=bool)
    for pi in range(len(p_ids)):
        if p_cats[pi] not in cats:
            continue
        for tj in range(len(t_ids)):
            if t_cats[tj] != p_cats[pi] or inter[pi, tj] == 0:
                continue
            union = p_areas[pi] + t_areas[tj] - inter[pi, tj]
            iou = inter[pi, tj] / union
            is_stuff = int(p_cats[pi]) in stuffs
            # modified PQ: stuff segments score their IoU without the 0.5 match rule
            if iou > 0.5 or (modified and is_stuff and iou > 0):
                c = int(p_cats[pi])
                stats[c][0] += iou
                stats[c][1] += 1
                matched_p[pi] = True
                matched_t[tj] = True
    for pi in range(len(p_ids)):
        if p_cats[pi] in cats and not matched_p[pi] and p_areas[pi] > 0:
            stats[int(p_cats[pi])][2] += 1
    for tj in range(len(t_ids)):
        if t_cats[tj] in cats and not matched_t[tj] and t_areas[tj] > 0:
            stats[int(t_cats[tj])][3] += 1
    return {c: tuple(v) for c, v in stats.items()}


class PanopticQuality(Metric):
    """Panoptic Quality for panoptic segmentation (reference ``detection/panoptic_qualities.py:36``).

    Inputs are ``(..., H, W, 2)`` arrays of (category_id, instance_id).

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> preds = jnp.asarray(np.array([[[[6, 0], [0, 0]], [[6, 0], [6, 0]]]]))
    >>> target = jnp.asarray(np.array([[[[6, 0], [0, 1]], [[6, 0], [6, 0]]]]))
    >>> pq = PanopticQuality(things={0, 6}, stuffs=set())
    >>> pq.update(preds, target)
    >>> float(pq.compute()) > 0
    True
    """

    __jit_ineligible__ = True
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        things: Collection[int],
        stuffs: Collection[int],
        allow_unknown_preds_category: bool = False,
        return_sq_and_rq: bool = False,
        return_per_class: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        things, stuffs = set(int(t) for t in things), set(int(s) for s in stuffs)
        if things & stuffs:
            raise ValueError(f"Expected arguments `things` and `stuffs` to have distinct keys, but got {things & stuffs}")
        self.things = things
        self.stuffs = stuffs
        self.allow_unknown_preds_category = allow_unknown_preds_category
        self.return_sq_and_rq = return_sq_and_rq
        self.return_per_class = return_per_class
        cats = sorted(things | stuffs)
        self._cat_index = {c: i for i, c in enumerate(cats)}
        n = len(cats)
        self.add_state("iou_sum", jnp.zeros(n), dist_reduce_fx="sum")
        self.add_state("true_positives", jnp.zeros(n, dtype=count_dtype()), dist_reduce_fx="sum")
        self.add_state("false_positives", jnp.zeros(n, dtype=count_dtype()), dist_reduce_fx="sum")
        self.add_state("false_negatives", jnp.zeros(n, dtype=count_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Update state with panoptic label maps."""
        p = np.asarray(preds)
        t = np.asarray(target)
        if p.shape != t.shape or p.shape[-1] != 2:
            raise ValueError(
                f"Expected argument `preds` and `target` to have shape (..., H, W, 2) but got {p.shape} and {t.shape}"
            )
        if not self.allow_unknown_preds_category:
            unknown = set(np.unique(p[..., 0]).tolist()) - self.things - self.stuffs
            if unknown:
                raise ValueError(f"Unknown categories found in `preds`: {unknown}")
        p2 = p.reshape(-1, *p.shape[-3:]) if p.ndim > 3 else p[None]
        t2 = t.reshape(-1, *t.shape[-3:]) if t.ndim > 3 else t[None]
        iou_sum = np.zeros(len(self._cat_index))
        tp = np.zeros(len(self._cat_index), dtype=np.int64)
        fp = np.zeros(len(self._cat_index), dtype=np.int64)
        fn = np.zeros(len(self._cat_index), dtype=np.int64)
        for img_p, img_t in zip(p2, t2):
            stats = _panoptic_stats(img_p, img_t, self.things, self.stuffs, getattr(self, '_modified', False))
            for c, (isum, tpc, fpc, fnc) in stats.items():
                i = self._cat_index[c]
                iou_sum[i] += isum
                tp[i] += tpc
                fp[i] += fpc
                fn[i] += fnc
        self.iou_sum = self.iou_sum + jnp.asarray(iou_sum)
        self.true_positives = self.true_positives + jnp.asarray(tp, dtype=jnp.int32)
        self.false_positives = self.false_positives + jnp.asarray(fp, dtype=jnp.int32)
        self.false_negatives = self.false_negatives + jnp.asarray(fn, dtype=jnp.int32)

    def compute(self) -> Array:
        """Compute metric: PQ = Σ IoU / (TP + FP/2 + FN/2), averaged over categories."""
        denom = self.true_positives + 0.5 * self.false_positives + 0.5 * self.false_negatives
        valid = denom > 0
        sq = jnp.where(self.true_positives > 0, self.iou_sum / jnp.maximum(self.true_positives, 1), 0.0)
        rq = jnp.where(valid, self.true_positives / jnp.where(valid, denom, 1.0), 0.0)
        pq = sq * rq
        pq_avg = jnp.where(valid, pq, 0.0).sum() / jnp.maximum(valid.sum(), 1)
        if self.return_per_class:
            return pq[None] if not self.return_sq_and_rq else jnp.stack([pq, sq, rq])[None]
        if self.return_sq_and_rq:
            sq_avg = jnp.where(valid, sq, 0.0).sum() / jnp.maximum(valid.sum(), 1)
            rq_avg = jnp.where(valid, rq, 0.0).sum() / jnp.maximum(valid.sum(), 1)
            return jnp.stack([pq_avg, sq_avg, rq_avg])
        return pq_avg


class ModifiedPanopticQuality(PanopticQuality):
    """Modified PQ (reference ``detection/panoptic_qualities.py`` second class):
    stuff segments score their IoU directly without the 0.5 matching threshold."""

    _modified = True
