"""Native MeanAveragePrecision — the COCO protocol without pycocotools.

Capability parity with reference ``detection/mean_ap.py:77-640`` (which shells out
to pycocotools' C / faster_coco_eval's C++ on CPU — SURVEY §3.4). The full pipeline
is reimplemented here (BASELINE config 5):

* per-image/class IoU matrices are one broadcast kernel (``functional/detection/iou``),
* greedy score-ordered matching with crowd/ignore and area-range semantics follows
  COCOeval exactly (dt→gt preference order, crowd fallbacks, unmatched-out-of-range
  detections ignored),
* accumulation builds the 101-point interpolated PR curve per (class, IoU thr,
  area range, maxDet) and reports the standard 12 COCO numbers.

States are per-image list states (``dist_reduce_fx=None`` gather semantics,
reference ``mean_ap.py:450-458``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.metric import Metric

_BBOX_AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e10),
}


def _np_box_iou(dets: np.ndarray, gts: np.ndarray, iscrowd: np.ndarray) -> np.ndarray:
    """IoU with COCO crowd semantics: for crowd gt, denominator is the det area only."""
    if len(dets) == 0 or len(gts) == 0:
        return np.zeros((len(dets), len(gts)))
    lt = np.maximum(dets[:, None, :2], gts[None, :, :2])
    rb = np.minimum(dets[:, None, 2:], gts[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    det_area = np.clip(dets[:, 2] - dets[:, 0], 0, None) * np.clip(dets[:, 3] - dets[:, 1], 0, None)
    gt_area = np.clip(gts[:, 2] - gts[:, 0], 0, None) * np.clip(gts[:, 3] - gts[:, 1], 0, None)
    union = det_area[:, None] + gt_area[None, :] - inter
    union = np.where(iscrowd[None, :], det_area[:, None], union)
    return inter / np.clip(union, 1e-9, None)


def _match_image(
    ious: np.ndarray,
    gt_ignore: np.ndarray,
    gt_crowd: np.ndarray,
    det_areas: np.ndarray,
    area_rng: Tuple[float, float],
    iou_thrs: np.ndarray,
    max_det: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """COCOeval greedy matching for one image/class: returns (dt_matched, dt_ignore), each (T, D)."""
    n_det = min(ious.shape[0], max_det)
    n_gt = ious.shape[1]
    t_n = len(iou_thrs)
    gt_order = np.argsort(gt_ignore, kind="stable")  # non-ignored gts first
    dtm = np.zeros((t_n, n_det), dtype=bool)
    dtig = np.zeros((t_n, n_det), dtype=bool)
    for ti, t in enumerate(iou_thrs):
        gtm = np.full(n_gt, -1)
        for d in range(n_det):
            iou = min(t, 1 - 1e-10)
            m = -1
            for gi in gt_order:
                if gtm[gi] >= 0 and not gt_crowd[gi]:
                    continue  # already matched, and only crowd gts may be re-matched (COCOeval)
                if m > -1 and not gt_ignore[m] and gt_ignore[gi]:
                    break  # can't do better than a non-ignored match
                if ious[d, gi] < iou:
                    continue
                iou = ious[d, gi]
                m = gi
            if m == -1:
                continue
            dtig[ti, d] = gt_ignore[m]
            dtm[ti, d] = True
            gtm[m] = d
        # unmatched detections outside the area range are ignored, not false positives
        out_of_rng = (det_areas[:n_det] < area_rng[0]) | (det_areas[:n_det] > area_rng[1])
        dtig[ti] = dtig[ti] | (~dtm[ti] & out_of_rng)
    return dtm, dtig


class MeanAveragePrecision(Metric):
    """Mean Average Precision for object detection (reference ``detection/mean_ap.py:77``).

    Accepts per-image dicts with keys ``boxes`` (xyxy), ``scores``, ``labels`` for
    predictions and ``boxes``, ``labels`` (+ optional ``iscrowd``, ``area``) for
    targets — the reference input contract (``mean_ap.py:478-520``).

    >>> import jax.numpy as jnp
    >>> preds = [{"boxes": jnp.array([[258.0, 41.0, 606.0, 285.0]]),
    ...           "scores": jnp.array([0.536]), "labels": jnp.array([0])}]
    >>> target = [{"boxes": jnp.array([[214.0, 41.0, 562.0, 285.0]]), "labels": jnp.array([0])}]
    >>> metric = MeanAveragePrecision()
    >>> metric.update(preds, target)
    >>> round(float(metric.compute()["map_50"]), 4)
    1.0
    """

    __jit_ineligible__ = True  # list-of-dict host inputs
    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: str = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        extended_summary: bool = False,
        average: str = "macro",
        backend: str = "native",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if box_format not in ("xyxy", "xywh", "cxcywh"):
            raise ValueError(f"Expected argument `box_format` to be one of ('xyxy', 'xywh', 'cxcywh') but got {box_format}")
        if iou_type not in ("bbox",):
            raise ValueError(f"Only `iou_type='bbox'` is supported natively this round, got {iou_type}")
        if average not in ("macro", "micro"):
            raise ValueError(f"Expected argument `average` to be one of ('macro', 'micro') but got {average}")
        self.box_format = box_format
        self.iou_type = iou_type
        self.iou_thresholds = iou_thresholds or np.linspace(0.5, 0.95, 10).tolist()
        self.rec_thresholds = rec_thresholds or np.linspace(0.0, 1.00, 101).tolist()
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        self.class_metrics = class_metrics
        self.extended_summary = extended_summary
        self.average = average

        self.add_state("detection_box", [], dist_reduce_fx=None)
        self.add_state("detection_score", [], dist_reduce_fx=None)
        self.add_state("detection_label", [], dist_reduce_fx=None)
        self.add_state("gt_box", [], dist_reduce_fx=None)
        self.add_state("gt_label", [], dist_reduce_fx=None)
        self.add_state("gt_crowd", [], dist_reduce_fx=None)
        self.add_state("gt_area", [], dist_reduce_fx=None)

    def _to_xyxy(self, boxes: np.ndarray) -> np.ndarray:
        if self.box_format == "xyxy" or boxes.size == 0:
            return boxes
        out = boxes.copy()
        if self.box_format == "xywh":
            out[:, 2:] = boxes[:, :2] + boxes[:, 2:]
        else:  # cxcywh
            out[:, :2] = boxes[:, :2] - boxes[:, 2:] / 2
            out[:, 2:] = boxes[:, :2] + boxes[:, 2:] / 2
        return out

    def update(self, preds: Sequence[Dict[str, Array]], target: Sequence[Dict[str, Array]]) -> None:
        """Append per-image detections/ground truths (reference ``mean_ap.py:478-520``)."""
        if len(preds) != len(target):
            raise ValueError("Expected argument `preds` and `target` to have the same length")
        for item in preds:
            for key in ("boxes", "scores", "labels"):
                if key not in item:
                    raise ValueError(f"Expected all dicts in `preds` to contain the `{key}` key")
        for item in target:
            for key in ("boxes", "labels"):
                if key not in item:
                    raise ValueError(f"Expected all dicts in `target` to contain the `{key}` key")
        for p, t in zip(preds, target):
            boxes = self._to_xyxy(np.asarray(p["boxes"], dtype=np.float64).reshape(-1, 4))
            self.detection_box.append(boxes)
            self.detection_score.append(np.asarray(p["scores"], dtype=np.float64).reshape(-1))
            self.detection_label.append(np.asarray(p["labels"]).reshape(-1))
            gt_boxes = self._to_xyxy(np.asarray(t["boxes"], dtype=np.float64).reshape(-1, 4))
            self.gt_box.append(gt_boxes)
            self.gt_label.append(np.asarray(t["labels"]).reshape(-1))
            n_gt = gt_boxes.shape[0]
            crowd = np.asarray(t.get("iscrowd", np.zeros(n_gt))).reshape(-1).astype(bool)
            self.gt_crowd.append(crowd)
            area = t.get("area")
            if area is None:
                area_arr = (gt_boxes[:, 2] - gt_boxes[:, 0]) * (gt_boxes[:, 3] - gt_boxes[:, 1])
            else:
                area_arr = np.asarray(area, dtype=np.float64).reshape(-1)
            self.gt_area.append(area_arr)

    # ------------------------------------------------------------------ evaluation core
    def _evaluate(self, average: Optional[str] = None):
        micro = (average or self.average) == "micro"
        iou_thrs = np.asarray(self.iou_thresholds)
        rec_thrs = np.asarray(self.rec_thresholds)
        max_dets = self.max_detection_thresholds
        n_imgs = len(self.detection_box)
        classes = sorted(
            set(np.concatenate([np.asarray(lbl).reshape(-1) for lbl in self.gt_label]).tolist())
            | set(np.concatenate([np.asarray(lbl).reshape(-1) for lbl in self.detection_label]).tolist())
        ) if n_imgs else []
        area_names = list(_BBOX_AREA_RANGES)
        t_n, r_n, k_n, a_n, m_n = len(iou_thrs), len(rec_thrs), len(classes), len(area_names), len(max_dets)
        precision = -np.ones((t_n, r_n, k_n, a_n, m_n))
        recall = -np.ones((t_n, k_n, a_n, m_n))
        scores_out = -np.ones((t_n, r_n, k_n, a_n, m_n))

        if micro:
            eval_classes = [None]  # pool everything into one pseudo-class
            precision = -np.ones((t_n, r_n, 1, a_n, m_n))
            recall = -np.ones((t_n, 1, a_n, m_n))
            scores_out = -np.ones((t_n, r_n, 1, a_n, m_n))
        else:
            eval_classes = classes
        for ki, cls in enumerate(eval_classes):
            # per-image det/gt for this class, dets pre-sorted by score
            per_img = []
            for i in range(n_imgs):
                if cls is None:
                    dmask = np.ones(len(np.asarray(self.detection_label[i]).reshape(-1)), dtype=bool)
                    gmask = np.ones(len(np.asarray(self.gt_label[i]).reshape(-1)), dtype=bool)
                else:
                    dmask = np.asarray(self.detection_label[i]) == cls
                    gmask = np.asarray(self.gt_label[i]) == cls
                dboxes = self.detection_box[i][dmask]
                dscores = self.detection_score[i][dmask]
                order = np.argsort(-dscores, kind="stable")
                dboxes, dscores = dboxes[order], dscores[order]
                gboxes = self.gt_box[i][gmask]
                gcrowd = self.gt_crowd[i][gmask]
                garea = self.gt_area[i][gmask]
                ious = _np_box_iou(dboxes, gboxes, gcrowd)
                det_areas = (dboxes[:, 2] - dboxes[:, 0]) * (dboxes[:, 3] - dboxes[:, 1])
                per_img.append((dscores, det_areas, gboxes, gcrowd, garea, ious))

            for ai, aname in enumerate(area_names):
                rng = _BBOX_AREA_RANGES[aname]
                for mi, max_det in enumerate(max_dets):
                    all_scores, all_tps, all_ig = [], [], []
                    npig = 0
                    for dscores, det_areas, gboxes, gcrowd, garea, ious in per_img:
                        gt_ignore = gcrowd | (garea < rng[0]) | (garea > rng[1])
                        npig += int((~gt_ignore).sum())
                        dtm, dtig = _match_image(ious, gt_ignore, gcrowd, det_areas, rng, iou_thrs, max_det)
                        n_det = dtm.shape[1]
                        all_scores.append(dscores[:n_det])
                        all_tps.append(dtm)
                        all_ig.append(dtig)
                    if npig == 0:
                        continue
                    scores_cat = np.concatenate(all_scores) if all_scores else np.zeros(0)
                    order = np.argsort(-scores_cat, kind="mergesort")
                    tps = np.concatenate(all_tps, axis=1)[:, order] if all_scores else np.zeros((t_n, 0), bool)
                    ig = np.concatenate(all_ig, axis=1)[:, order] if all_scores else np.zeros((t_n, 0), bool)
                    scores_sorted = scores_cat[order]
                    tp_c = np.cumsum(tps & ~ig, axis=1).astype(np.float64)
                    fp_c = np.cumsum(~tps & ~ig, axis=1).astype(np.float64)
                    for ti in range(t_n):
                        tp, fp = tp_c[ti], fp_c[ti]
                        rc = tp / npig
                        pr = tp / np.maximum(tp + fp, np.finfo(np.float64).eps)
                        recall[ti, ki, ai, mi] = rc[-1] if len(rc) else 0.0
                        # make precision monotonically decreasing, then sample at rec_thrs
                        pr = np.maximum.accumulate(pr[::-1])[::-1] if len(pr) else pr
                        inds = np.searchsorted(rc, rec_thrs, side="left")
                        q = np.zeros(r_n)
                        s = np.zeros(r_n)
                        valid = inds < len(pr)
                        q[valid] = pr[inds[valid]]
                        s[valid] = scores_sorted[inds[valid]]
                        precision[ti, :, ki, ai, mi] = q
                        scores_out[ti, :, ki, ai, mi] = s
        return precision, recall, scores_out, classes

    @staticmethod
    def _summarize(precision, recall, t_slice=None, area="all", max_det_idx=-1, area_names=("all", "small", "medium", "large")):
        ai = area_names.index(area)
        if precision is not None:
            p = precision[:, :, :, ai, max_det_idx]
            if t_slice is not None:
                p = p[t_slice : t_slice + 1]
            p = p[p > -1]
            return float(np.mean(p)) if p.size else -1.0
        r = recall[:, :, ai, max_det_idx]
        if t_slice is not None:
            r = r[t_slice : t_slice + 1]
        r = r[r > -1]
        return float(np.mean(r)) if r.size else -1.0

    def compute(self) -> Dict[str, Array]:
        """Run the full COCO evaluation and return the standard summary dict."""
        precision, recall, scores, classes = self._evaluate()
        md_idx = len(self.max_detection_thresholds) - 1
        iou_thrs = np.asarray(self.iou_thresholds)

        def t_idx(v):
            hits = np.where(np.isclose(iou_thrs, v))[0]
            return int(hits[0]) if len(hits) else None

        res = {"map": self._summarize(precision, None, None, "all", md_idx)}
        i50, i75 = t_idx(0.5), t_idx(0.75)
        res["map_50"] = self._summarize(precision, None, i50, "all", md_idx) if i50 is not None else -1.0
        res["map_75"] = self._summarize(precision, None, i75, "all", md_idx) if i75 is not None else -1.0
        for aname in ("small", "medium", "large"):
            res[f"map_{aname}"] = self._summarize(precision, None, None, aname, md_idx)
            res[f"mar_{aname}"] = self._summarize(None, recall, None, aname, md_idx)
        for mi, md in enumerate(self.max_detection_thresholds):
            res[f"mar_{md}"] = self._summarize(None, recall, None, "all", mi)
        res["classes"] = jnp.asarray(classes, dtype=jnp.int32)
        if self.class_metrics and len(classes):
            if self.average == "micro":
                # micro pooled everything into one pseudo-class; per-class numbers
                # need a second macro pass (reference computes per-class regardless).
                # Bind to separate names: extended_summary must keep the micro arrays.
                cls_precision, cls_recall, _, _ = self._evaluate(average="macro")
            else:
                cls_precision, cls_recall = precision, recall
            map_per_class = []
            mar_per_class = []
            for ki in range(len(classes)):
                p = cls_precision[:, :, ki, 0, md_idx]
                p = p[p > -1]
                map_per_class.append(float(np.mean(p)) if p.size else -1.0)
                r = cls_recall[:, ki, 0, md_idx]
                r = r[r > -1]
                mar_per_class.append(float(np.mean(r)) if r.size else -1.0)
            res["map_per_class"] = jnp.asarray(map_per_class, dtype=jnp.float32)
            res[f"mar_{self.max_detection_thresholds[-1]}_per_class"] = jnp.asarray(mar_per_class, dtype=jnp.float32)
        if self.extended_summary:
            res["precision"] = jnp.asarray(precision, dtype=jnp.float32)
            res["recall"] = jnp.asarray(recall, dtype=jnp.float32)
            res["scores"] = jnp.asarray(scores, dtype=jnp.float32)
        return {k: (jnp.asarray(v, dtype=jnp.float32) if not isinstance(v, jnp.ndarray) else v) for k, v in res.items()}
