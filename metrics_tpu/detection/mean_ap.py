"""Native MeanAveragePrecision — the COCO protocol with device-resident matching.

Capability parity with reference ``detection/mean_ap.py:77-640`` (which shells out
to pycocotools' C / faster_coco_eval's C++ on CPU — SURVEY §3.4), rebuilt
TPU-first (BASELINE config 5):

* evaluation units (image, class) are padded to fixed capacities and every
  pairwise IoU matrix is one broadcast kernel
  (:func:`metrics_tpu.functional.detection.map_matching.batched_box_iou`);
* greedy COCO matching for ALL units × area-ranges × IoU-thresholds runs as a
  single jitted ``lax.scan``
  (:func:`metrics_tpu.functional.detection.map_matching.match_units`) — the
  triple Python loop of pycocotools becomes one XLA program;
* accumulation (sort, cumsum, 101-point interpolation) is vectorized numpy on
  host — it is O(total detections) and sits after a device→host boundary the
  reference also has;
* ``iou_type="segm"`` stores masks as RLE (:mod:`metrics_tpu.detection.rle`)
  and computes mask IoU as dense matmuls.

States are per-image list states (``dist_reduce_fx=None`` gather semantics,
reference ``mean_ap.py:450-458``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.detection.rle import mask_to_rle, rle_area, rle_iou, rle_to_mask
from metrics_tpu.functional.detection.map_matching import (
    batched_box_iou_jit,
    batched_mask_iou,
    match_units_jit,
)
from metrics_tpu.metric import Metric

_BBOX_AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e10),
}


def _next_capacity(n: int, quantum: int = 8) -> int:
    """Round up to a shape bucket so jit reuses executables across compute calls."""
    return max(quantum, -(-n // quantum) * quantum)


class MeanAveragePrecision(Metric):
    """Mean Average Precision for object detection (reference ``detection/mean_ap.py:77``).

    Accepts per-image dicts with keys ``boxes`` (xyxy), ``scores``, ``labels`` for
    predictions and ``boxes``, ``labels`` (+ optional ``iscrowd``, ``area``) for
    targets — the reference input contract (``mean_ap.py:478-520``). With
    ``iou_type="segm"`` the dicts carry ``masks`` of shape ``(n, h, w)`` instead.

    >>> import jax.numpy as jnp
    >>> preds = [{"boxes": jnp.array([[258.0, 41.0, 606.0, 285.0]]),
    ...           "scores": jnp.array([0.536]), "labels": jnp.array([0])}]
    >>> target = [{"boxes": jnp.array([[214.0, 41.0, 562.0, 285.0]]), "labels": jnp.array([0])}]
    >>> metric = MeanAveragePrecision()
    >>> metric.update(preds, target)
    >>> round(float(metric.compute()["map_50"]), 4)
    1.0
    """

    __jit_ineligible__ = True  # list-of-dict host inputs
    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: Union[str, Tuple[str, ...]] = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        extended_summary: bool = False,
        average: str = "macro",
        backend: str = "native",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if box_format not in ("xyxy", "xywh", "cxcywh"):
            raise ValueError(f"Expected argument `box_format` to be one of ('xyxy', 'xywh', 'cxcywh') but got {box_format}")
        if isinstance(iou_type, str):
            iou_type = (iou_type,)
        for t in iou_type:
            if t not in ("bbox", "segm"):
                raise ValueError(f"Expected argument `iou_type` to be one of ('bbox', 'segm') but got {t}")
        if average not in ("macro", "micro"):
            raise ValueError(f"Expected argument `average` to be one of ('macro', 'micro') but got {average}")
        self.box_format = box_format
        self.iou_type = tuple(iou_type)
        self.iou_thresholds = iou_thresholds or np.linspace(0.5, 0.95, 10).tolist()
        self.rec_thresholds = rec_thresholds or np.linspace(0.0, 1.00, 101).tolist()
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        self.class_metrics = class_metrics
        self.extended_summary = extended_summary
        self.average = average

        self.add_state("detection_box", [], dist_reduce_fx=None)
        self.add_state("detection_score", [], dist_reduce_fx=None)
        self.add_state("detection_label", [], dist_reduce_fx=None)
        self.add_state("detection_rle", [], dist_reduce_fx=None)
        self.add_state("gt_box", [], dist_reduce_fx=None)
        self.add_state("gt_label", [], dist_reduce_fx=None)
        self.add_state("gt_crowd", [], dist_reduce_fx=None)
        self.add_state("gt_area", [], dist_reduce_fx=None)
        self.add_state("gt_rle", [], dist_reduce_fx=None)

    # ------------------------------------------------------------------ input handling
    def _to_xyxy(self, boxes: np.ndarray) -> np.ndarray:
        if self.box_format == "xyxy" or boxes.size == 0:
            return boxes
        out = boxes.copy()
        if self.box_format == "xywh":
            out[:, 2:] = boxes[:, :2] + boxes[:, 2:]
        else:  # cxcywh
            out[:, :2] = boxes[:, :2] - boxes[:, 2:] / 2
            out[:, 2:] = boxes[:, :2] + boxes[:, 2:] / 2
        return out

    @property
    def _needs_masks(self) -> bool:
        return "segm" in self.iou_type

    @property
    def _needs_boxes(self) -> bool:
        return "bbox" in self.iou_type

    def update(self, preds: Sequence[Dict[str, Array]], target: Sequence[Dict[str, Array]]) -> None:
        """Append per-image detections/ground truths (reference ``mean_ap.py:478-520``)."""
        if len(preds) != len(target):
            raise ValueError("Expected argument `preds` and `target` to have the same length")
        pred_keys = (("boxes",) if self._needs_boxes else ()) + (("masks",) if self._needs_masks else ())
        for item in preds:
            for key in pred_keys + ("scores", "labels"):
                if key not in item:
                    raise ValueError(f"Expected all dicts in `preds` to contain the `{key}` key")
        for item in target:
            for key in pred_keys + ("labels",):
                if key not in item:
                    raise ValueError(f"Expected all dicts in `target` to contain the `{key}` key")
        for p, t in zip(preds, target):
            n_det = len(np.asarray(p["labels"]).reshape(-1))
            n_gt = len(np.asarray(t["labels"]).reshape(-1))
            if self._needs_boxes:
                boxes = self._to_xyxy(np.asarray(p["boxes"], dtype=np.float64).reshape(-1, 4))
                gt_boxes = self._to_xyxy(np.asarray(t["boxes"], dtype=np.float64).reshape(-1, 4))
            else:
                boxes = np.zeros((n_det, 4))
                gt_boxes = np.zeros((n_gt, 4))
            self.detection_box.append(boxes)
            self.detection_score.append(np.asarray(p["scores"], dtype=np.float64).reshape(-1))
            self.detection_label.append(np.asarray(p["labels"]).reshape(-1))
            self.gt_box.append(gt_boxes)
            self.gt_label.append(np.asarray(t["labels"]).reshape(-1))
            if self._needs_masks:
                self.detection_rle.append([mask_to_rle(np.asarray(m)) for m in np.asarray(p["masks"])])
                self.gt_rle.append([mask_to_rle(np.asarray(m)) for m in np.asarray(t["masks"])])
            else:
                self.detection_rle.append([])
                self.gt_rle.append([])
            crowd = np.asarray(t.get("iscrowd", np.zeros(n_gt))).reshape(-1).astype(bool)
            self.gt_crowd.append(crowd)
            area = t.get("area")
            self.gt_area.append(None if area is None else np.asarray(area, dtype=np.float64).reshape(-1))

    # ------------------------------------------------------------------ evaluation core
    def _areas(self, i_type: str, img: int) -> Tuple[np.ndarray, np.ndarray]:
        """(det_areas, gt_areas) for one image under the given iou_type; explicit gt area wins."""
        if i_type == "bbox":
            db = self.detection_box[img]
            det = (db[:, 2] - db[:, 0]) * (db[:, 3] - db[:, 1]) if len(db) else np.zeros(0)
            gb = self.gt_box[img]
            gt = (gb[:, 2] - gb[:, 0]) * (gb[:, 3] - gb[:, 1]) if len(gb) else np.zeros(0)
        else:
            det = rle_area(self.detection_rle[img]) if self.detection_rle[img] else np.zeros(0)
            gt = rle_area(self.gt_rle[img]) if self.gt_rle[img] else np.zeros(0)
        if self.gt_area[img] is not None:
            gt = self.gt_area[img]
        return np.asarray(det, dtype=np.float64), np.asarray(gt, dtype=np.float64)

    def _build_units(self, i_type: str, micro: bool, classes: List[int]):
        """Materialize (image, class) evaluation units with score-sorted detections."""
        max_det_cap = max(self.max_detection_thresholds)
        units = []  # (class_idx, det_order_global, gt_idx, img)
        n_imgs = len(self.detection_box)
        eval_classes = [None] if micro else classes
        for img in range(n_imgs):
            dlab = np.asarray(self.detection_label[img]).reshape(-1)
            glab = np.asarray(self.gt_label[img]).reshape(-1)
            det_areas, gt_areas = self._areas(i_type, img)
            for ki, cls in enumerate(eval_classes):
                dmask = np.ones(len(dlab), bool) if cls is None else dlab == cls
                gmask = np.ones(len(glab), bool) if cls is None else glab == cls
                if not dmask.any() and not gmask.any():
                    continue
                didx = np.nonzero(dmask)[0]
                scores = self.detection_score[img][didx]
                order = np.argsort(-scores, kind="stable")[:max_det_cap]
                didx = didx[order]
                gidx = np.nonzero(gmask)[0]
                units.append(
                    {
                        "ki": ki,
                        "img": img,
                        "didx": didx,
                        "scores": scores[order],
                        "det_areas": det_areas[didx],
                        "gidx": gidx,
                        "gt_areas": gt_areas[gidx],
                        "gt_crowd": self.gt_crowd[img][gidx],
                    }
                )
        return units

    def _unit_ious(self, units, i_type: str, d_cap: int, g_cap: int) -> np.ndarray:
        """(U, D_cap, G_cap) padded IoU stack for one unit chunk.

        bbox: one broadcast device kernel for the whole chunk. segm: units are
        grouped by image resolution and each group's mask IoU runs as one device
        einsum over decoded masks (:func:`batched_mask_iou`) — small groups fall
        back to the host codec path to avoid compile churn.
        """
        u_n = len(units)
        if i_type == "bbox":
            db = np.zeros((u_n, d_cap, 4))
            gb = np.zeros((u_n, g_cap, 4))
            gc = np.zeros((u_n, g_cap), bool)
            for i, u in enumerate(units):
                db[i, : len(u["didx"])] = self.detection_box[u["img"]][u["didx"]]
                gb[i, : len(u["gidx"])] = self.gt_box[u["img"]][u["gidx"]]
                gc[i, : len(u["gidx"])] = u["gt_crowd"]
            # stays on device: the caller feeds this straight into the matching
            # kernel and fetches IoUs + match results with ONE device→host sync
            return batched_box_iou_jit(jnp.asarray(db), jnp.asarray(gb), jnp.asarray(gc))

        ious = np.zeros((u_n, d_cap, g_cap))
        by_shape: Dict[Tuple[int, int], List[int]] = {}
        for i, u in enumerate(units):
            if not (len(u["didx"]) and len(u["gidx"])):
                continue
            size = tuple(self.gt_rle[u["img"]][u["gidx"][0]]["size"])
            by_shape.setdefault(size, []).append(i)
        for (h, w), members in by_shape.items():
            if len(members) < 4:
                for i in members:
                    u = units[i]
                    dt = [self.detection_rle[u["img"]][j] for j in u["didx"]]
                    gt = [self.gt_rle[u["img"]][j] for j in u["gidx"]]
                    ious[i, : len(dt), : len(gt)] = rle_iou(dt, gt, u["gt_crowd"])
                continue
            p = h * w
            dm = np.zeros((len(members), d_cap, p), np.uint8)
            gm = np.zeros((len(members), g_cap, p), np.uint8)
            gc = np.zeros((len(members), g_cap), bool)
            for row, i in enumerate(members):
                u = units[i]
                for col, j in enumerate(u["didx"]):
                    dm[row, col] = rle_to_mask(self.detection_rle[u["img"]][j]).reshape(-1)
                for col, j in enumerate(u["gidx"]):
                    gm[row, col] = rle_to_mask(self.gt_rle[u["img"]][j]).reshape(-1)
                gc[row, : len(u["gidx"])] = u["gt_crowd"]
            out = np.asarray(batched_mask_iou(jnp.asarray(dm), jnp.asarray(gm), jnp.asarray(gc)))
            for row, i in enumerate(members):
                ious[i] = out[row]
        return ious

    def _evaluate(self, i_type: str, average: Optional[str] = None):
        micro = (average or self.average) == "micro"
        iou_thrs = np.asarray(self.iou_thresholds)
        rec_thrs = np.asarray(self.rec_thresholds)
        max_dets = self.max_detection_thresholds
        n_imgs = len(self.detection_box)
        classes = sorted(
            set(np.concatenate([np.asarray(lbl).reshape(-1) for lbl in self.gt_label]).tolist())
            | set(np.concatenate([np.asarray(lbl).reshape(-1) for lbl in self.detection_label]).tolist())
        ) if n_imgs else []
        area_names = list(_BBOX_AREA_RANGES)
        t_n, r_n, a_n, m_n = len(iou_thrs), len(rec_thrs), len(area_names), len(max_dets)
        k_n = 1 if micro else len(classes)
        precision = -np.ones((t_n, r_n, k_n, a_n, m_n))
        recall = -np.ones((t_n, k_n, a_n, m_n))
        scores_out = -np.ones((t_n, r_n, k_n, a_n, m_n))
        if not n_imgs or not classes:
            return precision, recall, scores_out, classes, {}

        units = self._build_units(i_type, micro, classes)
        if not units:
            return precision, recall, scores_out, classes, {}

        # Match in size-sorted chunks: capacities are chunk-local maxima, so one
        # detection- or gt-dense image cannot inflate every unit's padded tensors
        # (device memory stays bounded at COCO scale); _next_capacity bucketing
        # keeps the number of distinct jit shapes small.
        ranges = np.asarray([_BBOX_AREA_RANGES[a] for a in area_names])  # (A, 2)
        chunk_size = 256 if i_type == "segm" else 2048
        order_by_size = sorted(range(len(units)), key=lambda i: (len(units[i]["didx"]), len(units[i]["gidx"])))
        unit_dtm: List[np.ndarray] = [None] * len(units)  # each (A, T, nd)
        unit_dtig: List[np.ndarray] = [None] * len(units)
        unit_gtig: List[np.ndarray] = [None] * len(units)  # each (A, ng)
        unit_ious: List[np.ndarray] = [None] * len(units)

        def _fetch(entry):
            # one device→host sync per chunk, issued only after later chunks
            # have been dispatched — device compute overlaps host prep/fetch
            sel_idx, gt_ignore, device_tup = entry
            ious, dtm_c, dtig_c = jax.device_get(device_tup)
            for row, i in enumerate(sel_idx):
                nd, ng = len(units[i]["didx"]), len(units[i]["gidx"])
                unit_dtm[i] = dtm_c[row, :, :, :nd]
                unit_dtig[i] = dtig_c[row, :, :, :nd]
                unit_gtig[i] = gt_ignore[row, :, :ng]
                unit_ious[i] = ious[row, :nd, :ng]

        # Async chunk pipeline: dispatch up to `window` chunks ahead of the
        # oldest un-fetched one. jax dispatch is asynchronous, so while the
        # device matches chunk N the host pads chunk N+1; the per-chunk sync
        # that used to serialize the two (round-2 weak #1) now lands on
        # already-finished results. The window bounds in-flight device memory.
        window = 4
        in_flight: List[Any] = []
        for start in range(0, len(order_by_size), chunk_size):
            sel_idx = order_by_size[start : start + chunk_size]
            chunk = [units[i] for i in sel_idx]
            # bucket the unit axis too (pad rows are all-invalid) so a varying
            # dataset size replays cached executables instead of recompiling
            u_n = _next_capacity(len(chunk), quantum=32)
            d_cap = _next_capacity(max((len(u["didx"]) for u in chunk), default=1))
            g_cap = _next_capacity(max((len(u["gidx"]) for u in chunk), default=1))
            ious_j = jnp.asarray(self._unit_ious(chunk, i_type, d_cap, g_cap))
            if ious_j.shape[0] < u_n:
                ious_j = jnp.concatenate([ious_j, jnp.zeros((u_n - ious_j.shape[0], d_cap, g_cap), ious_j.dtype)])
            det_valid = np.zeros((u_n, d_cap), bool)
            gt_valid = np.zeros((u_n, g_cap), bool)
            gt_crowd = np.zeros((u_n, g_cap), bool)
            gt_ignore = np.zeros((u_n, a_n, g_cap), bool)
            det_oor = np.zeros((u_n, a_n, d_cap), bool)
            for row, u in enumerate(chunk):
                nd, ng = len(u["didx"]), len(u["gidx"])
                det_valid[row, :nd] = True
                gt_valid[row, :ng] = True
                gt_crowd[row, :ng] = u["gt_crowd"]
                out_rng_gt = (u["gt_areas"][None, :] < ranges[:, :1]) | (u["gt_areas"][None, :] > ranges[:, 1:])
                gt_ignore[row, :, :ng] = u["gt_crowd"][None, :] | out_rng_gt
                det_oor[row, :, :nd] = (u["det_areas"][None, :] < ranges[:, :1]) | (u["det_areas"][None, :] > ranges[:, 1:])
            dtm_c, dtig_c = match_units_jit(
                ious_j,
                jnp.asarray(gt_valid),
                jnp.asarray(gt_crowd),
                jnp.asarray(gt_ignore),
                jnp.asarray(det_valid),
                jnp.asarray(det_oor),
                jnp.asarray(iou_thrs),
            )
            in_flight.append((sel_idx, gt_ignore, (ious_j, dtm_c, dtig_c)))
            if len(in_flight) > window:
                _fetch(in_flight.pop(0))
        for entry in in_flight:
            _fetch(entry)

        # ---------------- host accumulate: sort + cumsum + 101-pt interpolation
        ious_dict = {(u["img"], (classes[u["ki"]] if not micro else -1)): unit_ious[i]
                     for i, u in enumerate(units)}
        unit_ki = np.asarray([u["ki"] for u in units])
        unit_npig = np.stack([(~g).sum(axis=1) for g in unit_gtig])  # (U, A) non-ignored gts
        for ki in range(k_n):
            sel = np.nonzero(unit_ki == ki)[0]
            if not len(sel):
                continue
            npig_per_area = unit_npig[sel].sum(axis=0)
            for mi, max_det in enumerate(max_dets):
                scores_cat = np.concatenate([units[i]["scores"][:max_det] for i in sel]) if len(sel) else np.zeros(0)
                order = np.argsort(-scores_cat, kind="mergesort")
                tps = np.concatenate([unit_dtm[i][:, :, :max_det] for i in sel], axis=2)
                igs = np.concatenate([unit_dtig[i][:, :, :max_det] for i in sel], axis=2)
                tps = tps[:, :, order]  # (A, T, N)
                igs = igs[:, :, order]
                scores_sorted = scores_cat[order]
                tp_c = np.cumsum(tps & ~igs, axis=2, dtype=np.float64)
                fp_c = np.cumsum(~tps & ~igs, axis=2, dtype=np.float64)
                n = tp_c.shape[2]
                if n == 0:
                    for ai in np.nonzero(npig_per_area)[0]:
                        recall[:, ki, ai, mi] = 0.0
                        precision[:, :, ki, ai, mi] = 0.0
                        scores_out[:, :, ki, ai, mi] = 0.0
                    continue
                # all (area, threshold) cells at once: the per-cell math is a
                # cumsum ratio + reverse running max + a batched searchsorted
                # (``rc`` is nondecreasing, so ``searchsorted(rc, thr, 'left')``
                # == count of entries < thr, a broadcast sum)
                live = npig_per_area > 0  # (A,)
                npig_safe = np.maximum(npig_per_area, 1).astype(np.float64)
                rc = tp_c / npig_safe[:, None, None]  # (A, T, N)
                pr = tp_c / np.maximum(tp_c + fp_c, np.finfo(np.float64).eps)
                recall[:, ki, live, mi] = rc[live, :, -1].T
                pr = np.maximum.accumulate(pr[:, :, ::-1], axis=2)[:, :, ::-1]
                # per-(area, threshold) searchsorted: O(A·T·R·log N), avoiding
                # an (A, T, N, R) boolean intermediate at COCO-scale N
                inds = np.empty((a_n, t_n, r_n), dtype=np.int64)
                for ai in range(a_n):
                    for ti in range(t_n):
                        inds[ai, ti] = np.searchsorted(rc[ai, ti], rec_thrs, side="left")
                valid = inds < n
                inds_c = np.minimum(inds, n - 1)
                q = np.where(valid, np.take_along_axis(pr, inds_c.reshape(a_n, t_n, -1), axis=2), 0.0)
                s = np.where(valid, scores_sorted[inds_c], 0.0)
                precision[:, :, ki, live, mi] = q[live].transpose(1, 2, 0)
                scores_out[:, :, ki, live, mi] = s[live].transpose(1, 2, 0)
        return precision, recall, scores_out, classes, ious_dict

    @staticmethod
    def _summarize(precision, recall, t_slice=None, area="all", max_det_idx=-1, area_names=("all", "small", "medium", "large")):
        ai = area_names.index(area)
        if precision is not None:
            p = precision[:, :, :, ai, max_det_idx]
            if t_slice is not None:
                p = p[t_slice : t_slice + 1]
            p = p[p > -1]
            return float(np.mean(p)) if p.size else -1.0
        r = recall[:, :, ai, max_det_idx]
        if t_slice is not None:
            r = r[t_slice : t_slice + 1]
        r = r[r > -1]
        return float(np.mean(r)) if r.size else -1.0

    def compute(self) -> Dict[str, Array]:
        """Run the full COCO evaluation and return the standard summary dict."""
        md_idx = len(self.max_detection_thresholds) - 1
        iou_thrs = np.asarray(self.iou_thresholds)

        def t_idx(v):
            hits = np.where(np.isclose(iou_thrs, v))[0]
            return int(hits[0]) if len(hits) else None

        res: Dict[str, Any] = {}
        classes: List[int] = []
        for i_type in self.iou_type:
            prefix = "" if len(self.iou_type) == 1 else f"{i_type}_"
            precision, recall, scores, classes, ious_dict = self._evaluate(i_type)
            res[f"{prefix}map"] = self._summarize(precision, None, None, "all", md_idx)
            i50, i75 = t_idx(0.5), t_idx(0.75)
            res[f"{prefix}map_50"] = self._summarize(precision, None, i50, "all", md_idx) if i50 is not None else -1.0
            res[f"{prefix}map_75"] = self._summarize(precision, None, i75, "all", md_idx) if i75 is not None else -1.0
            for aname in ("small", "medium", "large"):
                res[f"{prefix}map_{aname}"] = self._summarize(precision, None, None, aname, md_idx)
                res[f"{prefix}mar_{aname}"] = self._summarize(None, recall, None, aname, md_idx)
            for mi, md in enumerate(self.max_detection_thresholds):
                res[f"{prefix}mar_{md}"] = self._summarize(None, recall, None, "all", mi)
            if self.class_metrics and len(classes):
                if self.average == "micro":
                    # micro pooled everything into one pseudo-class; per-class numbers
                    # need a second macro pass (reference computes per-class regardless)
                    cls_precision, cls_recall, _, _, _ = self._evaluate(i_type, average="macro")
                else:
                    cls_precision, cls_recall = precision, recall
                map_per_class = []
                mar_per_class = []
                for ki in range(len(classes)):
                    p = cls_precision[:, :, ki, 0, md_idx]
                    p = p[p > -1]
                    map_per_class.append(float(np.mean(p)) if p.size else -1.0)
                    r = cls_recall[:, ki, 0, md_idx]
                    r = r[r > -1]
                    mar_per_class.append(float(np.mean(r)) if r.size else -1.0)
                res[f"{prefix}map_per_class"] = jnp.asarray(map_per_class, dtype=jnp.float32)
                res[f"{prefix}mar_{self.max_detection_thresholds[-1]}_per_class"] = jnp.asarray(
                    mar_per_class, dtype=jnp.float32
                )
            if self.extended_summary:
                res[f"{prefix}ious"] = {k: jnp.asarray(v, dtype=jnp.float32) for k, v in ious_dict.items()}
                res[f"{prefix}precision"] = jnp.asarray(precision, dtype=jnp.float32)
                res[f"{prefix}recall"] = jnp.asarray(recall, dtype=jnp.float32)
                res[f"{prefix}scores"] = jnp.asarray(scores, dtype=jnp.float32)
        res["classes"] = jnp.asarray(classes, dtype=jnp.int32)
        return {
            k: (jnp.asarray(v, dtype=jnp.float32) if not isinstance(v, (jnp.ndarray, dict)) else v)
            for k, v in res.items()
        }
