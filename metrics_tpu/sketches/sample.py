"""Seeded bottom-k reservoir sample metric (modular layer)."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.sketches.reservoir import (
    reservoir_empty,
    reservoir_fold,
    reservoir_merge,
    reservoir_values,
)
from metrics_tpu.metric import Metric

__all__ = ["ReservoirSample"]


class ReservoirSample(Metric):
    """A k-element uniform sample of the distinct stream values, exactly mergeable.

    Bottom-k priority sampling: every value's priority is a pure seeded hash,
    and the state keeps the k smallest-priority (priority, value) pairs packed
    into one (3, k) f32 buffer. Because the kept set is a rank filter over the
    stream's value multiset, *any* shard split, merge order, or re-grouping
    reproduces the single-pass sample bit-exactly — the merge harness holds
    this class to EXACT agreement, not a tolerance (DESIGN §16).

    ``compute()`` returns the (k,) sampled values; slots still unfilled (k
    larger than the distinct count seen) read 0.0.

    Args:
        k: sample capacity.
        seed: priority hash seed; determines *which* uniform sample is drawn,
            and must match across shards for merges to be meaningful.
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(self, k: int = 128, seed: int = 0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if k < 1:
            raise ValueError(f"`k` must be >= 1, got {k}")
        self.k = int(k)
        self.seed = int(seed)
        # bottom-k of a union is invariant under shard order and grouping, so
        # the custom reduction declares its algebra (DL001) and the dynamic
        # merge harness verifies the claim.
        self.add_state(
            "packed",
            default=reservoir_empty(self.k),
            dist_reduce_fx=reservoir_merge,
            merge_associative=True,
        )

    def update(self, value: Array) -> None:
        value = jnp.asarray(value)
        # bottom-k is a rank filter — an order-invariant fold the static rule
        # can't recognize; the dynamic merge harness verifies the claim
        self.packed = reservoir_fold(  # distlint: disable=DL002
            self.packed, value, jnp.ones(value.shape, bool), seed=self.seed
        )

    def compute(self) -> Array:
        return reservoir_values(self.packed)
