"""DDSketch streaming quantile metric (modular layer)."""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.sketches.ddsketch import (
    ddsketch_delta,
    ddsketch_gamma,
    ddsketch_quantiles,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.compute import count_dtype

__all__ = ["DDSketch"]


class DDSketch(Metric):
    """Streaming quantiles with relative-error guarantee α in O(num_buckets) memory.

    Holds three fixed-shape count states (positive/negative log-γ bucket
    histograms + a zero count, all ``sum`` algebra), so the sketch is
    donation-eligible, fleet-stackable, and exactly mergeable across shards.
    ``compute()`` returns one estimate per requested quantile; each is within
    ``alpha`` *relative* error of the exact stream quantile for values inside
    the covered magnitude range (DESIGN §16).

    Args:
        alpha: relative accuracy of every quantile estimate (bucket growth
            γ = (1+α)/(1−α)).
        quantiles: which quantiles ``compute()`` estimates.
        num_buckets: buckets per sign; with ``key_offset`` fixes the covered
            magnitude window (defaults cover ≈ [1.3e−9, 7.7e8] at α = 0.01).
        key_offset: log-γ key of bucket 0; ``None`` centers the window on
            magnitude 1.0 (``−num_buckets // 2``).
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        alpha: float = 0.01,
        quantiles: Sequence[float] = (0.5, 0.9, 0.99),
        num_buckets: int = 2048,
        key_offset: int | None = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        ddsketch_gamma(alpha)  # validates alpha
        if num_buckets < 2:
            raise ValueError(f"`num_buckets` must be >= 2, got {num_buckets}")
        qs = tuple(float(q) for q in quantiles)
        if not qs or any(not 0.0 <= q <= 1.0 for q in qs):
            raise ValueError(f"`quantiles` must be non-empty values in [0, 1], got {quantiles}")
        self.alpha = float(alpha)
        self.quantiles = qs
        self.num_buckets = int(num_buckets)
        self.key_offset = int(-num_buckets // 2 if key_offset is None else key_offset)
        self.add_state(
            "pos_buckets", default=jnp.zeros((self.num_buckets,), count_dtype()), dist_reduce_fx="sum"
        )
        self.add_state(
            "neg_buckets", default=jnp.zeros((self.num_buckets,), count_dtype()), dist_reduce_fx="sum"
        )
        self.add_state("zero_count", default=jnp.zeros((), count_dtype()), dist_reduce_fx="sum")

    def update(self, value: Array) -> None:
        value = jnp.asarray(value)
        d_pos, d_neg, d_zero = ddsketch_delta(
            value,
            jnp.ones(value.shape, bool),
            alpha=self.alpha,
            key_offset=self.key_offset,
            num_buckets=self.num_buckets,
        )
        self.pos_buckets = self.pos_buckets + d_pos
        self.neg_buckets = self.neg_buckets + d_neg
        self.zero_count = self.zero_count + d_zero

    def compute(self) -> Array:
        return ddsketch_quantiles(
            self.pos_buckets,
            self.neg_buckets,
            self.zero_count,
            self.quantiles,
            alpha=self.alpha,
            key_offset=self.key_offset,
        )
