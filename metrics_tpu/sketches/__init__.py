"""Sketch-state metrics: bounded-memory summaries of unbounded streams (DESIGN §16).

Every class here holds *fixed-shape* state with a declared associative merge
algebra — the combination that makes the whole family donation-eligible on
the single-dispatch hot path, stackable into ``StreamEngine`` fleet buckets,
checkpointable, and exactly shard-mergeable under distlint's split-update-
merge harness. Accuracy is traded for memory with a *theoretical* bound per
sketch (DDSketch relative error α, HyperLogLog standard error 1.04/√m,
binned-AUROC same-bin pair mass), each asserted by the oracle tests.
"""

from metrics_tpu.sketches.cardinality import HyperLogLog
from metrics_tpu.sketches.curve import StreamingAUROC, StreamingCalibrationError
from metrics_tpu.sketches.quantile import DDSketch
from metrics_tpu.sketches.sample import ReservoirSample

__all__ = [
    "DDSketch",
    "HyperLogLog",
    "ReservoirSample",
    "StreamingAUROC",
    "StreamingCalibrationError",
]
