"""Binned-ECDF streaming curve metrics: AUROC and calibration error (modular layer)."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.sketches.ecdf import (
    binned_auroc,
    binned_auroc_bound,
    binned_ece,
    calibration_delta,
    score_hist_delta,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.compute import acc_dtype, count_dtype

__all__ = ["StreamingAUROC", "StreamingCalibrationError"]


class StreamingAUROC(Metric):
    """Binary AUROC over an unbounded score stream in O(num_bins) memory.

    Two per-bin int32 histograms (positive/negative scores over ``num_bins``
    equal-width bins of [0, 1], ``sum`` algebra). Cross-bin pairs contribute
    their exact Mann-Whitney term; same-bin pairs get half credit, so
    ``|compute() − exact| ≤ error_bound()`` — a bound the sketch computes
    from its own state, asserted (not eyeballed) by the oracle tests.

    Args:
        num_bins: score histogram resolution; the error bound shrinks with
            the same-bin pair mass, i.e. roughly with 1/num_bins.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, num_bins: int = 2048, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if num_bins < 2:
            raise ValueError(f"`num_bins` must be >= 2, got {num_bins}")
        self.num_bins = int(num_bins)
        self.add_state(
            "pos_hist", default=jnp.zeros((self.num_bins,), count_dtype()), dist_reduce_fx="sum"
        )
        self.add_state(
            "neg_hist", default=jnp.zeros((self.num_bins,), count_dtype()), dist_reduce_fx="sum"
        )

    def update(self, preds: Array, target: Array) -> None:
        preds = jnp.asarray(preds)
        d_pos, d_neg = score_hist_delta(
            preds, target, jnp.ones(preds.shape, bool), num_bins=self.num_bins
        )
        self.pos_hist = self.pos_hist + d_pos
        self.neg_hist = self.neg_hist + d_neg

    def compute(self) -> Array:
        return binned_auroc(self.pos_hist, self.neg_hist)

    def error_bound(self) -> Array:
        """Worst-case |compute() − exact AUROC|, from the current state."""
        return binned_auroc_bound(self.pos_hist, self.neg_hist)


class StreamingCalibrationError(Metric):
    """Top-label expected calibration error (L1) over an unbounded stream.

    Per-bin confidence sums plus prediction/correct counts (``sum`` algebra)
    over ``num_bins`` equal-width confidence bins. Binning is part of ECE's
    definition, so against an exact ECE computed with the *same* bins this
    sketch is not an approximation at all — it agrees to float rounding while
    holding O(num_bins) state instead of the stream.

    Args:
        num_bins: confidence bins (the reference metric's ``n_bins``).
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(self, num_bins: int = 15, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if num_bins < 2:
            raise ValueError(f"`num_bins` must be >= 2, got {num_bins}")
        self.num_bins = int(num_bins)
        self.add_state(
            "conf_sum", default=jnp.zeros((self.num_bins,), acc_dtype()), dist_reduce_fx="sum"
        )
        self.add_state(
            "bin_count", default=jnp.zeros((self.num_bins,), count_dtype()), dist_reduce_fx="sum"
        )
        self.add_state(
            "bin_correct", default=jnp.zeros((self.num_bins,), count_dtype()), dist_reduce_fx="sum"
        )

    def update(self, preds: Array, target: Array) -> None:
        preds = jnp.asarray(preds)
        d_conf, d_count, d_correct = calibration_delta(
            preds, target, jnp.ones(preds.shape, bool), num_bins=self.num_bins
        )
        self.conf_sum = self.conf_sum + d_conf
        self.bin_count = self.bin_count + d_count
        self.bin_correct = self.bin_correct + d_correct

    def compute(self) -> Array:
        return binned_ece(self.conf_sum, self.bin_count, self.bin_correct)
