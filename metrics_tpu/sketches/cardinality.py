"""HyperLogLog distinct-count metric (modular layer)."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.sketches.hll import hll_delta, hll_estimate, hll_std_error
from metrics_tpu.metric import Metric

__all__ = ["HyperLogLog"]


class HyperLogLog(Metric):
    """Approximate distinct-value count in 2^p int32 registers.

    Standard error is ``1.04/√(2^p)`` (≈ 1.6% at the default p = 12 / 16 KiB
    of state) for any stream length. The register state's ``max`` algebra is
    associative, commutative, *and idempotent*, so shard merges — and even
    accidental re-merges — are exact (DESIGN §16).

    Args:
        p: register-index bits; 2^p registers, in [4, 16].
        seed: hash-family seed; sketches only merge meaningfully when built
            with the same seed.
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(self, p: int = 12, seed: int = 0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not 4 <= int(p) <= 16:
            raise ValueError(f"`p` must be in [4, 16], got {p}")
        self.p = int(p)
        self.seed = int(seed)
        self.add_state(
            "registers", default=jnp.zeros((1 << self.p,), jnp.int32), dist_reduce_fx="max"
        )

    @property
    def std_error(self) -> float:
        """Theoretical relative standard error of ``compute()``."""
        return hll_std_error(self.p)

    def update(self, value: Array) -> None:
        value = jnp.asarray(value)
        delta = hll_delta(value, jnp.ones(value.shape, bool), p=self.p, seed=self.seed)
        self.registers = jnp.maximum(self.registers, delta)

    def compute(self) -> Array:
        return hll_estimate(self.registers)
