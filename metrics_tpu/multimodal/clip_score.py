"""CLIP-based multimodal metrics with injectable encoders.

Parity with reference ``multimodal/clip_score.py:43`` and ``clip_iqa.py`` (which
pull HF transformers CLIP checkpoints — SURVEY §2.9). Offline build: inject
``image_encoder``/``text_encoder`` callables returning embeddings; the metric owns
the score math (cosine similarity ×100, clamped at 0; score list state).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.compute import count_dtype


def _unit(x: Array) -> Array:
    return x / jnp.clip(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12, None)


class CLIPScore(Metric):
    """CLIPScore: 100 · max(cos(img_emb, txt_emb), 0) (reference ``multimodal/clip_score.py:43``).

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(42)
    >>> img_enc = lambda imgs: jnp.asarray(rng.rand(len(imgs), 8).astype(np.float32))
    >>> txt_enc = lambda txts: jnp.asarray(rng.rand(len(txts), 8).astype(np.float32))
    >>> metric = CLIPScore(image_encoder=img_enc, text_encoder=txt_enc)
    >>> metric.update([object(), object()], ["a cat", "a dog"])
    >>> float(metric.compute()) > 0
    True
    """

    __jit_ineligible__ = True
    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        image_encoder: Optional[Callable] = None,
        text_encoder: Optional[Callable] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if image_encoder is None or text_encoder is None:
            # default path = local HF Flax CLIP checkpoint (reference downloads it,
            # multimodal/clip_score.py:30); raises a clear error if absent on disk
            from metrics_tpu.models.hub import load_clip

            image_encoder, text_encoder = load_clip(
                model_name_or_path or "openai/clip-vit-large-patch14"
            )
        self.image_encoder = image_encoder
        self.text_encoder = text_encoder
        self.add_state("score", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("n_samples", jnp.zeros((), dtype=count_dtype()), dist_reduce_fx="sum")

    def update(self, images: Union[Array, Sequence], text: Union[str, Sequence[str]]) -> None:
        """Update with images and matching captions."""
        text_ = [text] if isinstance(text, str) else list(text)
        if hasattr(images, "ndim") and images.ndim == 3:
            images = images[None]
        if len(images) != len(text_):
            raise ValueError(
                f"Expected the number of images and text examples to be the same but got {len(images)} and {len(text_)}"
            )
        img_emb = _unit(jnp.asarray(self.image_encoder(images)))
        txt_emb = _unit(jnp.asarray(self.text_encoder(text_)))
        score = 100 * jnp.sum(img_emb * txt_emb, axis=-1)
        # raw sum; the clamp applies once to the MEAN in compute (reference
        # clip_score.py accumulates unclamped and clamps the final average)
        self.score = self.score + score.sum()
        self.n_samples = self.n_samples + score.shape[0]

    def compute(self) -> Array:
        """Average CLIPScore, clamped at 0."""
        return jnp.maximum(self.score / self.n_samples, 0.0).astype(jnp.float32)


class CLIPImageQualityAssessment(Metric):
    """CLIP-IQA (reference ``multimodal/clip_iqa.py:72``): softmax over paired
    positive/negative prompt similarities.

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(42)
    >>> img_enc = lambda imgs: jnp.asarray(rng.rand(len(imgs), 8).astype(np.float32))
    >>> txt_enc = lambda txts: jnp.asarray(rng.rand(len(txts), 8).astype(np.float32))
    >>> metric = CLIPImageQualityAssessment(image_encoder=img_enc, text_encoder=txt_enc)
    >>> metric.update(jnp.zeros((2, 3, 8, 8)))
    >>> out = metric.compute()
    >>> bool((np.asarray(out) >= 0).all() and (np.asarray(out) <= 1).all())
    True
    """

    __jit_ineligible__ = True
    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        prompts: Tuple[Union[str, Tuple[str, str]], ...] = ("quality",),
        image_encoder: Optional[Callable] = None,
        text_encoder: Optional[Callable] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if image_encoder is None or text_encoder is None:
            from metrics_tpu.models.hub import load_clip

            image_encoder, text_encoder = load_clip(
                model_name_or_path or "openai/clip-vit-large-patch14"
            )
        self.image_encoder = image_encoder
        self.text_encoder = text_encoder
        # single-sourced prompt table + resolver (functional/multimodal/clip_iqa.py)
        from metrics_tpu.functional.multimodal.clip_iqa import _resolve_prompts

        self.prompt_pairs, self.prompt_names = _resolve_prompts(prompts)
        self.add_state("scores", [], dist_reduce_fx="cat")

    def update(self, images: Array) -> None:
        """Update with an image batch."""
        img_emb = _unit(jnp.asarray(self.image_encoder(images)))
        per_prompt = []
        for pos, neg in self.prompt_pairs:
            txt_emb = _unit(jnp.asarray(self.text_encoder([pos, neg])))
            import jax

            logits = 100 * img_emb @ txt_emb.T  # (N, 2)
            probs = jax.nn.softmax(logits, axis=-1)[:, 0]  # max-subtracted, no f32 overflow
            per_prompt.append(probs)
        self.scores.append(jnp.stack(per_prompt, axis=-1))  # (N, P)

    def compute(self) -> Union[Array, Dict[str, Array]]:
        """Per-image scores (single prompt) or dict of per-prompt score vectors."""
        scores = dim_zero_cat(self.scores)
        if len(self.prompt_names) == 1:
            return scores[:, 0]
        return {name: scores[:, i] for i, name in enumerate(self.prompt_names)}
