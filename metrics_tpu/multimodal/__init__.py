"""Multimodal metrics (reference ``torchmetrics/multimodal/__init__.py``)."""

from metrics_tpu.multimodal.clip_score import CLIPImageQualityAssessment, CLIPScore

__all__ = ["CLIPImageQualityAssessment", "CLIPScore"]
