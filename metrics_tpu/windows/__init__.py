"""Windowed & time-decayed streaming semantics (DESIGN §20).

Production metrics are rarely since-process-start. This package recasts
windowed aggregation as *fixed-shape O(1) recurrences* — no O(window) buffer
splice, nothing host-side — so every class here keeps the full fleet
contract: donation-eligible single-dispatch updates, StreamEngine
bucketability, MTCKPT checkpoints and WAL replay, with merges that stay
sound under the declared-algebra MapReduce discipline by folding both sides
to a **common reference time** before applying the original algebra.

* :class:`TimeDecayed` — exponential time-decay as a scalar-rescale fold,
  for any sum-algebra base metric (``state·2^(−Δt/half_life) + batch``).
* :class:`TumblingWindow` — exact sliding windows from a rotating stack of
  tumbling panes addressed by absolute pane number.
* :class:`DecayedDDSketch` / :class:`DecayedHLL` — time-decayed variants of
  the ``sketches/`` family via bucket-count / register rescale.
"""

from metrics_tpu.windows.decay import TimeDecayed
from metrics_tpu.windows.panes import TumblingWindow
from metrics_tpu.windows.sketch_decay import DecayedDDSketch, DecayedHLL

__all__ = ["DecayedDDSketch", "DecayedHLL", "TimeDecayed", "TumblingWindow"]
