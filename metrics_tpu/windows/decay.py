"""Exponential time-decay as a scalar-rescale fold over sum-algebra metrics."""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.ops.decay import decay_weights
from metrics_tpu.utils.compute import neumaier_add, neumaier_value
from metrics_tpu.utils.data import dim_zero_sum
from metrics_tpu.utils.exceptions import TPUMetricsUserError

__all__ = ["TimeDecayed"]


def _base_spec(metric: Metric) -> Any:
    """Hashable stand-in for the held base metric in the jit-cache key.

    The base instance itself is excluded from the key (``__jit_key_exclude__``)
    because Metric-valued attrs are defined to be unhashable; what the traced
    update *actually* closes over is the base's static config and state avals,
    so that pair (plus the class path) is the honest key component. A base
    whose own config is not fingerprintable poisons the key the usual way: the
    Metric value itself is returned, ``_hashable_config_value`` raises, and the
    wrapper is correctly not shareable.
    """
    fp = metric.config_fingerprint()
    if fp is None:
        return metric
    cls = type(metric)
    return (f"{cls.__module__}.{cls.__qualname__}", fp, metric.state_avals())


def _validate_decay_base(metric: Metric, wrapper: str) -> None:
    """Reject base metrics whose update/merge semantics break the decay fold."""
    if not isinstance(metric, Metric):
        raise TPUMetricsUserError(f"{wrapper} expects a Metric instance, got {type(metric).__name__}")
    if type(metric).__jit_ineligible__:
        raise TPUMetricsUserError(
            f"{wrapper} cannot wrap {type(metric).__name__}: its update body is "
            "declared jit-ineligible, so it cannot be traced into the wrapper's "
            "single-dispatch update."
        )
    if metric._has_list_state():
        raise TPUMetricsUserError(
            f"{wrapper} cannot wrap {type(metric).__name__}: list ('cat') states "
            "are variable-shape and have no scalar-rescale decay."
        )
    if metric._jit_update_opt is False:
        raise TPUMetricsUserError(
            f"{wrapper} cannot wrap this {type(metric).__name__}: its update runs "
            "host-side (e.g. nan_strategy='warn'/'error'); construct the base "
            "with a traceable configuration such as nan_strategy='disable'."
        )
    if metric.full_state_update is not False:
        raise TPUMetricsUserError(
            f"{wrapper} cannot wrap {type(metric).__name__}: the decay fold "
            "requires batch-local updates (full_state_update=False)."
        )


class TimeDecayed(Metric):
    """Exponential time-decay for any sum-algebra metric, as an O(1) rescale fold.

    Wraps a base metric *all* of whose states carry the ``sum`` reduce algebra
    (counts, totals, histograms — e.g. ``SumMetric``, ``MeanMetric``,
    ``BinnedHistogram``-style states) and reweights every observation by
    ``2^(-(now - t)/half_life_s)``: an observation ``half_life_s`` old counts
    half as much, one two half-lives old a quarter, and so on. The state is
    exactly ``Σ_i batch_i · 2^(-(ref - t_i)/half_life)`` where ``ref`` is the
    newest timestamp seen — an order-invariant weighted sum, so per-shard
    partials merge soundly by decaying both sides to a common reference time
    (carried as the extra synced scalar state ``last_t``) and adding.

    The update is branch-free and fixed-shape: ``state*w_old + batch*w_new``
    with weights from :func:`metrics_tpu.ops.decay.decay_weights`. It is
    donation-eligible, fleet-bucketable (the base metric enters the bucket key
    via its config fingerprint, not its identity), and checkpoint/WAL-eligible
    with zero engine changes.

    ``update(t, *args, **kwargs)`` prepends a timestamp to the base metric's
    update signature: ``t`` is a () float32 of *nonnegative stream-relative
    seconds* (f32 holds ~7 significant digits — epoch nanoseconds will alias).
    Pass ``t`` as a 0-d array when driving a :class:`~metrics_tpu.StreamEngine`
    fleet so submission waves group by aval instead of splitting per value.

    Example::

        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SumMetric
        >>> from metrics_tpu.windows import TimeDecayed
        >>> m = TimeDecayed(SumMetric(nan_strategy="disable"), half_life_s=10.0)
        >>> m.update(jnp.float32(0.0), jnp.asarray(1.0))
        >>> m.update(jnp.float32(10.0), jnp.asarray(1.0))  # first obs is 1 half-life old
        >>> float(m.compute())
        1.5

    Args:
        metric: base metric; every registered state must use ``sum`` algebra.
            A pristine clone is taken, so the passed instance stays untouched.
        half_life_s: decay half-life in the same unit as ``t`` (> 0).
        compensated: opt into Neumaier accumulation of the decay fold — each
            decayed state carries a ``<name>_comp`` residual (itself decayed
            and summed by the same algebra), so the repeated
            ``state*w_old + batch*w_new`` additions lose O(eps) instead of
            O(n*eps) over long horizons (numlint NL004 / DESIGN §25).
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False
    # the held base metric never enters the jit-cache key directly (Metric
    # values are defined unhashable there); `base_spec` carries its honest
    # hashable identity instead
    __jit_key_exclude__ = frozenset({"_base"})

    def __init__(self, metric: Metric, half_life_s: float, compensated: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.compensated = bool(compensated)
        _validate_decay_base(metric, type(self).__name__)
        if not float(half_life_s) > 0.0:
            raise ValueError(f"`half_life_s` must be > 0, got {half_life_s}")
        bad = [n for n, fn in metric._reductions.items() if fn is not dim_zero_sum]
        if bad:
            raise TPUMetricsUserError(
                f"{type(self).__name__} requires every base state to use the 'sum' "
                f"reduce algebra (decay distributes over +); {type(metric).__name__} "
                f"states {bad} do not. Mean-style metrics qualify when their "
                "numerator and denominator are both registered as sums."
            )
        if "last_t" in metric._defaults:
            raise TPUMetricsUserError(
                f"{type(self).__name__} reserves the state name 'last_t'; "
                f"{type(metric).__name__} already registers it."
            )
        self.half_life_s = float(half_life_s)
        base = metric.clone()
        base.reset()
        self._base = base
        self.base_spec = _base_spec(base)
        for name, default in base._defaults.items():
            d = jnp.asarray(default)
            if not jnp.issubdtype(d.dtype, jnp.floating):
                # integer counts become fractional the moment they decay
                d = d.astype(jnp.float32)
            self.add_state(
                name, default=d, dist_reduce_fx="sum",
                precision="compensated" if self.compensated else None,
            )
            if self.compensated:
                self.add_state(
                    f"{name}_comp", default=jnp.zeros_like(d), dist_reduce_fx="sum", precision="compensated"
                )
        self.add_state("last_t", default=jnp.zeros((), jnp.float32), dist_reduce_fx="max")

    def update(self, t: Array, *args: Any, **kwargs: Any) -> None:
        batch = self._base._functional_update(self._base._fresh_state(), *args, **kwargs)
        ref, w_old, w_new = decay_weights(self.last_t, t, self.half_life_s)
        for name in self._base._defaults:
            cur = getattr(self, name)
            add = jnp.asarray(batch[name], cur.dtype) * w_new
            if self.compensated:
                # residual decays with its sum; the fold's additions are compensated
                comp = getattr(self, f"{name}_comp") * w_old
                total, comp = neumaier_add(cur * w_old, comp, add)
                setattr(self, name, total)
                setattr(self, f"{name}_comp", comp)
            else:
                setattr(self, name, cur * w_old + add)
        self.last_t = ref

    def compute(self) -> Any:
        state = self.__dict__["_state"]
        if self.compensated:
            folded = {name: neumaier_value(state[name], state[f"{name}_comp"]) for name in self._base._defaults}
            return self._base._functional_compute(folded)
        return self._base._functional_compute({name: state[name] for name in self._base._defaults})

    def _merge_state_dicts(
        self, state_a: Dict[str, Any], state_b: Dict[str, Any], count_a: int, count_b: int
    ) -> Dict[str, Any]:
        # decay both sides to the common (newer) reference time, then the base
        # sum algebra applies unchanged — the declared per-state reductions
        # alone would add states anchored at *different* times, which is why
        # this override (not `_sync_dist`'s per-state path) is the merge
        # contract for decayed metrics (DESIGN §20)
        ref, w_a, w_b = decay_weights(state_a["last_t"], state_b["last_t"], self.half_life_s)
        names = list(self._base._defaults)
        if self.compensated:
            names += [f"{n}_comp" for n in self._base._defaults]  # residuals decay like their sums
        out = {name: state_a[name] * w_a + state_b[name] * w_b for name in names}
        out["last_t"] = ref
        return out
