"""Exact sliding windows from a rotating stack of tumbling panes."""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.ops.decay import pane_id, pane_slot_onehot
from metrics_tpu.utils.data import dim_zero_sum
from metrics_tpu.utils.exceptions import TPUMetricsUserError
from metrics_tpu.windows.decay import _base_spec, _validate_decay_base

__all__ = ["TumblingWindow"]


class TumblingWindow(Metric):
    """Exact sliding-window metrics over the last ``n_panes × pane_s`` seconds.

    Keeps the base metric's sum-algebra states *per tumbling pane* in a fixed
    ``(n_panes, …)`` stacked axis addressed by the absolute pane number
    ``floor(t / pane_s)`` stored at rotating slot ``pane_id % n_panes`` — O(1)
    per update, never a buffer splice, unlike the O(window) deque fold in
    :class:`metrics_tpu.wrappers.Running`. ``compute()`` folds the panes whose
    ids fall inside the window ending at the newest pane seen and runs the
    base compute, so the answer is *exact* over that window (the oldest pane
    expires wholesale — tumbling, not smoothly sliding, at pane granularity).

    Every state is fixed-shape, the update is branch-free (an out-of-order
    batch older than the window is dropped via a ``where`` mask rather than
    clobbering a newer pane), so the wrapper is donation-eligible,
    fleet-bucketable, and checkpoint/WAL-eligible with zero engine changes.
    Merging two replicas is slot-wise newest-pane-id-wins (ties: both replicas
    observed the *same* pane, so their sub-states add) — associative and
    commutative, hence MERGE_SOUND under the merge harness.

    ``update(t, *args, **kwargs)`` prepends a () float32 timestamp of
    nonnegative stream-relative seconds to the base update signature; pass it
    as a 0-d array when driving a fleet so submission waves group by aval.

    Args:
        metric: base metric; every registered state must use ``sum`` algebra.
            A pristine clone is taken, so the passed instance stays untouched.
        pane_s: tumbling pane width in seconds (> 0).
        n_panes: number of live panes; the window covers ``n_panes * pane_s``
            seconds ending at the newest pane boundary (≥ 1).
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False
    # the held base metric never enters the jit-cache key; `base_spec` does
    __jit_key_exclude__ = frozenset({"_base"})

    def __init__(self, metric: Metric, pane_s: float, n_panes: int, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_decay_base(metric, type(self).__name__)
        if not float(pane_s) > 0.0:
            raise ValueError(f"`pane_s` must be > 0, got {pane_s}")
        if int(n_panes) < 1:
            raise ValueError(f"`n_panes` must be >= 1, got {n_panes}")
        bad = [n for n, fn in metric._reductions.items() if fn is not dim_zero_sum]
        if bad:
            raise TPUMetricsUserError(
                f"{type(self).__name__} requires every base state to use the 'sum' "
                f"reduce algebra (panes fold by +); {type(metric).__name__} "
                f"states {bad} do not."
            )
        if "pane_ids" in metric._defaults:
            raise TPUMetricsUserError(
                f"{type(self).__name__} reserves the state name 'pane_ids'; "
                f"{type(metric).__name__} already registers it."
            )
        self.pane_s = float(pane_s)
        self.n_panes = int(n_panes)
        base = metric.clone()
        base.reset()
        self._base = base
        self.base_spec = _base_spec(base)
        for name, default in base._defaults.items():
            d = jnp.asarray(default)
            stacked = jnp.zeros((self.n_panes,) + d.shape, d.dtype) + d
            self.add_state(name, default=stacked, dist_reduce_fx="sum")
        # absolute pane number held in each slot; -1 = never written. "max" is
        # the declared algebra, but real merges run through the slot-aligned
        # override below.
        self.add_state(
            "pane_ids", default=jnp.full((self.n_panes,), -1, jnp.int32), dist_reduce_fx="max"
        )

    def _pane_mask(self, mask: Array, name: str) -> Array:
        """Reshape a (n_panes,) mask to broadcast against the stacked state."""
        extra = jnp.ndim(self._base._defaults[name])
        return jnp.reshape(mask, (self.n_panes,) + (1,) * extra)

    def update(self, t: Array, *args: Any, **kwargs: Any) -> None:
        batch = self._base._functional_update(self._base._fresh_state(), *args, **kwargs)
        cur = pane_id(t, self.pane_s)
        onehot = pane_slot_onehot(cur, self.n_panes)
        slot_prev = jnp.sum(jnp.where(onehot, self.pane_ids, 0))
        # a batch older than what its slot holds has already rotated out of the
        # window: drop it branch-free instead of clobbering the newer pane
        accept = cur >= slot_prev
        write = onehot & accept
        stale = write & (self.pane_ids != cur)
        for name in self._base._defaults:
            stacked = getattr(self, name)
            kept = jnp.where(self._pane_mask(stale, name), jnp.zeros_like(stacked), stacked)
            add = self._pane_mask(write, name).astype(stacked.dtype) * jnp.asarray(batch[name], stacked.dtype)
            setattr(self, name, kept + add)
        self.pane_ids = jnp.where(write, cur, self.pane_ids)

    def compute(self) -> Any:
        state = self.__dict__["_state"]
        ids = state["pane_ids"]
        live = (ids > jnp.max(ids) - self.n_panes) & (ids >= 0)
        folded = {
            name: jnp.sum(
                state[name] * self._pane_mask(live, name).astype(state[name].dtype), axis=0
            )
            for name in self._base._defaults
        }
        return self._base._functional_compute(folded)

    def _merge_state_dicts(
        self, state_a: Dict[str, Any], state_b: Dict[str, Any], count_a: int, count_b: int
    ) -> Dict[str, Any]:
        # slot-wise newest-pane-wins; equal ids mean both replicas saw the SAME
        # pane, so their partial states add. A losing slot's pane id differs by
        # a multiple of n_panes, putting it outside the merged window — summing
        # it in would be wrong, which is why the declared per-state algebras
        # alone do not merge this metric (DESIGN §20).
        ids_a, ids_b = state_a["pane_ids"], state_b["pane_ids"]
        out_ids = jnp.maximum(ids_a, ids_b)
        keep_a, keep_b = ids_a == out_ids, ids_b == out_ids
        out = {
            name: state_a[name] * self._pane_mask(keep_a, name).astype(state_a[name].dtype)
            + state_b[name] * self._pane_mask(keep_b, name).astype(state_b[name].dtype)
            for name in self._base._defaults
        }
        out["pane_ids"] = out_ids
        return out
