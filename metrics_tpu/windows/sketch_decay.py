"""Time-decayed variants of the ``sketches/`` family via bucket-count rescale."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.sketches.ddsketch import (
    ddsketch_delta,
    ddsketch_gamma,
    ddsketch_quantiles,
)
from metrics_tpu.functional.sketches.hll import hll_delta
from metrics_tpu.metric import Metric
from metrics_tpu.ops.decay import decay_weights, decayed_hll_estimate

__all__ = ["DecayedDDSketch", "DecayedHLL"]


def _require_positive_half_life(half_life_s: float) -> float:
    if not float(half_life_s) > 0.0:
        raise ValueError(f"`half_life_s` must be > 0, got {half_life_s}")
    return float(half_life_s)


class DecayedDDSketch(Metric):
    """Time-decayed streaming quantiles: a DDSketch whose counts forget.

    Identical bucket geometry to :class:`metrics_tpu.sketches.DDSketch`, but
    the three count states are float32 and every update first rescales them by
    ``2^(-Δt/half_life_s)`` — an observation one half-life old carries half a
    count. ``compute()`` therefore estimates the quantiles of the
    *recency-weighted* value distribution, which is what a latency dashboard
    or canary wants from an unbounded stream. The state is exactly the
    per-bucket decayed sum ``Σ_i 1[v_i ∈ bucket]·2^(-(ref-t_i)/half_life)``,
    order-invariant, so replicas merge by decaying both sides to a common
    reference time and adding (DESIGN §20).

    ``update(t, value)`` prepends a () float32 timestamp of nonnegative
    stream-relative seconds to the plain sketch's signature.

    Args: as :class:`~metrics_tpu.sketches.DDSketch`, plus ``half_life_s``.
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        half_life_s: float,
        alpha: float = 0.01,
        quantiles: Sequence[float] = (0.5, 0.9, 0.99),
        num_buckets: int = 2048,
        key_offset: int | None = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        ddsketch_gamma(alpha)  # validates alpha
        if num_buckets < 2:
            raise ValueError(f"`num_buckets` must be >= 2, got {num_buckets}")
        qs = tuple(float(q) for q in quantiles)
        if not qs or any(not 0.0 <= q <= 1.0 for q in qs):
            raise ValueError(f"`quantiles` must be non-empty values in [0, 1], got {quantiles}")
        self.half_life_s = _require_positive_half_life(half_life_s)
        self.alpha = float(alpha)
        self.quantiles = qs
        self.num_buckets = int(num_buckets)
        self.key_offset = int(-num_buckets // 2 if key_offset is None else key_offset)
        # float32 is the *declared* contract here, not an oversight: exp2 decay
        # keeps every bucket's mass bounded by O(rate x half_life), so the
        # counter never grows past the horizon where f32 ulps matter
        decay_contract = {"horizon": "decay-bounded", "note": "mass <= update_rate * half_life / ln(2)"}
        self.add_state(
            "pos_buckets", default=jnp.zeros((self.num_buckets,), jnp.float32), dist_reduce_fx="sum",
            precision=decay_contract,
        )
        self.add_state(
            "neg_buckets", default=jnp.zeros((self.num_buckets,), jnp.float32), dist_reduce_fx="sum",
            precision=decay_contract,
        )
        self.add_state("zero_count", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum", precision=decay_contract)
        self.add_state("last_t", default=jnp.zeros((), jnp.float32), dist_reduce_fx="max")

    def update(self, t: Array, value: Array) -> None:
        value = jnp.asarray(value)
        d_pos, d_neg, d_zero = ddsketch_delta(
            value,
            jnp.ones(value.shape, bool),
            alpha=self.alpha,
            key_offset=self.key_offset,
            num_buckets=self.num_buckets,
        )
        ref, w_old, w_new = decay_weights(self.last_t, t, self.half_life_s)
        self.pos_buckets = self.pos_buckets * w_old + d_pos.astype(jnp.float32) * w_new
        self.neg_buckets = self.neg_buckets * w_old + d_neg.astype(jnp.float32) * w_new
        self.zero_count = self.zero_count * w_old + d_zero.astype(jnp.float32) * w_new
        self.last_t = ref

    def compute(self) -> Array:
        return ddsketch_quantiles(
            self.pos_buckets,
            self.neg_buckets,
            self.zero_count,
            self.quantiles,
            alpha=self.alpha,
            key_offset=self.key_offset,
        )

    def _merge_state_dicts(
        self, state_a: Dict[str, Any], state_b: Dict[str, Any], count_a: int, count_b: int
    ) -> Dict[str, Any]:
        ref, w_a, w_b = decay_weights(state_a["last_t"], state_b["last_t"], self.half_life_s)
        out = {
            name: state_a[name] * w_a + state_b[name] * w_b
            for name in ("pos_buckets", "neg_buckets", "zero_count")
        }
        out["last_t"] = ref
        return out


class DecayedHLL(Metric):
    """Time-decayed distinct-count sketch: HyperLogLog registers that forget.

    Registers are float32 *decaying-max ranks*: ``regs = max(regs·w_old,
    delta·w_new)``. Because the decay rescale is a positive monotone map it
    distributes over ``max``, so the state is exactly
    ``max_i rank_i·2^(-(ref-t_i)/half_life)`` — order-invariant, and two
    replicas merge by decaying both to a common reference time and taking the
    elementwise max (DESIGN §20). At ``half_life_s → ∞`` this is bit-for-bit
    ordinary HyperLogLog; at finite half-life the estimate tracks the
    *recently seen* cardinality, decaying toward 0 when a key stops appearing.
    ``compute()`` uses :func:`metrics_tpu.ops.decay.decayed_hll_estimate`,
    whose linear-counting correction treats a register decayed below rank ½ as
    empty (a plain ``== 0`` test would floor the estimate at α·m forever).

    ``update(t, values)`` prepends a () float32 timestamp of nonnegative
    stream-relative seconds to the plain sketch's signature.

    Args: as :class:`~metrics_tpu.sketches.HyperLogLog`, plus ``half_life_s``.
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(self, half_life_s: float, p: int = 12, seed: int = 0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not 4 <= int(p) <= 18:
            raise ValueError(f"`p` must be in [4, 18], got {p}")
        self.half_life_s = _require_positive_half_life(half_life_s)
        self.p = int(p)
        self.seed = int(seed)
        self.add_state(
            "registers", default=jnp.zeros((1 << self.p,), jnp.float32), dist_reduce_fx="max"
        )
        self.add_state("last_t", default=jnp.zeros((), jnp.float32), dist_reduce_fx="max")

    def update(self, t: Array, values: Array) -> None:
        values = jnp.asarray(values)
        delta = hll_delta(values, jnp.ones(values.shape, bool), p=self.p, seed=self.seed)
        ref, w_old, w_new = decay_weights(self.last_t, t, self.half_life_s)
        self.registers = jnp.maximum(
            self.registers * w_old, delta.astype(jnp.float32) * w_new
        )
        self.last_t = ref

    def compute(self) -> Array:
        return decayed_hll_estimate(self.registers)

    def _merge_state_dicts(
        self, state_a: Dict[str, Any], state_b: Dict[str, Any], count_a: int, count_b: int
    ) -> Dict[str, Any]:
        ref, w_a, w_b = decay_weights(state_a["last_t"], state_b["last_t"], self.half_life_s)
        return {
            "registers": jnp.maximum(state_a["registers"] * w_a, state_b["registers"] * w_b),
            "last_t": ref,
        }
