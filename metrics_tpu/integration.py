"""Training-loop integration — the Lightning-contract equivalent for flax/optax loops.

The reference's L5 integration (SURVEY §1, §4.8; validated by
``/root/reference/tests/integrations/lightning/test_lightning.py``) gives metrics
a managed lifecycle inside a trainer: ``self.log(metric)`` values surface per
step and per epoch, metrics sync across processes when epoch results are read,
and every logged metric is reset automatically at epoch end.

JAX training loops are hand-written, so the equivalent here is an explicit
manager object with the same contract:

* :meth:`MetricLogbook.log` registers a metric under a name (once; re-logging
  the same name is a no-op so the call can live inside the step function);
* :meth:`MetricLogbook.log_batch` = ``self.log(metric, on_step=True)``: runs
  ``forward`` — the batch-local value comes back, the global state accumulates;
* :meth:`MetricLogbook.epoch_end` = the trainer's epoch boundary: computes every
  logged metric (``sync_on_compute`` applies, so multi-process state is
  all-gathered exactly once per epoch) and resets them afterwards;
* :meth:`MetricLogbook.epoch` is the same as a context manager for eval loops.

The manager is deliberately tiny: metrics keep their own functional core, so a
fully-jitted training step can instead carry metric state pytrees explicitly
(``metric.functional()``) and only hand final states to the logbook.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, List, Optional

from metrics_tpu.collections import MetricCollection
from metrics_tpu.metric import Metric

__all__ = ["MetricLogbook"]


class MetricLogbook:
    """Lightning-``self.log`` lifecycle for hand-written JAX loops.

    >>> import jax.numpy as jnp
    >>> from metrics_tpu.aggregation import MeanMetric
    >>> book = MetricLogbook()
    >>> for epoch_data in ([1.0, 2.0], [10.0]):
    ...     for batch in epoch_data:
    ...         _ = book.log_batch("train_loss", MeanMetric, jnp.asarray(batch))
    ...     print(sorted((k, float(v)) for k, v in book.epoch_end().items()))
    [('train_loss', 1.5)]
    [('train_loss', 10.0)]
    """

    def __init__(self) -> None:
        self._metrics: Dict[str, Metric] = {}
        self._history: List[Dict[str, Any]] = []

    # ------------------------------------------------------------------ registration
    def log(self, name: str, metric: Any) -> Metric:
        """Register ``metric`` under ``name`` (idempotent, so it can sit in the step fn).

        ``metric`` may be a :class:`Metric`, a :class:`MetricCollection`, or a
        zero-arg factory/class producing one.
        """
        if name not in self._metrics:
            if not isinstance(metric, (Metric, MetricCollection)):
                metric = metric()
            if not isinstance(metric, (Metric, MetricCollection)):
                raise ValueError(f"Expected a Metric/MetricCollection (or factory) for {name!r}, got {type(metric)}")
            self._metrics[name] = metric
        return self._metrics[name]

    def __getitem__(self, name: str) -> Metric:
        return self._metrics[name]

    def __contains__(self, name: str) -> bool:
        return name in self._metrics

    # ------------------------------------------------------------------ step / epoch
    def log_batch(self, name: str, metric: Any, *args: Any, **kwargs: Any) -> Any:
        """``self.log(metric, on_step=True)``: forward → batch value + accumulation."""
        m = self.log(name, metric)
        return m(*args, **kwargs)

    def update(self, name: str, metric: Any, *args: Any, **kwargs: Any) -> None:
        """``self.log(metric)`` without a step value: update only (no batch compute)."""
        m = self.log(name, metric)
        m.update(*args, **kwargs)

    def epoch_end(self, reset: bool = True) -> Dict[str, Any]:
        """Compute every logged metric (distributed sync applies), then reset.

        Mirrors the Lightning epoch boundary: compute-once-per-epoch, values
        recorded into :attr:`history`, state cleared for the next epoch.
        """
        values: Dict[str, Any] = {}
        for name, metric in self._metrics.items():
            out = metric.compute()
            if isinstance(out, dict):
                values.update({f"{name}_{k}" if k != name else k: v for k, v in out.items()})
                # the dict itself is reachable under the bare name unless a member
                # metric already claimed it (scalar entries win)
                values.setdefault(name, out)
            else:
                values[name] = out
        self._history.append(values)
        if reset:
            self.reset()
        return values

    @contextmanager
    def epoch(self) -> Iterator["MetricLogbook"]:
        """Context manager over one eval epoch: compute+reset on exit."""
        yield self
        self.epoch_end()

    def reset(self) -> None:
        for metric in self._metrics.values():
            metric.reset()

    @property
    def history(self) -> List[Dict[str, Any]]:
        """Per-epoch computed values, oldest first (the logger's scalar trace)."""
        return self._history
