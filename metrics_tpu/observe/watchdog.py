"""Self-monitoring watchdog: our own metric designs over our own telemetry (DESIGN §22).

The recorder (DESIGN §11) and flight recorder (DESIGN §19) *emit* counters,
spans and latency sketches, but nothing watches them: a recompile storm, a
collapsing cache hit rate or a WAL-lag runaway is only visible if a human
polls ``fleet_top.py`` at the right moment. This module closes the loop by
running host-side twins of the repo's own streaming-metric designs on the
telemetry stream itself:

* :class:`HostTimeDecayedRate` — the ``windows.TimeDecayed`` fold (state ·
  2^(−Δt/half_life) + batch) as two plain floats, giving exponentially
  time-decayed compile/eviction/fallback/rollback rates;
* :class:`HostCUSUM` — Page's two-sided CUSUM in the exact ``(total,
  statistic, max-prefix, watermark)`` segment-compose form of
  ``ops/decay.cusum_compose``, so per-shard watchdog states merge to the
  single-stream trajectory bit-for-bit (local segment first, peer appended);
* :func:`host_psi` — the ``drift.PSI`` formula (Σ (p_live − p_ref) ·
  ln(p_live / p_ref), probabilities clipped at 1e-6) over the fleet
  occupancy histogram, referenced against the first populated sample;
* tick/dispatch latency quantiles read straight from the recorder's
  per-(phase, label) :class:`~metrics_tpu.observe.latency.HostDDSketch`
  instances (merged across labels — duck-typed, so this module stays
  stdlib-only and import-light like the recorder).

Each :meth:`Watchdog.sample` turns recorder counter/gauge deltas into a
``signals`` dict, publishes every numeric signal as a ``watchdog_signal``
gauge, and evaluates the declarative :class:`SloRule` list: a rule fires
after ``for_ticks`` *consecutive* breaching samples (``slo_fired`` event +
counter, ``slo_firing`` gauge → 1) and resolves on the first healthy sample
(``slo_resolved``, gauge → 0). Everything lands in the ordinary recorder
surfaces, so ``observe.snapshot()`` / ``observe.prometheus()`` /
``tools/fleet_top.py`` carry the alert state with zero new plumbing — and
zero device dispatches anywhere on this path.

Wiring: :func:`install_watchdog` registers the instance with the recorder;
``StreamEngine.tick`` / ``ShardedStreamEngine.tick`` poke it (telemetry on)
via ``recorder.poke_watchdog``, which samples at most once per
``min_interval_s``. Cross-process fleets merge shard watchdogs through
:meth:`Watchdog.export_state` / :meth:`Watchdog.sync_telemetry`, mirroring
``observe.latency``'s path.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from metrics_tpu.observe import recorder as _rec

__all__ = [
    "DEFAULT_SLOS",
    "HostCUSUM",
    "HostTimeDecayedRate",
    "SloRule",
    "Watchdog",
    "host_psi",
    "install_watchdog",
    "installed_watchdog",
    "uninstall_watchdog",
]

# counter families summed into each decayed rate / hit-rate signal — the same
# names the recorder's note_* hooks use, across all compiled-program caches
_COMPILE_COUNTERS = ("jit_compile", "jit_compile_unshared", "fleet_compile", "replica_compile", "fused_compile")
_EVICT_COUNTERS = ("jit_cache_eviction", "fleet_evict", "replica_evict")
_FALLBACK_COUNTERS = ("eager_fallback", "fleet_fallback", "replica_fallback", "fused_fallback")
_HIT_COUNTERS = ("jit_cache_hit", "fleet_hit", "replica_hit", "fused_hit")

_PSI_BINS = 10
_PSI_EPS = 1e-6  # probability clip — mirrors drift/histogram.py's _EPS


# ------------------------------------------------------------------ host twins

class HostTimeDecayedRate:
    """Host twin of ``windows.TimeDecayed`` over an event-count stream.

    Two floats fold the decayed event mass and the decayed observed seconds::

        w = 2^(−Δt / half_life_s);  sum ← sum·w + n;  norm ← norm·w + Δt

    ``rate()`` is events/second over the effective window (None until any
    time has elapsed). ``merge_state`` aligns the peer to the newer
    timestamp, *sums* the event mass and takes the *max* of the time norms:
    two shards watch the same wall clock, so equal windows merge to the sum
    of their rates, not the average.
    """

    __slots__ = ("half_life_s", "_sum", "_norm", "_t")

    def __init__(self, half_life_s: float = 30.0) -> None:
        if not half_life_s > 0.0:
            raise ValueError(f"`half_life_s` must be > 0, got {half_life_s}")
        self.half_life_s = float(half_life_s)
        self._sum = 0.0
        self._norm = 0.0
        self._t: Optional[float] = None

    def observe(self, n: float, now: float) -> None:
        if self._t is None:
            self._sum = float(n)
            self._norm = 0.0
            self._t = float(now)
            return
        dt = max(0.0, float(now) - self._t)
        w = 2.0 ** (-dt / self.half_life_s)
        self._sum = self._sum * w + float(n)
        self._norm = self._norm * w + dt
        self._t = float(now)

    def rate(self) -> Optional[float]:
        if self._norm <= 0.0:
            return None
        return self._sum / self._norm

    def state(self) -> Dict[str, Any]:
        return {"half_life_s": self.half_life_s, "sum": self._sum, "norm": self._norm, "t": self._t}

    @classmethod
    def from_state(cls, state: Mapping[str, Any]) -> "HostTimeDecayedRate":
        out = cls(state["half_life_s"])
        out._sum = float(state["sum"])
        out._norm = float(state["norm"])
        out._t = None if state["t"] is None else float(state["t"])
        return out

    def merge_state(self, state: Mapping[str, Any]) -> None:
        peer = self.from_state(state)
        if peer._t is None:
            return
        if self._t is None:
            self._sum, self._norm, self._t = peer._sum, peer._norm, peer._t
            return
        old, new = (self, peer) if peer._t >= self._t else (peer, self)
        w = 2.0 ** (-(new._t - old._t) / self.half_life_s)  # type: ignore[operator]
        self._sum = old._sum * w + new._sum
        self._norm = max(old._norm * w, new._norm)
        self._t = new._t


def _cusum_compose(a: Tuple[float, float, float, float], b: Tuple[float, float, float, float]) -> Tuple[float, float, float, float]:
    # float mirror of ops/decay.cusum_compose: a strictly before b in stream order
    ta, sa, pa, ma = a
    tb, sb, pb, mb = b
    return (ta + tb, max(sb, sa + tb), max(pa, ta + pb), max(ma, mb, sa + pb))


class HostCUSUM:
    """Host twin of ``drift.CUSUM``: Page's two-sided recursion in segment form.

    Each side holds the ``(total, statistic, max-prefix, watermark)`` summary
    of ``ops/decay.cusum_compose``; one observation composes a single-element
    segment, so the running ``statistic()`` equals the sequential recursion
    S ← max(0, S + contribution) exactly, and :meth:`merge_state` (local
    segment first, peer appended after — the fleet's stream order) is the
    same order-sensitive fold ``drift.CUSUM._merge_state_dicts`` declares.

    The watchdog alarms on the *current* statistic, not the watermark: an
    alert must resolve once the storm stops, while the watermark — the
    highest the statistic ever got — stays up by construction.
    """

    __slots__ = ("target", "k", "pos", "neg")

    def __init__(self, target: float, k: float = 0.5) -> None:
        if not float(k) >= 0.0:
            raise ValueError(f"`k` must be >= 0, got {k}")
        self.target = float(target)
        self.k = float(k)
        self.pos = (0.0, 0.0, 0.0, 0.0)
        self.neg = (0.0, 0.0, 0.0, 0.0)

    @staticmethod
    def _segment(c: float) -> Tuple[float, float, float, float]:
        up = max(0.0, c)
        return (c, up, up, up)

    def observe(self, x: float) -> None:
        v = float(x)
        if not math.isfinite(v):
            return
        self.pos = _cusum_compose(self.pos, self._segment(v - self.target - self.k))
        self.neg = _cusum_compose(self.neg, self._segment(self.target - self.k - v))

    def statistic(self) -> float:
        return max(self.pos[1], self.neg[1])

    def watermark(self) -> float:
        return max(self.pos[3], self.neg[3])

    def state(self) -> Dict[str, Any]:
        return {"target": self.target, "k": self.k, "pos": list(self.pos), "neg": list(self.neg)}

    @classmethod
    def from_state(cls, state: Mapping[str, Any]) -> "HostCUSUM":
        out = cls(state["target"], state["k"])
        out.pos = tuple(float(v) for v in state["pos"])  # type: ignore[assignment]
        out.neg = tuple(float(v) for v in state["neg"])  # type: ignore[assignment]
        return out

    def merge_state(self, state: Mapping[str, Any]) -> None:
        peer = self.from_state(state)
        self.pos = _cusum_compose(self.pos, peer.pos)
        self.neg = _cusum_compose(self.neg, peer.neg)


def host_psi(ref: Sequence[float], live: Sequence[float], eps: float = _PSI_EPS) -> Optional[float]:
    """Population-stability index between two count histograms.

    The float mirror of ``drift.PSI``: normalize both to probabilities, clip
    at ``eps``, sum ``(p_live − p_ref) · ln(p_live / p_ref)``. None when
    either histogram is empty.
    """
    tr = float(sum(ref))
    tl = float(sum(live))
    if tr <= 0.0 or tl <= 0.0 or len(ref) != len(live):
        return None
    total = 0.0
    for r, l in zip(ref, live):
        pr = max(r / tr, eps)
        pl = max(l / tl, eps)
        total += (pl - pr) * math.log(pl / pr)
    return total


def _occupancy_hist(fractions: Iterable[float]) -> List[float]:
    counts = [0.0] * _PSI_BINS
    for f in fractions:
        idx = int(max(0.0, min(1.0, f)) * _PSI_BINS)
        counts[min(idx, _PSI_BINS - 1)] += 1.0
    return counts


# ------------------------------------------------------------------- SLO rules

_OPS: Dict[str, Callable[[float, float], bool]] = {
    "<=": lambda v, t: v <= t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    ">": lambda v, t: v > t,
}


class SloRule:
    """One declarative objective: ``signal op threshold`` must hold.

    A sample *breaches* when the signal exists and the comparison fails;
    ``for_ticks`` consecutive breaches fire the alert, the first healthy
    sample resolves it. A missing signal (None — e.g. no AOT lookups this
    window) neither breaches nor resolves: the streak and firing state are
    simply carried.
    """

    __slots__ = ("name", "signal", "op", "threshold", "for_ticks")

    def __init__(self, name: str, signal: str, op: str, threshold: float, for_ticks: int = 1) -> None:
        if op not in _OPS:
            raise ValueError(f"`op` must be one of {sorted(_OPS)}, got {op!r}")
        if int(for_ticks) < 1:
            raise ValueError(f"`for_ticks` must be >= 1, got {for_ticks}")
        self.name = str(name)
        self.signal = str(signal)
        self.op = str(op)
        self.threshold = float(threshold)
        self.for_ticks = int(for_ticks)

    def healthy(self, value: float) -> bool:
        return _OPS[self.op](float(value), self.threshold)

    def __repr__(self) -> str:
        return (f"SloRule({self.name!r}, {self.signal!r}, {self.op!r}, "
                f"{self.threshold!r}, for_ticks={self.for_ticks})")


#: Steady-state objectives for a healthy fleet. ``dispatch_economy`` pins the
#: one-dispatch-per-flushed-bucket contract, the hit-rate floors catch cache
#: thrash, ``recompile_storm`` is the CUSUM change detector on per-sample
#: compile deltas (statistic decays by ``k`` per clean sample, so the alert
#: resolves after the storm), and the latency/lag ceilings bound the tick
#: path and durability debt.
DEFAULT_SLOS: Tuple[SloRule, ...] = (
    SloRule("dispatch_economy", "dispatches_per_bucket_per_tick", "<=", 1.0, for_ticks=3),
    SloRule("jit_hit_rate_floor", "jit_hit_rate", ">=", 0.5, for_ticks=3),
    SloRule("aot_hit_rate_floor", "aot_hit_rate", ">=", 0.5, for_ticks=3),
    SloRule("tick_latency_p99", "tick_p99_s", "<=", 0.25, for_ticks=3),
    SloRule("wal_lag", "wal_lag_records", "<=", 10_000.0, for_ticks=3),
    SloRule("recompile_storm", "recompile_cusum_stat", "<=", 3.0, for_ticks=2),
)


# -------------------------------------------------------------------- watchdog

class Watchdog:
    """Samples recorder deltas into host-side metric twins and evaluates SLOs.

    One instance is cheap and lock-protected; :meth:`sample` is a pure host
    computation over recorder counters/gauges/latency sketches — no jax, no
    device dispatch. Signals (None when undefined this window):

    ========================================  =====================================
    signal                                    meaning
    ========================================  =====================================
    ``compile_rate_per_s``                    time-decayed XLA/program compiles
    ``eviction_rate_per_s``                   time-decayed cache evictions
    ``fallback_rate_per_s``                   time-decayed eager fallbacks
    ``rollback_rate_per_s``                   time-decayed rolled-back updates
    ``compiles_delta``                        raw compiles since last sample
    ``recompile_cusum_stat``                  CUSUM statistic on compiles_delta
    ``dispatches_per_bucket_per_tick``        Δfleet_dispatch / Δfleet_flush
    ``dispatch_economy_cusum_stat``           CUSUM statistic on the above
    ``jit_hit_rate``                          windowed hits/(hits+compiles)
    ``jit_hit_cusum_stat``                    CUSUM (downward) on jit_hit_rate
    ``aot_hit_rate``                          windowed AOT hits/lookups
    ``aot_hit_cusum_stat``                    CUSUM (downward) on aot_hit_rate
    ``tick_p99_s``                            windowed DDSketch p99, phase "tick"
    ``dispatch_p99_s``                        windowed DDSketch p99, phase "dispatch"
    ``wal_lag_records``                       summed durability-lag gauge
    ``occupancy_psi``                         PSI of the bucket-occupancy histogram
    ``serve_ingest_rate_per_s``               time-decayed front-door record ingest
    ``serve_shed_rate_per_s``                 time-decayed loose-first sheds
    ``serve_queue_depth``                     front-door decoded-not-yet-applied gauge
    ========================================  =====================================

    The three ``serve_*`` signals (DESIGN §26) are additive — no default SLO
    reads them, so fleets without a network front door see them as 0/None and
    operators with one can pin their own :class:`SloRule` rows on top.
    """

    def __init__(
        self,
        rules: Optional[Sequence[SloRule]] = None,
        half_life_s: float = 30.0,
        min_interval_s: float = 0.25,
    ) -> None:
        self.rules: List[SloRule] = list(DEFAULT_SLOS if rules is None else rules)
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        self._rates = {
            "compile": HostTimeDecayedRate(half_life_s),
            "eviction": HostTimeDecayedRate(half_life_s),
            "fallback": HostTimeDecayedRate(half_life_s),
            "rollback": HostTimeDecayedRate(half_life_s),
            "serve_ingest": HostTimeDecayedRate(half_life_s),
            "serve_shed": HostTimeDecayedRate(half_life_s),
        }
        self._cusums = {
            "recompile": HostCUSUM(target=0.0, k=1.0),
            "dispatch_economy": HostCUSUM(target=1.0, k=0.25),
            "jit_hit": HostCUSUM(target=1.0, k=0.25),
            "aot_hit": HostCUSUM(target=1.0, k=0.25),
        }
        self._prev: Dict[str, float] = {}
        self._prev_sketch: Dict[str, Any] = {}  # phase -> cumulative merged sketch
        self._psi_ref: Optional[List[float]] = None
        self._rule_state: Dict[str, Dict[str, Any]] = {
            r.name: {"streak": 0, "firing": False} for r in self.rules
        }
        self._samples = 0
        self._last_signals: Dict[str, Optional[float]] = {}
        self._last_sample_t: Optional[float] = None

    # ---------------------------------------------------------------- sampling
    def maybe_sample(self) -> None:
        """Rate-limited :meth:`sample` — the engine-tick poke entry point."""
        if not _rec.ENABLED:
            return
        now = _rec.clock()
        if self._last_sample_t is not None and now - self._last_sample_t < self.min_interval_s:
            return
        self.sample(now)

    def _read_raw(self) -> Dict[str, Any]:
        rec = _rec.RECORDER
        with rec._lock:
            sums: Dict[str, float] = {}
            for (name, _label), v in rec.counters.items():
                sums[name] = sums.get(name, 0.0) + v
            active: Dict[str, float] = {}
            capacity: Dict[str, float] = {}
            wal_lag = 0.0
            serve_queue = 0.0
            for (name, label), v in rec.gauges.items():
                if name == "fleet_rows_active":
                    active[label] = v
                elif name == "fleet_rows_capacity":
                    capacity[label] = v
                elif name == "wal_lag_records":
                    wal_lag += v
                elif name == "serve_queue_depth":
                    serve_queue += v
            tick_sketches = [sk.copy() for (ph, _l), sk in rec.latency.items() if ph == "tick"]
            dispatch_sketches = [sk.copy() for (ph, _l), sk in rec.latency.items() if ph == "dispatch"]
        fractions = [active.get(lbl, 0.0) / cap for lbl, cap in capacity.items() if cap > 0]
        return {
            "sums": sums,
            "wal_lag_records": wal_lag,
            "serve_queue_depth": serve_queue,
            "occupancy_fractions": fractions,
            "tick_sketches": tick_sketches,
            "dispatch_sketches": dispatch_sketches,
        }

    def _windowed_p99(self, phase: str, sketches: List[Any]) -> Optional[float]:
        """p99 of the durations recorded *since the previous sample*.

        The recorder's sketches are cumulative, so an expensive warmup tick
        would otherwise poison the p99 for the process lifetime. The sketch's
        bucket counts are monotone under merge, which makes the cumulative
        sketch differencable: subtract the previous sample's merged buckets
        and read the quantile off the window. None when the window recorded
        nothing (or the recorder was reset — counts go negative and the new
        cumulative state re-seeds the baseline). The first sample only seeds
        the baseline, mirroring the counter deltas.
        """
        if not sketches:
            return None
        merged = sketches[0]
        for sk in sketches[1:]:
            merged.merge(sk)
        prev = self._prev_sketch.get(phase)
        self._prev_sketch[phase] = merged.copy()
        if prev is None:
            return None
        merged.pos = merged.pos - prev.pos
        merged.neg = merged.neg - prev.neg
        merged.zero -= prev.zero
        merged.count -= prev.count
        if merged.count <= 0 or merged.pos.min() < 0 or merged.neg.min() < 0:
            return None
        return float(merged.quantile(0.99))

    def sample(self, now: Optional[float] = None) -> Optional[Dict[str, Optional[float]]]:
        """One watchdog evaluation; returns the signals dict (None if disabled)."""
        if not _rec.ENABLED:
            return None
        t = _rec.clock() if now is None else float(now)
        raw = self._read_raw()
        sums = raw["sums"]

        def family(names: Tuple[str, ...]) -> float:
            return float(sum(sums.get(n, 0.0) for n in names))

        fired: List[Tuple[SloRule, float]] = []
        resolved: List[Tuple[SloRule, float]] = []
        with self._lock:
            self._last_sample_t = t

            def delta(key: str, total: float) -> float:
                # first sample seeds the baseline: history that predates the
                # watchdog (e.g. warmup compiles) is not a storm
                prev = self._prev.get(key)
                self._prev[key] = total
                if prev is None:
                    return 0.0
                return max(0.0, total - prev)

            d_compiles = delta("compiles", family(_COMPILE_COUNTERS))
            d_evicts = delta("evictions", family(_EVICT_COUNTERS))
            d_fallbacks = delta("fallbacks", family(_FALLBACK_COUNTERS))
            d_rollbacks = delta("rollbacks", float(sums.get("update_rolled_back", 0.0)))
            d_hits = delta("hits", family(_HIT_COUNTERS))
            d_aot_hits = delta("aot_hits", float(sums.get("aot_hit", 0.0)))
            d_aot_misses = delta("aot_misses", float(sums.get("aot_miss", 0.0)))
            d_dispatches = delta("dispatches", float(sums.get("fleet_dispatch", 0.0)))
            d_flushes = delta("flushes", float(sums.get("fleet_flush", 0.0)))
            d_serve_frames = delta("serve_frames", float(sums.get("serve_frames", 0.0)))
            d_serve_shed = delta("serve_shed", float(sums.get("serve_shed_sessions", 0.0)))

            self._rates["compile"].observe(d_compiles, t)
            self._rates["eviction"].observe(d_evicts, t)
            self._rates["fallback"].observe(d_fallbacks, t)
            self._rates["rollback"].observe(d_rollbacks, t)
            self._rates["serve_ingest"].observe(d_serve_frames, t)
            self._rates["serve_shed"].observe(d_serve_shed, t)

            self._cusums["recompile"].observe(d_compiles)
            per_bucket = (d_dispatches / d_flushes) if d_flushes > 0 else None
            if per_bucket is not None:
                self._cusums["dispatch_economy"].observe(per_bucket)
            jit_lookups = d_hits + d_compiles
            jit_hit_rate = (d_hits / jit_lookups) if jit_lookups > 0 else None
            if jit_hit_rate is not None:
                self._cusums["jit_hit"].observe(jit_hit_rate)
            aot_lookups = d_aot_hits + d_aot_misses
            aot_hit_rate = (d_aot_hits / aot_lookups) if aot_lookups > 0 else None
            if aot_hit_rate is not None:
                self._cusums["aot_hit"].observe(aot_hit_rate)

            psi = None
            fractions = raw["occupancy_fractions"]
            if fractions:
                live_hist = _occupancy_hist(fractions)
                if self._psi_ref is None:
                    self._psi_ref = live_hist
                psi = host_psi(self._psi_ref, live_hist)

            signals: Dict[str, Optional[float]] = {
                "compile_rate_per_s": self._rates["compile"].rate(),
                "eviction_rate_per_s": self._rates["eviction"].rate(),
                "fallback_rate_per_s": self._rates["fallback"].rate(),
                "rollback_rate_per_s": self._rates["rollback"].rate(),
                "compiles_delta": d_compiles,
                "recompile_cusum_stat": self._cusums["recompile"].statistic(),
                "dispatches_per_bucket_per_tick": per_bucket,
                "dispatch_economy_cusum_stat": self._cusums["dispatch_economy"].statistic(),
                "jit_hit_rate": jit_hit_rate,
                "jit_hit_cusum_stat": self._cusums["jit_hit"].statistic(),
                "aot_hit_rate": aot_hit_rate,
                "aot_hit_cusum_stat": self._cusums["aot_hit"].statistic(),
                "tick_p99_s": self._windowed_p99("tick", raw["tick_sketches"]),
                "dispatch_p99_s": self._windowed_p99("dispatch", raw["dispatch_sketches"]),
                "wal_lag_records": raw["wal_lag_records"],
                "occupancy_psi": psi,
                "serve_ingest_rate_per_s": self._rates["serve_ingest"].rate(),
                "serve_shed_rate_per_s": self._rates["serve_shed"].rate(),
                "serve_queue_depth": raw["serve_queue_depth"],
            }

            for rule in self.rules:
                value = signals.get(rule.signal)
                state = self._rule_state[rule.name]
                if value is None:
                    continue
                if rule.healthy(value):
                    state["streak"] = 0
                    if state["firing"]:
                        state["firing"] = False
                        resolved.append((rule, value))
                else:
                    state["streak"] += 1
                    if state["streak"] >= rule.for_ticks and not state["firing"]:
                        state["firing"] = True
                        fired.append((rule, value))
            firing_now = {r.name: self._rule_state[r.name]["firing"] for r in self.rules}
            self._samples += 1
            self._last_signals = signals

        rec = _rec.RECORDER
        rec.add_count("watchdog_sample", "watchdog")
        for name, value in signals.items():
            if value is not None:
                rec.set_gauge("watchdog_signal", name, float(value))
        for rule_name, firing in firing_now.items():
            rec.set_gauge("slo_firing", rule_name, 1.0 if firing else 0.0)
        for rule, value in fired:
            rec.add_count("slo_fired", rule.name)
            rec.add_event(
                "slo_fired", rule=rule.name, signal=rule.signal, value=float(value),
                op=rule.op, threshold=rule.threshold, for_ticks=rule.for_ticks,
            )
        for rule, value in resolved:
            rec.add_count("slo_resolved", rule.name)
            rec.add_event(
                "slo_resolved", rule=rule.name, signal=rule.signal, value=float(value),
                op=rule.op, threshold=rule.threshold,
            )
        return signals

    # ------------------------------------------------------------------ verdict
    def health(self) -> Dict[str, Any]:
        """Fleet-health verdict from the last evaluated sample."""
        with self._lock:
            firing = sorted(n for n, st in self._rule_state.items() if st["firing"])
            return {
                "ok": not firing,
                "verdict": "degraded" if firing else "healthy",
                "firing": firing,
                "samples": self._samples,
                "signals": dict(self._last_signals),
            }

    # ---------------------------------------------------------- shard mergeability
    def export_state(self) -> Dict[str, Any]:
        """JSON-able mergeable watchdog state (rates + CUSUM segments + PSI ref)."""
        with self._lock:
            return {
                "schema": 1,
                "samples": self._samples,
                "rates": {k: r.state() for k, r in self._rates.items()},
                "cusums": {k: c.state() for k, c in self._cusums.items()},
                "psi_ref": None if self._psi_ref is None else list(self._psi_ref),
            }

    def sync_telemetry(self, peer_states: Iterable[Mapping[str, Any]]) -> "Watchdog":
        """Fold peer shards' exported states into this watchdog (local first,
        each peer appended in iteration order — the CUSUM stream order)."""
        with self._lock:
            for state in peer_states:
                for key, rate in self._rates.items():
                    peer = (state.get("rates") or {}).get(key)
                    if peer is not None:
                        rate.merge_state(peer)
                for key, cusum in self._cusums.items():
                    peer = (state.get("cusums") or {}).get(key)
                    if peer is not None:
                        cusum.merge_state(peer)
                if self._psi_ref is None and state.get("psi_ref"):
                    self._psi_ref = [float(v) for v in state["psi_ref"]]
                self._samples += int(state.get("samples", 0))
        return self


# ----------------------------------------------------------------- installation

_ACTIVE: Optional[Watchdog] = None


def install_watchdog(watchdog: Optional[Watchdog] = None, **kwargs: Any) -> Watchdog:
    """Register a process-wide watchdog; engine ticks auto-sample it.

    Pass an instance, or keyword args forwarded to :class:`Watchdog`. The
    recorder's ``poke_watchdog`` (called from ``StreamEngine.tick`` /
    ``ShardedStreamEngine.tick`` while telemetry is enabled) rate-limits
    sampling to ``min_interval_s``; loops without an engine call
    ``observe.poke_watchdog()`` themselves or ``sample()`` directly.
    """
    global _ACTIVE
    wd = watchdog if watchdog is not None else Watchdog(**kwargs)
    _ACTIVE = wd
    _rec._set_watchdog(wd)
    return wd


def uninstall_watchdog() -> None:
    global _ACTIVE
    _ACTIVE = None
    _rec._set_watchdog(None)


def installed_watchdog() -> Optional[Watchdog]:
    return _ACTIVE
