"""Static XLA cost profiling of compiled metric updates (DESIGN §11).

For every jit-eligible exported metric class in :data:`PROFILE_CASES` the
harness lowers the pure update — ``jax.jit(m._functional_update).lower(state,
*abstract_args)`` — and reads XLA's own cost model
(``Lowered.cost_analysis()``: FLOPs + bytes accessed) plus, optionally, the
compiled executable's memory footprint (``Compiled.memory_analysis()``: peak
temp/argument/output bytes). Zero data-dependent execution: the numbers are a
pure function of the program XLA was handed, which is exactly what a perf
ratchet wants to pin (the harness pattern follows
``analysis/abstract_contracts.py``; the compiler-first cost accounting follows
DrJAX's MapReduce-primitive cost model, PAPERS.md).

Each case also reports the *sharing* story: whether the class produces a
hashable static-config key (``Metric._jit_cache_key``) so N config-equal
instances replay ONE executable, and — via a tiny real two-instance update
under the observe runtime — how many compiles two instances actually cost.
A third dynamic probe measures the *cold-start* story (DESIGN §18): whether
the class's executable persists through the AOT disk cache
(``aot_cacheable``) and how many XLA compiles a fresh process still pays for
its first update with a warmed cache mounted (``cold_start_compile_count``,
0 when disk reuse works).

Run via ``tools/profile_metrics.py`` / the ``profile-metrics`` console script;
baselined in ``tools/perf_baseline.json`` (see :mod:`metrics_tpu.observe.profile`).
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "PROFILE_CASES",
    "CostReport",
    "ProfileCase",
    "collect_cost_report",
    "profile_case",
]

# canonical problem sizes — small, TPU-lane-agnostic, matched to the
# abstract-contracts harness so the two static passes describe the same regime
_N, _C = 64, 4
_IMG = (2, 3, 16, 16)


@dataclasses.dataclass(frozen=True)
class ProfileCase:
    """One exported Metric class plus a deterministic synthetic batch source."""

    name: str  # exported class name — the baseline key
    ctor: Callable[[], Any]
    batch: Callable[[np.random.RandomState], Tuple[Any, ...]]


@dataclasses.dataclass
class CostReport:
    case: ProfileCase
    ok: bool
    cost: Dict[str, Any] = dataclasses.field(default_factory=dict)
    error: str = ""


def _rng(case: ProfileCase) -> np.random.RandomState:
    return np.random.RandomState(zlib.crc32(case.name.encode()) % (2**31))


def _abstract(args: Sequence[Any]) -> List[Any]:
    return [
        jax.ShapeDtypeStruct(a.shape, a.dtype) if isinstance(a, (jax.Array, np.ndarray)) else a
        for a in args
    ]


def profile_case(case: ProfileCase, include_memory: bool = True, dynamic: bool = True) -> CostReport:
    """Lower one class's update and read XLA's cost model.

    ``dynamic=True`` additionally runs TWO config-equal instances through one
    real (tiny) update each under the observe runtime and reports the compile
    count — 1 proves shared-cache sharing works end to end, 2 means every
    instance pays its own trace+compile (the regression the ratchet exists to
    catch). ``include_memory=False`` skips backend compilation (lower-only is
    several times faster; FLOPs/bytes are unaffected).
    """
    from metrics_tpu.metric import _SHARED_JIT_CACHE, Metric, clear_jit_cache
    from metrics_tpu.observe import recorder as _observe

    try:
        m = case.ctor()
        if not isinstance(m, Metric):
            return CostReport(case, ok=False, error=f"{case.name} did not construct a Metric")
        if type(m).__jit_ineligible__ or m._has_list_state():
            return CostReport(case, ok=False, error="not jit-eligible (list state or host-side update)")
        args = case.batch(_rng(case))
        state = m._fresh_state()
        lowered = jax.jit(m._functional_update).lower(state, *_abstract(args))
        analysis = lowered.cost_analysis() or {}
        if isinstance(analysis, (list, tuple)):  # older jax: one entry per computation
            analysis = analysis[0] if analysis else {}
        cost: Dict[str, Any] = {
            "flops": float(analysis.get("flops", 0.0)),
            "bytes_accessed": float(analysis.get("bytes accessed", 0.0)),
            "shareable": m._jit_cache_key() is not None,
            # static per-class eligibility: stable unless the class grows a list
            # state or opts out — a True→False flip is a perf regression (the
            # update loop starts reallocating O(state) every step)
            "donation_eligible": m._donation_eligible(),
        }
        if include_memory:
            mem = lowered.compile().memory_analysis()
            if mem is not None:
                cost["peak_memory_bytes"] = int(
                    getattr(mem, "argument_size_in_bytes", 0)
                    + getattr(mem, "output_size_in_bytes", 0)
                    + getattr(mem, "temp_size_in_bytes", 0)
                )
        if dynamic:
            # two fresh instances, a pristine shared cache, real updates: the
            # observed compile count IS the sharing behavior users get
            saved_cache = dict(_SHARED_JIT_CACHE)
            was_enabled = _observe.ENABLED
            probe = _observe.Recorder()
            real, _observe.RECORDER = _observe.RECORDER, probe
            try:
                clear_jit_cache()
                _observe.ENABLED = True
                for inst in (case.ctor(), case.ctor()):
                    inst.update(*args)
            finally:
                _observe.ENABLED = was_enabled
                _observe.RECORDER = real
                _SHARED_JIT_CACHE.clear()
                _SHARED_JIT_CACHE.update(saved_cache)
            cls_label = type(m).__name__
            compiles = probe.counters.get(("jit_compile", cls_label), 0) + probe.counters.get(
                ("jit_compile_unshared", cls_label), 0
            )
            cost["compile_count"] = int(compiles)
            cost["cache_hits"] = int(probe.counters.get(("jit_cache_hit", cls_label), 0))
            if probe.counters.get(("eager_fallback", cls_label)):
                return CostReport(case, ok=False, error="update latched eager fallback under jit")
            cost.update(_cold_start_probe(case, args, cls_label, bool(compiles)))
        return CostReport(case, ok=True, cost=cost)
    except Exception as exc:  # noqa: BLE001 — the error text IS the result
        return CostReport(case, ok=False, error=f"{type(exc).__name__}: {exc}")


def _cold_start_probe(
    case: ProfileCase, args: Sequence[Any], cls_label: str, compiled: bool
) -> Dict[str, Any]:
    """Measure what a FRESH process pays for this class's first update when a
    warmed AOT executable cache (DESIGN §18) is mounted.

    Warm a throwaway disk cache with one real update, drop the in-memory shared
    cache (the stand-in for a process boundary), then update again:

    * ``aot_cacheable`` — the warm leg persisted at least one executable;
    * ``cold_start_compile_count`` — XLA compiles the second leg still paid
      (0 when disk reuse works; for an uncacheable class, the compile every
      process re-pays).

    A class that never compiled in the sharing probe skips the disk legs: its
    update is eager by design, so a new process pays zero compiles anyway.
    """
    if not compiled:
        return {"aot_cacheable": False, "cold_start_compile_count": 0}
    import tempfile

    from metrics_tpu.aot import cache as _aot_cache
    from metrics_tpu.metric import _SHARED_JIT_CACHE, clear_jit_cache
    from metrics_tpu.observe import recorder as _observe

    prev_dir = _aot_cache.cache_dir()
    saved_cache = dict(_SHARED_JIT_CACHE)
    was_enabled = _observe.ENABLED
    probe = _observe.Recorder()
    real, _observe.RECORDER = _observe.RECORDER, probe
    try:
        with tempfile.TemporaryDirectory(prefix="aot_profile_") as tmp:
            _aot_cache.set_cache_dir(tmp)
            _observe.ENABLED = True
            clear_jit_cache()
            case.ctor().update(*args)  # warm leg: compile AOT, serialize to disk
            stored = probe.counters.get(("aot_store", cls_label), 0)
            clear_jit_cache()  # the process boundary: only the disk survives
            before = dict(probe.counters)
            case.ctor().update(*args)  # cold-start leg: should reload, not compile
            cold = (
                probe.counters.get(("jit_compile", cls_label), 0)
                - before.get(("jit_compile", cls_label), 0)
                + probe.counters.get(("jit_compile_unshared", cls_label), 0)
                - before.get(("jit_compile_unshared", cls_label), 0)
            )
    finally:
        _observe.ENABLED = was_enabled
        _observe.RECORDER = real
        _SHARED_JIT_CACHE.clear()
        _SHARED_JIT_CACHE.update(saved_cache)
        _aot_cache.set_cache_dir(prev_dir)
    return {"aot_cacheable": bool(stored), "cold_start_compile_count": int(cold)}


def collect_cost_report(
    cases: Optional[Sequence[ProfileCase]] = None,
    include_memory: bool = True,
    dynamic: bool = True,
) -> List[CostReport]:
    """Profile every case; returns all results (callers apply the baseline)."""
    return [
        profile_case(c, include_memory=include_memory, dynamic=dynamic)
        for c in (cases if cases is not None else _cases())
    ]


# --------------------------------------------------------------------------- registry
def _rand(rng: np.random.RandomState, *shape: int) -> jax.Array:
    return jnp.asarray(rng.rand(*shape).astype(np.float32))


def _randint(rng: np.random.RandomState, hi: int, *shape: int) -> jax.Array:
    return jnp.asarray(rng.randint(0, hi, shape).astype(np.int32))


def _probs(rng: np.random.RandomState, *shape: int) -> jax.Array:
    p = rng.rand(*shape).astype(np.float32) + 0.05
    return jnp.asarray(p / p.sum(-1, keepdims=True))


def _make_cases() -> List[ProfileCase]:
    import metrics_tpu as M
    import metrics_tpu.classification as C
    import metrics_tpu.segmentation as S

    case = ProfileCase
    bin_batch = lambda r: (_rand(r, _N), _randint(r, 2, _N))  # noqa: E731
    reg_batch = lambda r: (_rand(r, _N), _rand(r, _N))  # noqa: E731
    mc_batch = lambda r: (_probs(r, _N, _C), _randint(r, _C, _N))  # noqa: E731
    ml_batch = lambda r: (_rand(r, _N, _C), _randint(r, 2, _N, _C))  # noqa: E731
    img_batch = lambda r: (_rand(r, *_IMG), _rand(r, *_IMG))  # noqa: E731
    seg_batch = lambda r: (_randint(r, _C, 2, 8, 8), _randint(r, _C, 2, 8, 8))  # noqa: E731
    nom_batch = lambda r: (_randint(r, _C, _N), _randint(r, _C, _N))  # noqa: E731

    return [
        # ---- classification (binary) ------------------------------------------
        case("BinaryAccuracy", C.BinaryAccuracy, bin_batch),
        case("BinaryPrecision", C.BinaryPrecision, bin_batch),
        case("BinaryRecall", C.BinaryRecall, bin_batch),
        case("BinaryF1Score", C.BinaryF1Score, bin_batch),
        case("BinarySpecificity", C.BinarySpecificity, bin_batch),
        case("BinaryStatScores", C.BinaryStatScores, bin_batch),
        case("BinaryHammingDistance", C.BinaryHammingDistance, bin_batch),
        case("BinaryCohenKappa", C.BinaryCohenKappa, bin_batch),
        case("BinaryMatthewsCorrCoef", C.BinaryMatthewsCorrCoef, bin_batch),
        case("BinaryJaccardIndex", C.BinaryJaccardIndex, bin_batch),
        case("BinaryHingeLoss", C.BinaryHingeLoss, bin_batch),
        case("BinaryCalibrationError", C.BinaryCalibrationError, bin_batch),
        case("BinaryAUROC", lambda: C.BinaryAUROC(thresholds=16), bin_batch),
        case("BinaryAveragePrecision", lambda: C.BinaryAveragePrecision(thresholds=16), bin_batch),
        case("BinaryNegativePredictiveValue", C.BinaryNegativePredictiveValue, bin_batch),
        # ---- classification (multiclass / multilabel) -------------------------
        case("MulticlassAccuracy", lambda: C.MulticlassAccuracy(num_classes=_C), mc_batch),
        case("MulticlassPrecision", lambda: C.MulticlassPrecision(num_classes=_C), mc_batch),
        case("MulticlassRecall", lambda: C.MulticlassRecall(num_classes=_C), mc_batch),
        case("MulticlassF1Score", lambda: C.MulticlassF1Score(num_classes=_C), mc_batch),
        case("MulticlassConfusionMatrix", lambda: C.MulticlassConfusionMatrix(num_classes=_C), mc_batch),
        case("MulticlassCohenKappa", lambda: C.MulticlassCohenKappa(num_classes=_C), mc_batch),
        case("MulticlassAUROC", lambda: C.MulticlassAUROC(num_classes=_C, thresholds=16), mc_batch),
        case("MulticlassExactMatch", lambda: C.MulticlassExactMatch(num_classes=_C),
             lambda r: (_randint(r, _C, 8, 6), _randint(r, _C, 8, 6))),
        case("MultilabelFBetaScore", lambda: C.MultilabelFBetaScore(beta=2.0, num_labels=_C), ml_batch),
        case("MultilabelAccuracy", lambda: C.MultilabelAccuracy(num_labels=_C), ml_batch),
        # ---- regression --------------------------------------------------------
        case("MeanSquaredError", M.MeanSquaredError, reg_batch),
        case("MeanAbsoluteError", M.MeanAbsoluteError, reg_batch),
        case("MeanSquaredLogError", M.MeanSquaredLogError, reg_batch),
        case("MeanAbsolutePercentageError", M.MeanAbsolutePercentageError, reg_batch),
        case("SymmetricMeanAbsolutePercentageError", M.SymmetricMeanAbsolutePercentageError, reg_batch),
        case("WeightedMeanAbsolutePercentageError", M.WeightedMeanAbsolutePercentageError, reg_batch),
        case("ExplainedVariance", M.ExplainedVariance, reg_batch),
        case("R2Score", M.R2Score, reg_batch),
        case("PearsonCorrCoef", M.PearsonCorrCoef, reg_batch),
        case("ConcordanceCorrCoef", M.ConcordanceCorrCoef, reg_batch),
        case("MinkowskiDistance", lambda: M.MinkowskiDistance(p=3), reg_batch),
        case("LogCoshError", M.LogCoshError, reg_batch),
        case("TweedieDevianceScore", lambda: M.TweedieDevianceScore(power=1.5),
             lambda r: (_rand(r, _N) + 0.1, _rand(r, _N) + 0.1)),
        case("RelativeSquaredError", M.RelativeSquaredError, reg_batch),
        case("NormalizedRootMeanSquaredError", M.NormalizedRootMeanSquaredError, reg_batch),
        case("CosineSimilarity", M.CosineSimilarity, lambda r: (_rand(r, _N, _C), _rand(r, _N, _C))),
        case("KLDivergence", M.KLDivergence, lambda r: (_probs(r, _N, _C), _probs(r, _N, _C))),
        # ---- aggregation -------------------------------------------------------
        case("MeanMetric", M.MeanMetric, lambda r: (_rand(r, _N),)),
        case("SumMetric", M.SumMetric, lambda r: (_rand(r, _N),)),
        case("MaxMetric", M.MaxMetric, lambda r: (_rand(r, _N),)),
        case("MinMetric", M.MinMetric, lambda r: (_rand(r, _N),)),
        case("RunningMean", lambda: M.RunningMean(window=3), lambda r: (_rand(r, _N),)),
        # ---- image -------------------------------------------------------------
        case("PeakSignalNoiseRatio", lambda: M.PeakSignalNoiseRatio(data_range=1.0), img_batch),
        case("StructuralSimilarityIndexMeasure",
             lambda: M.StructuralSimilarityIndexMeasure(data_range=1.0), img_batch),
        case("UniversalImageQualityIndex", M.UniversalImageQualityIndex, img_batch),
        case("TotalVariation", M.TotalVariation, lambda r: (_rand(r, *_IMG),)),
        case("SpectralAngleMapper", M.SpectralAngleMapper, img_batch),
        case("RelativeAverageSpectralError", M.RelativeAverageSpectralError, img_batch),
        # ---- audio -------------------------------------------------------------
        case("SignalNoiseRatio", M.SignalNoiseRatio, lambda r: (_rand(r, 2, 256), _rand(r, 2, 256))),
        case("ScaleInvariantSignalNoiseRatio", M.ScaleInvariantSignalNoiseRatio,
             lambda r: (_rand(r, 2, 256), _rand(r, 2, 256))),
        case("ScaleInvariantSignalDistortionRatio", M.ScaleInvariantSignalDistortionRatio,
             lambda r: (_rand(r, 2, 256), _rand(r, 2, 256))),
        # ---- nominal -----------------------------------------------------------
        case("CramersV", lambda: M.CramersV(num_classes=_C), nom_batch),
        case("TschuprowsT", lambda: M.TschuprowsT(num_classes=_C), nom_batch),
        case("TheilsU", lambda: M.TheilsU(num_classes=_C), nom_batch),
        case("PearsonsContingencyCoefficient",
             lambda: M.PearsonsContingencyCoefficient(num_classes=_C), nom_batch),
        # ---- segmentation / text ----------------------------------------------
        case("MeanIoU", lambda: S.MeanIoU(num_classes=_C, input_format="index"), seg_batch),
        case("GeneralizedDiceScore",
             lambda: S.GeneralizedDiceScore(num_classes=_C, input_format="index"), seg_batch),
        case("Perplexity", M.Perplexity, lambda r: (_probs(r, 2, 8, 16), _randint(r, 16, 2, 8))),
        # ---- sketches (fixed-shape mergeable stream state, DESIGN §16) ---------
        case("DDSketch", lambda: M.DDSketch(num_buckets=512),
             lambda r: (_rand(r, _N) + 0.01,)),
        case("HyperLogLog", lambda: M.HyperLogLog(p=8), lambda r: (_rand(r, _N),)),
        case("ReservoirSample", lambda: M.ReservoirSample(k=16), lambda r: (_rand(r, _N),)),
        case("StreamingAUROC", lambda: M.StreamingAUROC(num_bins=128), bin_batch),
        case("StreamingCalibrationError", lambda: M.StreamingCalibrationError(num_bins=10),
             bin_batch),
        # ---- windows & drift (time-decayed / windowed semantics, DESIGN §20) --
        # timestamps are 0-d f32 *arrays* so submission waves group by aval
        case("TimeDecayed", lambda: M.TimeDecayed(M.MeanMetric(nan_strategy="disable"),
                                                  half_life_s=60.0),
             lambda r: (jnp.asarray(5.0, jnp.float32), _rand(r, _N))),
        case("TumblingWindow", lambda: M.TumblingWindow(M.SumMetric(nan_strategy="disable"),
                                                        pane_s=1.0, n_panes=8),
             lambda r: (jnp.asarray(5.0, jnp.float32), _rand(r, _N))),
        case("DecayedDDSketch", lambda: M.DecayedDDSketch(half_life_s=60.0, num_buckets=512),
             lambda r: (jnp.asarray(5.0, jnp.float32), _rand(r, _N) + 0.01)),
        case("DecayedHLL", lambda: M.DecayedHLL(half_life_s=60.0, p=8),
             lambda r: (jnp.asarray(5.0, jnp.float32), _rand(r, _N))),
        case("PSI", lambda: M.PSI(lo=0.0, hi=1.0, num_bins=32),
             lambda r: (_rand(r, _N), _rand(r, _N))),
        case("KSDistance", lambda: M.KSDistance(lo=0.0, hi=1.0, num_bins=32),
             lambda r: (_rand(r, _N), _rand(r, _N))),
        case("CUSUM", lambda: M.CUSUM(target=0.5, k=0.1, h=5.0),
             lambda r: (_rand(r, _N),)),
    ]


_CASES_CACHE: Optional[List[ProfileCase]] = None


def _cases() -> List[ProfileCase]:
    global _CASES_CACHE
    if _CASES_CACHE is None:
        _CASES_CACHE = _make_cases()
    return _CASES_CACHE


class _LazyCases:
    """Sequence façade over the lazily-built registry (import stays cheap)."""

    def __iter__(self):
        return iter(_cases())

    def __len__(self):
        return len(_cases())

    def __getitem__(self, i):
        return _cases()[i]


PROFILE_CASES = _LazyCases()
