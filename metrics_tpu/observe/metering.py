"""Fleet metering: per-session / per-bucket / per-shard cost & memory attribution (DESIGN §23).

The fleet engine drives 100k multi-tenant sessions through *shared* donated
dispatches (DESIGN §15/§21): one XLA executable per bucket per tick serves
every resident session at once. That economy is the point — and it erases the
per-tenant cost signal. All wall-time, FLOPs, bytes and HBM show up in the
recorder as undifferentiated totals; nothing answers *who is consuming the
fleet*. This module is that answer: a host-side cost-attribution ledger fed
from the engine hot path behind the existing single ``ENABLED`` flag check.

**Amortization rule.** Each successful bucket dispatch is measured once
(host wall clock around the donated ``engine_update`` call) and charged to
the wave's active rows in equal shares: a wave of *n* sessions costing *w*
seconds charges *w/n* to each. The bucket's compiled program computes all
``capacity`` rows — padding included — so the static FLOPs/bytes read from
XLA's cost model (the :mod:`metrics_tpu.observe.costs` lowering pattern,
``capacity × per-row cost``) amortize over the *active* wave the same way:
active sessions pay for the padding they force the program to carry. Wall
time that buys no attribution (a dispatch that died mid-flight) still
accrues to ``measured_dispatch_s`` but never to a session, so
``attributed_s / measured_dispatch_s`` is a conservation check: ~1.0 means
every success path charges all of its wall time somewhere (``bench.py``
asserts ≥ 99% on the clean fleet configs).

**Bounded memory.** Exact :class:`SessionLedger` rows are kept for at most
``top_k`` sessions (first-come admission); every session beyond that folds
into a mergeable weighted :class:`SpaceSaving` heavy-hitter sketch keyed on
dispatch-seconds — the ranking resource — with the classic guarantee
``|estimate - true| ≤ total_weight / capacity``. Host memory is therefore
``O(top_k + sketch_capacity)`` regardless of fleet size, and a late-arriving
runaway session still surfaces in :meth:`FleetMeter.top_sessions` (with its
error bar) even though its exact ledger was never admitted.

**Merge discipline.** :meth:`FleetMeter.export_state` /
:meth:`FleetMeter.sync_telemetry` fold shard meters exactly the way
``HostDDSketch`` and the watchdog fold (DESIGN §19/§22): exact ledgers merge
field-wise, overflow demotes the smallest back into the sketch, and sketches
merge by pointwise counter sum + top-``capacity`` truncation (Agarwal et
al.'s mergeable-summaries bound: merged error ≤ combined weight / capacity).

**Quota semantics.** :class:`MeterPolicy` is an opt-in *soft* quota: a
breach fires a ``quota_exceeded`` event + the watchdog-visible
``quota_sessions_over`` gauge, and — only when ``action="demote"`` — asks
the owning engine to demote the runaway session to a loose (eager) session
via the existing blast-radius machinery. Nothing is ever failed or dropped:
demotion removes the session's ability to slow the shared dispatch while its
metric keeps updating correctly.

Everything here is import-light (stdlib only; jax is touched lazily inside
:func:`program_cost`) so the recorder's disabled fast path stays free of it.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Tuple

from metrics_tpu.observe import recorder as _rec

__all__ = [
    "FleetMeter",
    "MeterPolicy",
    "SessionLedger",
    "SpaceSaving",
    "install_meter",
    "installed_meter",
    "program_cost",
    "uninstall_meter",
]

DEFAULT_TOP_K = 64
DEFAULT_SKETCH_CAPACITY = 256

# per-session resource fields carried by an exact ledger; merge = field-wise +
_LEDGER_FIELDS = (
    "updates", "dispatch_s", "est_flops", "est_bytes",
    "loose_updates", "quarantines", "wal_bytes", "ckpt_bytes",
)


class SessionLedger:
    """One session's exact resource account (all fields merge by ``+``)."""

    __slots__ = _LEDGER_FIELDS

    def __init__(self) -> None:
        self.updates = 0
        self.dispatch_s = 0.0
        self.est_flops = 0.0
        self.est_bytes = 0.0
        self.loose_updates = 0
        self.quarantines = 0
        self.wal_bytes = 0
        self.ckpt_bytes = 0.0

    def merge(self, other: "SessionLedger") -> None:
        for f in _LEDGER_FIELDS:
            setattr(self, f, getattr(self, f) + getattr(other, f))

    def as_dict(self) -> Dict[str, Any]:
        return {f: getattr(self, f) for f in _LEDGER_FIELDS}

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "SessionLedger":
        led = cls()
        for f in _LEDGER_FIELDS:
            if f in d:
                setattr(led, f, d[f])
        return led


class SpaceSaving:
    """Weighted SpaceSaving heavy-hitter sketch (Metwally et al.), mergeable.

    Holds at most ``capacity`` counters. :meth:`offer` of a tracked key adds
    its weight exactly; an untracked key evicts the minimum counter *m* and
    inherits its count (``m + w``) with error ``m`` — so every estimate is an
    overestimate by at most its recorded error, and both the error and the
    gap to the true count are bounded by ``total / capacity``. Merge is the
    mergeable-summaries fold: pointwise counter sum (errors add), truncate to
    the top ``capacity`` by count — the merged sketch keeps the combined
    bound ``(total_a + total_b) / capacity``.
    """

    __slots__ = ("capacity", "total", "_counts")

    def __init__(self, capacity: int = DEFAULT_SKETCH_CAPACITY) -> None:
        if capacity < 1:
            raise ValueError(f"SpaceSaving capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.total = 0.0
        self._counts: Dict[str, List[float]] = {}  # key -> [count, error]

    def __len__(self) -> int:
        return len(self._counts)

    def offer(self, key: str, weight: float = 1.0) -> None:
        w = float(weight)
        if w <= 0.0:
            return
        self.total += w
        entry = self._counts.get(key)
        if entry is not None:
            entry[0] += w
            return
        if len(self._counts) < self.capacity:
            self._counts[key] = [w, 0.0]
            return
        evict_key = min(self._counts, key=lambda k: self._counts[k][0])
        floor = self._counts.pop(evict_key)[0]
        self._counts[key] = [floor + w, floor]

    def estimate(self, key: str) -> Optional[Tuple[float, float]]:
        """(count, error) for a tracked key — ``true ∈ [count - error, count]``
        — or None when the key holds no counter."""
        entry = self._counts.get(key)
        return None if entry is None else (entry[0], entry[1])

    def error_bound(self) -> float:
        """Worst-case gap between any estimate and its true weight."""
        return self.total / self.capacity

    def items(self) -> List[Tuple[str, float, float]]:
        """``(key, count, error)`` rows, heaviest first."""
        return sorted(
            ((k, c, e) for k, (c, e) in self._counts.items()),
            key=lambda row: -row[1],
        )

    def merge(self, other: "SpaceSaving") -> None:
        merged: Dict[str, List[float]] = {k: list(v) for k, v in self._counts.items()}
        for k, (c, e) in other._counts.items():
            entry = merged.get(k)
            if entry is None:
                merged[k] = [c, e]
            else:
                entry[0] += c
                entry[1] += e
        if len(merged) > self.capacity:
            keep = sorted(merged, key=lambda k: -merged[k][0])[: self.capacity]
            merged = {k: merged[k] for k in keep}
        self._counts = merged
        self.total += other.total

    # -------------------------------------------------------------- export
    def state(self) -> Dict[str, Any]:
        return {
            "capacity": self.capacity,
            "total": self.total,
            "entries": [[k, c, e] for k, (c, e) in self._counts.items()],
        }

    def merge_state(self, state: Mapping[str, Any]) -> None:
        self.merge(SpaceSaving.from_state(state))

    @classmethod
    def from_state(cls, state: Mapping[str, Any]) -> "SpaceSaving":
        sk = cls(int(state["capacity"]))
        sk.total = float(state.get("total", 0.0))
        sk._counts = {str(k): [float(c), float(e)] for k, c, e in state.get("entries", [])}
        return sk


class MeterPolicy:
    """Opt-in soft quota over the meter's exact ledgers.

    Limits are checked against exact ledgers only (a session the meter never
    admitted exactly cannot be precisely accused). ``max_dispatch_share`` is
    a fraction of fleet-wide attributed dispatch seconds and is only
    evaluated once ``min_total_dispatch_s`` of work has been attributed, so
    the first session of a quiet fleet (share = 100%) never trips it.
    ``action="observe"`` fires events/gauges only; ``action="demote"`` also
    queues the session for demote-to-loose by its owning engine — the
    gentlest blast-radius rung: the tenant keeps computing, it just stops
    sharing the fleet's compiled dispatch.
    """

    __slots__ = (
        "max_dispatch_share", "max_updates", "max_wal_bytes",
        "min_total_dispatch_s", "action", "cooldown_s",
    )

    def __init__(
        self,
        max_dispatch_share: Optional[float] = None,
        max_updates: Optional[int] = None,
        max_wal_bytes: Optional[int] = None,
        min_total_dispatch_s: float = 0.0,
        action: str = "observe",
        cooldown_s: float = 60.0,
    ) -> None:
        if action not in ("observe", "demote"):
            raise ValueError(f"MeterPolicy action must be 'observe' or 'demote', got {action!r}")
        if max_dispatch_share is not None and not 0.0 < max_dispatch_share <= 1.0:
            raise ValueError(f"max_dispatch_share must be in (0, 1], got {max_dispatch_share}")
        self.max_dispatch_share = max_dispatch_share
        self.max_updates = max_updates
        self.max_wal_bytes = max_wal_bytes
        self.min_total_dispatch_s = float(min_total_dispatch_s)
        self.action = action
        self.cooldown_s = float(cooldown_s)

    def breaches(self, skey: str, led: SessionLedger, total_dispatch_s: float) -> List[Tuple[str, float, float]]:
        """``(reason, value, limit)`` rows for every limit this ledger exceeds."""
        out: List[Tuple[str, float, float]] = []
        if (
            self.max_dispatch_share is not None
            and total_dispatch_s >= self.min_total_dispatch_s
            and total_dispatch_s > 0.0
            and led.dispatch_s / total_dispatch_s > self.max_dispatch_share
        ):
            out.append(("dispatch_share", led.dispatch_s / total_dispatch_s, self.max_dispatch_share))
        if self.max_updates is not None and led.updates > self.max_updates:
            out.append(("updates", float(led.updates), float(self.max_updates)))
        if self.max_wal_bytes is not None and led.wal_bytes > self.max_wal_bytes:
            out.append(("wal_bytes", float(led.wal_bytes), float(self.max_wal_bytes)))
        return out


def program_cost(template: Any, capacity: int, args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> Tuple[float, float]:
    """Static (FLOPs, bytes-accessed) of one bucket's compiled program.

    The observe/costs.py lowering pattern applied to the bucket: lower the
    per-row functional update against abstract row avals (the stacked batch
    with its capacity-sized leading axis stripped) and scale by ``capacity``
    — the vmapped program computes every row, padding included. Any failure
    (non-lowerable update, exotic operands) degrades to (0, 0): FLOPs/bytes
    attribution is best-effort, wall-time attribution never depends on it.
    """
    try:
        import jax

        def _row_aval(v: Any) -> Any:
            if hasattr(v, "shape") and hasattr(v, "dtype") and getattr(v, "ndim", 0) >= 1:
                return jax.ShapeDtypeStruct(tuple(v.shape[1:]), v.dtype)
            return v

        state = template._fresh_state()
        row_args = tuple(_row_aval(a) for a in args)
        row_kwargs = {k: _row_aval(v) for k, v in kwargs.items()}
        # lowering-only (never compiled/dispatched), and callers cache the result
        # per (bucket, capacity) — no per-tick program churn
        lowered = jax.jit(template._functional_update).lower(state, *row_args, **row_kwargs)  # hotlint: disable=HL004
        analysis = lowered.cost_analysis() or {}
        if isinstance(analysis, (list, tuple)):  # older jax: one entry per computation
            analysis = analysis[0] if analysis else {}
        flops = float(analysis.get("flops", 0.0) or 0.0)
        nbytes = float(analysis.get("bytes accessed", 0.0) or 0.0)
        return max(0.0, flops) * capacity, max(0.0, nbytes) * capacity
    except Exception:  # noqa: BLE001 — cost attribution is strictly best-effort
        return 0.0, 0.0


class FleetMeter:
    """Host-side fleet cost/memory-attribution ledger (install via :func:`install_meter`).

    Fed from the engine hot paths while telemetry is enabled; every public
    note hook is a dict update + a few float adds under one lock, so enabled
    overhead stays inside the telemetry lint budget (<2% of a fleet tick,
    ``observe/overhead.py``). Session keys are ``str(session_id)`` throughout
    (JSON-able exports; stable across processes for the shard fold).
    """

    def __init__(
        self,
        top_k: int = DEFAULT_TOP_K,
        sketch_capacity: int = DEFAULT_SKETCH_CAPACITY,
        policy: Optional[MeterPolicy] = None,
        max_program_costs: int = 512,
        poll_interval_s: float = 0.25,
    ) -> None:
        if top_k < 1:
            raise ValueError(f"FleetMeter top_k must be >= 1, got {top_k}")
        self.top_k = int(top_k)
        self.sketch_capacity = int(sketch_capacity)
        self.policy = policy
        # quota polls rate-limit like watchdog pokes: engines call poll_quota
        # every tick, the full ledger scan runs at most once per interval
        self.poll_interval_s = float(poll_interval_s)
        self._last_poll = float("-inf")
        self._lock = threading.Lock()
        self._exact: Dict[str, SessionLedger] = {}
        self._sketch = SpaceSaving(sketch_capacity)
        self._measured_dispatch_s = 0.0  # every dispatch attempt's wall, success or not
        self._attributed_s = 0.0  # wall actually charged to sessions (exact + sketch)
        # lazy per-(bucket label, capacity, submission signature) static program
        # cost; one XLA lowering per entry, off the steady-state path, bounded LRU
        self._program_costs: "OrderedDict[Any, Tuple[float, float]]" = OrderedDict()
        self._max_program_costs = int(max_program_costs)
        # (engine name, bucket label) -> memory ledger row; engine name is the
        # shard-unique "<fleet>/shardN" for sharded fleets, so rows never collide
        self._memory: Dict[Tuple[str, str], Dict[str, float]] = {}
        # soft-quota bookkeeping: last fire clock per (skey, reason) for the
        # cooldown, plus the demote handshake sets (engine claims ownership)
        self._quota_fired_at: Dict[Tuple[str, str], float] = {}
        self._quota_exceeded_total = 0
        self._pending_demote: set = set()
        self._demoted: set = set()
        # per-producer network-ingest ledger (serve front door, DESIGN §26):
        # producer name -> {"records", "bytes", "dedup_skipped"}; producers are
        # operator-named connections, so cardinality is fleet-operator-bounded
        self._ingest: Dict[str, Dict[str, int]] = {}

    # ------------------------------------------------------------------ charging
    def _ledger(self, skey: str) -> Optional[SessionLedger]:
        led = self._exact.get(skey)
        if led is None and len(self._exact) < self.top_k:
            led = self._exact[skey] = SessionLedger()
        return led

    def _resolve_cost(self, cost_key: Any, cost_fn: Optional[Callable[[], Tuple[float, float]]]) -> Tuple[float, float]:
        if cost_key is None:
            return 0.0, 0.0
        with self._lock:
            cached = self._program_costs.get(cost_key)
        if cached is not None:
            return cached
        cost = cost_fn() if cost_fn is not None else (0.0, 0.0)
        with self._lock:
            self._program_costs[cost_key] = cost
            while len(self._program_costs) > self._max_program_costs:
                self._program_costs.popitem(last=False)
        return cost

    def note_dispatch(
        self,
        label: str,
        session_keys: List[str],
        wall_s: float,
        cost_key: Any = None,
        cost_fn: Optional[Callable[[], Tuple[float, float]]] = None,
    ) -> None:
        """Charge one successful bucket dispatch to its wave's sessions.

        ``wall_s`` (and the program's static FLOPs/bytes, resolved lazily per
        ``cost_key`` — first sight pays one XLA lowering) amortize in equal
        shares over ``session_keys``. Sessions without an exact ledger fold
        their dispatch-seconds share into the SpaceSaving sketch.
        """
        n = len(session_keys)
        if n == 0:
            with self._lock:
                self._measured_dispatch_s += wall_s
            return
        flops, nbytes = self._resolve_cost(cost_key, cost_fn)
        share_s = wall_s / n
        share_flops = flops / n
        share_bytes = nbytes / n
        # hot path: inline the admission check and bind lookups once — this
        # runs per dispatch inside the engine's tick
        with self._lock:
            self._measured_dispatch_s += wall_s
            self._attributed_s += wall_s  # n equal shares, summed exactly
            exact = self._exact
            top_k = self.top_k
            sketch_offer = self._sketch.offer
            for skey in session_keys:
                led = exact.get(skey)
                if led is None:
                    if len(exact) < top_k:
                        led = exact[skey] = SessionLedger()
                    else:
                        sketch_offer(skey, share_s)
                        continue
                led.updates += 1
                led.dispatch_s += share_s
                led.est_flops += share_flops
                led.est_bytes += share_bytes

    def note_failed_dispatch(self, label: str, wall_s: float) -> None:
        """Wall time a dying dispatch burned: measured, attributable to no one."""
        with self._lock:
            self._measured_dispatch_s += wall_s

    def note_loose_update(self, skey: str) -> None:
        # hot path (one call per eager update): admission inlined, no helper
        with self._lock:
            led = self._exact.get(skey)
            if led is None:
                if len(self._exact) >= self.top_k:
                    return
                led = self._exact[skey] = SessionLedger()
            led.updates += 1
            led.loose_updates += 1

    def note_quarantine(self, skey: str) -> None:
        with self._lock:
            led = self._ledger(skey)
            if led is not None:
                led.quarantines += 1

    def note_wal_bytes(self, skey: str, nbytes: int) -> None:
        with self._lock:
            led = self._ledger(skey)
            if led is not None:
                led.wal_bytes += int(nbytes)

    def note_ckpt_bytes(self, session_keys: List[str], nbytes: int) -> None:
        """Amortize one bucket checkpoint blob over its resident sessions."""
        if not session_keys:
            return
        share = nbytes / len(session_keys)
        with self._lock:
            for skey in session_keys:
                led = self._ledger(skey)
                if led is not None:
                    led.ckpt_bytes += share

    def note_ingest(self, producer: str, records: int = 0, nbytes: int = 0, dedup_skipped: int = 0) -> None:
        """Charge one front-door poll's intake to its producer connection."""
        with self._lock:
            row = self._ingest.get(producer)
            if row is None:
                row = self._ingest[producer] = {"records": 0, "bytes": 0, "dedup_skipped": 0}
            row["records"] += int(records)
            row["bytes"] += int(nbytes)
            row["dedup_skipped"] += int(dedup_skipped)

    def ingest_ledger(self) -> Dict[str, Any]:
        """Per-producer ingest rows plus fleet-wide totals."""
        with self._lock:
            rows = {p: dict(row) for p, row in sorted(self._ingest.items())}
        totals = {"records": 0, "bytes": 0, "dedup_skipped": 0}
        for row in rows.values():
            for f in totals:
                totals[f] += row[f]
        return {"producers": rows, "totals": totals}

    # ------------------------------------------------------------------ memory ledger
    def note_bucket_memory(self, engine: str, label: str, capacity: int, active: int, row_bytes: int) -> None:
        """Refresh one bucket's memory ledger row (from its state avals).

        ``live_bytes`` is what active sessions actually use, ``pad_waste``
        what the padded capacity burns on top, ``peak_capacity_bytes`` the
        historical high-water of the stacked allocation, and ``projected_2x``
        what the next :meth:`_Bucket.grow` doubling would allocate — the
        number ROADMAP item 1 (shard_map-sharded state) needs per bucket.
        """
        key = (engine, label)
        stacked = capacity * row_bytes
        with self._lock:
            prev_peak = self._memory.get(key, {}).get("peak_capacity_bytes", 0)
            self._memory[key] = {
                "capacity": capacity,
                "active": active,
                "row_bytes": row_bytes,
                "live_bytes": active * row_bytes,
                "pad_waste_bytes": (capacity - active) * row_bytes,
                "peak_capacity_bytes": max(prev_peak, stacked),
                "projected_2x_bytes": 2 * stacked,
            }

    def drop_bucket_memory(self, engine: str, label: str) -> None:
        with self._lock:
            self._memory.pop((engine, label), None)

    def memory_ledger(self) -> Dict[str, Any]:
        """Per-bucket rows plus per-engine (per-shard) and fleet-wide totals."""
        with self._lock:
            rows = {f"{eng}::{lbl}": dict(row) for (eng, lbl), row in sorted(self._memory.items())}
            per_engine: Dict[str, Dict[str, float]] = {}
            for (eng, _lbl), row in self._memory.items():
                agg = per_engine.setdefault(
                    eng, {"live_bytes": 0, "pad_waste_bytes": 0, "peak_capacity_bytes": 0, "projected_2x_bytes": 0}
                )
                for f in agg:
                    agg[f] += row[f]
        totals = {"live_bytes": 0, "pad_waste_bytes": 0, "peak_capacity_bytes": 0, "projected_2x_bytes": 0}
        for agg in per_engine.values():
            for f in totals:
                totals[f] += agg[f]
        return {"buckets": rows, "engines": {k: per_engine[k] for k in sorted(per_engine)}, "totals": totals}

    # ------------------------------------------------------------------ readout
    def totals(self) -> Dict[str, float]:
        with self._lock:
            return {
                "measured_dispatch_s": self._measured_dispatch_s,
                "attributed_s": self._attributed_s,
                "attribution_pct": (
                    100.0 * self._attributed_s / self._measured_dispatch_s
                    if self._measured_dispatch_s > 0.0
                    else None
                ),
                "sessions_exact": len(self._exact),
                "sessions_sketched": len(self._sketch),
                "sketch_total_s": self._sketch.total,
                "sketch_error_bound_s": self._sketch.error_bound(),
                "quota_exceeded_total": self._quota_exceeded_total,
            }

    def top_sessions(self, n: int = 10) -> List[Dict[str, Any]]:
        """The ``n`` heaviest sessions by dispatch-seconds, exact rows first-class.

        Exact ledgers rank by their precise ``dispatch_s`` and carry every
        field; sketch entries rank by their (over-)estimate and carry the
        error bar instead — a heavy tenant that arrived after the exact set
        filled still surfaces here.
        """
        with self._lock:
            rows: List[Dict[str, Any]] = [
                {"session": skey, "source": "exact", "dispatch_s": led.dispatch_s, "error_s": 0.0, **led.as_dict()}
                for skey, led in self._exact.items()
            ]
            sketch_rows = self._sketch.items()
        rows.extend(
            {"session": skey, "source": "sketch", "dispatch_s": est, "error_s": err}
            for skey, est, err in sketch_rows
        )
        rows.sort(key=lambda r: -r["dispatch_s"])
        return rows[:n]

    def explain_session(self, session_id: Any) -> Dict[str, Any]:
        """Everything the meter knows about one session (never raises)."""
        skey = str(session_id)
        with self._lock:
            led = self._exact.get(skey)
            total = self._attributed_s
            if led is not None:
                out = {"session": skey, "tracked": "exact", **led.as_dict()}
                out["dispatch_share_pct"] = 100.0 * led.dispatch_s / total if total > 0.0 else None
                return out
            est = self._sketch.estimate(skey)
        if est is not None:
            count, err = est
            return {
                "session": skey, "tracked": "sketch",
                "dispatch_s": count, "error_s": err,
                "dispatch_share_pct": 100.0 * count / total if total > 0.0 else None,
            }
        return {"session": skey, "tracked": None}

    # ------------------------------------------------------------------ soft quota
    def poll_quota(self, now: Optional[float] = None) -> None:
        """Evaluate the policy over the exact ledgers (engine ticks call this).

        Each breach (per session, per reason, rate-limited by the policy
        cooldown) lands a ``quota_exceeded`` event + counter; the
        ``quota_sessions_over`` gauge — watchdog-visible like any other
        recorder gauge, so an :class:`SloRule` can alert on it — tracks how
        many sessions are currently over. ``action="demote"`` additionally
        queues the session; the engine that owns it picks it up via
        :meth:`pending_demotions` / :meth:`confirm_demotion`.

        The full ledger scan rate-limits to ``poll_interval_s`` (watchdog-poke
        discipline): the per-tick fast path is one clock read.
        """
        pol = self.policy
        if pol is None:
            return
        t = _rec.clock() if now is None else now
        if t - self._last_poll < self.poll_interval_s:
            return
        self._last_poll = t
        fired: List[Tuple[str, str, float, float]] = []
        with self._lock:
            total = self._attributed_s
            over = 0
            for skey, led in self._exact.items():
                rows = pol.breaches(skey, led, total)
                if rows:
                    over += 1
                    if pol.action == "demote" and skey not in self._demoted:
                        self._pending_demote.add(skey)
                for reason, value, limit in rows:
                    last = self._quota_fired_at.get((skey, reason))
                    if last is not None and t - last < pol.cooldown_s:
                        continue
                    self._quota_fired_at[(skey, reason)] = t
                    self._quota_exceeded_total += 1
                    fired.append((skey, reason, value, limit))
        if _rec.ENABLED:
            _rec.RECORDER.set_gauge("quota_sessions_over", "meter", float(over))
            for skey, reason, value, limit in fired:
                _rec.RECORDER.add_count("quota_exceeded", reason)
                _rec.RECORDER.add_event(
                    "quota_exceeded", session=skey, reason=reason, value=value, limit=limit,
                    action=pol.action,
                )

    def pending_demotions(self) -> List[str]:
        with self._lock:
            return sorted(self._pending_demote)

    def confirm_demotion(self, skey: str) -> None:
        """The owning engine demoted this session (or verified it is no longer
        demotable); stop asking."""
        with self._lock:
            self._pending_demote.discard(skey)
            self._demoted.add(skey)

    # ------------------------------------------------------------------ shard fold
    def export_state(self) -> Dict[str, Any]:
        """JSON-able mergeable meter state (the watchdog/HostDDSketch discipline)."""
        with self._lock:
            return {
                "schema": 1,
                "top_k": self.top_k,
                "measured_dispatch_s": self._measured_dispatch_s,
                "attributed_s": self._attributed_s,
                "quota_exceeded_total": self._quota_exceeded_total,
                "exact": {skey: led.as_dict() for skey, led in self._exact.items()},
                "sketch": self._sketch.state(),
                "memory": [
                    [eng, lbl, dict(row)] for (eng, lbl), row in sorted(self._memory.items())
                ],
                "ingest": {p: dict(row) for p, row in sorted(self._ingest.items())},
            }

    def sync_telemetry(self, peer_states: Iterable[Mapping[str, Any]]) -> "FleetMeter":
        """Fold peer shards' exported states into this meter (local first).

        Exact ledgers merge field-wise; if the union exceeds ``top_k``, the
        lightest (by dispatch-seconds) demote into the sketch — their exact
        dispatch total becomes a zero-error sketch entry, so the heavy-hitter
        ranking survives the fold within the SpaceSaving bound. Sketches and
        memory rows merge by their own algebras (counter sum / field sum with
        peak = max).
        """
        with self._lock:
            for state in peer_states:
                self._measured_dispatch_s += float(state.get("measured_dispatch_s", 0.0))
                self._attributed_s += float(state.get("attributed_s", 0.0))
                self._quota_exceeded_total += int(state.get("quota_exceeded_total", 0))
                for skey, row in (state.get("exact") or {}).items():
                    led = self._exact.get(skey)
                    if led is None:
                        self._exact[skey] = SessionLedger.from_dict(row)
                    else:
                        led.merge(SessionLedger.from_dict(row))
                sketch_state = state.get("sketch")
                if sketch_state:
                    self._sketch.merge_state(sketch_state)
                for producer, row in (state.get("ingest") or {}).items():
                    mine_row = self._ingest.setdefault(
                        producer, {"records": 0, "bytes": 0, "dedup_skipped": 0}
                    )
                    for f in mine_row:
                        mine_row[f] += int(row.get(f, 0))
                for eng, lbl, row in state.get("memory") or []:
                    key = (str(eng), str(lbl))
                    mine = self._memory.get(key)
                    if mine is None:
                        self._memory[key] = dict(row)
                    else:
                        for f in ("capacity", "active", "live_bytes", "pad_waste_bytes", "projected_2x_bytes"):
                            mine[f] = mine.get(f, 0) + row.get(f, 0)
                        mine["peak_capacity_bytes"] = max(
                            mine.get("peak_capacity_bytes", 0), row.get("peak_capacity_bytes", 0)
                        )
                        mine["row_bytes"] = max(mine.get("row_bytes", 0), row.get("row_bytes", 0))
            if len(self._exact) > self.top_k:
                ranked = sorted(self._exact, key=lambda k: -self._exact[k].dispatch_s)
                for skey in ranked[self.top_k :]:
                    led = self._exact.pop(skey)
                    self._sketch.offer(skey, led.dispatch_s)
        return self

    # ------------------------------------------------------------------ export surfaces
    def snapshot_payload(self, top_n: int = 10) -> Dict[str, Any]:
        """The ``snapshot()["metering"]`` section (recorder calls this lazily)."""
        totals = self.totals()
        return {
            "installed": True,
            "top_k": self.top_k,
            "sketch_capacity": self.sketch_capacity,
            "totals": totals,
            "top_sessions": self.top_sessions(top_n),
            "memory": self.memory_ledger(),
            "ingest": self.ingest_ledger(),
            "policy": None if self.policy is None else {
                "action": self.policy.action,
                "max_dispatch_share": self.policy.max_dispatch_share,
                "max_updates": self.policy.max_updates,
                "max_wal_bytes": self.policy.max_wal_bytes,
            },
        }

    def prometheus_lines(self, prom_name: Callable[[str], str], prom_label: Callable[[str], str]) -> List[str]:
        """Metering families for the recorder's exposition dump.

        Cardinality is bounded by construction: per-session families emit
        only the exact ledgers (≤ ``top_k`` label values regardless of fleet
        size — sketch entries are aggregates, never labels), per-bucket
        families only live buckets.
        """
        lines: List[str] = []

        def _family(prom: str, kind: str, help_text: str) -> None:
            lines.append(f"# HELP {prom} {help_text}")
            lines.append(f"# TYPE {prom} {kind}")

        with self._lock:
            exact = [(skey, led.as_dict()) for skey, led in sorted(self._exact.items())]
            memory = [(eng, lbl, dict(row)) for (eng, lbl), row in sorted(self._memory.items())]
            measured = self._measured_dispatch_s
            attributed = self._attributed_s
            sketch_total = self._sketch.total
            sketch_bound = self._sketch.error_bound()
        for field, kind, help_text in (
            ("dispatch_s", "counter", "attributed dispatch wall seconds per session (top-K exact ledgers)"),
            ("updates", "counter", "engine updates applied per session (top-K exact ledgers)"),
            ("est_flops", "counter", "estimated FLOPs attributed per session (static XLA cost model)"),
            ("est_bytes", "counter", "estimated bytes-accessed attributed per session (static XLA cost model)"),
            ("wal_bytes", "counter", "WAL bytes journaled per session (top-K exact ledgers)"),
        ):
            prom = prom_name(f"meter_session_{field}") + "_total"
            _family(prom, kind, f"metrics_tpu fleet metering: {help_text}.")
            for skey, row in exact:
                lines.append(f'{prom}{{session="{prom_label(skey)}"}} {row[field]}')
        for field in ("live_bytes", "pad_waste_bytes", "peak_capacity_bytes", "projected_2x_bytes"):
            prom = prom_name(f"meter_bucket_{field}")
            _family(prom, "gauge", f"metrics_tpu fleet metering: per-bucket memory ledger {field}.")
            for eng, lbl, row in memory:
                sel = f'engine="{prom_label(eng)}",bucket="{prom_label(lbl)}"'
                lines.append(f"{prom}{{{sel}}} {row[field]}")
        for name, value, help_text in (
            ("meter_measured_dispatch_seconds", measured, "dispatch wall seconds the meter measured"),
            ("meter_attributed_dispatch_seconds", attributed, "dispatch wall seconds attributed to sessions"),
            ("meter_sketch_weight_seconds", sketch_total, "dispatch seconds folded into the heavy-hitter sketch"),
            ("meter_sketch_error_bound_seconds", sketch_bound, "SpaceSaving worst-case estimate error"),
        ):
            prom = prom_name(name)
            _family(prom, "gauge", f"metrics_tpu fleet metering: {help_text}.")
            lines.append(f"{prom} {value:.9f}")
        return lines


# ----------------------------------------------------------------- installation

_ACTIVE: Optional[FleetMeter] = None


def install_meter(meter: Optional[FleetMeter] = None, **kwargs: Any) -> FleetMeter:
    """Register a process-wide fleet meter; engine hot paths feed it.

    Pass an instance, or keyword args forwarded to :class:`FleetMeter`. Like
    the watchdog, the meter is held on the recorder module (one attribute
    read per hot path) but is process-local state independent of the
    recorder instance — a swapped-in probe recorder still feeds the same
    installed meter.
    """
    global _ACTIVE
    mt = meter if meter is not None else FleetMeter(**kwargs)
    _ACTIVE = mt
    _rec._set_meter(mt)
    return mt


def uninstall_meter() -> None:
    global _ACTIVE
    _ACTIVE = None
    _rec._set_meter(None)


def installed_meter() -> Optional[FleetMeter]:
    return _ACTIVE
