"""Per-(phase, label) latency histograms backed by host-side DDSketch (DESIGN §19).

Every flight-recorder span (:mod:`metrics_tpu.observe.tracing`) folds its
duration into a :class:`HostDDSketch` keyed ``(phase, label)`` on the
process-wide recorder. The sketch is a numpy port of the in-tree fixed-window
DDSketch kernel (:mod:`metrics_tpu.functional.sketches.ddsketch`): identical
γ/key-offset bucketing, identical quantile read-out, so host telemetry and
device-side sketch metrics share one error model — relative error ≤ α per
quantile, fixed memory per sketch, and merge = elementwise ``+``.

That mergeability is the point: :func:`sync_telemetry` hierarchically merges
exported host sketches (this process + any peers) into fleet-wide quantiles
the same way metric state merges under its declared algebras (DrJAX-style
mergeable aggregates, 2403.07128) — no raw event shipping.

Defaults are tuned for host phase latencies: α = 0.02 (2 % relative error),
key window covering ~[30 ns, 2000 s], ~12 KB per (phase, label) pair.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from metrics_tpu.observe import recorder as _recorder

__all__ = [
    "DEFAULT_ALPHA",
    "DEFAULT_KEY_OFFSET",
    "DEFAULT_NUM_BUCKETS",
    "HostDDSketch",
    "SUMMARY_QUANTILES",
    "export_state",
    "merge_latency_states",
    "observe_duration",
    "snapshot_latency",
    "summarize",
    "sync_telemetry",
]

DEFAULT_ALPHA = 0.02
# ceil(log_γ 30e-9) ≈ -437, ceil(log_γ 2000) ≈ 190 with γ ≈ 1.0408: window
# [key_offset, key_offset + num_buckets) = [-440, 200) covers both with slack
DEFAULT_KEY_OFFSET = -440
DEFAULT_NUM_BUCKETS = 640

SUMMARY_QUANTILES: Tuple[float, ...] = (0.5, 0.9, 0.99, 0.999)


class HostDDSketch:
    """Fixed-window DDSketch over host floats; numpy twin of ``ddsketch_delta``.

    Counts are int64 (a host sketch can absorb billions of spans), and exact
    ``count``/``sum``/``min``/``max`` ride along — all five pieces merge by
    the obvious algebra, so sketches from many hosts collapse losslessly into
    one (bucket counts add exactly; only the quantile *read-out* carries the
    ≤ α relative error).
    """

    __slots__ = (
        "alpha", "gamma", "_ln_gamma", "key_offset", "num_buckets",
        "pos", "neg", "zero", "count", "sum", "min", "max",
    )

    def __init__(
        self,
        alpha: float = DEFAULT_ALPHA,
        key_offset: int = DEFAULT_KEY_OFFSET,
        num_buckets: int = DEFAULT_NUM_BUCKETS,
    ) -> None:
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"`alpha` must be in (0, 1), got {alpha}")
        if num_buckets < 1:
            raise ValueError(f"`num_buckets` must be >= 1, got {num_buckets}")
        self.alpha = float(alpha)
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._ln_gamma = math.log(self.gamma)
        self.key_offset = int(key_offset)
        self.num_buckets = int(num_buckets)
        self.pos = np.zeros(num_buckets, dtype=np.int64)
        self.neg = np.zeros(num_buckets, dtype=np.int64)
        self.zero = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -------------------------------------------------------------- ingest
    def observe(self, value: float) -> None:
        v = float(value)
        if not math.isfinite(v):
            return
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if v == 0.0:
            self.zero += 1
            return
        key = math.ceil(math.log(abs(v)) / self._ln_gamma)
        idx = key - self.key_offset
        if idx < 0:
            idx = 0
        elif idx >= self.num_buckets:
            idx = self.num_buckets - 1
        if v > 0.0:
            self.pos[idx] += 1
        else:
            self.neg[idx] += 1

    # --------------------------------------------------------------- merge
    def _check_compatible(self, other: "HostDDSketch") -> None:
        if (self.alpha, self.key_offset, self.num_buckets) != (
            other.alpha, other.key_offset, other.num_buckets,
        ):
            raise ValueError(
                "cannot merge incompatible sketches: "
                f"(alpha={self.alpha}, key_offset={self.key_offset}, num_buckets={self.num_buckets}) vs "
                f"(alpha={other.alpha}, key_offset={other.key_offset}, num_buckets={other.num_buckets})"
            )

    def merge(self, other: "HostDDSketch") -> "HostDDSketch":
        """In-place merge; afterwards ``self`` describes the combined stream."""
        self._check_compatible(other)
        self.pos += other.pos
        self.neg += other.neg
        self.zero += other.zero
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def copy(self) -> "HostDDSketch":
        out = HostDDSketch(self.alpha, self.key_offset, self.num_buckets)
        out.pos = self.pos.copy()
        out.neg = self.neg.copy()
        out.zero = self.zero
        out.count = self.count
        out.sum = self.sum
        out.min = self.min
        out.max = self.max
        return out

    # ------------------------------------------------------------- readout
    def quantiles(self, qs: Sequence[float]) -> np.ndarray:
        """Quantile estimates; the numpy mirror of ``ddsketch_quantiles``."""
        keys = np.arange(self.num_buckets, dtype=np.float64) + float(self.key_offset)
        rep = 2.0 * np.exp(keys * self._ln_gamma) / (self.gamma + 1.0)
        line = np.concatenate([-rep[::-1], np.zeros(1), rep])
        counts = np.concatenate([self.neg[::-1], [self.zero], self.pos]).astype(np.float64)
        cum = np.cumsum(counts)
        n = cum[-1]
        rank = np.asarray(qs, dtype=np.float64) * max(n - 1.0, 0.0)
        bucket = np.searchsorted(cum, rank, side="right")
        out = line[np.clip(bucket, 0, line.shape[0] - 1)]
        return np.where(n > 0, out, 0.0)

    def quantile(self, q: float) -> float:
        return float(self.quantiles([q])[0])

    # --------------------------------------------------------------- state
    def state(self) -> Dict[str, Any]:
        """JSON-able mergeable state (what :func:`export_state` ships)."""
        return {
            "alpha": self.alpha,
            "key_offset": self.key_offset,
            "num_buckets": self.num_buckets,
            "pos": self.pos.tolist(),
            "neg": self.neg.tolist(),
            "zero": int(self.zero),
            "count": int(self.count),
            "sum": float(self.sum),
            "min": None if self.count == 0 else float(self.min),
            "max": None if self.count == 0 else float(self.max),
        }

    @classmethod
    def from_state(cls, state: Dict[str, Any]) -> "HostDDSketch":
        out = cls(state["alpha"], state["key_offset"], state["num_buckets"])
        out.pos = np.asarray(state["pos"], dtype=np.int64)
        out.neg = np.asarray(state["neg"], dtype=np.int64)
        out.zero = int(state["zero"])
        out.count = int(state["count"])
        out.sum = float(state["sum"])
        out.min = math.inf if state["min"] is None else float(state["min"])
        out.max = -math.inf if state["max"] is None else float(state["max"])
        return out


# ---------------------------------------------------------------- recorder glue
def observe_duration(phase: str, label: str, seconds: float) -> None:
    """Fold one duration into the recorder's (phase, label) sketch.

    Called by the span machinery only while telemetry is enabled; the sketch
    dict lives on the Recorder so ``reset()``/``scope()`` clear it with
    everything else.
    """
    rec = _recorder.RECORDER
    key = (phase, label)
    with rec._lock:
        sk = rec.latency.get(key)
        if sk is None:
            sk = rec.latency[key] = HostDDSketch()
        sk.observe(seconds)


def summarize(sk: HostDDSketch, quantiles: Sequence[float] = SUMMARY_QUANTILES) -> Dict[str, Any]:
    """One sketch as a JSON-able summary: exact count/mean/min/max + quantiles."""
    out: Dict[str, Any] = {
        "count": int(sk.count),
        "total_s": float(sk.sum),
        "mean_s": float(sk.sum / sk.count) if sk.count else 0.0,
        "min_s": float(sk.min) if sk.count else 0.0,
        "max_s": float(sk.max) if sk.count else 0.0,
    }
    qs = sk.quantiles(quantiles)
    for q, v in zip(quantiles, qs):
        out[_quantile_key(q)] = float(v)
    return out


def _quantile_key(q: float) -> str:
    """0.5 -> "p50_s", 0.9 -> "p90_s", 0.99 -> "p99_s", 0.999 -> "p999_s"."""
    digits = f"{q:g}".split(".", 1)[1] if "." in f"{q:g}" else "0"
    if len(digits) == 1:
        digits += "0"
    return f"p{digits}_s"


def snapshot_latency(quantiles: Sequence[float] = SUMMARY_QUANTILES) -> Dict[str, Dict[str, Dict[str, Any]]]:
    """All recorder sketches summarized as ``{phase: {label: summary}}``.

    Caller must NOT hold the recorder lock (this takes it to copy the dict).
    """
    rec = _recorder.RECORDER
    with rec._lock:
        sketches = {key: sk.copy() for key, sk in rec.latency.items()}
    out: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for (phase, label), sk in sorted(sketches.items()):
        out.setdefault(phase, {})[label] = summarize(sk, quantiles)
    return out


# ------------------------------------------------------------- fleet aggregation
def export_state() -> Dict[str, Dict[str, Dict[str, Any]]]:
    """This process's sketches as JSON-able mergeable state ``{phase: {label: state}}``."""
    rec = _recorder.RECORDER
    with rec._lock:
        sketches = {key: sk.copy() for key, sk in rec.latency.items()}
    out: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for (phase, label), sk in sketches.items():
        out.setdefault(phase, {})[label] = sk.state()
    return out


def merge_latency_states(
    states: Iterable[Dict[str, Dict[str, Dict[str, Any]]]],
) -> Dict[Tuple[str, str], HostDDSketch]:
    """Merge exported states from many hosts into one sketch per (phase, label).

    Phases/labels present on only some hosts merge fine (absent = empty
    sketch); incompatible sketch geometry raises.
    """
    merged: Dict[Tuple[str, str], HostDDSketch] = {}
    for state in states:
        for phase, by_label in state.items():
            for label, sk_state in by_label.items():
                sk = HostDDSketch.from_state(sk_state)
                prior = merged.get((phase, label))
                if prior is None:
                    merged[(phase, label)] = sk
                else:
                    prior.merge(sk)
    return merged


def sync_telemetry(
    peer_states: Optional[Iterable[Dict[str, Dict[str, Dict[str, Any]]]]] = None,
    quantiles: Sequence[float] = SUMMARY_QUANTILES,
) -> Dict[str, Dict[str, Dict[str, Any]]]:
    """Fleet-wide latency quantiles: local sketches merged with peers'.

    ``peer_states`` is an iterable of :func:`export_state` payloads from other
    hosts (any transport — an RPC layer, a shared filesystem, or jax multihost
    broadcast of the JSON). Merging is hierarchical and associative: a rack
    aggregator may merge its hosts and forward one payload upward; quantiles
    of the merged sketch match a sketch that saw every host's stream (bucket
    counts add exactly).
    """
    states: List[Dict[str, Dict[str, Dict[str, Any]]]] = [export_state()]
    if peer_states is not None:
        states.extend(peer_states)
    merged = merge_latency_states(states)
    out: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for (phase, label), sk in sorted(merged.items()):
        out.setdefault(phase, {})[label] = summarize(sk, quantiles)
    return out
