"""metrics_tpu.observe — runtime telemetry and XLA cost profiling (DESIGN §11, §19).

The third subsystem of the tooling triad (correctness → jitlint, distribution
→ distlint, performance → observe). Three parts:

* **runtime half** (:mod:`metrics_tpu.observe.recorder`) — near-zero-overhead
  counters/timers/structured events the core runtime reports into: per-metric
  update/compute wall time, jit compile count vs. cache hits/evictions,
  retrace causes, eager-fallback latches with the triggering exception, and
  sync/merge timings. Off by default; one flag check per hot path when off.
* **flight recorder** (:mod:`metrics_tpu.observe.tracing` +
  :mod:`metrics_tpu.observe.latency`) — nested host-side spans over the whole
  hot path (engine tick phases, update/compute/merge/sync, checkpoint/WAL,
  AOT load/store) in a bounded ring, each span folded into per-(phase, label)
  DDSketch latency histograms. Export as Chrome-trace JSON
  (:func:`timeline`), Prometheus quantile families (:func:`prometheus`), or
  fleet-merged quantiles (:func:`sync_telemetry`); ``tools/fleet_top.py``
  renders the live health report.
* **watchdog rung** (:mod:`metrics_tpu.observe.watchdog` +
  :mod:`metrics_tpu.observe.explain`, DESIGN §22) — host-side twins of our own
  metric designs (TimeDecayed rates, DDSketch quantiles, CUSUM, PSI) sampled
  over the recorder's own counters, declarative :class:`SloRule` alerting
  with firing/resolved events, and per-cache recompile-cause attribution
  (``compile_explain`` events; ``tools/why_recompile.py`` renders them).
* **fleet meter** (:mod:`metrics_tpu.observe.metering`, DESIGN §23) — host-side
  cost & memory attribution: per-dispatch wall time and static XLA program
  cost amortized over the wave's active sessions, exact ledgers for the top-K
  tenants plus a mergeable SpaceSaving heavy-hitter sketch beyond, per-bucket
  memory ledgers from state avals, and an opt-in soft-quota
  :class:`MeterPolicy` that can demote a runaway session to loose.
* **static half** (:mod:`metrics_tpu.observe.costs` +
  :mod:`metrics_tpu.observe.profile`) — XLA cost profiling via
  ``jax.jit(update).lower(...).cost_analysis()`` over the jit-eligible
  exported metric classes (FLOPs, bytes accessed, peak memory per compiled
  update), ratcheted against ``tools/perf_baseline.json`` by the
  ``profile-metrics`` CLI exactly like the jitlint/distlint baselines.

Quick start::

    from metrics_tpu import observe
    with observe.scope():                  # or observe.enable()
        ...  # run your eval loop
        print(observe.snapshot()["latency"])   # DDSketch p50/p99 per phase
        json.dump(observe.timeline(), open("trace.json", "w"))  # chrome://tracing

``costs``/``profile`` load lazily (PEP 562) so the import of this package
stays free of jax-tracing machinery; ``overhead`` hosts the disabled-mode
overhead smoke behind ``tools/lint_metrics.py --all``.
"""

from metrics_tpu.observe.latency import sync_telemetry
from metrics_tpu.observe.metering import (
    FleetMeter,
    MeterPolicy,
    SpaceSaving,
    install_meter,
    installed_meter,
    uninstall_meter,
)
from metrics_tpu.observe.recorder import (
    RECORDER,
    SCHEMA_VERSION,
    Recorder,
    disable,
    enable,
    enabled,
    poke_watchdog,
    prometheus,
    record_event,
    reset,
    scope,
    snapshot,
    snapshot_json,
)
from metrics_tpu.observe.tracing import drain_spans, record_complete, span, timeline
from metrics_tpu.observe.watchdog import (
    DEFAULT_SLOS,
    SloRule,
    Watchdog,
    install_watchdog,
    installed_watchdog,
    uninstall_watchdog,
)

# submodules (costs/profile/recorder/...) resolve via __getattr__ below; they
# are deliberately absent from __all__ — JL006 requires every listed name be
# bound at module top level, and binding them eagerly would defeat the lazy
# import
__all__ = [
    "DEFAULT_SLOS",
    "FleetMeter",
    "MeterPolicy",
    "RECORDER",
    "Recorder",
    "SCHEMA_VERSION",
    "SloRule",
    "SpaceSaving",
    "Watchdog",
    "disable",
    "drain_spans",
    "enable",
    "enabled",
    "install_meter",
    "install_watchdog",
    "installed_meter",
    "installed_watchdog",
    "poke_watchdog",
    "prometheus",
    "record_complete",
    "record_event",
    "reset",
    "scope",
    "snapshot",
    "snapshot_json",
    "span",
    "sync_telemetry",
    "timeline",
    "uninstall_meter",
    "uninstall_watchdog",
]

_LAZY_SUBMODULES = ("costs", "explain", "latency", "metering", "overhead", "profile", "recorder", "tracing", "watchdog")


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib

        value = importlib.import_module(f"metrics_tpu.observe.{name}")
        globals()[name] = value
        return value
    raise AttributeError(f"module 'metrics_tpu.observe' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY_SUBMODULES))
