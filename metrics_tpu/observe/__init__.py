"""metrics_tpu.observe — runtime telemetry and XLA cost profiling (DESIGN §11).

The third subsystem of the tooling triad (correctness → jitlint, distribution
→ distlint, performance → observe). Two halves:

* **runtime half** (:mod:`metrics_tpu.observe.recorder`) — near-zero-overhead
  counters/timers/structured events the core runtime reports into: per-metric
  update/compute wall time, jit compile count vs. cache hits/evictions,
  retrace causes, eager-fallback latches with the triggering exception, and
  sync/merge timings. Off by default; one flag check per hot path when off.
* **static half** (:mod:`metrics_tpu.observe.costs` +
  :mod:`metrics_tpu.observe.profile`) — XLA cost profiling via
  ``jax.jit(update).lower(...).cost_analysis()`` over the jit-eligible
  exported metric classes (FLOPs, bytes accessed, peak memory per compiled
  update), ratcheted against ``tools/perf_baseline.json`` by the
  ``profile-metrics`` CLI exactly like the jitlint/distlint baselines.

Quick start::

    from metrics_tpu import observe
    observe.enable()
    ...  # run your eval loop
    print(observe.snapshot()["derived"])   # compile counts, cache hit rate, ...
    print(observe.prometheus())            # Prometheus text exposition

``costs``/``profile`` load lazily (PEP 562) so the core runtime's unconditional
``observe.recorder`` import stays free of jax-tracing machinery.
"""

from metrics_tpu.observe.recorder import (
    RECORDER,
    Recorder,
    disable,
    enable,
    enabled,
    prometheus,
    record_event,
    reset,
    snapshot,
    snapshot_json,
)

# submodules (costs/profile/recorder) resolve via __getattr__ below; they are
# deliberately absent from __all__ — JL006 requires every listed name be bound
# at module top level, and binding them eagerly would defeat the lazy import
__all__ = [
    "RECORDER",
    "Recorder",
    "disable",
    "enable",
    "enabled",
    "prometheus",
    "record_event",
    "reset",
    "snapshot",
    "snapshot_json",
]

_LAZY_SUBMODULES = ("costs", "profile", "recorder")


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib

        value = importlib.import_module(f"metrics_tpu.observe.{name}")
        globals()[name] = value
        return value
    raise AttributeError(f"module 'metrics_tpu.observe' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY_SUBMODULES))
