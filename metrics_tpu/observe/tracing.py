"""Flight-recorder spans: nested host-side phase timing with a bounded ring (DESIGN §19).

The counters/timers in :mod:`metrics_tpu.observe.recorder` answer *how much*;
spans answer *when* and *inside what*. A span is a ``with``-scoped interval
tagged ``(phase, label)`` — ``span("flush", bucket.label)`` nested inside
``span("tick", "engine")`` — recorded into a bounded ring on the process-wide
:data:`~metrics_tpu.observe.recorder.RECORDER` and folded into the per-phase
DDSketch latency histograms of :mod:`metrics_tpu.observe.latency`. Each span
also enters a ``jax.profiler.TraceAnnotation`` so host phases line up with
device activity in a ``jax.profiler.trace()`` capture.

Overhead contract (same as PR 3, pinned by ``tests/test_observe_disabled.py``):
while telemetry is disabled, :func:`span` performs exactly one module-flag
check and returns a preallocated no-op singleton — zero allocations, nothing
appended anywhere. Spans time *host-side* sections only; nothing here may run
inside a jitted body (``jax.named_scope`` remains the only trace-safe marker,
and the jitted kernels already carry it).

Export: :func:`timeline` renders the ring as Chrome-trace/Perfetto JSON
(``chrome://tracing``, https://ui.perfetto.dev); :func:`drain_spans` pops the
raw records for embedding per-config digests in ``bench.py`` output.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

from metrics_tpu.observe import latency as _latency
from metrics_tpu.observe import recorder as _recorder

__all__ = [
    "chrome_events",
    "drain_spans",
    "record_complete",
    "span",
    "timeline",
]


class _NullSpan:
    """Shared no-op context manager returned while telemetry is disabled."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        return False


_NULL_SPAN = _NullSpan()

_LOCAL = threading.local()

# jax.profiler.TraceAnnotation, resolved on the first *enabled* span so this
# module imports (and disabled mode runs) without touching jax at all.
# None = not yet probed; False = probe failed (jax absent/ancient).
_ANNOTATION: Any = None


def _annotation_cls() -> Any:
    global _ANNOTATION
    if _ANNOTATION is None:
        try:
            from jax.profiler import TraceAnnotation

            _ANNOTATION = TraceAnnotation
        except Exception:
            _ANNOTATION = False
    return _ANNOTATION or None


def _stack() -> List["_Span"]:
    st = getattr(_LOCAL, "stack", None)
    if st is None:
        st = _LOCAL.stack = []
    return st


def _record(phase: str, label: str, t0: float, t1: float, depth: int) -> None:
    rec = _recorder.RECORDER
    entry = {
        "phase": phase,
        "label": label,
        "t0": t0,
        "t1": t1,
        "depth": depth,
        "tid": threading.get_ident(),
    }
    with rec._lock:
        rec._span_total += 1
        rec.spans.append(entry)
    _latency.observe_duration(phase, label, t1 - t0)


class _Span:
    __slots__ = ("phase", "label", "t0", "t1", "depth", "_annot")

    def __init__(self, phase: str, label: str) -> None:
        self.phase = phase
        self.label = label
        self._annot: Any = None

    def __enter__(self) -> "_Span":
        st = _stack()
        self.depth = len(st)
        st.append(self)
        cls = _annotation_cls()
        if cls is not None:
            try:
                annot = cls(self.phase if not self.label else f"{self.phase}:{self.label}")
                annot.__enter__()
                self._annot = annot
            except Exception:
                self._annot = None
        self.t0 = _recorder.clock()
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        self.t1 = _recorder.clock()
        if self._annot is not None:
            try:
                self._annot.__exit__(exc_type, exc, tb)
            except Exception:
                pass
            self._annot = None
        st = _stack()
        # exceptions can unwind spans out of order; tolerate both shapes
        if st and st[-1] is self:
            st.pop()
        elif self in st:
            st.remove(self)
        _record(self.phase, self.label, self.t0, self.t1, self.depth)
        return False


def span(phase: str, label: str = ""):
    """Open a flight-recorder span; no-op singleton while telemetry is off.

    Usage: ``with span("flush", bucket.label): ...``. Nested spans record
    their depth so :func:`timeline` renders proper parent/child tracks.
    """
    if not _recorder.ENABLED:
        return _NULL_SPAN
    return _Span(phase, label)


def record_complete(phase: str, label: str, t0: float, t1: float) -> None:
    """Record an already-measured ``[t0, t1]`` interval as a leaf span.

    For call sites that already bracket themselves with ``observe.clock()``
    (``metric.py``'s update/compute/merge/sync timers): one extra call, no
    second pair of clock reads, no context-manager overhead.
    """
    if not _recorder.ENABLED:
        return
    st = getattr(_LOCAL, "stack", None)
    _record(phase, label, t0, t1, len(st) if st else 0)


# ------------------------------------------------------------------ export
def chrome_events(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Render raw span records as Chrome-trace complete ("X") events.

    ``ts``/``dur`` are microseconds; ``ts`` is rebased so the earliest span in
    the batch sits at 0 (``perf_counter`` has an arbitrary epoch).
    """
    if not spans:
        return []
    base = min(s["t0"] for s in spans)
    pid = os.getpid()
    events: List[Dict[str, Any]] = []
    for s in spans:
        events.append(
            {
                "name": s["phase"] if not s["label"] else f'{s["phase"]}:{s["label"]}',
                "cat": s["phase"],
                "ph": "X",
                "ts": (s["t0"] - base) * 1e6,
                "dur": (s["t1"] - s["t0"]) * 1e6,
                "pid": pid,
                "tid": s["tid"],
                "args": {"label": s["label"], "depth": s["depth"]},
            }
        )
    # stable render order: per track, by start time, outermost (longest) first
    events.sort(key=lambda e: (e["tid"], e["ts"], -e["dur"]))
    return events


def timeline() -> Dict[str, Any]:
    """The span ring as a Chrome-trace/Perfetto JSON object.

    ``json.dump(observe.timeline(), f)`` produces a file that loads directly
    in ``chrome://tracing`` or https://ui.perfetto.dev. The ring is bounded
    (``Recorder.max_spans``), so long runs keep the most recent spans;
    ``otherData.spans_total`` counts everything ever recorded.
    """
    rec = _recorder.RECORDER
    with rec._lock:
        spans = list(rec.spans)
        total = rec._span_total
    return {
        "traceEvents": chrome_events(spans),
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "metrics_tpu.observe flight recorder",
            "spans_total": total,
            "spans_retained": len(spans),
        },
    }


def drain_spans() -> List[Dict[str, Any]]:
    """Pop and return every raw span record in the ring (oldest first).

    Latency sketches and the ``spans_total`` counter are untouched — draining
    is for incremental export (e.g. ``bench.py`` embedding one digest per
    config), not a reset.
    """
    rec = _recorder.RECORDER
    with rec._lock:
        spans = list(rec.spans)
        rec.spans.clear()
    return spans
