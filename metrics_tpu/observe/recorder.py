"""Near-zero-overhead runtime telemetry for the metrics_tpu runtime (DESIGN §11).

The runtime makes invisible performance decisions — shared-jit cache
hits/evictions (``metric.py:_lookup_shared_jit``), silent eager-fallback
latching (``metric.py:_wrapped_update``), compute-group fusion
(``collections.py:_fused_group_update``), cross-replica sync
(``parallel/sync.py``) — that determine whether an update loop runs as one XLA
dispatch or a Python interpreter crawl. This module makes them observable:

* **counters** — monotonically increasing ``(name, label)`` integers:
  compiles, cache hits, evictions, fallback latches, per-path update counts;
* **timers** — ``(name, label)`` wall-time aggregates (count/total/min/max)
  over host-side ``update``/``compute``/``sync``/``merge`` dispatch;
* **events** — a bounded structured log (ring buffer) carrying the *causes*:
  which exception latched an eager fallback, why a class recompiled
  (new config vs. cache eviction), when the cache was cleared.

Overhead contract: with observability **disabled (the default)** every
instrumented hot path pays a single module-flag check (``ENABLED``) and
allocates nothing — verified by ``tests/test_observe_disabled.py``. Timers
measure *host-side* wall time around (async) dispatch: the first call of a
compiled update includes its trace+compile cost, so a retrace storm shows up
as a fat ``max_s`` even though steady-state dispatch is microseconds.

Everything here is import-light (stdlib only; jax is only touched lazily via
``rank_zero_warn``'s process probe) so the core runtime can import it
unconditionally.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

__all__ = [
    "RECORDER",
    "Recorder",
    "SCHEMA_VERSION",
    "disable",
    "enable",
    "enabled",
    "note_aot_hit",
    "note_aot_miss",
    "note_aot_stale",
    "note_aot_store",
    "note_autonomic_action",
    "note_compile_miss",
    "note_eager_fallback",
    "note_engine_compile",
    "note_engine_dispatch",
    "note_engine_evict",
    "note_engine_hit",
    "note_fleet_fallback",
    "note_fleet_flush",
    "note_fleet_loose_update",
    "note_fleet_quarantine",
    "note_fleet_restore",
    "note_fleet_row_replay",
    "note_fleet_sample",
    "note_fleet_session",
    "note_fleet_tick",
    "note_fused_compile",
    "note_fused_fallback",
    "note_jit_cache_cleared",
    "note_jit_cache_hit",
    "note_jit_compile",
    "note_jit_eviction",
    "note_replica_compile",
    "note_replica_dispatch",
    "note_replica_fallback",
    "note_replica_hit",
    "note_serve_admission",
    "note_serve_bytes",
    "note_serve_connect",
    "note_serve_dedup",
    "note_serve_disconnect",
    "note_serve_frame",
    "note_serve_protocol_error",
    "note_serve_shed",
    "note_wal_append",
    "note_wal_gauges",
    "note_wal_replay",
    "note_wal_truncate",
    "poke_watchdog",
    "prometheus",
    "record_event",
    "reset",
    "scope",
    "set_fleet_gauges",
    "set_serve_gauges",
    "snapshot",
    "snapshot_json",
]

# Module-level fast flag: hot paths read this ONE attribute and skip all
# instrumentation when False. Mutated only via enable()/disable().
ENABLED = False

# snapshot() schema generation: bumped whenever the top-level or derived key
# set changes, so downstream consumers (fleet_top, why_recompile, external
# scrapers) can detect which contract a serialized snapshot file carries.
# 2 = PR 14 (schema_version itself + watchdog/SLO/compile-explain deriveds).
# 3 = PR 15 (top-level "metering" section + meter/sync-bytes deriveds).
# 4 = PR 18 (serve front-door + autonomic deriveds: ingest volume, admission
#     verdict totals, dedup/protocol-error/shed totals, reflex action total).
SCHEMA_VERSION = 4

# process-wide watchdog (observe/watchdog.py) registered via _set_watchdog;
# held here — not in the watchdog module — so engine hot paths can poke it
# through this already-imported module with one attribute read
_WATCHDOG: Optional[Any] = None

# process-wide fleet meter (observe/metering.py) registered via _set_meter —
# same pattern as the watchdog: engine hot paths reach it with one attribute
# read, and it survives a swapped-in probe Recorder (bench configs)
_METER: Optional[Any] = None

clock: Callable[[], float] = time.perf_counter

# counter names owned by the compiled-update caches (per-metric shared cache,
# fused collection cache, replica/fleet engine program caches) — cleared
# together with them so `clear_jit_cache()` leaves counters consistent with
# the (now empty) caches
_JIT_CACHE_COUNTERS = (
    "jit_compile", "jit_compile_unshared", "jit_cache_hit", "jit_cache_eviction",
    "fused_compile", "fused_hit", "replica_compile", "replica_hit", "replica_evict",
    "fleet_compile", "fleet_hit", "fleet_evict",
)

# one warning per metric class across the process, independent of ENABLED —
# losing compiled updates is user-facing even when telemetry is off
_FALLBACK_WARNED: set = set()


class Recorder:
    """Holds all telemetry. Internal containers start empty and stay empty while
    disabled (the zero-allocation half of the overhead contract)."""

    __slots__ = (
        "counters", "timers", "events", "gauges", "spans", "series", "latency",
        "max_events", "max_spans", "_seq", "_span_total", "_compiled", "_evicted", "_lock",
    )

    def __init__(self, max_events: int = 1024, max_spans: int = 4096) -> None:
        self.counters: Dict[Tuple[str, str], int] = {}
        self.timers: Dict[Tuple[str, str], List[float]] = {}  # [count, total, min, max]
        self.events: Deque[Dict[str, Any]] = deque(maxlen=max_events)
        self.gauges: Dict[Tuple[str, str], float] = {}  # last-write-wins levels
        # flight recorder (DESIGN §19): bounded span ring (observe/tracing.py),
        # rolling fleet time-series samples, and per-(phase, label) DDSketch
        # latency histograms (observe/latency.py HostDDSketch instances)
        self.spans: Deque[Dict[str, Any]] = deque(maxlen=max_spans)
        self.series: Deque[Dict[str, Any]] = deque(maxlen=512)
        self.latency: Dict[Tuple[str, str], Any] = {}
        self.max_events = max_events
        self.max_spans = max_spans
        self._seq = 0
        self._span_total = 0
        self._compiled: Dict[str, int] = {}  # metric class -> distinct shared compiles
        self._evicted: set = set()  # metric classes whose executables were evicted
        self._lock = threading.Lock()

    # ------------------------------------------------------------------ primitives
    def add_count(self, name: str, label: str, n: int = 1) -> None:
        key = (name, label)
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + n

    def set_gauge(self, name: str, label: str, value: float) -> None:
        with self._lock:
            self.gauges[(name, label)] = value

    def add_time(self, name: str, label: str, seconds: float) -> None:
        key = (name, label)
        with self._lock:
            agg = self.timers.get(key)
            if agg is None:
                self.timers[key] = [1, seconds, seconds, seconds]
            else:
                agg[0] += 1
                agg[1] += seconds
                agg[2] = min(agg[2], seconds)
                agg[3] = max(agg[3], seconds)

    def add_event(self, kind: str, **fields: Any) -> None:
        with self._lock:
            self._seq += 1
            self.events.append({"seq": self._seq, "kind": kind, **fields})

    def clear(self) -> None:
        with self._lock:
            self.counters.clear()
            self.timers.clear()
            self.events.clear()
            self.gauges.clear()
            self.spans.clear()
            self.series.clear()
            self.latency.clear()
            self._seq = 0
            self._span_total = 0
            self._compiled.clear()
            self._evicted.clear()
        # the recompile-attribution key history resets with the telemetry it
        # explains (scope/test isolation) — but NOT on clear_jit_cache(), so a
        # post-clear miss still attributes as "rebuild" rather than "first"
        explain = sys.modules.get("metrics_tpu.observe.explain")
        if explain is not None:
            explain.clear_history()

    def clear_jit_cache_stats(self) -> None:
        """Reset the shared-jit-cache counters (the cache itself was just cleared)."""
        with self._lock:
            for key in [k for k in self.counters if k[0] in _JIT_CACHE_COUNTERS]:
                del self.counters[key]
            self._compiled.clear()
            self._evicted.clear()


RECORDER = Recorder()


# ---------------------------------------------------------------------- lifecycle
def enable(max_events: int = 1024, reset: bool = False, max_spans: int = 4096) -> None:
    """Turn telemetry collection on (counters/timers/events start accumulating).

    ``enable()`` alone keeps whatever was already recorded — re-enabling
    mid-run must not destroy data. Pass ``reset=True`` to start from zero
    counters in one call (the shape every counter-asserting test fixture
    wants; stale counters from a previous test otherwise satisfy or break
    assertions at random). ``max_spans`` bounds the flight-recorder span ring
    (observe/tracing.py) the same way ``max_events`` bounds the event log.
    """
    global ENABLED
    if reset:
        RECORDER.clear()
    RECORDER.max_events = max_events
    if RECORDER.events.maxlen != max_events:
        RECORDER.events = deque(RECORDER.events, maxlen=max_events)
    RECORDER.max_spans = max_spans
    if RECORDER.spans.maxlen != max_spans:
        RECORDER.spans = deque(RECORDER.spans, maxlen=max_spans)
    ENABLED = True


def disable() -> None:
    """Turn telemetry collection off (recorded data is kept until :func:`reset`)."""
    global ENABLED
    ENABLED = False


class scope:
    """``with observe.scope(reset=True): ...`` — telemetry on for one block.

    The context-manager form of the ``enable(reset=True)`` / ``disable()`` /
    ``reset(include_warnings=True)`` dance every test fixture used to spell by
    hand. Enter clears recorded data (when ``reset``, re-arming the one-time
    fallback warnings too) and enables collection; exit restores the prior
    enabled state and clears again so nothing recorded inside leaks into the
    next test. Pass ``reset=False`` to accumulate into existing data and keep
    it on exit (the mid-run inspection shape).
    """

    __slots__ = ("_reset", "_max_events", "_max_spans", "_prior")

    def __init__(self, reset: bool = True, max_events: int = 1024, max_spans: int = 4096) -> None:
        self._reset = reset
        self._max_events = max_events
        self._max_spans = max_spans
        self._prior: Optional[bool] = None

    def __enter__(self) -> "Recorder":
        self._prior = ENABLED
        if self._reset:
            RECORDER.clear()
            _FALLBACK_WARNED.clear()
        enable(max_events=self._max_events, max_spans=self._max_spans)
        return RECORDER

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        global ENABLED
        ENABLED = bool(self._prior)
        if self._reset:
            RECORDER.clear()
            _FALLBACK_WARNED.clear()
        return False


def enabled() -> bool:
    return ENABLED


def reset(include_warnings: bool = False) -> None:
    """Drop all recorded telemetry; optionally re-arm the one-time fallback warnings."""
    RECORDER.clear()
    if include_warnings:
        _FALLBACK_WARNED.clear()


def record_event(kind: str, **fields: Any) -> None:
    """Append a structured event to the log (no-op while disabled)."""
    if ENABLED:
        RECORDER.add_event(kind, **fields)


# ------------------------------------------------------------------- runtime hooks
# Called by metric.py / collections.py / parallel/sync.py. All are no-ops while
# disabled except note_eager_fallback's one-time user warning.
def note_jit_compile(metric: str, shared: bool = True) -> None:
    if not ENABLED:
        return
    if not shared:
        RECORDER.add_count("jit_compile_unshared", metric)
        RECORDER.add_event("jit_compile", metric=metric, shared=False)
        return
    RECORDER.add_count("jit_compile", metric)
    prior = RECORDER._compiled.get(metric, 0)
    RECORDER._compiled[metric] = prior + 1
    if metric in RECORDER._evicted:
        RECORDER.add_event("recompile", metric=metric, cause="after_eviction")
    elif prior:
        RECORDER.add_event("recompile", metric=metric, cause="new_config")
    else:
        RECORDER.add_event("jit_compile", metric=metric, shared=True)


def note_jit_cache_hit(metric: str) -> None:
    if ENABLED:
        RECORDER.add_count("jit_cache_hit", metric)


def note_explicit_transfer(site: str) -> None:
    """One annotated, intentional host↔device transfer executed.

    Every ``# hotlint: intentional-transfer`` site (engine wave assembly, WAL
    journaling, expiry slicing, collection merge fetch, …) bumps this counter
    when it actually moves data, so ``fleet_top``'s "== compiles ==" section
    can show the fleet's explicit-transfer budget next to its compile budget —
    any transfer NOT counted here is implicit and hotlint/transfer-contract
    material.
    """
    if ENABLED:
        RECORDER.add_count("explicit_transfer", site)


def note_jit_eviction(metric: str) -> None:
    if ENABLED:
        RECORDER.add_count("jit_cache_eviction", metric)
        RECORDER._evicted.add(metric)
        RECORDER.add_event("jit_cache_evict", metric=metric)


def note_jit_cache_cleared() -> None:
    """The shared cache was dropped: its counters reset with it so hit rates and
    compile counts keep describing the cache that actually exists."""
    RECORDER.clear_jit_cache_stats()
    if ENABLED:
        RECORDER.add_event("jit_cache_clear")


def note_compile_miss(kind: str, label: str, components: Any) -> None:
    """Attribute one compiled-cache miss to the key component that changed.

    ``kind`` names the cache ("shared_jit" / "fleet" / "replica" / "fused" /
    "aot"); ``components`` is the decomposed cache key as ``(name, value)``
    pairs. The diff against the nearest prior key of the same kind
    (observe/explain.py) lands in the event log as a ``compile_explain``
    event plus ``compile_explain`` (per cache) and ``compile_cause`` (per
    cause) counters — the raw material for ``tools/why_recompile.py`` and
    ``fleet_top``'s "== compiles ==" section. Call sites only build the
    component tuple when ``ENABLED`` is already true.
    """
    if not ENABLED:
        return
    # lazy: explain.py is stdlib-only, but this module must stay importable
    # without it for the disabled fast path's sake
    from metrics_tpu.observe import explain as _explain

    cause, changed, detail = _explain.attribute(kind, components)
    RECORDER.add_count("compile_explain", kind)
    RECORDER.add_count("compile_cause", cause)
    RECORDER.add_event(
        "compile_explain", cache=kind, label=label, cause=cause,
        changed=list(changed), detail=detail,
    )


def _set_watchdog(watchdog: Optional[Any]) -> None:
    """Register (or clear) the process-wide watchdog; observe/watchdog.py owns this."""
    global _WATCHDOG
    _WATCHDOG = watchdog


def _set_meter(meter: Optional[Any]) -> None:
    """Register (or clear) the process-wide fleet meter; observe/metering.py owns this."""
    global _METER
    _METER = meter


def poke_watchdog() -> None:
    """Give the installed watchdog a sampling opportunity (rate-limited).

    Engine ticks call this from their already-ENABLED-guarded telemetry
    branch; with no watchdog installed it is one module-attribute read.
    """
    wd = _WATCHDOG
    if wd is not None and ENABLED:
        wd.maybe_sample()


def note_eager_fallback(metric: str, exc: BaseException) -> None:
    """A tracer error latched ``_jit_failed``: warn ONCE per class (always), and
    record the triggering exception class in the event log (when enabled)."""
    if metric not in _FALLBACK_WARNED:
        _FALLBACK_WARNED.add(metric)
        from metrics_tpu.utils.prints import rank_zero_warn

        rank_zero_warn(
            f"Metric {metric!r} could not be jit-compiled ({type(exc).__name__}) and has "
            "latched eager-mode updates for this instance's lifetime. Its update loop now "
            "runs per-op on the host instead of as one XLA executable. See "
            "`metrics_tpu.observe.snapshot()` for details.",
            UserWarning,
        )
    if ENABLED:
        RECORDER.add_count("eager_fallback", metric)
        RECORDER.add_event("eager_fallback", metric=metric, error=type(exc).__name__, detail=str(exc)[:200])


# AOT disk-cache hooks (aot/cache.py + aot/runtime.py). Deliberately NOT in
# _JIT_CACHE_COUNTERS: clear_jit_cache() drops the in-memory caches, but the
# disk cache (and the counters describing its traffic) outlives them.
def note_aot_hit(label: str) -> None:
    """A serialized executable was loaded from disk instead of compiling."""
    if ENABLED:
        RECORDER.add_count("aot_hit", label)


def note_aot_miss(label: str) -> None:
    if ENABLED:
        RECORDER.add_count("aot_miss", label)


def note_aot_stale(label: str, reason: str) -> None:
    """An entry was found but unusable (version/backend drift or corruption);
    it is latched and rewritten by the next store, not re-tried every lookup."""
    if ENABLED:
        RECORDER.add_count("aot_stale", label)
        RECORDER.add_event("aot_stale", metric=label, reason=reason[:200])


def note_aot_store(label: str, nbytes: int) -> None:
    if ENABLED:
        RECORDER.add_count("aot_store", label)
        RECORDER.add_event("aot_store", metric=label, bytes=nbytes)


def note_fused_compile(n_leaders: int, shared: bool) -> None:
    if ENABLED:
        RECORDER.add_count("fused_compile", str(n_leaders))
        RECORDER.add_event("fused_compile", leaders=n_leaders, shared=shared)


def note_fused_fallback(n_leaders: int, exc: BaseException) -> None:
    if ENABLED:
        RECORDER.add_count("fused_fallback", str(n_leaders))
        RECORDER.add_event("fused_fallback", leaders=n_leaders, error=type(exc).__name__)


# engine hooks (engine/core.py ProgramCache + its two users): kind is
# "replica" (label "<InnerClass>x<N>", wrappers/replicated.py) or "fleet"
# (label "<Class>@<fingerprint8>", engine/stream.py buckets)
def note_engine_compile(kind: str, label: str, n_rows: int) -> None:
    if ENABLED:
        RECORDER.add_count(f"{kind}_compile", label)
        RECORDER.add_event(f"{kind}_compile", engine=label, rows=n_rows)


def note_engine_hit(kind: str, label: str) -> None:
    if ENABLED:
        RECORDER.add_count(f"{kind}_hit", label)


def note_engine_evict(kind: str, label: str) -> None:
    if ENABLED:
        RECORDER.add_count(f"{kind}_evict", label)
        RECORDER.add_event(f"{kind}_evict", engine=label)


def note_engine_dispatch(kind: str, label: str) -> None:
    if ENABLED:
        RECORDER.add_count(f"{kind}_dispatch", label)


# replica-shaped conveniences kept for the wrapper call sites
def note_replica_compile(label: str, n_replicas: int) -> None:
    note_engine_compile("replica", label, n_replicas)


def note_replica_hit(label: str) -> None:
    note_engine_hit("replica", label)


def note_replica_dispatch(label: str) -> None:
    note_engine_dispatch("replica", label)


def note_replica_fallback(label: str, exc: BaseException) -> None:
    if ENABLED:
        RECORDER.add_count("replica_fallback", label)
        RECORDER.add_event("replica_fallback", engine=label, error=type(exc).__name__, detail=str(exc)[:200])


# fleet StreamEngine hooks (engine/stream.py): bucket label is "<Class>@<fp8>"
def note_fleet_tick(n_dispatches: int) -> None:
    if ENABLED:
        RECORDER.add_count("fleet_tick", "engine")
        RECORDER.add_count("fleet_tick_dispatches", "engine", n_dispatches)


def note_fleet_flush(label: str) -> None:
    if ENABLED:
        RECORDER.add_count("fleet_flush", label)


def note_fleet_session(label: str, change: str) -> None:
    """``change`` is "add" or "expire"; counts arrivals/expiries per bucket."""
    if ENABLED:
        RECORDER.add_count(f"fleet_session_{change}", label)


def note_fleet_loose_update(label: str) -> None:
    if ENABLED:
        RECORDER.add_count("fleet_loose_update", label)


def note_fleet_fallback(label: str, exc: BaseException) -> None:
    if ENABLED:
        RECORDER.add_count("fleet_fallback", label)
        RECORDER.add_event("fleet_fallback", engine=label, error=type(exc).__name__, detail=str(exc)[:200])


def note_fleet_fused_fallback(label: str, exc: BaseException) -> None:
    """The fused whole-tick program failed (trace refusal or a runtime death
    with buffers intact) and the flush fell back to per-bucket dispatches —
    where the per-wave/per-row ladder isolates the actual poison."""
    if ENABLED:
        RECORDER.add_count("fleet_fused_fallback", label)
        RECORDER.add_event("fleet_fused_fallback", engine=label, error=type(exc).__name__, detail=str(exc)[:200])


def note_fleet_quarantine(label: str, reason: str, exc: Optional[BaseException] = None) -> None:
    """One session was individually quarantined out of its bucket (blast-radius
    isolation): ``reason`` is "update_error", "nan_guard" or "probation"."""
    if ENABLED:
        RECORDER.add_count("fleet_quarantine", label)
        RECORDER.add_event(
            "fleet_quarantine", engine=label, reason=reason,
            error=type(exc).__name__ if exc is not None else None,
            detail=str(exc)[:200] if exc is not None else None,
        )


def note_fleet_row_replay(label: str, n: int = 1) -> None:
    """Rows replayed eagerly inside a surviving bucket after a dispatch death."""
    if ENABLED:
        RECORDER.add_count("fleet_row_replay", label, n)


def note_fleet_restore(label: str, n_sessions: int, n_replayed: int) -> None:
    """A StreamEngine was rebuilt from a fleet checkpoint (+ WAL replay)."""
    if ENABLED:
        RECORDER.add_count("fleet_restore", label)
        RECORDER.add_event("fleet_restore", engine=label, sessions=n_sessions, replayed=n_replayed)


# ingest write-ahead-log hooks (engine/durability.py IngestWAL)
def note_wal_append(label: str, n: int = 1) -> None:
    if ENABLED:
        RECORDER.add_count("wal_append", label, n)


def note_wal_replay(label: str, n: int) -> None:
    if ENABLED:
        RECORDER.add_count("wal_replay", label, n)
        RECORDER.add_event("wal_replay", engine=label, records=n)


def note_wal_truncate(label: str, kept: int) -> None:
    if ENABLED:
        RECORDER.add_count("wal_truncate", label)
        RECORDER.add_event("wal_truncate", engine=label, kept=kept)


def note_wal_torn_tail(label: str, frame_index: int, byte_offset: int) -> None:
    """WAL replay stopped at a damaged frame: the crash tore the journal's
    tail. ``frame_index`` is how many intact frames were recovered before the
    damage; ``byte_offset`` where in the file the scan stopped. Everything
    before the tear replayed normally — this event is the difference between
    "clean recovery" and "a synced-but-torn suffix was dropped"."""
    if ENABLED:
        RECORDER.add_count("wal_torn_tail", label)
        RECORDER.add_event("wal_torn_tail", engine=label, frame=frame_index, offset=byte_offset)


# sharded fleet hooks (engine/sharded.py ShardedStreamEngine): label is the
# inner engine name "<fleet>/shardN"
def set_shard_gauges(
    label: str,
    sessions: int,
    rows_active: int,
    rows_capacity: int,
    wal_lag_records: int,
    wal_lag_bytes: int,
    healthy: bool,
) -> None:
    """Publish one shard's occupancy/lag/health levels (refreshed per tick)."""
    if ENABLED:
        RECORDER.set_gauge("shard_sessions", label, sessions)
        RECORDER.set_gauge("shard_rows_active", label, rows_active)
        RECORDER.set_gauge("shard_rows_capacity", label, rows_capacity)
        RECORDER.set_gauge("shard_wal_lag_records", label, wal_lag_records)
        RECORDER.set_gauge("shard_wal_lag_bytes", label, wal_lag_bytes)
        RECORDER.set_gauge("shard_healthy", label, 1.0 if healthy else 0.0)


def note_shard_demoted(label: str, reason: str) -> None:
    """One shard walked the last rung of the blast-radius ladder: its bucketed
    sessions now run as eager loose sessions while every other shard keeps its
    one-dispatch-per-bucket-per-tick economy."""
    if ENABLED:
        RECORDER.add_count("shard_demoted", label)
        RECORDER.add_event("shard_demoted", engine=label, reason=reason[:200])


def note_shard_restore(label: str, n_sessions: int, n_replayed: int, recovered: bool) -> None:
    """One shard was rebuilt from its own checkpoint file + journal — the other
    shards were not touched. ``recovered=False`` means the shard's files were
    unrecoverable and it came back empty/demoted."""
    if ENABLED:
        RECORDER.add_count("shard_restore", label)
        RECORDER.add_event(
            "shard_restore", engine=label, sessions=n_sessions, replayed=n_replayed, recovered=recovered
        )


# serve front-door hooks (serve/server.py, serve/autonomic.py — DESIGN §26):
# network ingest, admission verdicts, and the autonomic observe→act loop
def note_serve_connect(producer: str) -> None:
    if ENABLED:
        RECORDER.add_count("serve_connect", producer)
        RECORDER.add_event("serve_connect", producer=producer)


def note_serve_disconnect(producer: str, reason: str) -> None:
    if ENABLED:
        RECORDER.add_count("serve_disconnect", producer)
        RECORDER.add_event("serve_disconnect", producer=producer, reason=reason[:200])


def note_serve_frame(kind: str) -> None:
    if ENABLED:
        RECORDER.add_count("serve_frames", kind)


def note_serve_bytes(n: int) -> None:
    if ENABLED:
        RECORDER.add_count("serve_bytes_in", "serve", n)


def note_serve_admission(verdict: str, rule: Optional[str] = None) -> None:
    """One admission decision; non-accept verdicts also land an event naming
    the table row that tripped."""
    if ENABLED:
        RECORDER.add_count("serve_admission", verdict)
        if verdict != "accept":
            RECORDER.add_event("serve_admission", verdict=verdict, rule=rule)


def note_serve_dedup(producer: str) -> None:
    """A resent record was squelched by the target shard's producer watermark
    — at-least-once delivery collapsed to exactly-once application."""
    if ENABLED:
        RECORDER.add_count("serve_dedup_skipped", producer)


def note_serve_protocol_error(reason: str) -> None:
    if ENABLED:
        RECORDER.add_count("serve_protocol_errors", "serve")
        RECORDER.add_event("serve_protocol_error", reason=reason[:200])


def note_serve_shed(session: str, reason: str) -> None:
    """One loose session was shed under overload (the gentlest eviction)."""
    if ENABLED:
        RECORDER.add_count("serve_shed_sessions", "serve")
        RECORDER.add_event("serve_shed", session=session, reason=reason[:200])


def note_autonomic_action(action: str, dry_run: bool = False) -> None:
    """One autonomic reflex fired (or, dry-run, would have): double / demote /
    resize / shed. The structured ``autonomic_action`` event carries the why."""
    if ENABLED:
        RECORDER.add_count("autonomic_actions", f"dry:{action}" if dry_run else action)


def set_serve_gauges(producers: int, queue_depth: int) -> None:
    """Publish the front door's live levels: authenticated producer
    connections and the ingest queue depth (decoded records not yet applied)."""
    if ENABLED:
        RECORDER.set_gauge("serve_producers", "serve", producers)
        RECORDER.set_gauge("serve_queue_depth", "serve", queue_depth)


def set_fleet_gauges(
    label: str, active: int, capacity: int, fragmented: int, bytes_stacked: int, bytes_active: int
) -> None:
    """Publish one bucket's occupancy levels (refreshed on tick/expire/stats)."""
    if ENABLED:
        RECORDER.set_gauge("fleet_rows_active", label, active)
        RECORDER.set_gauge("fleet_rows_capacity", label, capacity)
        RECORDER.set_gauge("fleet_rows_fragmented", label, fragmented)
        RECORDER.set_gauge("fleet_bytes_stacked", label, bytes_stacked)
        RECORDER.set_gauge("fleet_bytes_active", label, bytes_active)


def note_wal_gauges(label: str, lag_records: int, lag_bytes: int, ckpt_age_s: Optional[float]) -> None:
    """Publish one engine's durability lag: WAL records/bytes accumulated since
    the last fleet checkpoint, and that checkpoint's age (omitted when the
    engine has never checkpointed)."""
    if ENABLED:
        RECORDER.set_gauge("wal_lag_records", label, lag_records)
        RECORDER.set_gauge("wal_lag_bytes", label, lag_bytes)
        if ckpt_age_s is not None:
            RECORDER.set_gauge("last_ckpt_age_s", label, ckpt_age_s)


def note_fleet_sample(**fields: Any) -> None:
    """Append one tick sample to the rolling fleet time-series ring.

    The StreamEngine calls this once per tick (telemetry on) with its health
    levels — sessions, occupancy, dispatches, WAL lag, quarantine count — so
    ``tools/fleet_top.py`` can render rates from consecutive samples without
    an external scrape loop. The ring is bounded (512 samples)."""
    if ENABLED:
        with RECORDER._lock:
            RECORDER.series.append({"t": clock(), **fields})


# resilience hooks (metric.py transactional updates, resilience/, parallel/sync.py)
def note_update_rollback(metric: str, exc: BaseException) -> None:
    if ENABLED:
        RECORDER.add_count("update_rolled_back", metric)
        RECORDER.add_event("update_rolled_back", metric=metric, error=type(exc).__name__, detail=str(exc)[:200])


def note_checkpoint_save(label: str, path: str, nbytes: int) -> None:
    if ENABLED:
        RECORDER.add_count("ckpt_save", label)
        RECORDER.add_event("ckpt_save", target=label, path=path, bytes=nbytes)


def note_checkpoint_restore(label: str, path: str) -> None:
    if ENABLED:
        RECORDER.add_count("ckpt_restore", label)
        RECORDER.add_event("ckpt_restore", target=label, path=path)


def note_sync_retry(label: str, attempt: int, exc: BaseException) -> None:
    if ENABLED:
        RECORDER.add_count("sync_retry", label)
        RECORDER.add_event("sync_retry", metric=label, attempt=attempt, error=type(exc).__name__)


def note_sync_degraded(label: str, exc: BaseException, n_survivors: int) -> None:
    if ENABLED:
        RECORDER.add_count("sync_degraded", label)
        RECORDER.add_event(
            "sync_degraded", metric=label, error=type(exc).__name__, survivors=n_survivors, detail=str(exc)[:200]
        )


def note_guard_quarantined(metric: str, n_batches: int) -> None:
    if ENABLED:
        RECORDER.add_count("guard_quarantined", metric)
        RECORDER.add_event("guard_quarantined", metric=metric, batches=n_batches)


# ------------------------------------------------------------------ export surfaces
def snapshot() -> Dict[str, Any]:
    """One JSON-able dict of everything recorded so far.

    Schema (stable — tests/test_observe_runtime.py pins it)::

        {"enabled": bool,
         "schema_version": int,   # SCHEMA_VERSION, bumped with any key change
         "counters": {name: {label: int}},
         "timers":   {name: {label: {"count", "total_s", "mean_s", "min_s", "max_s"}}},
         "events":   [{"seq", "kind", ...}, ...],
         "gauges":   {name: {label: float}},
         "latency":  {phase: {label: {"count", "total_s", "mean_s", "min_s",
                      "max_s", "p50_s", "p90_s", "p99_s", "p999_s"}}},
         "series":   [{"t", ...fleet sample fields...}, ...],
         "metering": {"installed": bool, ...FleetMeter.snapshot_payload()...},
         "derived":  {"jit_cache_hit_rate": float|None,
                      "jit_compiles_total": int, "jit_cache_hits_total": int,
                      "jit_cache_evictions_total": int, "eager_fallbacks_total": int,
                      "updates_rolled_back_total": int, "ckpt_saves_total": int,
                      "ckpt_restores_total": int, "sync_retries_total": int,
                      "sync_degraded_total": int, "guard_quarantined_total": int,
                      "fleet_sessions_total": int, "fleet_capacity_total": int,
                      "fleet_occupancy_pct": float|None,
                      "fleet_pad_waste_pct": float|None,
                      "fleet_dispatches_total": int,
                      "fleet_dispatches_per_flush": float|None,
                      "fleet_quarantined_total": int,
                      "fleet_restores_total": int,
                      "wal_appends_total": int,
                      "wal_records_replayed_total": int,
                      "aot_hits_total": int, "aot_misses_total": int,
                      "aot_stale_total": int, "aot_stores_total": int,
                      "aot_hit_rate": float|None,
                      "spans_total": int,
                      "wal_lag_records": int, "wal_lag_bytes": int,
                      "wal_torn_tails_total": int,
                      "fleet_shards_total": int, "fleet_shards_demoted": int,
                      "shard_occupancy_pct": float|None,
                      "shard_wal_lag_records": int,
                      "shard_wal_lag_bytes": int,
                      "compile_explains_total": int,
                      "watchdog_samples_total": int,
                      "slo_alerts_fired_total": int,
                      "slo_alerts_resolved_total": int,
                      "slo_alerts_firing": int,
                      "meter_sessions_tracked": int,
                      "meter_attributed_dispatch_s": float,
                      "meter_attribution_pct": float|None,
                      "meter_live_bytes": int,
                      "meter_pad_waste_bytes": int,
                      "meter_quota_exceeded_total": int,
                      "sync_bytes_total": int,
                      "serve_producers_connected": int,
                      "serve_frames_total": int,
                      "serve_bytes_in_total": int,
                      "serve_admitted_total": int,
                      "serve_deferred_total": int,
                      "serve_shed_total": int,
                      "serve_rejected_total": int,
                      "serve_dedup_skipped_total": int,
                      "serve_protocol_errors_total": int,
                      "autonomic_actions_total": int}}

    The ``fleet_*`` totals aggregate the StreamEngine gauges/counters across
    buckets: occupancy is live rows over padded capacity, pad waste is the
    byte-weighted share of stacked state bytes held by padding rows, and
    dispatches-per-flush is the engine's per-bucket-per-tick dispatch economy
    (1.0 = every flushed bucket cost exactly one XLA dispatch). ``latency`` is
    the flight recorder's DDSketch-backed per-(phase, label) summaries
    (observe/latency.py) and ``series`` the rolling fleet sample ring;
    ``spans_total`` counts every span ever recorded (the span ring itself is
    bounded and exported by ``observe.timeline()``, not here). The
    ``wal_lag_*`` deriveds sum the durability-lag gauges across engines. The
    ``shard_*`` / ``fleet_shards_*`` deriveds aggregate the per-shard gauges a
    :class:`ShardedStreamEngine` publishes: shard count and how many shards are
    currently demoted to eager loose sessions, fleet-wide shard occupancy, and
    the summed per-shard journal replay debt. The watchdog rung (DESIGN §22)
    adds attributed compile-miss counts (``compile_explains_total``), watchdog
    sample counts and the SLO alert totals, with ``slo_alerts_firing`` the
    number of rules currently in the firing state (the ``slo_firing`` gauges).
    The metering rung (DESIGN §23) adds the installed :class:`FleetMeter`'s
    full payload under ``metering`` (``{"installed": False}`` when none is
    installed), per-tenant attribution deriveds (``meter_*``), and the
    summed per-state collective traffic from ``parallel/sync.py``
    (``sync_bytes_total``). The serve rung (DESIGN §26) adds front-door
    deriveds: live producer connections, total frames/bytes ingested, the
    four admission verdict totals, watermark-dedup squelches, protocol
    errors, loose-first sheds, and the autonomic reflex action total
    (dry-run decisions count — they carry a ``dry:`` label prefix in the
    raw ``autonomic_actions`` counter but roll into the same derived).
    """
    if RECORDER.latency:
        # lazy: latency.py pulls in numpy, which this stdlib-only module must not
        from metrics_tpu.observe.latency import snapshot_latency

        latency = snapshot_latency()
    else:
        latency = {}
    with RECORDER._lock:
        counters: Dict[str, Dict[str, int]] = {}
        for (name, label), v in RECORDER.counters.items():
            counters.setdefault(name, {})[label] = v
        timers: Dict[str, Dict[str, Dict[str, float]]] = {}
        for (name, label), (count, total, mn, mx) in RECORDER.timers.items():
            timers.setdefault(name, {})[label] = {
                "count": int(count),
                "total_s": total,
                "mean_s": total / count if count else 0.0,
                "min_s": mn,
                "max_s": mx,
            }
        events = list(RECORDER.events)
        gauges: Dict[str, Dict[str, float]] = {}
        for (name, label), g in RECORDER.gauges.items():
            gauges.setdefault(name, {})[label] = g
        series = list(RECORDER.series)
        span_total = RECORDER._span_total
    compiles = sum(counters.get("jit_compile", {}).values())
    hits = sum(counters.get("jit_cache_hit", {}).values())
    lookups = compiles + hits
    fleet_active = sum(gauges.get("fleet_rows_active", {}).values())
    fleet_capacity = sum(gauges.get("fleet_rows_capacity", {}).values())
    fleet_bytes = sum(gauges.get("fleet_bytes_stacked", {}).values())
    fleet_bytes_active = sum(gauges.get("fleet_bytes_active", {}).values())
    fleet_dispatches = sum(counters.get("fleet_dispatch", {}).values())
    fleet_flushes = sum(counters.get("fleet_flush", {}).values())
    aot_hits = sum(counters.get("aot_hit", {}).values())
    aot_misses = sum(counters.get("aot_miss", {}).values())
    aot_lookups = aot_hits + aot_misses
    shard_active = sum(gauges.get("shard_rows_active", {}).values())
    shard_capacity = sum(gauges.get("shard_rows_capacity", {}).values())
    mt = _METER
    if mt is not None:
        metering = mt.snapshot_payload()
        meter_totals = metering["totals"]
        meter_memory = metering["memory"]["totals"]
    else:
        metering = {"installed": False}
        meter_totals = {}
        meter_memory = {}
    return {
        "enabled": ENABLED,
        "schema_version": SCHEMA_VERSION,
        "counters": {k: dict(sorted(v.items())) for k, v in sorted(counters.items())},
        "timers": {k: dict(sorted(v.items())) for k, v in sorted(timers.items())},
        "events": events,
        "gauges": {k: dict(sorted(v.items())) for k, v in sorted(gauges.items())},
        "latency": latency,
        "series": series,
        "metering": metering,
        "derived": {
            "jit_cache_hit_rate": (hits / lookups) if lookups else None,
            "jit_compiles_total": compiles,
            "jit_cache_hits_total": hits,
            "jit_cache_evictions_total": sum(counters.get("jit_cache_eviction", {}).values()),
            "eager_fallbacks_total": sum(counters.get("eager_fallback", {}).values()),
            "updates_rolled_back_total": sum(counters.get("update_rolled_back", {}).values()),
            "ckpt_saves_total": sum(counters.get("ckpt_save", {}).values()),
            "ckpt_restores_total": sum(counters.get("ckpt_restore", {}).values()),
            "sync_retries_total": sum(counters.get("sync_retry", {}).values()),
            "sync_degraded_total": sum(counters.get("sync_degraded", {}).values()),
            "guard_quarantined_total": sum(counters.get("guard_quarantined", {}).values()),
            "fleet_sessions_total": int(fleet_active),
            "fleet_capacity_total": int(fleet_capacity),
            "fleet_occupancy_pct": (100.0 * fleet_active / fleet_capacity) if fleet_capacity else None,
            "fleet_pad_waste_pct": (100.0 * (fleet_bytes - fleet_bytes_active) / fleet_bytes) if fleet_bytes else None,
            "fleet_dispatches_total": fleet_dispatches,
            "fleet_dispatches_per_flush": (fleet_dispatches / fleet_flushes) if fleet_flushes else None,
            "fleet_quarantined_total": sum(counters.get("fleet_quarantine", {}).values()),
            "fleet_restores_total": sum(counters.get("fleet_restore", {}).values()),
            "wal_appends_total": sum(counters.get("wal_append", {}).values()),
            "wal_records_replayed_total": sum(counters.get("wal_replay", {}).values()),
            "aot_hits_total": aot_hits,
            "aot_misses_total": aot_misses,
            "aot_stale_total": sum(counters.get("aot_stale", {}).values()),
            "aot_stores_total": sum(counters.get("aot_store", {}).values()),
            "aot_hit_rate": (aot_hits / aot_lookups) if aot_lookups else None,
            "spans_total": span_total,
            "wal_lag_records": int(sum(gauges.get("wal_lag_records", {}).values())),
            "wal_lag_bytes": int(sum(gauges.get("wal_lag_bytes", {}).values())),
            "wal_torn_tails_total": sum(counters.get("wal_torn_tail", {}).values()),
            "fleet_shards_total": len(gauges.get("shard_healthy", {})),
            "fleet_shards_demoted": sum(1 for v in gauges.get("shard_healthy", {}).values() if not v),
            "shard_occupancy_pct": (100.0 * shard_active / shard_capacity) if shard_capacity else None,
            "shard_wal_lag_records": int(sum(gauges.get("shard_wal_lag_records", {}).values())),
            "shard_wal_lag_bytes": int(sum(gauges.get("shard_wal_lag_bytes", {}).values())),
            "compile_explains_total": sum(counters.get("compile_explain", {}).values()),
            "watchdog_samples_total": sum(counters.get("watchdog_sample", {}).values()),
            "slo_alerts_fired_total": sum(counters.get("slo_fired", {}).values()),
            "slo_alerts_resolved_total": sum(counters.get("slo_resolved", {}).values()),
            "slo_alerts_firing": sum(1 for v in gauges.get("slo_firing", {}).values() if v),
            "meter_sessions_tracked": int(meter_totals.get("sessions_exact", 0))
            + int(meter_totals.get("sessions_sketched", 0)),
            "meter_attributed_dispatch_s": float(meter_totals.get("attributed_s", 0.0)),
            "meter_attribution_pct": meter_totals.get("attribution_pct"),
            "meter_live_bytes": int(meter_memory.get("live_bytes", 0)),
            "meter_pad_waste_bytes": int(meter_memory.get("pad_waste_bytes", 0)),
            "meter_quota_exceeded_total": sum(counters.get("quota_exceeded", {}).values()),
            "sync_bytes_total": sum(counters.get("sync_bytes", {}).values()),
            "serve_producers_connected": int(sum(gauges.get("serve_producers", {}).values())),
            "serve_frames_total": sum(counters.get("serve_frames", {}).values()),
            "serve_bytes_in_total": sum(counters.get("serve_bytes_in", {}).values()),
            "serve_admitted_total": counters.get("serve_admission", {}).get("accept", 0),
            "serve_deferred_total": counters.get("serve_admission", {}).get("defer", 0),
            "serve_shed_total": counters.get("serve_admission", {}).get("shed", 0),
            "serve_rejected_total": counters.get("serve_admission", {}).get("reject", 0),
            "serve_dedup_skipped_total": sum(counters.get("serve_dedup_skipped", {}).values()),
            "serve_protocol_errors_total": sum(counters.get("serve_protocol_errors", {}).values()),
            "autonomic_actions_total": sum(counters.get("autonomic_actions", {}).values()),
        },
    }


def _prom_name(name: str) -> str:
    return "metrics_tpu_" + "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _prom_label(label: str) -> str:
    # exposition-format label escaping: backslash first, then quote, and
    # newline as the literal two characters ``\n`` — replacing it with a space
    # (the old behavior) silently aliased distinct label values
    return label.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def prometheus() -> str:
    """Prometheus text-exposition dump of counters, gauges, timers and latency.

    Counters render as ``*_total`` counter families; gauges as gauge families;
    timers as summary-style ``*_seconds_count`` / ``*_seconds_sum`` pairs; and
    the flight recorder's DDSketch phase latencies as full summary families
    with ``quantile`` labels (p50/p90/p99/p999). Every family carries
    ``# HELP``/``# TYPE`` headers — ready for a textfile collector or a
    scrape handler. With a fleet meter installed (observe/metering.py) the
    ``metrics_tpu_meter_*`` families ride along, cardinality-bounded by
    construction: at most ``top_k`` session label values regardless of how
    many sessions the fleet has served.
    """
    snap = snapshot()
    lines: List[str] = []

    def _family(prom: str, kind: str, help_text: str) -> None:
        lines.append(f"# HELP {prom} {help_text}")
        lines.append(f"# TYPE {prom} {kind}")

    for name, by_label in snap["counters"].items():
        prom = _prom_name(name) + "_total"
        _family(prom, "counter", f"metrics_tpu runtime counter: {name} occurrences per label.")
        for label, v in by_label.items():
            lines.append(f'{prom}{{metric="{_prom_label(label)}"}} {v}')
    for name, by_label in snap["gauges"].items():
        prom = _prom_name(name)
        _family(prom, "gauge", f"metrics_tpu runtime gauge: last observed {name} level per label.")
        for label, v in by_label.items():
            lines.append(f'{prom}{{metric="{_prom_label(label)}"}} {v}')
    for name, by_label in snap["timers"].items():
        prom = _prom_name(name) + "_seconds"
        _family(prom, "summary", f"metrics_tpu host wall time over {name} dispatch.")
        for label, agg in by_label.items():
            sel = f'{{metric="{_prom_label(label)}"}}'
            lines.append(f"{prom}_count{sel} {agg['count']}")
            lines.append(f"{prom}_sum{sel} {agg['total_s']:.9f}")
    for phase, by_label in snap["latency"].items():
        prom = _prom_name(f"phase_{phase}") + "_seconds"
        _family(
            prom, "summary",
            f"metrics_tpu flight-recorder span latency for phase {phase} (DDSketch, rel. error <= 2%).",
        )
        for label, agg in by_label.items():
            esc = _prom_label(label)
            for key, value in agg.items():
                if key.startswith("p") and key.endswith("_s"):
                    q = "0." + key[1:-2]
                    lines.append(f'{prom}{{label="{esc}",quantile="{q}"}} {value:.9f}')
            lines.append(f'{prom}_count{{label="{esc}"}} {agg["count"]}')
            lines.append(f'{prom}_sum{{label="{esc}"}} {agg["total_s"]:.9f}')
    mt = _METER
    if mt is not None:
        lines.extend(mt.prometheus_lines(_prom_name, _prom_label))
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot_json(**dump_kwargs: Any) -> str:
    """:func:`snapshot` serialized to a JSON string (convenience for logging)."""
    return json.dumps(snapshot(), **dump_kwargs)
