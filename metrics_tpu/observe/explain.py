"""Recompile-cause attribution for the compiled-program caches (DESIGN §22).

Every compiled-update cache in the runtime — the per-metric shared-jit cache
(``metric.py:_lookup_shared_jit``), the replica/fleet :class:`ProgramCache`
LRUs (``engine/core.py``), the fused collection cache (``collections.py``) and
the AOT disk cache (``aot/runtime.py``) — keys its entries on a tuple of
static facts: metric class, config items, row capacity, batch avals, argument
structure, the donation decision, the x64 regime. A miss therefore always has
a *cause*: some component of the key differs from every entry that came before
it. This module names that component.

Call sites decompose their cache key into named ``(component, value)`` pairs
and report misses through :func:`metrics_tpu.observe.recorder.note_compile_miss`,
which calls :func:`attribute` here. Attribution diffs the new key against the
*nearest* prior key of the same cache kind (fewest differing components, most
recent wins ties) held in a bounded per-kind history, and classifies:

* ``"first"`` — no prior key of this kind exists (cold process, expected);
* ``"rebuild"`` — an identical key missed again: the entry was evicted,
  the cache was cleared, or an AOT entry went stale — capacity churn, not
  key churn;
* a single component name (``"config:num_classes"``, ``"capacity"``,
  ``"batch_avals"``, ``"donation"``, ``"x64"``, …) — the actionable case:
  exactly one thing changed;
* ``"multiple"`` — several components moved at once. Two collapse rules
  apply first: an x64-regime flip implies every aval-carrying component
  (``batch_avals`` / ``state_avals`` / ``call_signature``) changes with it,
  so those are dropped from the diff before counting; and a change to a fused
  key's member roster (``buckets`` on the fused-tick key, ``leaders`` on the
  fused collection key) implies every component that exists on only one side
  of the diff (a member joining or leaving brings its whole
  ``capacity[label]`` / ``batch_avals[label]`` / ``config[label]:…`` family
  with it), so one-sided components are dropped too. Both rules see through
  the per-member ``[label]`` suffix the decomposed fused key puts on each
  per-entry component.

The history deliberately survives ``clear_jit_cache()`` — that is what lets a
post-clear miss attribute as ``"rebuild"`` instead of ``"first"`` — and is
dropped by ``Recorder.clear()`` (test/scope isolation).

Everything here is stdlib-only so :mod:`metrics_tpu.observe.recorder` can
import it lazily without dragging numpy/jax into the telemetry fast path.
``main`` is the ``why-recompile`` console entry point (``tools/
why_recompile.py``): it renders the ``compile_explain`` events of a snapshot
JSON into a per-cache, per-cause report.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "attribute",
    "clear_history",
    "history_depth",
    "main",
    "render_report",
]

# components whose values are derived from array avals: an x64-regime flip
# rewrites all of them, so they are implied (not independent causes) whenever
# "x64" itself is in the diff
_AVAL_COMPONENTS = frozenset({"batch_avals", "state_avals", "call_signature"})

# roster components: the member list of a fused key — "buckets" on the engine's
# fused-tick key, "leaders" on the fused collection key. A roster change
# implies every component that exists on only one side of the diff.
_ROSTER_COMPONENTS = frozenset({"buckets", "leaders"})

# the decomposed fused key suffixes each per-entry component with its bucket
# label: ``batch_avals[cls]``, ``config[cls]:k``. Collapse rules match on the
# base name so they keep working on fused multi-bucket keys.
_SUFFIX_RE = re.compile(r"\[[^\][]*\]")


def _component_base(name: str) -> str:
    """``batch_avals[cls]`` → ``batch_avals``; ``config[cls]:k`` → ``config:k``."""
    return _SUFFIX_RE.sub("", name, count=1)

_HISTORY_DEPTH = 128
_VALUE_CAP = 160  # rendered component values are bounded for the event log

_HISTORY: Dict[str, Deque[Dict[str, str]]] = {}
_LOCK = threading.Lock()


def _render(value: Any) -> str:
    """Bounded, deterministic rendering of one component value."""
    try:
        text = repr(value)
    except Exception:  # noqa: BLE001 — a broken repr must not kill the hot path
        text = f"<unreprable {type(value).__name__}>"
    if len(text) > _VALUE_CAP:
        text = text[: _VALUE_CAP - 1] + "…"
    return text


def _normalize(components: Sequence[Tuple[str, Any]]) -> Dict[str, str]:
    return {str(name): _render(value) for name, value in components}


def _diff(prior: Dict[str, str], now: Dict[str, str]) -> Tuple[str, ...]:
    """Names whose values differ, or that exist on only one side."""
    changed = [k for k in now if prior.get(k) != now[k]]
    changed += [k for k in prior if k not in now]
    return tuple(sorted(changed))


def attribute(
    kind: str, components: Sequence[Tuple[str, Any]]
) -> Tuple[str, Tuple[str, ...], Dict[str, Dict[str, Optional[str]]]]:
    """Classify one cache miss; returns ``(cause, changed, detail)``.

    ``components`` is the decomposed cache key: ordered ``(name, value)``
    pairs. ``detail`` maps each changed component to its prior/new rendered
    values (``None`` for a side where the component did not exist).
    """
    now = _normalize(components)
    with _LOCK:
        hist = _HISTORY.get(kind)
        if hist is None:
            hist = _HISTORY[kind] = deque(maxlen=_HISTORY_DEPTH)
        nearest: Optional[Dict[str, str]] = None
        nearest_diff: Tuple[str, ...] = ()
        for prior in reversed(hist):  # most recent first: wins diff-count ties
            d = _diff(prior, now)
            if nearest is None or len(d) < len(nearest_diff):
                nearest, nearest_diff = prior, d
                if not d:
                    break
        first = nearest is None
        hist.append(now)
    if first:
        return "first", (), {}
    if not nearest_diff:
        return "rebuild", (), {}
    assert nearest is not None
    changed = nearest_diff
    if len(changed) > 1 and any(r in changed for r in _ROSTER_COMPONENTS):
        # the fused key's member roster changed: every component that exists
        # on only one side of the diff was brought (or taken) by the
        # joining/leaving member itself, not independently changed
        collapsed = tuple(
            c for c in changed
            if c in _ROSTER_COMPONENTS or ((c in now) == (c in nearest))
        )
        if collapsed:
            changed = collapsed
    if "x64" in changed and len(changed) > 1:
        collapsed = tuple(c for c in changed if _component_base(c) not in _AVAL_COMPONENTS)
        if collapsed:
            changed = collapsed
    cause = changed[0] if len(changed) == 1 else "multiple"
    detail = {c: {"prior": nearest.get(c), "now": now.get(c)} for c in changed}
    return cause, changed, detail


def clear_history() -> None:
    """Drop all per-kind key history (``Recorder.clear()`` calls this)."""
    with _LOCK:
        _HISTORY.clear()


def history_depth(kind: str) -> int:
    with _LOCK:
        hist = _HISTORY.get(kind)
        return len(hist) if hist is not None else 0


# ------------------------------------------------------------------ reporting

def render_report(snap: Dict[str, Any], tail: int = 8) -> str:
    """Text report over a snapshot's ``compile_explain`` events + counters."""
    events = [e for e in snap.get("events", []) if e.get("kind") == "compile_explain"]
    by_cache = snap.get("counters", {}).get("compile_explain", {}) or {}
    by_cause = snap.get("counters", {}).get("compile_cause", {}) or {}
    total = sum(by_cache.values())
    lines: List[str] = []
    lines.append("== why recompile ==")
    if not total and not events:
        lines.append("no attributed compile misses recorded — was telemetry enabled?")
        return "\n".join(lines)
    lines.append(
        f"{total} attributed cache miss(es) across {len(by_cache)} cache(s)"
        f" ({len(events)} event(s) still in the ring)"
    )
    lines.append("")
    lines.append(f"{'cache':<14}{'misses':>8}")
    for cache, n in sorted(by_cache.items(), key=lambda kv: (-kv[1], kv[0])):
        lines.append(f"{cache:<14}{n:>8}")
    lines.append("")
    lines.append(f"{'cause':<28}{'misses':>8}")
    for cause, n in sorted(by_cause.items(), key=lambda kv: (-kv[1], kv[0])):
        lines.append(f"{cause:<28}{n:>8}")
    actionable = [e for e in events if e.get("cause") not in ("first", "rebuild")]
    show = (actionable or events)[-tail:]
    if show:
        lines.append("")
        lines.append(f"last {len(show)} attributed miss(es):")
        for e in show:
            parts = [f"[{e.get('cache')}] {e.get('label')}: {e.get('cause')}"]
            detail = e.get("detail") or {}
            for comp, change in sorted(detail.items()):
                parts.append(f"    {comp}: {change.get('prior')} -> {change.get('now')}")
            lines.extend(parts)
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``why-recompile``: explain every attributed cache miss in a snapshot.

    Reads one ``observe.snapshot()`` JSON file (``-`` for stdin) and renders
    the per-cache / per-cause miss report with the changed key components of
    the most recent events. Exit codes: 0 rendered, 2 usage/unreadable input.
    """
    p = argparse.ArgumentParser(
        prog="why_recompile",
        description="Explain recompiles: per-cache, per-cause report over the "
                    "compile_explain events of an observe.snapshot() JSON file.",
    )
    p.add_argument("snapshot", help="snapshot JSON path, or - for stdin")
    p.add_argument("--tail", type=int, default=8,
                   help="how many recent attributed misses to detail (default 8)")
    args = p.parse_args(argv)
    try:
        if args.snapshot == "-":
            snap = json.load(sys.stdin)
        else:
            with open(args.snapshot) as f:
                snap = json.load(f)
    except (OSError, ValueError) as exc:
        print(f"why_recompile: cannot read {args.snapshot}: {exc}", file=sys.stderr)
        return 2
    print(render_report(snap, tail=args.tail))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
