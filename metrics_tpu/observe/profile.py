"""Perf-baseline ratchet over the XLA cost report (DESIGN §11).

``tools/perf_baseline.json`` pins, per jit-eligible exported metric class, the
XLA cost model of its compiled update (FLOPs, bytes accessed, peak memory) and
its jit-cache sharing behavior (``shareable`` + observed ``compile_count`` for
two config-equal instances). The check ratchets exactly like the
jitlint/distlint baselines:

* a class whose FLOPs or bytes grow beyond ``tolerance``× its baseline — or
  whose update stops sharing one compiled executable across instances, stops
  persisting through the AOT disk cache (``aot_cacheable`` True→False), or
  starts paying cold-start compiles a warmed cache used to absorb
  (``cold_start_compile_count`` 0→N, DESIGN §18) — is a **regression**
  (exit 1);
* a class that *improved* beyond tolerance, or vanished from the registry, is
  reported **stale** so the baseline ratchets down over time (exit 0);
* classes with no baseline entry are reported as **new** (exit 0; record them
  with ``--update-baseline``).

FLOPs/bytes come from XLA's cost model over the lowered (pre-optimization)
HLO, so they are deterministic per jax version — the default 1.5× tolerance
absorbs cost-model drift across versions while still failing a genuinely
doubled kernel. Peak memory is recorded for attribution but not ratcheted
(it tracks backend packing decisions, not the program we authored).

CLI: ``python tools/profile_metrics.py`` / the ``profile-metrics`` console
script; also runs as the ``perf`` pass of ``tools/lint_metrics.py --all``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from metrics_tpu.observe.costs import CostReport, collect_cost_report

__all__ = [
    "DEFAULT_TOLERANCE",
    "diff_cost_baseline",
    "load_cost_baseline",
    "main",
    "run_perf_check",
    "write_cost_baseline",
]

DEFAULT_TOLERANCE = 1.5
_DEFAULT_BASELINE = os.path.join("tools", "perf_baseline.json")
_RATCHETED = ("flops", "bytes_accessed")


def report_to_dict(results: Sequence[CostReport]) -> Dict[str, Dict[str, Any]]:
    """``{class name: cost dict}`` for the successful cases (the baseline shape)."""
    return {r.case.name: dict(r.cost) for r in results if r.ok}


def load_cost_baseline(path: str) -> Dict[str, Dict[str, Any]]:
    from metrics_tpu.analysis.engine import load_baseline_section

    return {str(k): dict(v) for k, v in load_baseline_section(path, "cost").items()}


def write_cost_baseline(path: str, results: Sequence[CostReport]) -> Dict[str, Dict[str, Any]]:
    from metrics_tpu.analysis.engine import write_baseline_section

    cost = dict(sorted(report_to_dict(results).items()))
    write_baseline_section(
        path,
        "cost",
        cost,
        "perf baseline — XLA cost model per compiled metric update, keyed by exported "
        "class name. Regenerate with `python tools/profile_metrics.py --update-baseline`.",
        seed={"tolerance": DEFAULT_TOLERANCE},
    )
    return cost


def diff_cost_baseline(
    results: Sequence[CostReport],
    baseline: Dict[str, Dict[str, Any]],
    tolerance: float = DEFAULT_TOLERANCE,
) -> Tuple[List[str], List[str], List[str]]:
    """Split into (regressions, stale_keys, new_keys); regressions fail the run."""
    regressions: List[str] = []
    new: List[str] = []
    observed = report_to_dict(results)
    for name, cost in sorted(observed.items()):
        base = baseline.get(name)
        if base is None:
            new.append(name)
            continue
        for field in _RATCHETED:
            cur, ref = float(cost.get(field, 0.0)), float(base.get(field, 0.0))
            if ref > 0 and cur > ref * tolerance:
                regressions.append(f"{name}: {field} {cur:.0f} > {tolerance}x baseline {ref:.0f}")
            elif ref == 0 and cur > 0 and field == "flops":
                regressions.append(f"{name}: {field} appeared ({cur:.0f}) where baseline had none")
        if base.get("shareable") and not cost.get("shareable"):
            regressions.append(f"{name}: update no longer shareable (jit-cache key became unhashable)")
        if base.get("donation_eligible") and cost.get("donation_eligible") is False:
            regressions.append(
                f"{name}: update no longer donation-eligible — every jitted step "
                "reallocates the state pytree instead of aliasing it in place"
            )
        # compile_count 0 means the class updates eagerly by design (e.g. the
        # aggregation metrics' host-scalar path) — starting to compile is not a
        # sharing regression, so only ratchet from a baseline of >= 1
        base_compiles = base.get("compile_count")
        cur_compiles = cost.get("compile_count")
        if base_compiles and cur_compiles is not None and cur_compiles > base_compiles:
            regressions.append(
                f"{name}: {cur_compiles} compiles for two config-equal instances "
                f"(baseline {base_compiles}) — jit-cache sharing broke"
            )
        # cold-start ratchet (DESIGN §18): a baseline of 0 means a warmed AOT
        # cache fully absorbs this class's first-update compile in a fresh
        # process; any compile reappearing there is disk reuse breaking. The
        # == 0 comparison (not falsy) keeps pre-AOT baselines exempt.
        cur_cold = cost.get("cold_start_compile_count")
        if base.get("cold_start_compile_count") == 0 and cur_cold:
            regressions.append(
                f"{name}: {cur_cold} cold-start compile(s) where the baseline had 0 "
                "— AOT disk executable reuse broke"
            )
        if base.get("aot_cacheable") and cost.get("aot_cacheable") is False:
            regressions.append(
                f"{name}: no longer AOT-cacheable — every new process pays this "
                "class's cold-start compile again"
            )
    stale: List[str] = []
    for name, base in sorted(baseline.items()):
        cost = observed.get(name)
        if cost is None:
            stale.append(f"{name}: in baseline but not profiled (class removed or now ineligible)")
            continue
        for field in _RATCHETED:
            cur, ref = float(cost.get(field, 0.0)), float(base.get(field, 0.0))
            if cur > 0 and ref > cur * tolerance:
                stale.append(f"{name}: {field} improved {ref:.0f} -> {cur:.0f}; ratchet the baseline down")
    return regressions, stale, new


def run_perf_check(
    root: str,
    baseline_path: Optional[str] = None,
    tolerance: float = DEFAULT_TOLERANCE,
    include_memory: bool = False,
    update_baseline: bool = False,
    quiet: bool = False,
    report: Optional[Dict[str, Any]] = None,
) -> int:
    """The ``perf`` pass of ``lint_metrics --all``: profile, ratchet, one verdict line.

    With ``report`` given (the CLI's ``--json`` path), findings are collected
    into it instead of printed — the caller owns the one JSON document on
    stdout.
    """
    from metrics_tpu.engine.smoke import (  # noqa: PLC0415 — pulls in jax + the registry
        diff_fleet_baseline,
        load_fleet_baseline,
        run_fleet_smoke,
        write_fleet_baseline,
    )

    path = baseline_path or os.path.join(root, _DEFAULT_BASELINE)
    results = collect_cost_report(include_memory=include_memory)
    failures = [r for r in results if not r.ok]
    fleet_obs = run_fleet_smoke()
    if update_baseline:
        cost = write_cost_baseline(path, results)
        fleet = write_fleet_baseline(path, fleet_obs)
        if not quiet:
            print(f"perf: baseline written to {path} ({len(cost)} classes + {len(fleet)} fleet keys)")
        return 0
    regressions, stale, new = diff_cost_baseline(results, load_cost_baseline(path), tolerance)
    f_reg, f_stale, f_new = diff_fleet_baseline(fleet_obs, load_fleet_baseline(path))
    regressions += f_reg
    stale += f_stale
    new += f_new
    if report is not None:
        report.update({
            "profiled": sum(1 for r in results if r.ok),
            "cases": len(results),
            "fleet": fleet_obs,
            "regressions": regressions,
            "stale": stale,
            "new": new,
            "skipped": {r.case.name: r.error for r in failures},
        })
        return 1 if regressions else 0
    for line in regressions:
        print(f"perf: REGRESSION {line}")
    if not quiet:
        for line in stale:
            print(f"perf: stale baseline entry: {line}")
        for name in new:
            print(f"perf: new class not in baseline: {name} (record with --update-baseline)")
        for r in failures:
            print(f"perf: skipped {r.case.name}: {r.error}")
        ok = sum(1 for r in results if r.ok)
        print(f"perf: {ok}/{len(results)} classes profiled, {len(regressions)} regression(s), "
              f"{len(stale)} stale, {len(new)} new; fleet smoke: "
              f"{fleet_obs['streams']} streams / {fleet_obs['buckets']} buckets, "
              f"{fleet_obs['dispatches_per_shard_tick']} dispatch(es)/tick, "
              f"{fleet_obs['update_compiles']} update compile(s), "
              f"{fleet_obs['poll_dispatches_per_poll']} compute dispatch(es)/poll")
    return 1 if regressions else 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="profile-metrics",
        description="XLA cost profiling of compiled metric updates (FLOPs / bytes accessed / "
                    "peak memory / jit-cache sharing), ratcheted against tools/perf_baseline.json.",
    )
    p.add_argument("--root", default=None, help="repo root (default: cwd)")
    p.add_argument("--baseline", default=None, help="perf baseline JSON path")
    p.add_argument("--update-baseline", action="store_true",
                   help="record the current cost report as the new baseline and exit 0")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help=f"allowed growth factor per ratcheted field (default {DEFAULT_TOLERANCE})")
    p.add_argument("--classes", default=None,
                   help="comma-separated class names to profile (default: the full registry)")
    p.add_argument("--no-memory", action="store_true",
                   help="skip backend compilation (no peak-memory column; several times faster)")
    p.add_argument("--static-only", action="store_true",
                   help="skip the dynamic two-instance sharing probe (no compile_count column)")
    p.add_argument("--format", choices=("text", "json"), default="text", dest="fmt")
    p.add_argument("-q", "--quiet", action="store_true", help="suppress the report body and summary")
    args = p.parse_args(argv)
    root = os.path.abspath(args.root or os.getcwd())
    baseline_path = args.baseline or os.path.join(root, _DEFAULT_BASELINE)

    from metrics_tpu.observe.costs import PROFILE_CASES

    cases = list(PROFILE_CASES)
    if args.classes:
        wanted = {c.strip() for c in args.classes.split(",") if c.strip()}
        cases = [c for c in cases if c.name in wanted]
        missing = wanted - {c.name for c in cases}
        if missing:
            print(f"profile-metrics: unknown class(es): {', '.join(sorted(missing))}")
            return 2
    results = collect_cost_report(
        cases, include_memory=not args.no_memory, dynamic=not args.static_only
    )

    from metrics_tpu.engine.smoke import (  # noqa: PLC0415
        diff_fleet_baseline,
        load_fleet_baseline,
        run_fleet_smoke,
        write_fleet_baseline,
    )

    # the fleet smoke rides along except under a --classes filter (whose point
    # is profiling a handful of updates quickly)
    fleet_obs = None if args.classes else run_fleet_smoke()

    if args.update_baseline:
        cost = write_cost_baseline(baseline_path, results)
        if fleet_obs is not None:
            write_fleet_baseline(baseline_path, fleet_obs)
        if not args.quiet:
            print(f"profile-metrics: baseline written to {baseline_path} ({len(cost)} classes)")
        return 0

    baseline = load_cost_baseline(baseline_path)
    regressions, stale, new = diff_cost_baseline(results, baseline, args.tolerance)
    if fleet_obs is not None:
        f_reg, f_stale, f_new = diff_fleet_baseline(fleet_obs, load_fleet_baseline(baseline_path))
        regressions += f_reg
        stale += f_stale
        new += f_new
    failures = [r for r in results if not r.ok]

    if args.fmt == "json":
        print(json.dumps({
            "cost": report_to_dict(results),
            "fleet": fleet_obs,
            "errors": {r.case.name: r.error for r in failures},
            "regressions": regressions,
            "stale": stale,
            "new": new,
        }, indent=2, sort_keys=True))
        return 1 if regressions else 0

    if not args.quiet:
        header = f"{'class':<40} {'flops':>12} {'bytes':>12} {'peak_mem':>10} {'compiles':>8} {'shared':>6}"
        print(header)
        print("-" * len(header))
        for r in sorted(results, key=lambda r: r.case.name):
            if not r.ok:
                print(f"{r.case.name:<40} SKIP: {r.error}")
                continue
            c = r.cost
            print(f"{r.case.name:<40} {c.get('flops', 0):>12.0f} {c.get('bytes_accessed', 0):>12.0f} "
                  f"{c.get('peak_memory_bytes', '-'):>10} {c.get('compile_count', '-'):>8} "
                  f"{str(c.get('shareable', '-')):>6}")
    for line in regressions:
        print(f"REGRESSION {line}")
    if not args.quiet:
        for line in stale:
            print(f"stale: {line}")
        for name in new:
            print(f"new (not in baseline): {name}")
        ok = sum(1 for r in results if r.ok)
        print(f"profile-metrics: {ok}/{len(results)} classes profiled, {len(regressions)} regression(s), "
              f"{len(stale)} stale, {len(new)} new")
    return 1 if regressions else 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
