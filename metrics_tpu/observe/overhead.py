"""Disabled-mode telemetry overhead smoke (DESIGN §19).

The flight recorder's contract is that *disabled* telemetry costs one
module-flag check per instrumented site — ``span()`` returns the preallocated
:data:`~metrics_tpu.observe.tracing._NULL_SPAN` singleton, ``record_complete``
returns before touching anything. ``tests/test_observe_disabled.py`` pins the
*mechanism* (singleton identity, zero allocations); this pass pins the
*budget*: the instrumentation a real 1k-step update loop passes through must
cost under :data:`MAX_OVERHEAD_PCT` of the loop's own step time.

Comparing two whole-loop timings (instrumented vs. hand-stripped) would drown
a sub-1% effect in jit/OS noise, so the check is built bottom-up instead:

* microbenchmark the two disabled primitives — a null ``with span(...)``
  (call + flag check + no-op ``__enter__``/``__exit__``) and a
  ``record_complete`` early return (flag check only) — min-of-repeats over
  a tight loop, with the empty loop's own cost subtracted so the number is
  the primitive, not the ``for`` statement;
* measure the real per-step cost of a 1k-step jitted
  ``MulticlassAccuracy.update`` loop (post-warmup, so compile time is
  excluded — steady-state steps are where per-site overhead could matter);
* charge a pessimistic per-step instrumentation budget and require
  ``budget / step_time < MAX_OVERHEAD_PCT``. A disabled ``update()`` call
  actually crosses two flag-class checks and *zero* spans
  (``metric.py``'s wrapper guards everything — including the
  ``record_complete`` call — behind one ``_observe.ENABLED`` read); the
  charge of :data:`SPANS_PER_STEP` full null spans plus
  :data:`CHECKS_PER_STEP` checks strictly overcounts it.

The *enabled*-watchdog budget (DESIGN §22) is checked the same bottom-up way:
with telemetry on and a default-interval :class:`~metrics_tpu.observe.watchdog.
Watchdog` installed, one ``poke_watchdog()`` per step is the entire hot-path
charge — the rate limiter turns almost every poke into a monotonic-clock read,
and a full ``sample()`` runs at most once per ``min_interval_s``. The check
charges one poke per step *plus* the amortized share of a real sample
(``sample_s * step_s / min_interval_s``) and requires the total under
:data:`MAX_OVERHEAD_PCT` of the same 1k-step loop.

The *enabled*-meter budget (DESIGN §23) follows too: with a
:class:`~metrics_tpu.observe.metering.FleetMeter` installed, a step rides
either the bucketed path (its amortized share of one ``note_dispatch`` over
the wave, key-list build included) or the eager path (one
``note_loose_update``) — the check charges the costlier of the two, plus the
rate-limited ``poll_quota`` fast path (one clock read per tick, amortized
over the wave that tick serves) and the amortized share of one full quota
scan per ``poll_interval_s`` (the watchdog-sample discipline), and requires
the total under the same :data:`MAX_OVERHEAD_PCT`.

The verdict is an absolute threshold, not a baseline ratchet — the contract
is "disabled telemetry is free", not "no slower than last week".
``--update-baseline`` still records the measured numbers under a
``telemetry`` section of ``tools/telemetry_baseline.json`` for trend-spotting.

Runs as the ``telemetry`` pass of ``tools/lint_metrics.py --all`` (cheapest
dynamic pass: one compile + ~1k tiny steps).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

__all__ = [
    "CHECKS_PER_STEP",
    "MAX_OVERHEAD_PCT",
    "SPANS_PER_STEP",
    "main",
    "measure_disabled_costs",
    "measure_metering_costs",
    "measure_step_cost",
    "measure_watchdog_costs",
    "run_telemetry_check",
]

MAX_OVERHEAD_PCT = 2.0
# Pessimistic per-step charge: a disabled update() crosses 2 flag-class
# checks and 0 spans; 1 full null span + 2 checks overcounts it (a null span
# alone costs several checks' worth of call + context-manager machinery).
SPANS_PER_STEP = 1
CHECKS_PER_STEP = 2
_DEFAULT_BASELINE = os.path.join("tools", "telemetry_baseline.json")

_MICRO_ITERS = 20_000
_MICRO_REPEATS = 5
_LOOP_STEPS = 1000
_LOOP_REPEATS = 3
# The verdict re-measures before failing: a single scheduler hiccup during a
# microbenchmark window should not fail CI.
_VERDICT_ATTEMPTS = 3


def measure_disabled_costs(iters: int = _MICRO_ITERS, repeats: int = _MICRO_REPEATS) -> Dict[str, float]:
    """Per-call cost (seconds) of the disabled instrumentation primitives.

    Returns ``{"span_s": ..., "check_s": ...}`` — min over ``repeats`` runs of
    ``iters`` calls each, measured with telemetry disabled (asserts it is).
    """
    from metrics_tpu.observe import recorder, tracing

    if recorder.ENABLED:
        raise RuntimeError("measure_disabled_costs requires telemetry disabled")

    span = tracing.span
    record_complete = tracing.record_complete
    best_span = float("inf")
    best_check = float("inf")
    best_empty = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            pass
        best_empty = min(best_empty, (time.perf_counter() - t0) / iters)
        t0 = time.perf_counter()
        for _ in range(iters):
            with span("bench", "overhead"):
                pass
        best_span = min(best_span, (time.perf_counter() - t0) / iters)
        t0 = time.perf_counter()
        for _ in range(iters):
            record_complete("bench", "overhead", 0.0, 0.0)
        best_check = min(best_check, (time.perf_counter() - t0) / iters)
    # the loop statement itself is not instrumentation cost
    return {
        "span_s": max(0.0, best_span - best_empty),
        "check_s": max(0.0, best_check - best_empty),
    }


def measure_watchdog_costs(iters: int = 4000, repeats: int = _MICRO_REPEATS) -> Dict[str, float]:
    """Enabled-watchdog hot-path costs (seconds): the per-step poke, one sample.

    Runs inside its own enabled ``observe.scope()`` with a default-interval
    watchdog installed. ``poke_s`` is the min-over-repeats per-call cost of
    ``poke_watchdog()`` (rate-limit fast path — the charge every instrumented
    tick pays); ``sample_s`` is the mean cost of a full ``Watchdog.sample()``
    (host-twin folds + SLO evaluation), which the rate limiter amortizes over
    ``min_interval_s`` of steps.
    """
    from metrics_tpu import observe
    from metrics_tpu.observe import recorder

    with observe.scope(reset=True):
        wd = observe.Watchdog()  # default min_interval_s
        observe.install_watchdog(wd)
        try:
            poke = recorder.poke_watchdog
            poke()  # first poke eats the initial sample outside the window
            best_poke = float("inf")
            best_empty = float("inf")
            for _ in range(repeats):
                t0 = time.perf_counter()
                for _ in range(iters):
                    pass
                best_empty = min(best_empty, (time.perf_counter() - t0) / iters)
                t0 = time.perf_counter()
                for _ in range(iters):
                    poke()
                best_poke = min(best_poke, (time.perf_counter() - t0) / iters)
            n_samples = 5
            t0 = time.perf_counter()
            for _ in range(n_samples):
                wd.sample()
            sample_s = (time.perf_counter() - t0) / n_samples
        finally:
            observe.uninstall_watchdog()
    return {
        "poke_s": max(0.0, best_poke - best_empty),
        "sample_s": sample_s,
        "min_interval_s": wd.min_interval_s,
    }


def measure_metering_costs(iters: int = 4000, repeats: int = _MICRO_REPEATS, wave: int = 32) -> Dict[str, float]:
    """Enabled-meter hot-path costs (seconds) per primitive.

    Runs inside its own enabled ``observe.scope()`` with a
    :class:`~metrics_tpu.observe.metering.FleetMeter` installed.
    ``dispatch_s`` is the min-over-repeats cost of one ``note_dispatch`` for a
    ``wave``-session wave *including* the key-list build the engine pays
    (indexing the bucket's cached ``slot_skeys`` — ``per_session_s`` is the
    amortized per-session share); ``loose_s`` the per-call cost of
    ``note_loose_update``; ``poll_fast_s`` the per-call cost of the
    rate-limited ``poll_quota`` fast path (the charge every tick pays — one
    clock read) and ``poll_scan_s`` the mean cost of a full ledger scan,
    which the rate limiter amortizes over ``poll_interval_s``.
    """
    from metrics_tpu import observe

    with observe.scope(reset=True):
        mt = observe.install_meter(
            top_k=64,
            policy=observe.MeterPolicy(max_updates=1 << 60, cooldown_s=3600.0),
        )
        try:
            # the engine indexes the bucket's cached slot_skeys per wave; the
            # list build below mirrors that (slots in a wave are a subset)
            skeys = [str(i) for i in range(wave)]
            best_dispatch = float("inf")
            best_loose = float("inf")
            best_empty = float("inf")
            for _ in range(repeats):
                t0 = time.perf_counter()
                for _ in range(iters):
                    pass
                best_empty = min(best_empty, (time.perf_counter() - t0) / iters)
                t0 = time.perf_counter()
                for _ in range(iters):
                    mt.note_dispatch("bench", [skeys[i] for i in range(wave)], 1e-9)
                best_dispatch = min(best_dispatch, (time.perf_counter() - t0) / iters)
                t0 = time.perf_counter()
                for _ in range(iters):
                    mt.note_loose_update("0")
                best_loose = min(best_loose, (time.perf_counter() - t0) / iters)
            best_poll = float("inf")
            for _ in range(repeats):
                t0 = time.perf_counter()
                for _ in range(iters):
                    mt.poll_quota()  # rate-limited: the per-tick fast path
                best_poll = min(best_poll, (time.perf_counter() - t0) / iters)
            n_scans = 20
            t0 = time.perf_counter()
            for i in range(n_scans):
                mt.poll_quota(now=1e9 + i)  # distinct clocks force full scans
            poll_scan_s = (time.perf_counter() - t0) / n_scans
        finally:
            observe.uninstall_meter()
    dispatch_s = max(0.0, best_dispatch - best_empty)
    return {
        "dispatch_s": dispatch_s,
        "per_session_s": dispatch_s / wave,
        "loose_s": max(0.0, best_loose - best_empty),
        "poll_fast_s": max(0.0, best_poll - best_empty),
        "poll_scan_s": poll_scan_s,
        "poll_interval_s": mt.poll_interval_s,
        "wave": float(wave),
    }


def measure_step_cost(steps: int = _LOOP_STEPS, repeats: int = _LOOP_REPEATS) -> float:
    """Steady-state per-step seconds of a jitted 1k-step update loop.

    Runs ``MulticlassAccuracy.update`` on fixed small batches (the shape of a
    per-step training-loop metric call), warms the jit cache first, and
    returns the min-over-repeats mean step time.
    """
    import jax.numpy as jnp

    from metrics_tpu.classification.accuracy import MulticlassAccuracy

    metric = MulticlassAccuracy(num_classes=8)
    preds = jnp.arange(32) % 8
    target = (jnp.arange(32) + 1) % 8
    for _ in range(3):  # warmup: compile + cache the update executable
        metric.update(preds, target)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            metric.update(preds, target)
        best = min(best, (time.perf_counter() - t0) / steps)
    return best


def _measure() -> Dict[str, Any]:
    micro = measure_disabled_costs()
    step_s = measure_step_cost()
    budget_s = SPANS_PER_STEP * micro["span_s"] + CHECKS_PER_STEP * micro["check_s"]
    overhead_pct = 100.0 * budget_s / step_s if step_s > 0 else float("inf")
    return {
        "span_ns": round(micro["span_s"] * 1e9, 1),
        "check_ns": round(micro["check_s"] * 1e9, 1),
        "step_us": round(step_s * 1e6, 2),
        "charged_spans": SPANS_PER_STEP,
        "charged_checks": CHECKS_PER_STEP,
        "overhead_pct": round(overhead_pct, 4),
        "max_overhead_pct": MAX_OVERHEAD_PCT,
    }


def _measure_watchdog(step_s: float) -> Dict[str, Any]:
    wd = measure_watchdog_costs()
    # per-step charge: one poke (rate-limit fast path) + the amortized share
    # of one full sample per min_interval_s window of steps
    amortized_s = wd["sample_s"] * step_s / wd["min_interval_s"] if wd["min_interval_s"] > 0 else wd["sample_s"]
    budget_s = wd["poke_s"] + amortized_s
    overhead_pct = 100.0 * budget_s / step_s if step_s > 0 else float("inf")
    return {
        "poke_ns": round(wd["poke_s"] * 1e9, 1),
        "sample_us": round(wd["sample_s"] * 1e6, 2),
        "min_interval_s": wd["min_interval_s"],
        "overhead_pct": round(overhead_pct, 4),
        "max_overhead_pct": MAX_OVERHEAD_PCT,
    }


def _measure_metering(step_s: float) -> Dict[str, Any]:
    m = measure_metering_costs()
    # per-step charge: a step rides EITHER the bucketed path (its wave share
    # of one note_dispatch) OR the eager path (one note_loose_update) —
    # charge the costlier — plus the per-tick poll, itself split into the
    # rate-limited fast path (one clock read per tick, amortized over the
    # wave the tick serves) and the amortized share of one full quota scan
    # per poll_interval_s of steps
    amortized_scan_s = (
        m["poll_scan_s"] * step_s / m["poll_interval_s"]
        if m["poll_interval_s"] > 0
        else m["poll_scan_s"]
    )
    budget_s = (
        max(m["per_session_s"], m["loose_s"])
        + m["poll_fast_s"] / m["wave"]
        + amortized_scan_s
    )
    overhead_pct = 100.0 * budget_s / step_s if step_s > 0 else float("inf")
    return {
        "dispatch_us": round(m["dispatch_s"] * 1e6, 3),
        "per_session_ns": round(m["per_session_s"] * 1e9, 1),
        "loose_ns": round(m["loose_s"] * 1e9, 1),
        "poll_fast_ns": round(m["poll_fast_s"] * 1e9, 1),
        "poll_scan_us": round(m["poll_scan_s"] * 1e6, 2),
        "poll_interval_s": m["poll_interval_s"],
        "wave": int(m["wave"]),
        "overhead_pct": round(overhead_pct, 4),
        "max_overhead_pct": MAX_OVERHEAD_PCT,
    }


def run_telemetry_check(
    root: str,
    baseline_path: Optional[str] = None,
    update_baseline: bool = False,
    quiet: bool = False,
    report: Optional[Dict[str, Any]] = None,
) -> int:
    """Dynamic ``telemetry`` pass: disabled-mode + enabled-watchdog +
    enabled-meter budgets (exit 0/1)."""
    from metrics_tpu.observe import recorder

    was_enabled = recorder.ENABLED
    recorder.ENABLED = False
    try:
        measured = _measure()
        attempts = 1
        while measured["overhead_pct"] >= MAX_OVERHEAD_PCT and attempts < _VERDICT_ATTEMPTS:
            measured = _measure()  # re-measure before failing: absorb one-off jitter
            attempts += 1
    finally:
        recorder.ENABLED = was_enabled
    step_s = measured["step_us"] * 1e-6
    wd_measured = _measure_watchdog(step_s)
    wd_attempts = 1
    while wd_measured["overhead_pct"] >= MAX_OVERHEAD_PCT and wd_attempts < _VERDICT_ATTEMPTS:
        wd_measured = _measure_watchdog(step_s)
        wd_attempts += 1
    wd_measured["attempts"] = wd_attempts
    measured["attempts"] = attempts
    mt_measured = _measure_metering(step_s)
    mt_attempts = 1
    while mt_measured["overhead_pct"] >= MAX_OVERHEAD_PCT and mt_attempts < _VERDICT_ATTEMPTS:
        mt_measured = _measure_metering(step_s)
        mt_attempts += 1
    mt_measured["attempts"] = mt_attempts
    ok = (
        measured["overhead_pct"] < MAX_OVERHEAD_PCT
        and wd_measured["overhead_pct"] < MAX_OVERHEAD_PCT
        and mt_measured["overhead_pct"] < MAX_OVERHEAD_PCT
    )

    if update_baseline:
        from metrics_tpu.analysis.engine import write_baseline_section

        path = baseline_path or os.path.join(root, _DEFAULT_BASELINE)
        write_baseline_section(
            path,
            "telemetry",
            {
                "disabled_mode": measured,
                "enabled_watchdog": wd_measured,
                "enabled_metering": mt_measured,
            },
            "telemetry overhead record — disabled-mode instrumentation cost vs a "
            "1k-step update loop. Informational (the pass verdict is the absolute "
            f"{MAX_OVERHEAD_PCT}% threshold); regenerate with "
            "`python -m metrics_tpu.observe.overhead --update-baseline`.",
        )
        if not quiet:
            print(f"telemetry: baseline written to {path}")

    if report is not None:
        report["disabled_mode"] = measured
        report["enabled_watchdog"] = wd_measured
        report["enabled_metering"] = mt_measured
    if not quiet:
        verdict = "ok" if ok else "FAIL"
        print(
            f"telemetry: disabled-mode overhead {measured['overhead_pct']:.3f}% "
            f"of a {measured['step_us']:.0f}us step "
            f"(null span {measured['span_ns']:.0f}ns x{SPANS_PER_STEP}, "
            f"flag check {measured['check_ns']:.0f}ns x{CHECKS_PER_STEP}; "
            f"budget {MAX_OVERHEAD_PCT}%); "
            f"watchdog overhead {wd_measured['overhead_pct']:.3f}% "
            f"(poke {wd_measured['poke_ns']:.0f}ns, sample "
            f"{wd_measured['sample_us']:.0f}us per {wd_measured['min_interval_s']:g}s); "
            f"metering overhead {mt_measured['overhead_pct']:.3f}% "
            f"(dispatch {mt_measured['dispatch_us']:.1f}us/{mt_measured['wave']}-wave, "
            f"loose {mt_measured['loose_ns']:.0f}ns, poll "
            f"{mt_measured['poll_scan_us']:.0f}us per {mt_measured['poll_interval_s']:g}s) "
            f"— {verdict}"
        )
    return 0 if ok else 1


def main(argv: Optional[Any] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(description="Disabled-mode telemetry overhead smoke.")
    p.add_argument("--root", default=None)
    p.add_argument("--baseline", default=None)
    p.add_argument("--update-baseline", action="store_true")
    p.add_argument("-q", "--quiet", action="store_true")
    args = p.parse_args(argv)
    root = os.path.abspath(args.root or os.getcwd())
    return run_telemetry_check(
        root,
        baseline_path=args.baseline,
        update_baseline=args.update_baseline,
        quiet=args.quiet,
    )


if __name__ == "__main__":
    raise SystemExit(main())
