"""Native (C++) host helpers, loaded via ctypes with build-on-demand.

The compute path of this framework is jax/XLA/Pallas; the runtime around it
uses native code where the reference leaned on C/C++ dependencies (SURVEY
§2.9: pycocotools' codec loops). The shared library is compiled once from the
in-tree source with the system compiler and cached beside it; everything has a
pure-numpy fallback, so the package works without any toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional

__all__ = ["load_rle_codec"]

_SRC = os.path.join(os.path.dirname(__file__), "rle_codec.cpp")
_LIB = os.path.join(os.path.dirname(__file__), f"_rle_codec_{sys.platform}.so")
_lock = threading.Lock()
_lib_cache: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    # compile to a temp path and atomically replace: a killed compile (or two
    # processes racing) must never leave a truncated .so at the final path
    tmp = f"{_LIB}.build.{os.getpid()}"
    for cc in ("g++", "clang++", "c++"):
        try:
            proc = subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
                capture_output=True, timeout=120,
            )
            if proc.returncode == 0:
                os.replace(tmp, _LIB)
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
    return False


def load_rle_codec() -> Optional[ctypes.CDLL]:
    """The compiled codec library, building it on first use; None if unavailable."""
    global _lib_cache, _build_failed
    if _lib_cache is not None or _build_failed:
        return _lib_cache
    with _lock:
        if _lib_cache is not None or _build_failed:
            return _lib_cache
        stale = (
            os.path.exists(_LIB) and os.path.exists(_SRC) and os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        )
        if (not os.path.exists(_LIB) or stale) and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            # a corrupt cached .so (e.g. from an older interrupted build) —
            # rebuild once before giving up on the native path
            try:
                os.remove(_LIB)
            except OSError:
                pass
            if not _build():
                _build_failed = True
                return None
            try:
                lib = ctypes.CDLL(_LIB)
            except OSError:
                _build_failed = True
                return None
        ll = ctypes.c_longlong
        u8p = ctypes.POINTER(ctypes.c_ubyte)
        llp = ctypes.POINTER(ll)
        lib.rle_compress_counts.restype = ll
        lib.rle_compress_counts.argtypes = [llp, ll, u8p]
        lib.rle_decompress_counts.restype = ll
        lib.rle_decompress_counts.argtypes = [u8p, ll, llp]
        lib.rle_expand.restype = ctypes.c_int
        lib.rle_expand.argtypes = [llp, ll, ll, u8p]
        _lib_cache = lib
        return lib
