"""Native (C++) host helpers, loaded via ctypes with build-on-demand.

The compute path of this framework is jax/XLA/Pallas; the runtime around it
uses native code where the reference leaned on C/C++ dependencies (SURVEY
§2.9: pycocotools' codec loops). The shared library is compiled once from the
in-tree source with the system compiler and cached beside it; everything has a
pure-numpy fallback, so the package works without any toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional

__all__ = ["load_rle_codec"]

_SRC = os.path.join(os.path.dirname(__file__), "rle_codec.cpp")
_LIB = os.path.join(os.path.dirname(__file__), f"_rle_codec_{sys.platform}.so")
_lock = threading.Lock()
_lib_cache: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    for cc in ("g++", "clang++", "c++"):
        try:
            proc = subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", "-o", _LIB, _SRC],
                capture_output=True, timeout=120,
            )
            if proc.returncode == 0:
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def load_rle_codec() -> Optional[ctypes.CDLL]:
    """The compiled codec library, building it on first use; None if unavailable."""
    global _lib_cache, _build_failed
    if _lib_cache is not None or _build_failed:
        return _lib_cache
    with _lock:
        if _lib_cache is not None or _build_failed:
            return _lib_cache
        if not os.path.exists(_LIB) and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _build_failed = True
            return None
        ll = ctypes.c_longlong
        u8p = ctypes.POINTER(ctypes.c_ubyte)
        llp = ctypes.POINTER(ll)
        lib.rle_compress_counts.restype = ll
        lib.rle_compress_counts.argtypes = [llp, ll, u8p]
        lib.rle_decompress_counts.restype = ll
        lib.rle_decompress_counts.argtypes = [u8p, ll, llp]
        lib.rle_expand.restype = ctypes.c_int
        lib.rle_expand.argtypes = [llp, ll, ll, u8p]
        _lib_cache = lib
        return lib
