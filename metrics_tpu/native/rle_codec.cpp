// COCO RLE codec hot loops, C ABI for ctypes (see metrics_tpu/native/__init__.py).
//
// The reference reaches equivalent functionality through pycocotools' C
// extension (SURVEY §2.9); here the array math of the codec lives in numpy
// (already vectorized) and ONLY the genuinely loopy byte-level parts are
// native: the LEB128-style compressed-counts string codec and a batch
// run-expansion behind rle_to_mask.

#include <cstdint>
#include <cstddef>

extern "C" {

// Encode run lengths into the COCO compressed string form.
// counts[n] -> out bytes; returns number of bytes written (out must hold 13*n:
// an int64 value spans at most 13 five-bit groups).
long long rle_compress_counts(const long long* counts, long long n, unsigned char* out) {
    long long pos = 0;
    for (long long i = 0; i < n; ++i) {
        long long x = counts[i];
        if (i > 2) x -= counts[i - 2];  // delta against two back, from the third on
        bool more = true;
        while (more) {
            long long bits = x & 0x1f;
            x >>= 5;
            more = !((x == 0 && !(bits & 0x10)) || (x == -1 && (bits & 0x10)));
            if (more) bits |= 0x20;
            out[pos++] = (unsigned char)(bits + 48);
        }
    }
    return pos;
}

// Decode the compressed string form back into run lengths.
// data[len] -> counts_out; returns number of counts (counts_out must hold len),
// or -1 for a malformed value wider than 13 5-bit groups (the int64 maximum —
// anything the matching compressor can emit decodes back; shifts run in
// unsigned arithmetic so even the 13th group's overflow past bit 63 is
// well-defined wraparound, mirroring the Python fallback's masked bigints).
long long rle_decompress_counts(const unsigned char* data, long long len, long long* counts_out) {
    long long n = 0;
    long long pos = 0;
    while (pos < len) {
        unsigned long long x = 0;
        int k = 0;
        bool more = true;
        while (more && pos < len) {
            if (k >= 13) return -1;
            unsigned long long byte = (unsigned long long)data[pos] - 48;
            if (5 * k < 64) x |= (byte & 0x1f) << (5 * k);
            more = (byte & 0x20) != 0;
            ++pos;
            ++k;
            if (!more && (byte & 0x10) && 5 * k < 64) x |= ~0ULL << (5 * k);
        }
        long long v = (long long)x;
        if (n > 2) v += counts_out[n - 2];
        counts_out[n++] = v;
    }
    return n;
}

// Expand run lengths into a column-major binary plane (one mask).
// Returns 0 on success, -1 if runs do not sum to h*w.
int rle_expand(const long long* counts, long long n, long long hw, unsigned char* plane) {
    long long idx = 0;
    unsigned char val = 0;
    for (long long i = 0; i < n; ++i) {
        long long run = counts[i];
        if (idx + run > hw) return -1;
        for (long long j = 0; j < run; ++j) plane[idx++] = val;
        val = 1 - val;
    }
    return idx == hw ? 0 : -1;
}

}  // extern "C"
