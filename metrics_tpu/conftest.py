"""Doctest rig: force the CPU platform with 8 virtual devices before jax initialises.

Lets ``pytest --doctest-modules metrics_tpu/`` run every docstring example (the
reference runs doctests in CI — SURVEY §4.6) without touching the TPU.
"""

import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")
