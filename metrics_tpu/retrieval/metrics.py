"""Modular retrieval metrics over the segment-reduce engine.

Parity with reference ``torchmetrics/retrieval/``: ``average_precision.py`` (MAP),
``reciprocal_rank.py`` (MRR), ``precision.py``, ``recall.py``, ``fall_out.py``,
``hit_rate.py``, ``ndcg.py``, ``r_precision.py``, ``auroc.py``,
``precision_recall_curve.py``. Every metric is a few segment reductions over the
one lex-sorted view — no per-query loops (BASELINE config 3).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.retrieval.base import GroupedQueries, RetrievalMetric
from metrics_tpu.utils.compute import _safe_divide

__all__ = [
    "RetrievalAUROC",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRPrecision",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
]


def _check_top_k(top_k: Optional[int]) -> Optional[int]:
    if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")
    return top_k


class _TopKRetrievalMetric(RetrievalMetric):
    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation: Any = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        self.top_k = _check_top_k(top_k)

    def _k_mask(self, gq: GroupedQueries) -> Array:
        if self.top_k is None:
            return jnp.ones_like(gq.pos)
        return (gq.pos < self.top_k).astype(jnp.float32)

    def _k_per_group(self, gq: GroupedQueries) -> Array:
        if self.top_k is None:
            return gq.n_docs
        return jnp.full_like(gq.n_docs, float(self.top_k))


class RetrievalMAP(_TopKRetrievalMetric):
    """Mean Average Precision for IR (reference ``retrieval/average_precision.py:34``).

    >>> import jax.numpy as jnp
    >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
    >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
    >>> target = jnp.array([False, False, True, False, True, False, True])
    >>> rmap = RetrievalMAP()
    >>> rmap.update(preds, target, indexes=indexes)
    >>> rmap.compute()
    Array(0.7916667, dtype=float32)
    """

    def _metric_vectorized(self, gq: GroupedQueries) -> Array:
        km = self._k_mask(gq)
        prec_at_i = gq.rel_cum / (gq.pos + 1.0)
        num = gq.seg_sum(prec_at_i * gq.rel * km)
        n_rel_at_k = gq.seg_sum(gq.rel * km)
        return _safe_divide(num, n_rel_at_k)


class RetrievalMRR(_TopKRetrievalMetric):
    """Mean Reciprocal Rank for IR (reference ``retrieval/reciprocal_rank.py:34``).

    >>> import jax.numpy as jnp
    >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
    >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
    >>> target = jnp.array([False, False, True, False, True, False, True])
    >>> mrr = RetrievalMRR()
    >>> mrr.update(preds, target, indexes=indexes)
    >>> mrr.compute()
    Array(0.75, dtype=float32)
    """

    def _metric_vectorized(self, gq: GroupedQueries) -> Array:
        km = self._k_mask(gq)
        first_rel = gq.seg_min(jnp.where((gq.rel > 0) & (km > 0), gq.pos + 1.0, jnp.inf))
        return jnp.where(jnp.isfinite(first_rel), 1.0 / jnp.where(jnp.isfinite(first_rel), first_rel, 1.0), 0.0)


class RetrievalPrecision(_TopKRetrievalMetric):
    """Precision@k for IR (reference ``retrieval/precision.py:34``)."""

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, adaptive_k: bool = False, aggregation: Any = "mean",
                 **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, top_k, aggregation, **kwargs)
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.adaptive_k = adaptive_k

    def _metric_vectorized(self, gq: GroupedQueries) -> Array:
        k = self._k_per_group(gq)
        if self.adaptive_k:
            k = jnp.minimum(k, gq.n_docs)
        hits = gq.seg_sum(gq.rel * (gq.pos < k[gq.group_id]))
        return _safe_divide(hits, k)


class RetrievalRecall(_TopKRetrievalMetric):
    """Recall@k for IR (reference ``retrieval/recall.py:34``)."""

    def _metric_vectorized(self, gq: GroupedQueries) -> Array:
        hits = gq.seg_sum(gq.rel * self._k_mask(gq))
        return _safe_divide(hits, gq.n_rel)


class RetrievalFallOut(_TopKRetrievalMetric):
    """Fall-out@k for IR (reference ``retrieval/fall_out.py:34``); empty action applies to queries with no NEGATIVE docs."""

    higher_is_better = False

    def _metric_vectorized(self, gq: GroupedQueries) -> Array:
        nonrel = 1.0 - gq.rel
        n_nonrel = gq.n_docs - gq.n_rel
        hits = gq.seg_sum(nonrel * self._k_mask(gq))
        return _safe_divide(hits, n_nonrel)

    def _empty_mask(self, gq: GroupedQueries) -> Array:
        """The empty-query condition is "no NEGATIVE docs" (reference ``fall_out.py:118-139``)."""
        return (gq.n_docs - gq.n_rel) == 0

    @staticmethod
    def _empty_counts_host(n_rel, n_docs):
        return (n_docs - n_rel) == 0

    _empty_error_msg = "`compute` method was provided with a query with no negative target."


class RetrievalHitRate(_TopKRetrievalMetric):
    """Hit-rate@k for IR (reference ``retrieval/hit_rate.py:34``)."""

    def _metric_vectorized(self, gq: GroupedQueries) -> Array:
        hits = gq.seg_sum(gq.rel * self._k_mask(gq))
        return (hits > 0).astype(jnp.float32)


class RetrievalRPrecision(RetrievalMetric):
    """R-precision for IR (reference ``retrieval/r_precision.py:32``)."""

    def _metric_vectorized(self, gq: GroupedQueries) -> Array:
        hits = gq.seg_sum(gq.rel * (gq.pos < gq.n_rel[gq.group_id]))
        return _safe_divide(hits, gq.n_rel)


class RetrievalNormalizedDCG(_TopKRetrievalMetric):
    """NDCG@k for IR with graded relevance (reference ``retrieval/ndcg.py:34``)."""

    _uses_ideal_order = True  # IDCG needs the lazy target-desc sort materialized

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation: Any = "mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, top_k, aggregation, **kwargs)
        self.allow_non_binary_target = True

    def _metric_vectorized(self, gq: GroupedQueries) -> Array:
        km = self._k_mask(gq)
        discount = 1.0 / jnp.log2(gq.pos + 2.0)
        dcg = gq.seg_sum(gq.graded * discount * km)
        idcg = gq.seg_sum(gq.ideal_graded * discount * km)
        return _safe_divide(dcg, idcg)


class RetrievalAUROC(_TopKRetrievalMetric):
    """AUROC per query for IR (reference ``retrieval/auroc.py:34``).

    The per-query AUROC is the rank U-statistic computed with segment sums — for
    each relevant doc, credit the fraction of negative docs ranked below it (ties
    on prediction value get half credit, matching the trapezoidal ROC).
    """

    def _metric_vectorized(self, gq: GroupedQueries) -> Array:
        km = self._k_mask(gq)
        rel = gq.rel * km
        nonrel = (1.0 - gq.rel) * km
        g = gq.group_id
        pred = gq.preds
        n = pred.shape[0]
        # tie runs: consecutive rows (already sorted by (group, -pred)) with equal pred
        new_run = jnp.concatenate([jnp.ones(1, bool), (g[1:] != g[:-1]) | (pred[1:] != pred[:-1])]) if n else jnp.zeros(0, bool)
        run_id = jnp.cumsum(new_run) - 1
        nonrel_in_run = jax.ops.segment_sum(nonrel, run_id, n)
        # exclusive cumulative nonrel; its minimum over a segment = value at the segment start
        ex_cum = jnp.cumsum(nonrel) - nonrel
        run_start_val = jax.ops.segment_min(ex_cum, run_id, n)
        group_start_val = jax.ops.segment_min(ex_cum, g, n)
        strictly_above = run_start_val[run_id] - group_start_val[g]

        n_rel = jax.ops.segment_sum(rel, g, gq.num_groups)
        n_nonrel = jax.ops.segment_sum(nonrel, g, gq.num_groups)
        # U-statistic with half credit for prediction ties (trapezoidal ROC):
        # credit = strictly-below + 0.5 · tied = n_nonrel − strictly_above − 0.5 · tied
        per_row_credit = n_nonrel[g] - strictly_above - 0.5 * nonrel_in_run[run_id]
        u = jax.ops.segment_sum(jnp.where(rel > 0, per_row_credit, 0.0), g, gq.num_groups)
        return _safe_divide(u.astype(jnp.float32), (n_rel * n_nonrel).astype(jnp.float32))


class RetrievalPrecisionRecallCurve(RetrievalMetric):
    """Precision/recall at k=1..max_k averaged over queries (reference ``retrieval/precision_recall_curve.py:40``)."""

    def __init__(self, max_k: Optional[int] = None, adaptive_k: bool = False,
                 empty_target_action: str = "neg", ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, "mean", **kwargs)
        if max_k is not None and not (isinstance(max_k, int) and max_k > 0):
            raise ValueError("`max_k` has to be a positive integer or None")
        self.max_k = max_k
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.adaptive_k = adaptive_k

    def _metric_vectorized(self, gq: GroupedQueries) -> Array:  # pragma: no cover - unused
        raise NotImplementedError

    def compute(self) -> Tuple[Array, Array, Array]:
        """Average precision/recall over queries at each k."""
        from metrics_tpu.utils.data import dim_zero_cat

        from metrics_tpu.retrieval.base import shared_grouped_view

        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        gq = shared_grouped_view(indexes, preds, target, self._state_anchors())
        max_k = self.max_k or int(jnp.max(gq.n_docs))
        ks = jnp.arange(1, max_k + 1, dtype=jnp.float32)
        # hits@k per group: (G, K) via segment sums of rank masks
        masks = gq.pos[None, :] < ks[:, None]  # (K, N)
        rel_hits = jax.vmap(gq.seg_sum)(gq.rel[None, :] * masks)  # (K, G)
        k_eff = jnp.minimum(ks[:, None], gq.n_docs[None, :]) if self.adaptive_k else ks[:, None]
        precision_kg = _safe_divide(rel_hits, k_eff)
        recall_kg = _safe_divide(rel_hits, gq.n_rel[None, :])
        valid = gq.n_docs > 0  # mask out the static-bound padding groups
        empty = (gq.n_rel == 0) & valid
        if self.empty_target_action == "error" and bool(empty.any()):
            raise ValueError("`compute` method was provided with a query with no positive target.")
        if self.empty_target_action == "pos":
            precision_kg = jnp.where(empty[None, :], 1.0, precision_kg)
            recall_kg = jnp.where(empty[None, :], 1.0, recall_kg)
        elif self.empty_target_action == "neg":
            precision_kg = jnp.where(empty[None, :], 0.0, precision_kg)
            recall_kg = jnp.where(empty[None, :], 0.0, recall_kg)
        else:  # skip: masked mean instead of boolean indexing
            valid = valid & ~empty
        denom = jnp.maximum(valid.sum(), 1)
        precision_k = (precision_kg * valid[None, :]).sum(axis=1) / denom
        recall_k = (recall_kg * valid[None, :]).sum(axis=1) / denom
        return precision_k, recall_k, jnp.arange(1, max_k + 1)

    def plot(self, curve: Optional[Tuple[Array, Array, Array]] = None, ax: Any = None):
        """Plot the retrieval precision-recall curve (reference ``retrieval/precision_recall_curve.py:257-293``).

        Recall runs along x and precision along y — the standard PR presentation
        (the reference passes ROC axis labels here, an upstream labeling slip we
        do not reproduce).
        """
        from metrics_tpu.utils.plot import plot_curve

        computed = curve if curve is not None else self.compute()
        curve_xy = (computed[1], computed[0]) + tuple(computed[2:])
        return plot_curve(curve_xy, ax=ax, label_names=("Recall", "Precision"), name=self.__class__.__name__)


class RetrievalRecallAtFixedPrecision(RetrievalPrecisionRecallCurve):
    """Highest recall@k with precision@k ≥ min_precision (reference ``retrieval/recall_fixed_precision.py:40``)."""

    def __init__(self, min_precision: float = 0.0, max_k: Optional[int] = None, adaptive_k: bool = False,
                 empty_target_action: str = "neg", ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(max_k, adaptive_k, empty_target_action, ignore_index, **kwargs)
        if not (isinstance(min_precision, float) and 0.0 <= min_precision <= 1.0):
            raise ValueError("`min_precision` has to be a float value between 0 and 1")
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:
        """Best (recall, k) under the precision constraint."""
        import numpy as np

        precision, recall, ks = super(RetrievalRecallAtFixedPrecision, self).compute()
        p, r, k = np.asarray(precision), np.asarray(recall), np.asarray(ks)
        ok = p >= self.min_precision
        if not ok.any():
            return jnp.asarray(0.0), jnp.asarray(int(k[-1]))
        best = int(np.argmax(np.where(ok, r, -1.0)))
        return jnp.asarray(r[best], dtype=jnp.float32), jnp.asarray(int(k[best]))

    def plot(self, val: Any = None, ax: Any = None):
        """Generic value plot of the best recall (reference ``precision_recall_curve.py:297,390-393``)."""
        val = val if val is not None else self.compute()[0]
        return Metric.plot(self, val, ax)
