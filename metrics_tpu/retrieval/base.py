"""Retrieval metric base — segment-reduce engine.

Capability parity with reference ``torchmetrics/retrieval/base.py:43-191``
(``RetrievalMetric``: list states ``indexes/preds/target`` with
``dist_reduce_fx=None`` i.e. gather-without-reduction; ``empty_target_action``
∈ {error, skip, neg, pos}; aggregation mean/median/min/max).

TPU redesign (SURVEY §2.7 / BASELINE config 3): the reference's compute sorts by
query id, splits into per-query Python chunks and loops ``_metric()`` over them
(``base.py:148-191``) — the hot anti-pattern. Here compute lex-sorts ONCE by
(query, -pred) and every metric is a handful of ``segment_sum``-style reductions
over the flat sorted arrays; there is no per-query loop anywhere.
"""

from __future__ import annotations

import functools
from abc import abstractmethod
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.utils.checks import _check_retrieval_inputs
from metrics_tpu.utils.data import dim_zero_cat

# Jitted compute_flat programs, keyed by (class, static-config) with pristine
# clone representatives — config-equal instances share one compilation and no
# live metric is ever pinned by the cache.
_JITTED_COMPUTE: Dict[Any, Any] = {}


def _retrieval_aggregate(values: Array, aggregation: str = "mean", mask: Optional[Array] = None) -> Array:
    """Masked aggregation of per-query scores (reference ``base.py:26-40``).

    ``mask`` marks valid groups; invalid entries never contribute (jit-safe
    replacement for boolean indexing).
    """
    if mask is None:
        mask = jnp.ones(values.shape, bool)
    count = mask.sum()
    if aggregation == "mean":
        return jnp.where(count > 0, (jnp.where(mask, values, 0.0)).sum() / jnp.maximum(count, 1), 0.0)
    if aggregation == "median":
        # torch.median semantics (reference ``base.py:34``): for an even count
        # the LOWER of the two middle values, not their average — sort the
        # valid entries to the front and index (count-1)//2 directly
        filled = jnp.sort(jnp.where(mask, values, jnp.inf))
        med = filled[jnp.maximum(count - 1, 0) // 2]
        return jnp.where(count > 0, med, 0.0)
    if aggregation == "min":
        return jnp.where(count > 0, jnp.where(mask, values, jnp.inf).min(), 0.0)
    if aggregation == "max":
        return jnp.where(count > 0, jnp.where(mask, values, -jnp.inf).max(), 0.0)
    # custom callable: host semantics (not jittable — see compute_flat's docstring)
    if isinstance(values, jax.core.Tracer) or isinstance(mask, jax.core.Tracer):
        raise TypeError(
            "A callable `aggregation` runs host-side and cannot be traced under jit;"
            " evaluate eagerly (Metric.compute) or use a string aggregation."
        )
    return aggregation(values[np.asarray(mask)])


def _device_order(indexes: Array, values: Array) -> Array:
    """On-device stable argsort by (query asc, value desc) in ONE sort pass.

    ``jnp.lexsort`` would run one stable sort per key (two passes over HBM);
    XLA's variadic sort compares all key operands in a single fused pass, so we
    hand ``lax.sort`` the pair (query, -value) as keys and ride an iota operand
    out as the permutation. NaN values rank last within their query (the float
    total order puts NaN after +inf), matching the host path.
    """
    n = indexes.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    neg = -values.astype(jnp.float32)
    _, _, perm = jax.lax.sort((indexes.astype(jnp.int32), neg, iota), num_keys=2, is_stable=True)
    return perm


def _order_by_query_desc(indexes: Array, values: Array) -> Array:
    """Stable argsort by (query asc, value desc) — the grouping sort.

    XLA's CPU sort is the dominant cost of retrieval compute (~60× slower than
    numpy's introsort for 400k keys on this class of host), so on the ``cpu``
    backend the argsort runs host-side through ``pure_callback`` on a single
    64-bit composite key (query id in the high 32 bits, descending-sortable IEEE
    bits of the value in the low 32). On accelerators the single-pass fused
    device sort (:func:`_device_order`) is used: the device→host transfer would
    cost more than the sort, and the composite trick needs 64-bit integers that
    jax disables by default. Set ``METRICS_TPU_FORCE_DEVICE_SORT=1`` to force
    the device path on any backend — the bench uses this to time the
    deployment (TPU) sort path explicitly on the CPU rig.
    """
    import os

    n = indexes.shape[0]
    force_device = os.environ.get("METRICS_TPU_FORCE_DEVICE_SORT", "") == "1"
    if jax.default_backend() != "cpu" or n == 0 or force_device:
        return _device_order(indexes, values)

    def _host(idx, vals):
        v = np.ascontiguousarray(np.asarray(vals, dtype=np.float32))
        v = np.where(v == 0.0, np.float32(0.0), v)  # collapse -0.0 with +0.0 (comparison semantics)
        bits = v.view(np.uint32)
        asc = np.where(bits >> 31 == 0, bits | np.uint32(0x80000000), ~bits)  # ascending-sortable IEEE key
        asc = np.where(np.isnan(v), np.uint32(0), asc)  # NaN ranks last in DESC order, like jnp.lexsort
        key = (np.asarray(idx).astype(np.uint64) << np.uint64(32)) | (~asc).astype(np.uint64)
        return np.argsort(key, kind="stable").astype(np.int32)

    return jax.pure_callback(
        _host, jax.ShapeDtypeStruct((n,), jnp.int32), indexes, values, vmap_method="sequential"
    )


@functools.partial(jax.jit, static_argnames=("num_groups",))
def _view_tail(idx_sorted: Array, preds_sorted: Array, graded: Array, num_groups: int):
    """Everything after the grouping sort, fused into one XLA program."""
    n = idx_sorted.shape[0]
    new_group = (
        jnp.concatenate([jnp.ones(1, bool), idx_sorted[1:] != idx_sorted[:-1]])
        if n
        else jnp.zeros(0, bool)
    )
    g = jnp.cumsum(new_group) - 1
    rel = (graded > 0).astype(jnp.float32)
    ones = jnp.ones(n, dtype=jnp.float32)
    n_docs = jax.ops.segment_sum(ones, g, num_groups)
    n_rel = jax.ops.segment_sum(rel, g, num_groups)
    starts = jnp.concatenate([jnp.zeros(1), jnp.cumsum(n_docs)[:-1]]) if n else jnp.zeros(0)
    pos = jnp.arange(n, dtype=jnp.float32) - starts[g]
    # cumulative relevant within group, inclusive of current position
    cum = jnp.cumsum(rel)
    offset = jnp.concatenate([jnp.zeros(1), n_rel.cumsum()[:-1]]) if n else jnp.zeros(0)
    rel_cum = cum - offset[g]
    return g, preds_sorted, rel, n_docs, n_rel, pos, rel_cum


class GroupedQueries:
    """Flat sorted view over all queries + the segment quantities every metric needs.

    SURVEY §2.7: ONE argsort by (query, -pred) (see :func:`_order_by_query_desc`),
    group ids compacted by neighbor comparison on the sorted keys, and every
    per-query quantity a ``segment_sum``-style reduction. ``num_groups`` is the
    static upper bound ``n`` (padding groups have ``n_docs == 0`` and are masked
    out), so the whole view — and every metric built on it — traces under ``jit``.

    Fields: ``rel`` (binary), ``graded`` (raw target), ``group_id``, ``pos``
    (0-based rank within query), ``n_rel``/``n_docs`` per group, and the
    ideal-order graded targets for NDCG.
    """

    def __init__(self, indexes: Array, preds: Array, target: Array):
        indexes = jnp.asarray(indexes)
        preds = jnp.asarray(preds)
        target = jnp.asarray(target)
        n = int(preds.shape[0])
        order = _order_by_query_desc(indexes, preds)
        self.order = order
        idx_sorted = indexes[order]
        if isinstance(idx_sorted, jax.core.Tracer):
            # under jit the group count is dynamic → static upper bound n; padding
            # groups have n_docs == 0 and are masked out of every aggregation
            self.num_groups = n
        else:
            # eager: one cheap host sync buys segment arrays sized to the TRUE
            # group count instead of n (often 100× smaller). Bucketed up to the
            # next power of two so datasets sharing a flat length n but varying
            # in query count (fixed eval batch, variable #queries) reuse compiled
            # _view_tail programs instead of one per distinct count. (When n
            # itself varies, each n recompiles regardless — the bucketing then
            # only costs ≤2× on the small segment arrays.) The extra groups have
            # n_docs == 0 and every aggregation masks them out.
            idx_np = np.asarray(idx_sorted)
            true_groups = (int((idx_np[1:] != idx_np[:-1]).sum()) + 1) if n else 0
            self.num_groups = 1 << (true_groups - 1).bit_length() if true_groups else 0
        self.graded = target[order].astype(jnp.float32)
        # post-sort tail as ONE fused program: eagerly this collapses ~10
        # dispatch round-trips (cumsums/gathers/segment sums) into one call,
        # inside jit it inlines — same trace either way
        (self.group_id, self.preds, self.rel, self.n_docs, self.n_rel, self.pos,
         self.rel_cum) = _view_tail(idx_sorted, preds[order], self.graded, self.num_groups)
        # ideal ordering (target desc within group) — ONLY NDCG consumes it, and
        # it costs a second full sort, so it materializes lazily on first access
        self._ideal_inputs = (indexes, target)
        self._ideal_graded: Optional[Array] = None

    @property
    def ideal_graded(self) -> Array:
        """Graded targets in ideal (target-desc within group) order — lazy."""
        if self._ideal_graded is None:
            if self._ideal_inputs is None:
                raise AttributeError(
                    "ideal_graded was not materialized before as_tree(); the owning "
                    "metric must declare `_uses_ideal_order = True`."
                )
            indexes, target = self._ideal_inputs
            ideal_order = _order_by_query_desc(indexes, target.astype(jnp.float32))
            self._ideal_graded = target[ideal_order].astype(jnp.float32)
        return self._ideal_graded

    def seg_sum(self, x: Array) -> Array:
        return jax.ops.segment_sum(x, self.group_id, self.num_groups)

    def seg_min(self, x: Array) -> Array:
        return jax.ops.segment_min(x, self.group_id, self.num_groups)

    def seg_max(self, x: Array) -> Array:
        return jax.ops.segment_max(x, self.group_id, self.num_groups)

    _TREE_FIELDS = (
        "order", "group_id", "preds", "graded", "rel", "n_docs", "n_rel", "pos", "rel_cum"
    )

    def as_tree(self, include_ideal: bool = False) -> Dict[str, Array]:
        """The view as a flat dict of arrays — the jit-crossable form.

        ``ideal_graded`` rides along only when the CALLER asks for it (NDCG via
        ``_uses_ideal_order``) — keyed on caller intent, not on whether a
        group-mate happened to materialize it, so a shared view never flips the
        pytree structure (and the jit cache) of metrics that don't use it.
        """
        tree = {k: getattr(self, k) for k in self._TREE_FIELDS}
        if include_ideal:
            tree["ideal_graded"] = self.ideal_graded
        return tree

    @classmethod
    def from_tree(cls, tree: Dict[str, Array]) -> "GroupedQueries":
        """Rebuild a view from :meth:`as_tree` arrays without re-sorting."""
        gq = cls.__new__(cls)
        for k in cls._TREE_FIELDS:
            setattr(gq, k, tree[k])
        gq.num_groups = tree["n_docs"].shape[0]
        gq._ideal_inputs = None
        gq._ideal_graded = tree.get("ideal_graded")
        return gq


# Sorted views shared across group-mate metrics, keyed by the identity of the
# stored state arrays (MetricCollection compute groups alias the SAME list
# objects across e.g. RetrievalMAP and RetrievalMRR, so the second metric's
# compute reuses the first's sort; the reference re-sorts per metric,
# ``base.py:148-191``). Anchors are held by WEAK reference: once the owning
# metric resets or is freed, the entry dies with its states instead of pinning
# up to several datasets' worth of sorted copies. A live weakref also makes
# id-reuse false hits impossible — ref() returning an object proves identity.
_VIEW_CACHE: Dict[Any, Any] = {}


def shared_grouped_view(indexes: Array, preds: Array, target: Array, anchors: Any) -> GroupedQueries:
    import weakref

    for k in [k for k, (refs, _) in _VIEW_CACHE.items() if any(r() is None for r in refs)]:
        _VIEW_CACHE.pop(k)
    key = tuple(map(id, anchors))
    hit = _VIEW_CACHE.get(key)
    if hit is not None:
        live = [r() for r in hit[0]]
        if len(live) == len(anchors) and all(a is b for a, b in zip(live, anchors)):
            _VIEW_CACHE[key] = _VIEW_CACHE.pop(key)  # LRU: reinsert so rotation over >4 views still hits
            return hit[1]
    gq = GroupedQueries(indexes, preds, target)
    try:
        refs = tuple(weakref.ref(a) for a in anchors)
    except TypeError:  # un-weakref-able anchor: serve the view uncached
        return gq
    _VIEW_CACHE[key] = (refs, gq)
    while len(_VIEW_CACHE) > 4:
        _VIEW_CACHE.pop(next(iter(_VIEW_CACHE)))
    return gq


class RetrievalMetric(Metric):
    """Base class for retrieval metrics (reference ``retrieval/base.py:43``).

    Subclasses implement :meth:`_metric_vectorized` returning one score per query
    from the :class:`GroupedQueries` view.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    # metrics consuming gq.ideal_graded (NDCG) set this so the lazy second sort
    # materializes BEFORE the view crosses into the jitted compute as a tree
    _uses_ideal_order = False

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        aggregation: Any = "mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False
        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index
        if not (aggregation in ("mean", "median", "min", "max") or callable(aggregation)):
            raise ValueError(
                "Argument `aggregation` must be one of `mean`, `median`, `min`, `max` or a custom callable function"
                f"which takes tensor of values, but got {aggregation}."
            )
        self.aggregation = aggregation

        self.add_state("indexes", [], dist_reduce_fx=None)
        self.add_state("preds", [], dist_reduce_fx=None)
        self.add_state("target", [], dist_reduce_fx=None)

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        """Check shape, flatten, check and store the inputs (reference ``base.py:135-146``)."""
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target, allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    _empty_error_msg = "`compute` method was provided with a query with no positive target."

    def _state_anchors(self) -> tuple:
        """The identity key for :func:`shared_grouped_view` — single-sourced so every
        compute path shares one view per state tuple."""
        return tuple(self.indexes) + tuple(self.preds) + tuple(self.target)

    def _empty_mask(self, gq: GroupedQueries) -> Array:
        """Which (valid) groups count as "empty" for ``empty_target_action``."""
        return gq.n_rel == 0

    def compute(self) -> Array:
        """Group by query with ONE lex-sort, score every query via segment reductions (no loops)."""
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        if self.empty_target_action == "error" and preds.shape[0]:
            # data-dependent raise: eager-only, via cheap host bincounts (no need to
            # build the full sorted GroupedQueries view twice per compute)
            idx_np = np.asarray(indexes)
            _, compact = np.unique(idx_np, return_inverse=True)
            n_rel = np.bincount(compact, weights=np.asarray(target) > 0)
            if bool((self._empty_counts_host(n_rel, np.bincount(compact))).any()):
                raise ValueError(self._empty_error_msg)
        if preds.shape[0] == 0:
            return jnp.asarray(0.0)
        # The sort-and-group view is built EAGERLY once per unique state tuple
        # (true group count → small segment arrays) and shared across group-mate
        # metrics; only the cheap scoring+aggregation runs as a per-class jitted
        # program. Keyed by static config with a pristine-clone representative
        # (same economics as Metric._lookup_shared_jit) so live instances — and
        # their accumulated list states — are never pinned by the cache.
        gq = shared_grouped_view(indexes, preds, target, self._state_anchors())
        if callable(self.aggregation) and not isinstance(self.aggregation, str):
            return self._score_groups(gq)  # host-side aggregation — eager
        key = self._jit_cache_key()
        if key is None:
            return self._score_groups(gq)
        jitted = _JITTED_COMPUTE.get(key)
        if jitted is None:
            from metrics_tpu.metric import _named_for_profiler

            rep = self.clone()
            rep.reset()
            jitted = jax.jit(_named_for_profiler(
                lambda tree: rep._score_groups(GroupedQueries.from_tree(tree)),
                f"{type(self).__name__}_compute",
            ))
            _JITTED_COMPUTE[key] = jitted
            if len(_JITTED_COMPUTE) > 128:
                _JITTED_COMPUTE.pop(next(iter(_JITTED_COMPUTE)))
        return jitted(gq.as_tree(include_ideal=self._uses_ideal_order))

    @staticmethod
    def _empty_counts_host(n_rel: "np.ndarray", n_docs: "np.ndarray") -> "np.ndarray":
        """Host-side form of :meth:`_empty_mask` for the eager error check."""
        return n_rel == 0

    def compute_flat(self, preds: Array, target: Array, indexes: Array) -> Array:
        """Pure, fully jittable evaluation over flat arrays — embed this in a jitted
        eval step to run grouping, scoring and aggregation as ONE XLA program.

        ``empty_target_action="error"`` is treated as "neg" here (a data-dependent
        raise cannot trace); the eager :meth:`compute` performs the raise. A
        CALLABLE ``aggregation`` is host-side and not jittable — only the string
        aggregations trace; call this eagerly (or use :meth:`compute`) otherwise.
        """
        if preds.shape[0] == 0:
            return jnp.asarray(0.0)
        return self._score_groups(GroupedQueries(indexes, preds, target))

    def _score_groups(self, gq: GroupedQueries) -> Array:
        """Score every group and aggregate — the post-sort tail of the evaluation."""
        scores = self._metric_vectorized(gq)  # (num_groups,) under the static bound
        valid = gq.n_docs > 0
        empty = self._empty_mask(gq) & valid
        if self.empty_target_action == "pos":
            scores = jnp.where(empty, 1.0, scores)
        elif self.empty_target_action == "neg" or self.empty_target_action == "error":
            scores = jnp.where(empty, 0.0, scores)
        else:  # skip: masked aggregation instead of boolean indexing
            valid = valid & ~empty
        return _retrieval_aggregate(scores, self.aggregation, valid)

    @abstractmethod
    def _metric_vectorized(self, gq: GroupedQueries) -> Array:
        """Return one score per query group."""
