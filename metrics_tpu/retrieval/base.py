"""Retrieval metric base — segment-reduce engine.

Capability parity with reference ``torchmetrics/retrieval/base.py:43-191``
(``RetrievalMetric``: list states ``indexes/preds/target`` with
``dist_reduce_fx=None`` i.e. gather-without-reduction; ``empty_target_action``
∈ {error, skip, neg, pos}; aggregation mean/median/min/max).

TPU redesign (SURVEY §2.7 / BASELINE config 3): the reference's compute sorts by
query id, splits into per-query Python chunks and loops ``_metric()`` over them
(``base.py:148-191``) — the hot anti-pattern. Here compute lex-sorts ONCE by
(query, -pred) and every metric is a handful of ``segment_sum``-style reductions
over the flat sorted arrays; there is no per-query loop anywhere.
"""

from __future__ import annotations

from abc import abstractmethod
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.utils.checks import _check_retrieval_inputs
from metrics_tpu.utils.data import dim_zero_cat


def _retrieval_aggregate(values: Array, aggregation: str = "mean") -> Array:
    """Aggregate per-query scores (reference ``base.py:26-40``)."""
    if aggregation == "mean":
        return values.mean() if values.size else jnp.asarray(0.0)
    if aggregation == "median":
        return jnp.median(values) if values.size else jnp.asarray(0.0)
    if aggregation == "min":
        return values.min() if values.size else jnp.asarray(0.0)
    if aggregation == "max":
        return values.max() if values.size else jnp.asarray(0.0)
    return aggregation(values)  # custom callable


class GroupedQueries:
    """Flat sorted view over all queries + the segment quantities every metric needs.

    ``sorted by (query, -pred)``: ``rel`` (binary), ``graded`` (raw target),
    ``group_id``, ``pos`` (0-based rank within query), ``n_rel``/``n_docs`` per
    query, and the ideal-order graded targets for NDCG.
    """

    def __init__(self, indexes: Array, preds: Array, target: Array):
        idx_np = np.asarray(indexes)
        preds_np = np.asarray(preds, dtype=np.float64)
        # compact the (arbitrary) query ids to 0..G-1
        _, compact = np.unique(idx_np, return_inverse=True)
        order = np.lexsort((-preds_np, compact))
        self.order = jnp.asarray(order)
        self.group_id = jnp.asarray(compact[order])
        self.num_groups = int(compact.max()) + 1 if compact.size else 0
        self.preds = jnp.asarray(preds)[self.order]
        self.graded = jnp.asarray(target)[self.order].astype(jnp.float32)
        self.rel = (self.graded > 0).astype(jnp.float32)

        n = self.rel.shape[0]
        g = self.group_id
        ones = jnp.ones(n, dtype=jnp.float32)
        self.n_docs = jax.ops.segment_sum(ones, g, self.num_groups)
        self.n_rel = jax.ops.segment_sum(self.rel, g, self.num_groups)
        starts = jnp.concatenate([jnp.zeros(1), jnp.cumsum(self.n_docs)[:-1]])
        self.pos = jnp.arange(n, dtype=jnp.float32) - starts[g]
        # cumulative relevant within group, inclusive of current position
        cum = jnp.cumsum(self.rel)
        offset = jnp.concatenate([jnp.zeros(1), self.n_rel.cumsum()[:-1]])
        self.rel_cum = cum - offset[g]
        # ideal ordering (target desc within group) for NDCG
        ideal_order = np.lexsort((-np.asarray(target, dtype=np.float64), compact))
        self.ideal_graded = jnp.asarray(target)[jnp.asarray(ideal_order)].astype(jnp.float32)

    def seg_sum(self, x: Array) -> Array:
        return jax.ops.segment_sum(x, self.group_id, self.num_groups)

    def seg_min(self, x: Array) -> Array:
        return jax.ops.segment_min(x, self.group_id, self.num_groups)

    def seg_max(self, x: Array) -> Array:
        return jax.ops.segment_max(x, self.group_id, self.num_groups)


class RetrievalMetric(Metric):
    """Base class for retrieval metrics (reference ``retrieval/base.py:43``).

    Subclasses implement :meth:`_metric_vectorized` returning one score per query
    from the :class:`GroupedQueries` view.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        aggregation: Any = "mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False
        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index
        if not (aggregation in ("mean", "median", "min", "max") or callable(aggregation)):
            raise ValueError(
                "Argument `aggregation` must be one of `mean`, `median`, `min`, `max` or a custom callable function"
                f"which takes tensor of values, but got {aggregation}."
            )
        self.aggregation = aggregation

        self.add_state("indexes", [], dist_reduce_fx=None)
        self.add_state("preds", [], dist_reduce_fx=None)
        self.add_state("target", [], dist_reduce_fx=None)

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        """Check shape, flatten, check and store the inputs (reference ``base.py:135-146``)."""
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target, allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        """Group by query with ONE lex-sort, score every query via segment reductions (no loops)."""
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        gq = GroupedQueries(indexes, preds, target)
        scores = self._metric_vectorized(gq)  # (num_groups,)

        empty = gq.n_rel == 0
        if self.empty_target_action == "error":
            if bool(empty.any()):
                raise ValueError("`compute` method was provided with a query with no positive target.")
        elif self.empty_target_action == "pos":
            scores = jnp.where(empty, 1.0, scores)
        elif self.empty_target_action == "neg":
            scores = jnp.where(empty, 0.0, scores)
        else:  # skip
            import numpy as _np

            keep = ~_np.asarray(empty)
            scores = scores[keep]
        return _retrieval_aggregate(scores, self.aggregation)

    @abstractmethod
    def _metric_vectorized(self, gq: GroupedQueries) -> Array:
        """Return one score per query group."""
