"""Retrieval metric base — segment-reduce engine.

Capability parity with reference ``torchmetrics/retrieval/base.py:43-191``
(``RetrievalMetric``: list states ``indexes/preds/target`` with
``dist_reduce_fx=None`` i.e. gather-without-reduction; ``empty_target_action``
∈ {error, skip, neg, pos}; aggregation mean/median/min/max).

TPU redesign (SURVEY §2.7 / BASELINE config 3): the reference's compute sorts by
query id, splits into per-query Python chunks and loops ``_metric()`` over them
(``base.py:148-191``) — the hot anti-pattern. Here compute lex-sorts ONCE by
(query, -pred) and every metric is a handful of ``segment_sum``-style reductions
over the flat sorted arrays; there is no per-query loop anywhere.
"""

from __future__ import annotations

from abc import abstractmethod
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.utils.checks import _check_retrieval_inputs
from metrics_tpu.utils.data import dim_zero_cat

# Jitted compute_flat programs, keyed by (class, static-config) with pristine
# clone representatives — config-equal instances share one compilation and no
# live metric is ever pinned by the cache.
_JITTED_COMPUTE: Dict[Any, Any] = {}


def _retrieval_aggregate(values: Array, aggregation: str = "mean", mask: Optional[Array] = None) -> Array:
    """Masked aggregation of per-query scores (reference ``base.py:26-40``).

    ``mask`` marks valid groups; invalid entries never contribute (jit-safe
    replacement for boolean indexing).
    """
    if mask is None:
        mask = jnp.ones(values.shape, bool)
    count = mask.sum()
    if aggregation == "mean":
        return jnp.where(count > 0, (jnp.where(mask, values, 0.0)).sum() / jnp.maximum(count, 1), 0.0)
    if aggregation == "median":
        # torch.median semantics (reference ``base.py:34``): for an even count
        # the LOWER of the two middle values, not their average — sort the
        # valid entries to the front and index (count-1)//2 directly
        filled = jnp.sort(jnp.where(mask, values, jnp.inf))
        med = filled[jnp.maximum(count - 1, 0) // 2]
        return jnp.where(count > 0, med, 0.0)
    if aggregation == "min":
        return jnp.where(count > 0, jnp.where(mask, values, jnp.inf).min(), 0.0)
    if aggregation == "max":
        return jnp.where(count > 0, jnp.where(mask, values, -jnp.inf).max(), 0.0)
    # custom callable: host semantics (not jittable — see compute_flat's docstring)
    if isinstance(values, jax.core.Tracer) or isinstance(mask, jax.core.Tracer):
        raise TypeError(
            "A callable `aggregation` runs host-side and cannot be traced under jit;"
            " evaluate eagerly (Metric.compute) or use a string aggregation."
        )
    return aggregation(values[np.asarray(mask)])


class GroupedQueries:
    """Flat sorted view over all queries + the segment quantities every metric needs.

    Fully on-device (SURVEY §2.7): ONE ``jnp.lexsort`` by (query, -pred), group
    ids compacted by neighbor comparison on the sorted keys, and every per-query
    quantity a ``segment_sum``-style reduction. ``num_groups`` is the static
    upper bound ``n`` (padding groups have ``n_docs == 0`` and are masked out),
    so the whole view — and every metric built on it — traces under ``jit``.

    Fields: ``rel`` (binary), ``graded`` (raw target), ``group_id``, ``pos``
    (0-based rank within query), ``n_rel``/``n_docs`` per group, and the
    ideal-order graded targets for NDCG.
    """

    def __init__(self, indexes: Array, preds: Array, target: Array):
        indexes = jnp.asarray(indexes)
        preds = jnp.asarray(preds)
        target = jnp.asarray(target)
        n = int(preds.shape[0])
        order = jnp.lexsort((-preds.astype(jnp.float32), indexes))
        self.order = order
        idx_sorted = indexes[order]
        new_group = jnp.concatenate([jnp.ones(1, bool), idx_sorted[1:] != idx_sorted[:-1]]) if n else jnp.zeros(0, bool)
        g = jnp.cumsum(new_group) - 1
        if isinstance(new_group, jax.core.Tracer):
            # under jit the group count is dynamic → static upper bound n; padding
            # groups have n_docs == 0 and are masked out of every aggregation
            self.num_groups = n
        else:
            # eager: one cheap host sync buys segment arrays sized to the TRUE
            # group count instead of n (often 100× smaller)
            self.num_groups = int(new_group.sum()) if n else 0
        self.group_id = g
        self.preds = preds[order]
        self.graded = target[order].astype(jnp.float32)
        self.rel = (self.graded > 0).astype(jnp.float32)

        ones = jnp.ones(n, dtype=jnp.float32)
        self.n_docs = jax.ops.segment_sum(ones, g, self.num_groups)
        self.n_rel = jax.ops.segment_sum(self.rel, g, self.num_groups)
        starts = jnp.concatenate([jnp.zeros(1), jnp.cumsum(self.n_docs)[:-1]]) if n else jnp.zeros(0)
        self.pos = jnp.arange(n, dtype=jnp.float32) - starts[g]
        # cumulative relevant within group, inclusive of current position
        cum = jnp.cumsum(self.rel)
        offset = jnp.concatenate([jnp.zeros(1), self.n_rel.cumsum()[:-1]]) if n else jnp.zeros(0)
        self.rel_cum = cum - offset[g]
        # ideal ordering (target desc within group) for NDCG
        ideal_order = jnp.lexsort((-target.astype(jnp.float32), indexes))
        self.ideal_graded = target[ideal_order].astype(jnp.float32)

    def seg_sum(self, x: Array) -> Array:
        return jax.ops.segment_sum(x, self.group_id, self.num_groups)

    def seg_min(self, x: Array) -> Array:
        return jax.ops.segment_min(x, self.group_id, self.num_groups)

    def seg_max(self, x: Array) -> Array:
        return jax.ops.segment_max(x, self.group_id, self.num_groups)


class RetrievalMetric(Metric):
    """Base class for retrieval metrics (reference ``retrieval/base.py:43``).

    Subclasses implement :meth:`_metric_vectorized` returning one score per query
    from the :class:`GroupedQueries` view.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        aggregation: Any = "mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False
        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index
        if not (aggregation in ("mean", "median", "min", "max") or callable(aggregation)):
            raise ValueError(
                "Argument `aggregation` must be one of `mean`, `median`, `min`, `max` or a custom callable function"
                f"which takes tensor of values, but got {aggregation}."
            )
        self.aggregation = aggregation

        self.add_state("indexes", [], dist_reduce_fx=None)
        self.add_state("preds", [], dist_reduce_fx=None)
        self.add_state("target", [], dist_reduce_fx=None)

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        """Check shape, flatten, check and store the inputs (reference ``base.py:135-146``)."""
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target, allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    _empty_error_msg = "`compute` method was provided with a query with no positive target."

    def _empty_mask(self, gq: GroupedQueries) -> Array:
        """Which (valid) groups count as "empty" for ``empty_target_action``."""
        return gq.n_rel == 0

    def compute(self) -> Array:
        """Group by query with ONE lex-sort, score every query via segment reductions (no loops)."""
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        if self.empty_target_action == "error" and preds.shape[0]:
            # data-dependent raise: eager-only, via cheap host bincounts (no need to
            # build the full sorted GroupedQueries view twice per compute)
            idx_np = np.asarray(indexes)
            _, compact = np.unique(idx_np, return_inverse=True)
            n_rel = np.bincount(compact, weights=np.asarray(target) > 0)
            if bool((self._empty_counts_host(n_rel, np.bincount(compact))).any()):
                raise ValueError(self._empty_error_msg)
        if callable(self.aggregation) and not isinstance(self.aggregation, str):
            return self.compute_flat(preds, target, indexes)  # host-side aggregation
        # ONE compiled program for grouping + scoring + aggregation: ~3× faster
        # than the eager op-by-op path even with the static n-bound segments.
        # Keyed by static config with a pristine-clone representative (same
        # economics as Metric._lookup_shared_jit) so live instances — and their
        # accumulated list states — are never pinned by the cache.
        key = self._jit_cache_key()
        if key is None:
            return self.compute_flat(preds, target, indexes)
        jitted = _JITTED_COMPUTE.get(key)
        if jitted is None:
            rep = self.clone()
            rep.reset()
            jitted = jax.jit(rep.compute_flat)
            _JITTED_COMPUTE[key] = jitted
            if len(_JITTED_COMPUTE) > 128:
                _JITTED_COMPUTE.pop(next(iter(_JITTED_COMPUTE)))
        return jitted(preds, target, indexes)

    @staticmethod
    def _empty_counts_host(n_rel: "np.ndarray", n_docs: "np.ndarray") -> "np.ndarray":
        """Host-side form of :meth:`_empty_mask` for the eager error check."""
        return n_rel == 0

    def compute_flat(self, preds: Array, target: Array, indexes: Array) -> Array:
        """Pure, fully jittable evaluation over flat arrays — embed this in a jitted
        eval step to run grouping, scoring and aggregation as ONE XLA program.

        ``empty_target_action="error"`` is treated as "neg" here (a data-dependent
        raise cannot trace); the eager :meth:`compute` performs the raise. A
        CALLABLE ``aggregation`` is host-side and not jittable — only the string
        aggregations trace; call this eagerly (or use :meth:`compute`) otherwise.
        """
        if preds.shape[0] == 0:
            return jnp.asarray(0.0)
        gq = GroupedQueries(indexes, preds, target)
        scores = self._metric_vectorized(gq)  # (num_groups,) under the static bound
        valid = gq.n_docs > 0
        empty = self._empty_mask(gq) & valid
        if self.empty_target_action == "pos":
            scores = jnp.where(empty, 1.0, scores)
        elif self.empty_target_action == "neg" or self.empty_target_action == "error":
            scores = jnp.where(empty, 0.0, scores)
        else:  # skip: masked aggregation instead of boolean indexing
            valid = valid & ~empty
        return _retrieval_aggregate(scores, self.aggregation, valid)

    @abstractmethod
    def _metric_vectorized(self, gq: GroupedQueries) -> Array:
        """Return one score per query group."""
