"""Modular retrieval metrics (reference ``torchmetrics/retrieval/__init__.py``)."""

from metrics_tpu.retrieval.base import RetrievalMetric
from metrics_tpu.retrieval.metrics import (
    RetrievalAUROC,
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
    RetrievalRPrecision,
)

__all__ = [
    "RetrievalAUROC",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalMetric",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
    "RetrievalRPrecision",
]
