"""Modular clustering metrics.

Parity with reference ``torchmetrics/clustering/`` (``mutual_info_score.py:78-79``
list states; contingency computed at the compute boundary — SURVEY §2.8).
"""

from __future__ import annotations

from typing import Any, Callable, List

from jax import Array

from metrics_tpu.functional.clustering.extrinsic import (
    adjusted_mutual_info_score,
    adjusted_rand_score,
    completeness_score,
    fowlkes_mallows_index,
    homogeneity_score,
    mutual_info_score,
    normalized_mutual_info_score,
    rand_score,
    v_measure_score,
)
from metrics_tpu.functional.clustering.intrinsic import (
    calinski_harabasz_score,
    davies_bouldin_score,
    dunn_index,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat


class _LabelClusteringMetric(Metric):
    """Shared plumbing: list states ``preds``/``target`` of cluster labels."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    preds: List[Array]
    target: List[Array]

    _compute_fn: Callable

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Update state with predicted and target cluster labels."""
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        """Compute metric over all accumulated labels."""
        return type(self)._compute_fn(dim_zero_cat(self.preds), dim_zero_cat(self.target))


class MutualInfoScore(_LabelClusteringMetric):
    """Compute mutual information between clusterings (reference ``clustering/mutual_info_score.py:30``).

    >>> import jax.numpy as jnp
    >>> metric = MutualInfoScore()
    >>> metric.update(jnp.array([2, 1, 0, 1, 0]), jnp.array([0, 2, 1, 1, 0]))
    >>> metric.compute()
    Array(0.50040245, dtype=float32)
    """

    _compute_fn = staticmethod(mutual_info_score)


class RandScore(_LabelClusteringMetric):
    """Compute the Rand score (reference ``clustering/rand_score.py:30``).

    >>> import jax.numpy as jnp
    >>> metric = RandScore()
    >>> metric.update(jnp.array([2, 1, 0, 1, 0]), jnp.array([0, 2, 1, 1, 0]))
    >>> metric.compute()
    Array(0.6, dtype=float32)
    """

    _compute_fn = staticmethod(rand_score)


class AdjustedRandScore(_LabelClusteringMetric):
    """Compute the adjusted Rand score (reference ``clustering/adjusted_rand_score.py:30``)."""

    plot_lower_bound = -1.0
    _compute_fn = staticmethod(adjusted_rand_score)


class FowlkesMallowsIndex(_LabelClusteringMetric):
    """Compute the Fowlkes-Mallows index (reference ``clustering/fowlkes_mallows_index.py:30``)."""

    _compute_fn = staticmethod(fowlkes_mallows_index)


class HomogeneityScore(_LabelClusteringMetric):
    """Compute the homogeneity score (reference ``clustering/homogeneity_completeness_v_measure.py``)."""

    _compute_fn = staticmethod(homogeneity_score)


class CompletenessScore(_LabelClusteringMetric):
    """Compute the completeness score (reference ``clustering/homogeneity_completeness_v_measure.py``)."""

    _compute_fn = staticmethod(completeness_score)


class VMeasureScore(_LabelClusteringMetric):
    """Compute the V-measure (reference ``clustering/homogeneity_completeness_v_measure.py``)."""

    def __init__(self, beta: float = 1.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(beta, (int, float)) and beta > 0):
            raise ValueError(f"Argument `beta` should be a positive float. Got {beta}.")
        self.beta = float(beta)

    def compute(self) -> Array:
        """Compute metric."""
        return v_measure_score(dim_zero_cat(self.preds), dim_zero_cat(self.target), self.beta)


class NormalizedMutualInfoScore(_LabelClusteringMetric):
    """Compute normalized mutual information (reference ``clustering/normalized_mutual_info_score.py:30``)."""

    def __init__(self, average_method: str = "arithmetic", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if average_method not in ("min", "geometric", "arithmetic", "max"):
            raise ValueError(f"Expected argument `average_method` to be one of (min, geometric, arithmetic, max),"
                             f" but got {average_method}")
        self.average_method = average_method

    def compute(self) -> Array:
        """Compute metric."""
        return normalized_mutual_info_score(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.average_method
        )


class AdjustedMutualInfoScore(NormalizedMutualInfoScore):
    """Compute adjusted mutual information (reference ``clustering/adjusted_mutual_info_score.py:30``)."""

    plot_lower_bound = -1.0

    def compute(self) -> Array:
        """Compute metric."""
        return adjusted_mutual_info_score(dim_zero_cat(self.preds), dim_zero_cat(self.target), self.average_method)


class _EmbeddingClusteringMetric(Metric):
    """Shared plumbing: list states ``data``/``labels``."""

    is_differentiable = True
    full_state_update = True
    data: List[Array]
    labels: List[Array]

    _compute_fn: Callable

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("data", [], dist_reduce_fx="cat")
        self.add_state("labels", [], dist_reduce_fx="cat")

    def update(self, data: Array, labels: Array) -> None:
        """Update state with embeddings and cluster labels."""
        self.data.append(data)
        self.labels.append(labels)

    def compute(self) -> Array:
        """Compute metric over all accumulated embeddings."""
        return type(self)._compute_fn(dim_zero_cat(self.data), dim_zero_cat(self.labels))


class CalinskiHarabaszScore(_EmbeddingClusteringMetric):
    """Compute the Calinski-Harabasz score (reference ``clustering/calinski_harabasz_score.py:28``).

    >>> import jax.numpy as jnp
    >>> metric = CalinskiHarabaszScore()
    >>> metric.update(jnp.array([[0., 0.], [0., 1.], [10., 10.], [10., 11.]]), jnp.array([0, 0, 1, 1]))
    >>> metric.compute()
    Array(400., dtype=float32)
    """

    higher_is_better = True
    plot_lower_bound = 0.0
    _compute_fn = staticmethod(calinski_harabasz_score)


class DaviesBouldinScore(_EmbeddingClusteringMetric):
    """Compute the Davies-Bouldin score (reference ``clustering/davies_bouldin_score.py:28``)."""

    higher_is_better = False
    plot_lower_bound = 0.0
    _compute_fn = staticmethod(davies_bouldin_score)


class DunnIndex(_EmbeddingClusteringMetric):
    """Compute the Dunn index (reference ``clustering/dunn_index.py:28``)."""

    higher_is_better = True
    plot_lower_bound = 0.0

    def __init__(self, p: float = 2.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.p = p

    def compute(self) -> Array:
        """Compute metric."""
        return dunn_index(dim_zero_cat(self.data), dim_zero_cat(self.labels), self.p)
