"""Modular clustering metrics (reference ``torchmetrics/clustering/__init__.py``)."""

from metrics_tpu.clustering.metrics import (
    AdjustedMutualInfoScore,
    AdjustedRandScore,
    CalinskiHarabaszScore,
    CompletenessScore,
    DaviesBouldinScore,
    DunnIndex,
    FowlkesMallowsIndex,
    HomogeneityScore,
    MutualInfoScore,
    NormalizedMutualInfoScore,
    RandScore,
    VMeasureScore,
)

__all__ = [
    "AdjustedMutualInfoScore",
    "AdjustedRandScore",
    "CalinskiHarabaszScore",
    "CompletenessScore",
    "DaviesBouldinScore",
    "DunnIndex",
    "FowlkesMallowsIndex",
    "HomogeneityScore",
    "MutualInfoScore",
    "NormalizedMutualInfoScore",
    "RandScore",
    "VMeasureScore",
]
