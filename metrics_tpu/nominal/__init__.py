"""Modular nominal-association metrics (reference ``torchmetrics/nominal/__init__.py``)."""

from metrics_tpu.nominal.metrics import (
    CramersV,
    FleissKappa,
    PearsonsContingencyCoefficient,
    TheilsU,
    TschuprowsT,
)

__all__ = ["CramersV", "FleissKappa", "PearsonsContingencyCoefficient", "TheilsU", "TschuprowsT"]
