"""Modular nominal metrics (reference ``torchmetrics/nominal/`` — all confmat-based, SURVEY §2.8)."""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from jax import Array

from metrics_tpu.functional.nominal.metrics import (
    cramers_v,
    fleiss_kappa,
    pearsons_contingency_coefficient,
    theils_u,
    tschuprows_t,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat


class _NominalMetric(Metric):
    """Shared plumbing: list states of the two categorical variables."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    preds: List[Array]
    target: List[Array]

    def __init__(self, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if nan_strategy not in ("replace", "drop"):
            raise ValueError(f"Argument `nan_strategy` is expected to be one of `('replace', 'drop')`, "
                             f"but got {nan_strategy}")
        if nan_strategy == "replace" and not isinstance(nan_replace_value, (int, float)):
            raise ValueError("Argument `nan_replace_value` is expected to be of a type `int` or `float` when "
                             f"`nan_strategy = 'replace`, but got {nan_replace_value}")
        self.nan_strategy = nan_strategy
        self.nan_replace_value = nan_replace_value
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Update state with the two categorical variables."""
        self.preds.append(preds.reshape(-1))
        self.target.append(target.reshape(-1))


class CramersV(_NominalMetric):
    """Compute Cramer's V between two categorical variables (reference ``nominal/cramers.py:26``).

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> rng = np.random.RandomState(42)
    >>> preds = jnp.asarray(rng.randint(0, 4, (100,)))
    >>> target = jnp.asarray((np.asarray(preds) + rng.randint(0, 2, (100,))) % 4)
    >>> metric = CramersV(num_classes=4)
    >>> metric.update(preds, target)
    >>> round(float(metric.compute()), 4)
    0.577
    """

    def __init__(self, num_classes: int, bias_correction: bool = True, nan_strategy: str = "replace",
                 nan_replace_value: Optional[float] = 0.0, **kwargs: Any) -> None:
        super().__init__(nan_strategy, nan_replace_value, **kwargs)
        if not isinstance(num_classes, int) or num_classes < 1:
            raise ValueError("Argument `num_classes` has to be a positive integer")
        self.num_classes = num_classes
        self.bias_correction = bias_correction

    def compute(self) -> Array:
        """Compute metric."""
        return cramers_v(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.bias_correction,
            self.nan_strategy, self.nan_replace_value,
        )


class TschuprowsT(CramersV):
    """Compute Tschuprow's T between two categorical variables (reference ``nominal/tschuprows.py:26``)."""

    def compute(self) -> Array:
        """Compute metric."""
        return tschuprows_t(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.bias_correction,
            self.nan_strategy, self.nan_replace_value,
        )


class PearsonsContingencyCoefficient(_NominalMetric):
    """Compute Pearson's contingency coefficient (reference ``nominal/pearson.py:26``)."""

    def __init__(self, num_classes: int, nan_strategy: str = "replace",
                 nan_replace_value: Optional[float] = 0.0, **kwargs: Any) -> None:
        super().__init__(nan_strategy, nan_replace_value, **kwargs)
        if not isinstance(num_classes, int) or num_classes < 1:
            raise ValueError("Argument `num_classes` has to be a positive integer")
        self.num_classes = num_classes

    def compute(self) -> Array:
        """Compute metric."""
        return pearsons_contingency_coefficient(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.nan_strategy, self.nan_replace_value
        )


class TheilsU(PearsonsContingencyCoefficient):
    """Compute Theil's U — uncertainty coefficient (reference ``nominal/theils_u.py:26``)."""

    def compute(self) -> Array:
        """Compute metric."""
        return theils_u(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.nan_strategy, self.nan_replace_value
        )


class FleissKappa(Metric):
    """Compute Fleiss' kappa for inter-rater agreement (reference ``nominal/fleiss_kappa.py:26``).

    >>> import jax.numpy as jnp
    >>> metric = FleissKappa(mode='counts')
    >>> metric.update(jnp.array([[0, 0, 14], [0, 2, 12], [0, 6, 8], [0, 12, 2]]))
    >>> round(float(metric.compute()), 4)
    0.4256
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    ratings: List[Array]

    def __init__(self, mode: str = "counts", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if mode not in ("counts", "probs"):
            raise ValueError("Argument ``mode`` must be one of 'counts' or 'probs'")
        self.mode = mode
        self.add_state("ratings", [], dist_reduce_fx="cat")

    def update(self, ratings: Array) -> None:
        """Update state with rating counts or probabilities."""
        self.ratings.append(ratings)

    def compute(self) -> Array:
        """Compute metric."""
        import jax.numpy as jnp

        cat_axis = 0 if self.mode == "counts" else 1
        ratings = jnp.concatenate(self.ratings, axis=cat_axis)
        return fleiss_kappa(ratings, self.mode)
