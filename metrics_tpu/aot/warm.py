"""Pre-populate the AOT executable cache for the whole metric registry.

``python tools/warm_cache.py --cache-dir /var/cache/metrics_tpu`` (or the
``warm-cache`` console script) runs ONE real update per profiled registry
class (:data:`metrics_tpu.observe.costs.PROFILE_CASES`, the same cases and
deterministic batches the perf ratchet lowers) with the disk cache pointed at
the target directory. Every compile that run pays is serialized, so the next
process — every fleet worker that mounts the directory — starts with zero
cold-start compiles for those programs.

Idempotent: a second run over a warm directory reports hits, stores nothing,
and rewrites only entries gone stale (jax upgrade, backend change). Safe to
call in-process (tests, notebooks): observe state, the shared jit cache and
the configured cache dir are all restored on exit.
"""

from __future__ import annotations

import argparse
import sys
from typing import Any, Dict, List, Optional, Sequence

from metrics_tpu.aot import cache as _cache

__all__ = ["main", "warm_registry"]


def warm_registry(
    cache_directory: Optional[str] = None,
    classes: Optional[Sequence[str]] = None,
    verbose: bool = False,
) -> Dict[str, Any]:
    """Warm the cache for every (matching) registry case; returns a summary.

    ``cache_directory`` defaults to the already-configured dir (env var or
    :func:`metrics_tpu.aot.set_cache_dir`). ``classes`` filters case names by
    case-insensitive substring. The summary maps each case name to its status:
    ``stored`` (entries written), ``hit`` (already warm), ``ineligible``
    (never jit-compiles, nothing to cache), ``unfingerprintable`` (config has
    no process-stable identity, so no disk key) or ``error``.
    """
    from metrics_tpu.metric import _SHARED_JIT_CACHE, clear_jit_cache
    from metrics_tpu.observe import recorder as _observe
    from metrics_tpu.observe.costs import PROFILE_CASES, _rng

    directory = cache_directory if cache_directory is not None else _cache.cache_dir()
    if directory is None:
        raise ValueError(
            "no cache directory: pass --cache-dir, set METRICS_TPU_AOT_CACHE, "
            "or call metrics_tpu.aot.set_cache_dir first"
        )

    selected = [
        c for c in PROFILE_CASES
        if not classes or any(s.lower() in c.name.lower() for s in classes)
    ]
    summary: Dict[str, Any] = {"directory": str(directory), "cases": {}}
    tally = {"stored": 0, "hit": 0, "ineligible": 0, "unfingerprintable": 0, "error": 0}

    prev_dir = _cache.cache_dir()
    saved_cache = dict(_SHARED_JIT_CACHE)
    was_enabled = _observe.ENABLED
    probe = _observe.Recorder()
    real, _observe.RECORDER = _observe.RECORDER, probe
    try:
        _cache.set_cache_dir(directory)
        clear_jit_cache()  # in-memory only: force every case through the disk path
        _observe.ENABLED = True
        for case in selected:
            status, detail = "stored", ""
            before = dict(probe.counters)
            try:
                inst = case.ctor()
                batch = case.batch(_rng(case))
                # _jit_eligible is the real dispatch gate: class-level opt-outs,
                # list state, per-instance jit_update=False (e.g. aggregation
                # metrics whose nan_strategy needs the host) all mean the update
                # never compiles, so there is nothing to persist
                if not inst._jit_eligible(batch, {}):
                    status = "ineligible"
                elif inst._jit_cache_key() is None:
                    status = "unfingerprintable"
                else:
                    inst.update(*batch)
                    label = type(inst).__name__
                    delta = lambda name: (  # noqa: E731
                        probe.counters.get((name, label), 0) - before.get((name, label), 0)
                    )
                    if probe.counters.get(("eager_fallback", label), 0) - before.get(("eager_fallback", label), 0):
                        status, detail = "error", "latched eager fallback under jit"
                    elif delta("aot_store"):
                        status = "stored"
                    elif delta("aot_hit"):
                        status = "hit"
                    else:
                        status, detail = "error", "update ran but neither stored nor hit"
            except Exception as exc:  # noqa: BLE001 — the error text IS the result
                status, detail = "error", f"{type(exc).__name__}: {exc}"
            tally[status] += 1
            summary["cases"][case.name] = {"status": status, **({"detail": detail} if detail else {})}
            if verbose:
                print(f"  {case.name:45s} {status}{(' — ' + detail) if detail else ''}")
    finally:
        _observe.ENABLED = was_enabled
        _observe.RECORDER = real
        _SHARED_JIT_CACHE.clear()
        _SHARED_JIT_CACHE.update(saved_cache)
        _cache.set_cache_dir(prev_dir)
    summary.update(tally)
    summary["stats"] = _cache.cache_stats(str(directory))
    return summary


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="warm-cache",
        description="Pre-populate the AOT executable cache (DESIGN §18) for the "
                    "whole profiled metric registry in one run.",
    )
    p.add_argument("--cache-dir", default=None,
                   help="target directory (default: $METRICS_TPU_AOT_CACHE)")
    p.add_argument("--classes", default=None,
                   help="comma-separated case-name substrings to warm (default: all)")
    p.add_argument("--purge", action="store_true",
                   help="delete existing entries first (force a full rebuild)")
    p.add_argument("-v", "--verbose", action="store_true", help="per-case lines")
    p.add_argument("-q", "--quiet", action="store_true", help="suppress the summary line")
    args = p.parse_args(argv)

    # probe the backend in a killable subprocess first (same as every other
    # CLI tool): a wedged accelerator tunnel must not hang the warm run, and
    # the entries must be fingerprinted against the backend that answers
    from metrics_tpu.utils.backend import ensure_backend

    ensure_backend(min_devices=1, quiet=args.quiet)

    classes = [s.strip() for s in args.classes.split(",") if s.strip()] if args.classes else None
    directory = args.cache_dir if args.cache_dir is not None else _cache.cache_dir()
    if directory is None:
        print("warm-cache: no cache directory (pass --cache-dir or set "
              f"{_cache.ENV_VAR})", file=sys.stderr)
        return 2
    if args.purge:
        removed = _cache.purge_cache(str(directory))
        if not args.quiet:
            print(f"warm-cache: purged {removed} entries from {directory}")
    try:
        summary = warm_registry(str(directory), classes=classes, verbose=args.verbose)
    except ValueError as exc:
        print(f"warm-cache: {exc}", file=sys.stderr)
        return 2
    if not args.quiet:
        stats = summary["stats"]
        print(
            f"warm-cache: {summary['stored']} stored, {summary['hit']} already warm, "
            f"{summary['ineligible']} ineligible, {summary['unfingerprintable']} unfingerprintable, "
            f"{summary['error']} errors — {stats['entries']} entries / {stats['bytes']} bytes in {stats['directory']}"
        )
        for name, info in summary["cases"].items():
            if info["status"] == "error":
                print(f"  ERROR {name}: {info.get('detail', '')}", file=sys.stderr)
    return 1 if summary["error"] else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
