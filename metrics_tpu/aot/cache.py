"""On-disk storage for AOT-compiled metric executables (DESIGN §18).

This is the persistence half of the AOT subsystem: stable content-addressed
keys, CRC-framed entry files, and validate-before-install reads. The dispatch
half — deciding when to consult the disk and how a loaded program replaces a
fresh trace — lives in :mod:`metrics_tpu.aot.runtime`.

Entry file layout (``<sha256>.aotx``, one executable per file)::

    MAGIC "MTAOT001"                       8 bytes
    header_len u32 | header_crc32 u32      big-endian frame
    header JSON                            format_version, key_digest, label,
                                           donate, payload_len, payload_crc32,
                                           env {jax, jaxlib, backend,
                                                backend_version, x64}
    payload                                pickle of (blob, in_tree, out_tree)
                                           from jax.experimental.
                                           serialize_executable.serialize

Files are written with the same tmp + fsync + ``os.replace`` discipline as the
§14 checkpoint container (``utils/io.py``), so a crashed writer never leaves a
torn file under the real name and concurrent warmers converge on last-writer-
wins without readers ever seeing a mix.

Staleness vs corruption: the environment fingerprint lives in the HEADER, not
the key, so an entry built by an older jax/XLA or another backend is found,
recognized as stale (``aot_stale``), latched in ``_STALE_DIGESTS`` so the file
is not re-read and re-validated on every subsequent lookup, and overwritten in
place by the next store — which lifts the latch. A corrupt file (bad magic,
CRC mismatch, unpicklable payload) takes the same path: fall back to a normal
trace, never crash or miscompute.

The cache is OFF unless ``METRICS_TPU_AOT_CACHE`` names a directory (or
:func:`set_cache_dir` is called); unset, no module in the hot path even
imports this one.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
import zlib
from typing import Any, Dict, Optional, Tuple

import jax

from metrics_tpu.observe import recorder as _observe
from metrics_tpu.observe import tracing as _trace
from metrics_tpu.utils.io import atomic_write_chunks, fsync_directory

__all__ = [
    "AOTCacheError",
    "CorruptEntryError",
    "ENV_VAR",
    "FORMAT_VERSION",
    "MAGIC",
    "StaleEntryError",
    "cache_dir",
    "cache_stats",
    "entry_digest",
    "entry_path",
    "environment_fingerprint",
    "lookup",
    "purge_cache",
    "read_entry",
    "set_cache_dir",
    "store",
    "write_entry",
]

ENV_VAR = "METRICS_TPU_AOT_CACHE"
MAGIC = b"MTAOT001"
FORMAT_VERSION = 1
_FRAME = struct.Struct(">II")  # header_len, header_crc32
_SUFFIX = ".aotx"

# header fields that must match the running process for an entry to be usable —
# serialized XLA executables are only portable on the same compiler + runtime
_ENV_FIELDS = ("jax", "jaxlib", "backend", "backend_version", "x64")


class AOTCacheError(Exception):
    """Base for AOT cache entry problems (never escapes to metric callers)."""


class CorruptEntryError(AOTCacheError):
    """The entry file is damaged: bad magic, CRC mismatch, undecodable parts."""


class StaleEntryError(AOTCacheError):
    """The entry is intact but built by a different jax/XLA/backend/x64 regime."""


_CACHE_DIR: Optional[str] = os.environ.get(ENV_VAR) or None

# digests known unusable in this process (stale or corrupt): lookups miss
# immediately instead of re-reading and re-validating the file every time;
# the next store overwrites the file and lifts the latch (refresh-once).
_STALE_DIGESTS: set = set()


def cache_dir() -> Optional[str]:
    """The configured cache directory, or None when the disk cache is off."""
    return _CACHE_DIR


def set_cache_dir(path: Optional[os.PathLike]) -> None:
    """Point the AOT cache at ``path`` (None turns the disk cache off).

    Overrides the ``METRICS_TPU_AOT_CACHE`` environment default for the rest
    of the process. Already-attached bindings keep their in-memory loaded
    programs; only new disk traffic moves. The stale latch resets — it
    described the old directory.
    """
    global _CACHE_DIR
    _CACHE_DIR = os.fspath(path) if path else None
    _STALE_DIGESTS.clear()


_BACKEND_FP: Optional[Dict[str, str]] = None


def environment_fingerprint() -> Dict[str, Any]:
    """The compatibility header fields: serialized executables are only valid
    on the exact jax + jaxlib + backend (and its runtime version) that built
    them, and under the same x64 regime (which changes every weak-typed aval).
    The backend part is cached; ``x64`` is re-read per call because tests flip
    it mid-process."""
    global _BACKEND_FP
    if _BACKEND_FP is None:
        import jaxlib  # noqa: PLC0415
        import jax.extend.backend as jeb  # noqa: PLC0415  (bare `jax.` lacks .extend)

        backend = jeb.get_backend()
        _BACKEND_FP = {
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "backend": str(backend.platform),
            "backend_version": str(backend.platform_version),
        }
    return {**_BACKEND_FP, "x64": bool(jax.config.jax_enable_x64)}


def entry_digest(key: Any) -> str:
    """Content address of a cache key: sha256 over its repr.

    Keys are built exclusively from primitives with deterministic reprs
    (strings, ints, bools, tuples) — the class path, config fingerprint,
    state avals, engine shape statics and the dispatch-time aval signature —
    so the digest is stable across processes.
    """
    return hashlib.sha256(repr(key).encode()).hexdigest()


def entry_path(digest: str, directory: Optional[str] = None) -> str:
    d = directory if directory is not None else _CACHE_DIR
    if d is None:
        raise AOTCacheError("AOT cache directory is not configured")
    return os.path.join(d, digest + _SUFFIX)


# ---------------------------------------------------------------- entry framing
def write_entry(path: str, key_digest: str, label: str, donate: bool, payload: bytes) -> int:
    """Atomically write one framed entry file; returns bytes written."""
    header = {
        "format_version": FORMAT_VERSION,
        "key_digest": key_digest,
        "label": label,
        "donate": bool(donate),
        "payload_len": len(payload),
        "payload_crc32": zlib.crc32(payload) & 0xFFFFFFFF,
        "env": environment_fingerprint(),
    }
    header_bytes = json.dumps(header, sort_keys=True).encode()
    frame = _FRAME.pack(len(header_bytes), zlib.crc32(header_bytes) & 0xFFFFFFFF)
    return atomic_write_chunks(path, (MAGIC, frame, header_bytes, payload))


def read_entry(path: str, key_digest: str) -> Tuple[Dict[str, Any], bytes]:
    """Parse and fully validate one entry file BEFORE anything is installed.

    Raises :class:`CorruptEntryError` for damage and :class:`StaleEntryError`
    for an intact entry from an incompatible environment; returns
    ``(header, payload)`` only when every frame, CRC, key and compatibility
    check passed.
    """
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except OSError as exc:
        raise CorruptEntryError(f"unreadable entry: {exc}") from exc
    base = len(MAGIC) + _FRAME.size
    if len(data) < base or data[: len(MAGIC)] != MAGIC:
        raise CorruptEntryError("bad magic")
    header_len, header_crc = _FRAME.unpack_from(data, len(MAGIC))
    header_bytes = data[base : base + header_len]
    if len(header_bytes) != header_len or zlib.crc32(header_bytes) & 0xFFFFFFFF != header_crc:
        raise CorruptEntryError("header CRC mismatch")
    try:
        header = json.loads(header_bytes.decode())
    except (ValueError, UnicodeDecodeError) as exc:
        raise CorruptEntryError(f"undecodable header: {exc}") from exc
    version = header.get("format_version")
    if version != FORMAT_VERSION:
        raise StaleEntryError(f"format_version {version!r} != {FORMAT_VERSION}")
    if header.get("key_digest") != key_digest:
        raise CorruptEntryError("key digest mismatch (file renamed or hash collision)")
    payload = data[base + header_len :]
    if len(payload) != header.get("payload_len") or zlib.crc32(payload) & 0xFFFFFFFF != header.get("payload_crc32"):
        raise CorruptEntryError("payload CRC mismatch")
    env = header.get("env") or {}
    mine = environment_fingerprint()
    for field in _ENV_FIELDS:
        if env.get(field) != mine[field]:
            raise StaleEntryError(f"{field}: entry {env.get(field)!r} != process {mine[field]!r}")
    return header, payload


def serialize_executable(compiled: Any) -> bytes:
    from jax.experimental.serialize_executable import serialize  # noqa: PLC0415

    blob, in_tree, out_tree = serialize(compiled)
    return pickle.dumps((blob, in_tree, out_tree), protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_executable(payload: bytes) -> Any:
    from jax.experimental.serialize_executable import deserialize_and_load  # noqa: PLC0415

    blob, in_tree, out_tree = pickle.loads(payload)
    return deserialize_and_load(blob, in_tree, out_tree)


# ----------------------------------------------------------------- cache traffic
def lookup(key: Any, label: str) -> Optional[Tuple[Any, bool]]:
    """Consult the disk for ``key``: ``(loaded_executable, donate)`` or None.

    Counts exactly one of ``aot_hit`` / ``aot_miss`` / ``aot_stale`` per call.
    A stale or corrupt entry is latched so later lookups of the same key miss
    without touching the file again; every failure mode returns None — the
    caller traces normally.
    """
    if _CACHE_DIR is None:
        return None
    digest = entry_digest(key)
    if digest in _STALE_DIGESTS:
        _observe.note_aot_miss(label)
        return None
    path = os.path.join(_CACHE_DIR, digest + _SUFFIX)
    if not os.path.exists(path):
        _observe.note_aot_miss(label)
        return None
    try:
        with _trace.span("aot", f"load:{label}"):
            header, payload = read_entry(path, digest)
            loaded = deserialize_executable(payload)
    except StaleEntryError as exc:
        _STALE_DIGESTS.add(digest)
        _observe.note_aot_stale(label, str(exc))
        return None
    except Exception as exc:  # CorruptEntryError + anything unpickling can raise
        _STALE_DIGESTS.add(digest)
        _observe.note_aot_stale(label, f"corrupt: {exc}")
        return None
    _observe.note_aot_hit(label)
    return loaded, bool(header.get("donate", False))


def store(key: Any, compiled: Any, donate: bool, label: str) -> bool:
    """Serialize ``compiled`` under ``key``; True on success.

    Overwrites whatever was there (the refresh path for stale entries — the
    latch lifts here, exactly once). Serialization failures (a backend without
    executable serialization, disk errors) are recorded as events and absorbed:
    the in-memory program the caller just compiled keeps working either way.
    """
    if _CACHE_DIR is None:
        return False
    digest = entry_digest(key)
    try:
        with _trace.span("aot", f"store:{label}"):
            payload = serialize_executable(compiled)
            os.makedirs(_CACHE_DIR, exist_ok=True)
            nbytes = write_entry(os.path.join(_CACHE_DIR, digest + _SUFFIX), digest, label, donate, payload)
    except Exception as exc:
        _observe.record_event("aot_store_failed", metric=label, error=type(exc).__name__, detail=str(exc)[:200])
        return False
    _STALE_DIGESTS.discard(digest)
    _observe.note_aot_store(label, nbytes)
    return True


def purge_cache(directory: Optional[str] = None) -> int:
    """Delete every entry file in ``directory`` (default: the configured dir).

    Returns the number of files removed; 0 when no directory is configured.
    Only ``*.aotx`` files are touched — the cache never owns the directory.
    """
    d = directory if directory is not None else _CACHE_DIR
    _STALE_DIGESTS.clear()
    if d is None or not os.path.isdir(d):
        return 0
    removed = 0
    for name in os.listdir(d):
        if name.endswith(_SUFFIX):
            try:
                os.unlink(os.path.join(d, name))
                removed += 1
            except OSError:
                pass
    fsync_directory(d)
    _observe.record_event("aot_purge", directory=d, removed=removed)
    return removed


def cache_stats(directory: Optional[str] = None) -> Dict[str, Any]:
    """Entry count and total bytes on disk (for tools and triage output)."""
    d = directory if directory is not None else _CACHE_DIR
    out: Dict[str, Any] = {"directory": d, "entries": 0, "bytes": 0}
    if d and os.path.isdir(d):
        for name in os.listdir(d):
            if name.endswith(_SUFFIX):
                out["entries"] += 1
                try:
                    out["bytes"] += os.path.getsize(os.path.join(d, name))
                except OSError:
                    pass
    return out
