"""Dispatch-side AOT integration (DESIGN §18).

An :class:`AotBinding` is attached to a ``_CompiledUpdate`` cache entry (the
shared per-metric cache in ``metric.py`` and the replica/fleet ``ProgramCache``
in ``engine/core.py``) when the disk cache is configured. The entry's
``__call__`` then routes through :meth:`AotBinding.dispatch`, which resolves
each distinct argument signature to ONE executable:

1. in-memory: a program already loaded/compiled for this signature replays;
2. disk hit: the serialized executable loads (``aot_hit``) — no trace, no
   XLA compile, the whole point;
3. miss/stale: the entry's own ``jax.jit`` wrapper is lowered and compiled
   AOT (``entry.fn.lower(...).compile()`` — same trace, same donation), then
   serialized back to disk (``aot_store``) so the NEXT process hits.

Donation interplay with the probation latch (``metric._probation_dispatch``):
compiling here captures the compile-time "donated buffers were not usable"
warning itself. On that warning the entry is latched to a plain non-donating
jit exactly as probation would, the program is recompiled without donation,
and the stored header records ``donate=False`` — so a later process loading
the entry learns the donation verdict without ever seeing the warning, and
its probation probe scans clean. First dispatches still run under probation
(copies donated), so a loaded program that DOES donate can never consume
buffers the caller still holds.

A loaded program's first call is guarded: a ``TypeError`` (argument/aval
rejection, raised before anything executes, buffers intact) demotes the entry
to stale and falls back to a fresh compile — corrupt or mismatched entries
degrade to exactly the behavior with the cache off.
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from metrics_tpu.aot import cache as _cache
from metrics_tpu.observe import recorder as _observe

__all__ = ["AotBinding", "active", "call_signature"]

# must match metric.py's probe string — both scan the same XLA warning
_DONATION_UNUSABLE_MSG = "donated buffers were not usable"


def active() -> bool:
    """Whether dispatches should consult the disk (a cache dir is configured)."""
    return _cache.cache_dir() is not None


def call_signature(args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> Tuple[Any, ...]:
    """Stable signature of one concrete call: per-leaf avals plus the treedef.

    Mirrors what makes ``jax.jit`` retrace — shape, dtype and weak-typedness
    per array leaf, the Python type for scalar operands (their values never
    shape the program), and the argument tree structure. Rendered from
    primitives only, so its repr is process-stable and safe to hash into the
    disk key.
    """
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for v in leaves:
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            sig.append(("arr", tuple(int(s) for s in v.shape), str(v.dtype), bool(getattr(v, "weak_type", False))))
        else:
            sig.append(("py", type(v).__name__))
    return (tuple(sig), str(treedef))


def _miss_components(base_key: Any, sig: Any) -> Tuple[Tuple[str, Any], ...]:
    """Decompose an AOT disk key for cause attribution (DESIGN §22).

    Both base-key layouts built by the runtime (``("shared", classpath, fp,
    state_avals, donate)`` from metric.py and ``("engine", kind, classpath,
    fp, state_avals, n) + statics`` from engine/core.py) split into named
    components; anything else reports as one opaque ``base_key`` component.
    The call signature is always its own component, so a new batch shape on a
    warmed entry attributes as exactly ``call_signature``.
    """
    comps: Tuple[Tuple[str, Any], ...]
    if isinstance(base_key, tuple) and len(base_key) == 5 and base_key[0] == "shared":
        _, classpath, fp, avals, donate = base_key
        comps = (
            ("class", classpath), ("config_fingerprint", fp),
            ("state_avals", avals), ("donation", donate),
        )
    elif isinstance(base_key, tuple) and len(base_key) >= 6 and base_key[0] == "engine":
        comps = (
            ("engine", base_key[1]), ("class", base_key[2]),
            ("config_fingerprint", base_key[3]), ("state_avals", base_key[4]),
            ("capacity", base_key[5]), ("statics", base_key[6:]),
        )
    else:
        comps = (("base_key", base_key),)
    return comps + (
        ("call_signature", sig),
        ("x64", bool(jax.config.jax_enable_x64)),
    )


class _Program:
    """One resolved executable for one call signature."""

    __slots__ = ("exe", "from_disk", "verified")

    def __init__(self, exe: Any, from_disk: bool) -> None:
        self.exe = exe
        self.from_disk = from_disk
        self.verified = not from_disk


class AotBinding:
    """Per-entry AOT dispatcher: maps call signatures to loaded executables.

    ``base_key`` identifies everything signature-independent about the entry
    (class path, config fingerprint, state avals, engine shape statics, the
    requested donation); the full disk key is ``(base_key, call_signature)``.
    ``on_compile`` defers the owner cache's compile counter to the moment an
    XLA compile actually happens — a disk hit counts ``aot_hit`` instead, so
    a warmed process reports zero compiles.
    """

    __slots__ = ("base_key", "label", "on_compile", "programs")

    def __init__(self, base_key: Any, label: str, on_compile: Optional[Callable[[], None]] = None) -> None:
        self.base_key = base_key
        self.label = label
        self.on_compile = on_compile
        self.programs: Dict[Any, _Program] = {}

    def dispatch(self, entry: Any, args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> Any:
        sig = call_signature(args, kwargs)
        prog = self.programs.get(sig)
        if prog is None:
            prog = self._resolve(entry, sig, args, kwargs)
            self.programs[sig] = prog
        if not prog.verified:
            try:
                out = prog.exe(*args, **kwargs)
            except TypeError as exc:
                # argument rejection happens before execution, so every buffer
                # (donated or not) is intact: demote to stale, trace fresh,
                # overwrite the bad entry
                _observe.note_aot_stale(self.label, f"load rejected: {exc}")
                prog = self._compile(entry, sig, args, kwargs)
                self.programs[sig] = prog
                return prog.exe(*args, **kwargs)
            prog.verified = True
            return out
        return prog.exe(*args, **kwargs)

    def _resolve(self, entry: Any, sig: Any, args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> _Program:
        rec = _cache.lookup((self.base_key, sig), self.label)
        if rec is not None:
            exe, donate = rec
            if entry.donate and not donate:
                # the stored program was built without donation (XLA reported
                # the aliasing unusable when it was compiled): latch the
                # in-memory entry the way the probation probe would, so the
                # recorded verdict and the live dispatch path agree
                entry.fn = jax.jit(entry.raw)
                entry.donate = False
                _observe.record_event("donation_unusable", metric=self.label, source="aot")
            return _Program(exe, from_disk=True)
        if _observe.ENABLED:
            _observe.note_compile_miss("aot", self.label, _miss_components(self.base_key, sig))
        return self._compile(entry, sig, args, kwargs)

    def _compile(self, entry: Any, sig: Any, args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> _Program:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            compiled = entry.fn.lower(*args, **kwargs).compile()
        unusable = False
        for w in caught:
            if _DONATION_UNUSABLE_MSG in str(w.message):
                unusable = True
                continue
            warnings.warn_explicit(w.message, w.category, w.filename, w.lineno)
        if unusable and entry.donate:
            # same latch as the probation probe, learned at compile time:
            # rebuild without donation so the stored program and the recorded
            # donate verdict agree (and later processes skip the probe)
            entry.fn = jax.jit(entry.raw)
            entry.donate = False
            _observe.record_event("donation_unusable", metric=self.label, source="aot")
            compiled = entry.fn.lower(*args, **kwargs).compile()
        if self.on_compile is not None:
            self.on_compile()
        _cache.store((self.base_key, sig), compiled, entry.donate, self.label)
        return _Program(compiled, from_disk=False)
