"""AOT executable cache: persist compiled metric programs across processes (DESIGN §18).

Every new process normally re-traces and re-compiles every metric it touches —
the shared jit cache, the replica cache and the fleet ``ProgramCache`` are all
process-local, so at fleet scale a restart costs minutes of warmup per worker.
This subsystem persists the compiled artifact itself: serialized XLA
executables keyed by (class, config fingerprint, state avals, call signature,
donation, engine shape statics) in CRC-framed files under a cache directory,
consulted before tracing and validated before install.

Off by default. Enable by pointing ``METRICS_TPU_AOT_CACHE`` at a directory
before the process starts, or calling :func:`set_cache_dir` at runtime;
``python tools/warm_cache.py --cache-dir <dir>`` pre-populates it for the
whole registry. Unset, nothing here is even imported by the hot path.
"""

from metrics_tpu.aot.cache import (
    AOTCacheError,
    CorruptEntryError,
    ENV_VAR,
    StaleEntryError,
    cache_dir,
    cache_stats,
    entry_digest,
    entry_path,
    environment_fingerprint,
    purge_cache,
    set_cache_dir,
)
from metrics_tpu.aot.runtime import AotBinding, active, call_signature

__all__ = [
    "AOTCacheError",
    "AotBinding",
    "CorruptEntryError",
    "ENV_VAR",
    "StaleEntryError",
    "active",
    "cache_dir",
    "cache_stats",
    "call_signature",
    "entry_digest",
    "entry_path",
    "environment_fingerprint",
    "purge_cache",
    "set_cache_dir",
    "warm_registry",
]


def warm_registry(*args, **kwargs):
    """Lazy alias for :func:`metrics_tpu.aot.warm.warm_registry` (imports the
    full metric registry, so it must not ride the package import)."""
    from metrics_tpu.aot.warm import warm_registry as _warm  # noqa: PLC0415

    return _warm(*args, **kwargs)
