"""Distributed state synchronization over a jax device mesh.

TPU-native replacement for the reference comm layer
(``torchmetrics/utilities/distributed.py:100-153`` ``gather_all_tensors`` +
``metric.py:501-540`` ``_sync_dist``): instead of NCCL all_gather-then-reduce of
replicated torch states, metric states here live on a ``jax.sharding.Mesh`` and the
per-state ``dist_reduce_fx`` lowers directly to the matching XLA collective over
ICI/DCN:

    sum → lax.psum       mean → lax.pmean      min/max → lax.pmin/pmax
    cat / None / custom  → lax.all_gather (+ concat / custom fold)

Sum-reducible states therefore never pay a gather at all — ``psum`` rides ICI as a
single fused all-reduce, strictly cheaper than the reference's gather+sum. Ragged
"cat" states use the reference's own robustness contract (ranks may hold unequal or
no data) via fixed-capacity buffers + counts (:func:`pad_to_capacity`) instead of the
dynamic pad-gather-trim of ``distributed.py:138-151``, which XLA cannot express.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, TypeVar

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array, lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metrics_tpu.observe import recorder as _observe
from metrics_tpu.observe import tracing as _tracing
from metrics_tpu.utils.data import dim_zero_cat, dim_zero_max, dim_zero_mean, dim_zero_min, dim_zero_sum
from metrics_tpu.utils.exceptions import TPUMetricsUserError

_BUILTIN_REDUCTIONS = (dim_zero_sum, dim_zero_mean, dim_zero_min, dim_zero_max, dim_zero_cat)

__all__ = [
    "sync_states",
    "gather_all_states",
    "allreduce_over_mesh",
    "pad_to_capacity",
    "build_mesh",
    "shard_map_compat",
    "SyncPolicy",
    "SyncPeerLostError",
    "get_sync_policy",
    "set_sync_policy",
    "sync_policy",
    "run_with_retries",
    "seed_retry_jitter",
]

_T = TypeVar("_T")


# ------------------------------------------------------------------ degraded-sync policy
@dataclasses.dataclass(frozen=True)
class SyncPolicy:
    """How the eager sync orchestration (``Metric.sync`` → ``gather_all_states``)
    behaves when a collective fails (DESIGN §14).

    - ``retries``: extra attempts after the first failure, each preceded by an
      exponentially growing sleep starting at ``backoff_s``.
    - ``timeout_s``: total retry budget in seconds — once exceeded, no further
      attempt is made even if ``retries`` remain. ``None`` = unbounded.
    - ``partial_merge``: when the final attempt still fails, degrade to a
      count-weighted merge of the surviving shards (the local state plus any
      survivors a :class:`SyncPeerLostError` carried) and record a
      ``sync_degraded`` observe event instead of raising.
    - ``jitter``: bounded randomization of each backoff sleep. The actual sleep
      is drawn uniformly from ``[delay * (1 - jitter), delay * (1 + jitter)]``
      so peers that failed a collective at the same instant do not retry at the
      same instant too (thundering herd). The exponential *base* delay stays
      deterministic; only the sleep is perturbed. Must lie in ``[0, 1]``;
      ``0`` disables jitter. Seed :func:`seed_retry_jitter` for deterministic
      backoff sequences in tests.

    Retries apply only to the eager/multi-host path; the in-trace
    :func:`sync_states` collectives compile into the caller's executable and
    cannot be retried from Python.
    """

    retries: int = 0
    backoff_s: float = 0.05
    timeout_s: Optional[float] = None
    partial_merge: bool = False
    jitter: float = 0.25


_SYNC_POLICY = SyncPolicy()


def get_sync_policy() -> SyncPolicy:
    return _SYNC_POLICY


def set_sync_policy(policy: SyncPolicy) -> SyncPolicy:
    """Install a new process-wide :class:`SyncPolicy`; returns the previous one."""
    global _SYNC_POLICY
    if not isinstance(policy, SyncPolicy):
        raise TPUMetricsUserError(f"set_sync_policy expects a SyncPolicy, got {type(policy).__name__}")
    previous = _SYNC_POLICY
    _SYNC_POLICY = policy
    return previous


class sync_policy:
    """Context manager form: ``with sync_policy(SyncPolicy(retries=2)): ...``"""

    def __init__(self, policy: SyncPolicy) -> None:
        self._policy = policy
        self._previous: Optional[SyncPolicy] = None

    def __enter__(self) -> SyncPolicy:
        self._previous = set_sync_policy(self._policy)
        return self._policy

    def __exit__(self, *exc_info: Any) -> None:
        assert self._previous is not None
        set_sync_policy(self._previous)


class SyncPeerLostError(RuntimeError):
    """A sync collective lost one or more peers.

    Raise this from a custom ``dist_sync_fn`` (or any transport shim) to hand
    the degraded-merge machinery whatever shards DID arrive: ``survivors`` is a
    list of per-peer state dicts (``{state_name: value}``, local rank excluded —
    it is always counted as a survivor) and ``survivor_counts`` the matching
    update counts for count-weighted merging. Not retried: a lost peer will not
    reappear within a backoff window, and the survivors are already in hand.
    """

    no_retry = True

    def __init__(
        self,
        message: str,
        survivors: Optional[List[Dict[str, Any]]] = None,
        survivor_counts: Optional[List[int]] = None,
    ) -> None:
        super().__init__(message)
        self.survivors = survivors or []
        self.survivor_counts = survivor_counts if survivor_counts is not None else [1] * len(self.survivors)
        if len(self.survivor_counts) != len(self.survivors):
            raise ValueError("survivor_counts must match survivors in length")


# Process-wide RNG for backoff jitter, deliberately separate from the global
# ``random`` state so seeding it (tests) or seeding ``random`` (user code)
# never perturbs the other.
_RETRY_RNG = random.Random()


def seed_retry_jitter(seed: Optional[int] = None) -> None:
    """Re-seed the backoff-jitter RNG; with a fixed seed the exact sleep
    sequence of :func:`run_with_retries` becomes reproducible."""
    _RETRY_RNG.seed(seed)


def _jittered(delay: float, jitter: float) -> float:
    """One bounded-jitter sleep draw: uniform in ``delay * [1-jitter, 1+jitter]``."""
    if not 0.0 <= jitter <= 1.0:
        raise TPUMetricsUserError(f"SyncPolicy.jitter must lie in [0, 1], got {jitter!r}")
    if not jitter or delay <= 0.0:
        return max(0.0, delay)
    return delay * (1.0 + jitter * (2.0 * _RETRY_RNG.random() - 1.0))


def run_with_retries(attempt: Callable[[], _T], label: str = "", policy: Optional[SyncPolicy] = None) -> _T:
    """Run ``attempt`` under the policy's retry/backoff/timeout envelope.

    Exceptions whose class sets ``no_retry = True`` (e.g. :class:`SyncPeerLostError`)
    and user errors propagate immediately; anything else is retried with
    exponential backoff — each sleep perturbed by the policy's bounded jitter so
    simultaneous peer failures do not re-collide — until attempts or the time
    budget run out. Each retry records a ``sync_retry`` observe event.
    """
    policy = policy if policy is not None else _SYNC_POLICY
    deadline = (time.monotonic() + policy.timeout_s) if policy.timeout_s is not None else None
    delay = policy.backoff_s
    for attempt_no in range(policy.retries + 1):
        try:
            return attempt()
        except Exception as exc:
            sleep_s = _jittered(delay, policy.jitter)
            # budget check uses the worst-case jittered sleep, not the draw, so
            # whether a retry fits the deadline never depends on RNG state
            worst = delay * (1.0 + policy.jitter) if delay > 0 else 0.0
            out_of_budget = deadline is not None and time.monotonic() + worst > deadline
            if (
                attempt_no == policy.retries
                or getattr(exc, "no_retry", False)
                or isinstance(exc, TPUMetricsUserError)
                or out_of_budget
            ):
                raise
            _observe.note_sync_retry(label, attempt_no + 1, exc)
            time.sleep(sleep_s)
            delay *= 2.0
    raise AssertionError("unreachable")  # pragma: no cover


def shard_map_compat(f: Callable, mesh: Mesh, in_specs: Any, out_specs: Any) -> Callable:
    """``jax.shard_map`` with replication checking off, across jax versions.

    Newer jax exposes top-level ``jax.shard_map(..., check_vma=)``; older releases
    only have ``jax.experimental.shard_map.shard_map(..., check_rep=)``. Collective
    code in this package (and the test rigs) must run on both.
    """
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)
        except TypeError:  # pre-rename signature
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as _shard_map  # noqa: PLC0415

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False)


def build_mesh(axis_names: Sequence[str] = ("data",), shape: Optional[Sequence[int]] = None, devices=None) -> Mesh:
    """Construct a mesh over the available devices.

    The replacement for the reference's ``process_group`` concept (``metric.py:131``):
    a named mesh axis identifies the set of replicas a metric syncs across.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    if shape is None:
        shape = [len(devices)] + [1] * (len(axis_names) - 1)
    return Mesh(devices.reshape(shape), tuple(axis_names))


def sync_states(
    state: Dict[str, Any],
    reductions: Dict[str, Any],
    axis_name: str,
    associative: Optional[Dict[str, Optional[bool]]] = None,
) -> Dict[str, Any]:
    """Reduce a metric state pytree across a mesh axis — call INSIDE ``shard_map``/``pjit``.

    This is the reference's ``Metric._sync_dist`` (``metric.py:501-540``) re-expressed
    as XLA collectives; used with :meth:`Metric.functional` to keep the entire
    train-step + metric-sync inside one compiled program.

    ``associative`` optionally carries each state's ``merge_associative`` flag
    (:attr:`MetricFunctions.associative`). A *custom callable* reduction declared
    ``merge_associative=False`` is refused at trace time: its gather-then-fold has
    no shard-order-independent answer, so syncing it would return numbers that
    silently depend on device ordering (DESIGN §10).
    """
    associative = associative or {}
    out: Dict[str, Any] = {}
    for name, value in state.items():
        fx = reductions.get(name)
        if callable(fx) and fx not in _BUILTIN_REDUCTIONS and associative.get(name) is False:
            raise TPUMetricsUserError(
                f"State {name!r} has a custom dist_reduce_fx declared merge_associative=False: "
                "its cross-shard fold depends on device order and cannot be synced. Reformulate "
                "the reduction as associative+commutative, or gather with dist_reduce_fx=None/'cat' "
                "and finish the order-sensitive fold on the host."
            )
        # named scopes are trace-safe: profiler timelines and HLO dumps attribute
        # each collective to the state it reduces (DESIGN §11)
        with jax.named_scope(f"sync_states.{name}"):
            if fx is dim_zero_sum or fx == "sum":
                out[name] = lax.psum(value, axis_name)
            elif fx is dim_zero_mean or fx == "mean":
                out[name] = lax.pmean(value, axis_name)
            elif fx is dim_zero_max or fx == "max":
                out[name] = lax.pmax(value, axis_name)
            elif fx is dim_zero_min or fx == "min":
                out[name] = lax.pmin(value, axis_name)
            elif fx is dim_zero_cat or fx == "cat":
                v = jnp.concatenate([jnp.atleast_1d(x) for x in value]) if isinstance(value, list) else value
                gathered = lax.all_gather(v, axis_name)  # (world, ...) → concat along sample dim
                out[name] = gathered.reshape((-1,) + gathered.shape[2:])
            elif fx is None:
                out[name] = lax.all_gather(value, axis_name)
            elif callable(fx):
                out[name] = fx(lax.all_gather(value, axis_name))
            else:  # pragma: no cover
                raise TypeError(f"Unsupported dist_reduce_fx for state {name!r}: {fx}")
    return out


def allreduce_over_mesh(
    per_rank_states: Sequence[Dict[str, Any]],
    reductions: Dict[str, Any],
    mesh: Optional[Mesh] = None,
    axis_name: str = "data",
) -> Dict[str, Any]:
    """Fan-in N per-rank state pytrees through the real collective path on a mesh.

    Stacks the states, shards the stack over ``axis_name``, and runs
    :func:`sync_states` under ``shard_map`` — i.e. the exact code path a multi-chip
    deployment uses, exercised here with N local (or host-platform virtual) devices.
    This is the test rig replacing the reference's 2-process gloo pool
    (``tests/unittests/conftest.py:47-84``).
    """
    n = len(per_rank_states)
    rec = _observe.RECORDER if _observe.ENABLED else None
    t0 = _observe.clock() if rec is not None else 0.0
    if mesh is None:
        mesh = build_mesh((axis_name,), devices=jax.devices()[:n])
    # list states: pre-concat per rank (reference metric.py:506-507), pad to common capacity
    prepped: List[Dict[str, Any]] = []
    empty_slots: List[Tuple[int, str]] = []
    for i, st in enumerate(per_rank_states):
        d = {}
        for k, v in st.items():
            if isinstance(v, list):
                # a rank that never updated holds an empty list (reference
                # no-data-rank contract, ``distributed.py:138-151``)
                if v:
                    d[k] = jnp.concatenate([jnp.atleast_1d(x) for x in v])
                else:
                    d[k] = None
                    empty_slots.append((i, k))
            else:
                d[k] = jnp.asarray(v)
        prepped.append(d)
    # Empty-rank placeholders take their dtype and trailing shape from a non-empty
    # peer so the merged state is not silently promoted to float32 / flattened to 1-D;
    # all-empty keys fall back to float32 (0,).
    for i, k in empty_slots:
        peer = next((p[k] for p in prepped if p[k] is not None), None)
        if peer is not None:
            prepped[i][k] = jnp.zeros((0,) + peer.shape[1:], peer.dtype)
        else:
            prepped[i][k] = jnp.zeros((0,))

    # Ragged cat/gather states — ranks holding unequal sample counts, the
    # reference's uneven-batch DDP contract (``distributed.py:138-151``) — ride
    # the same collective at a fixed capacity: pad each rank to the max leading
    # dim, sync, then trim the pad rows back out rank-by-rank on the host.
    ragged: Dict[str, List[int]] = {}
    for k in prepped[0]:
        fx = reductions.get(k)
        is_gatherish = fx is None or fx is dim_zero_cat or fx == "cat"
        dims = [p[k].shape[0] if p[k].ndim else 0 for p in prepped]
        if len(set(dims)) > 1 and not is_gatherish:
            raise NotImplementedError(
                f"State {k!r} has dist_reduce_fx={fx!r} with unequal per-rank sizes {dims}; "
                "non-concatenating reductions would consume pad rows inside the collective. Pad "
                "the per-rank states to a common capacity (pad_to_capacity) before calling "
                "allreduce_over_mesh."
            )
        if is_gatherish and prepped[0][k].ndim and len(set(dims)) > 1:
            cap = max(dims)
            for p in prepped:
                p[k], _ = pad_to_capacity(p[k], cap)
            ragged[k] = dims
    stacked = {k: jnp.stack([p[k] for p in prepped]) for k in prepped[0]}
    if rec is not None:
        # per-state collective traffic (DESIGN §23): the bytes this state pushes
        # through the mesh — the interconnect-pressure signal ROADMAP item 2
        # (quantized collectives) sizes its wins against
        for k, v in stacked.items():
            rec.add_count("sync_bytes", k, int(v.size) * np.dtype(v.dtype).itemsize)
    specs = {k: P(axis_name, *([None] * (stacked[k].ndim - 1))) for k in stacked}

    def _body(state):
        local = {k: v[0] for k, v in state.items()}  # strip the per-rank leading dim
        return sync_states(local, reductions, axis_name)

    synced = shard_map_compat(
        _body,
        mesh=mesh,
        in_specs=(specs,),
        out_specs={k: P() for k in stacked},
    )(stacked)
    for k, dims in ragged.items():
        cap = max(dims)
        v = synced[k]
        if reductions.get(k) is None:
            # (world, cap, ...) gathered stack: trim each rank's pad rows → list of ragged arrays
            synced[k] = [v[r, : dims[r]] for r in range(n)]
        else:
            # cat: (world*cap, ...) rank-major concat: splice out the valid spans
            synced[k] = jnp.concatenate([v[r * cap : r * cap + dims[r]] for r in range(n)])
    if rec is not None:
        t1 = _observe.clock()
        rec.add_time("allreduce", axis_name, t1 - t0)
        _tracing.record_complete("allreduce", axis_name, t0, t1)
        rec.add_count("allreduce", axis_name)
    return synced


def gather_all_states(states: List[Any], group: Any = None) -> List[List[Any]]:
    """Eagerly gather each state across JAX processes (multi-host).

    Analog of ``gather_all_tensors`` (``distributed.py:100-153``); used by the OO
    ``Metric.sync`` path when ``jax.process_count() > 1``. Uneven leading dims are
    padded to the max then trimmed, mirroring the reference's ragged contract.
    """
    if jax.process_count() == 1:
        return [[s] for s in states]
    from jax.experimental import multihost_utils

    rec = _observe.RECORDER if _observe.ENABLED else None
    t0 = _observe.clock() if rec is not None else 0.0
    world = jax.process_count()
    out: List[List[Any]] = []
    for s in states:
        if isinstance(s, list):
            s = jnp.concatenate([jnp.atleast_1d(x) for x in s]) if s else jnp.zeros((0,))
        s = jnp.asarray(s)
        # ragged leading dim: share sizes first, pad, gather, trim (distributed.py:138-151)
        local_size = jnp.asarray(s.shape[0] if s.ndim else 1)
        sizes = multihost_utils.process_allgather(local_size)
        max_size = int(np.max(np.asarray(sizes)))
        if s.ndim == 0:
            gathered = multihost_utils.process_allgather(s)
            if rec is not None:
                rec.add_count("sync_bytes", f"state{len(out)}", int(np.dtype(s.dtype).itemsize) * world)
            out.append([gathered[i] for i in range(world)])
            continue
        pad = [(0, max_size - s.shape[0])] + [(0, 0)] * (s.ndim - 1)
        padded = jnp.pad(s, pad)
        gathered = multihost_utils.process_allgather(padded)
        if rec is not None:
            # allgather moves every rank's padded copy: padded bytes × world
            rec.add_count("sync_bytes", f"state{len(out)}", int(padded.size) * np.dtype(padded.dtype).itemsize * world)
        out.append([gathered[i, : int(sizes[i])] for i in range(world)])
    if rec is not None:
        t1 = _observe.clock()
        rec.add_time("gather_all", str(world), t1 - t0)
        _tracing.record_complete("gather_all", str(world), t0, t1)
        rec.add_count("gather_all", str(world))
    return out


def pad_to_capacity(x: Array, capacity: int, axis: int = 0, fill_value: float = 0.0) -> Tuple[Array, Array]:
    """Pad ``x`` to a static ``capacity`` along ``axis``; returns (padded, valid_count).

    The static-shape strategy (SURVEY §7.1-2b) for sample-storing states inside jit:
    fixed-capacity buffer + count scalar instead of a dynamically-shaped array.
    """
    n = x.shape[axis]
    if n > capacity:
        raise ValueError(f"Buffer overflow: {n} > capacity {capacity}")
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, capacity - n)
    return jnp.pad(x, pad, constant_values=fill_value), jnp.asarray(n, dtype=jnp.int32)
