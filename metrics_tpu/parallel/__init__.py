"""Distributed/mesh layer: state sync over ICI/DCN via XLA collectives (SURVEY §2.2)."""

from metrics_tpu.parallel.sync import (
    allreduce_over_mesh,
    build_mesh,
    gather_all_states,
    pad_to_capacity,
    shard_map_compat,
    sync_states,
)

__all__ = ["allreduce_over_mesh", "build_mesh", "gather_all_states", "pad_to_capacity", "shard_map_compat", "sync_states"]
