"""Distributed/mesh layer: state sync over ICI/DCN via XLA collectives (SURVEY §2.2)."""

from metrics_tpu.parallel.sync import (
    SyncPeerLostError,
    SyncPolicy,
    allreduce_over_mesh,
    build_mesh,
    gather_all_states,
    get_sync_policy,
    pad_to_capacity,
    run_with_retries,
    seed_retry_jitter,
    set_sync_policy,
    shard_map_compat,
    sync_policy,
    sync_states,
)

__all__ = [
    "SyncPeerLostError",
    "SyncPolicy",
    "allreduce_over_mesh",
    "build_mesh",
    "gather_all_states",
    "get_sync_policy",
    "pad_to_capacity",
    "run_with_retries",
    "seed_retry_jitter",
    "set_sync_policy",
    "shard_map_compat",
    "sync_policy",
    "sync_states",
]
