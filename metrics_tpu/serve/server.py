"""Host-side ingest server: sockets in, per-bucket waves out (DESIGN §26).

``MetricsServer`` is a single-threaded ``selectors`` reactor (stdlib only)
in front of a ``StreamEngine`` or ``ShardedStreamEngine``:

* **authenticates** each connection's ``hello`` session key (constant-time
  compare) before any data record is honored;
* **routes** every record to its target shard by the same stable crc32 hash
  the sharded engine uses, and applies it through the normal public API —
  so remote submissions coalesce into exactly the per-bucket waves a local
  caller's would;
* **journals before acking**: each applied record write-ahead journals into
  the target shard's WAL via the engine, the producer's ``pseq`` rides along
  as a ``serve_mark`` record, and every touched journal is fsynced once per
  poll batch before the batch's acks go out — an acked record is durable;
* **dedups** resends against the target shard's per-producer watermark
  (``status="dup"``), turning the protocol's at-least-once delivery into
  exactly-once application — and keeps that watermark sound by resolving a
  producer's records strictly in ``pseq`` order: while a record sits
  deferred, every later ``pseq`` is answered ``defer`` (rule ``ordering``)
  instead of applied, so the watermark never advances over an unresolved
  gap and a deferred record's retry can never be mistaken for a duplicate;
* **admits** through the explicit verdict table (``serve/admission.py``),
  refreshing one signal snapshot per poll pass;
* optionally drives an :class:`AutonomicController` every poll, so the
  observe→act reflexes run even when the ingest loop is the only pump.

Drive it explicitly (``poll()`` + your own ``engine.tick()`` cadence — what
the tests, chaos scenarios and soak bench do) or hand it a background
thread with ``serve_in_thread()``.
"""

from __future__ import annotations

import hmac
import pickle
import selectors
import socket
import threading
from typing import Any, Dict, Hashable, List, Optional, Tuple

from metrics_tpu.metric import Metric
from metrics_tpu.observe import recorder as _observe
from metrics_tpu.observe.metering import installed_meter
from metrics_tpu.serve.admission import AdmissionController
from metrics_tpu.serve.autonomic import AutonomicController
from metrics_tpu.serve.protocol import (
    DATA_KINDS,
    DEFAULT_MAX_FRAME_BYTES,
    DEFAULT_WINDOW,
    PROTO_VERSION,
    FrameDecoder,
    ProtocolError,
    WAL_MAGIC,
    encode_frame,
)

__all__ = ["MetricsServer"]


class _Conn:
    __slots__ = ("sock", "peer", "decoder", "out", "producer", "pending", "closing", "bytes_unmetered")

    def __init__(self, sock: socket.socket, peer: Any, max_frame_bytes: int) -> None:
        self.sock = sock
        self.peer = peer
        self.decoder = FrameDecoder(max_frame_bytes=max_frame_bytes)
        self.out = bytearray(WAL_MAGIC)  # the server's stream is journal-framed too
        self.producer: Optional[str] = None  # set by an authenticated hello
        self.pending: List[Tuple[Any, ...]] = []  # decoded, not yet processed
        self.closing = False
        self.bytes_unmetered = 0  # received but not yet charged to the meter


class MetricsServer:
    """WAL-native network ingest in front of a (sharded) stream engine."""

    def __init__(
        self,
        engine: Any,
        session_key: str,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        admission: Optional[AdmissionController] = None,
        autonomic: Optional[AutonomicController] = None,
        window: int = DEFAULT_WINDOW,
        max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
        read_budget_bytes: int = 1 << 20,
        backlog: int = 16,
        name: str = "serve",
    ) -> None:
        self.engine = engine
        # the key is compared as bytes: hmac.compare_digest rejects non-ASCII
        # str input with a TypeError, which a hostile hello could trigger
        self._key_bytes = str(session_key).encode("utf-8", "replace")
        self.window = int(window)
        self.max_frame_bytes = int(max_frame_bytes)
        # fairness + memory guards per connection (one fast or non-conforming
        # producer must not monopolize a poll pass or grow pending unboundedly)
        self.read_budget_bytes = int(read_budget_bytes)
        self.pending_cap = max(2 * self.window, 8)
        self._name = str(name)
        self.admission = admission if admission is not None else AdmissionController()
        self.autonomic = autonomic
        self._sel = selectors.DefaultSelector()
        self._lsock: Optional[socket.socket] = None
        self.address: Optional[Tuple[str, int]] = None
        if host is not None:
            lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            lsock.bind((host, int(port)))
            lsock.listen(int(backlog))
            lsock.setblocking(False)
            self._sel.register(lsock, selectors.EVENT_READ, None)
            self._lsock = lsock
            self.address = lsock.getsockname()[:2]
        self._conns: Dict[socket.socket, _Conn] = {}
        self._signals: Dict[str, float] = {}
        # per-producer contiguous resolved prefix: every pseq <= this was
        # applied, rejected, errored or deduped. Seeded from the journal's
        # recovered watermarks at hello; the in-order gate in _apply keeps it
        # (and therefore the durable serve_marks) free of gaps.
        self._resolved: Dict[str, int] = {}
        self.frames_total = 0
        self.bytes_in_total = 0
        self.dedup_skipped = 0
        self.protocol_errors = 0
        self.ordering_defers = 0
        self.disconnects = 0
        self.queue_high_water = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---------------------------------------------------------------- engine adapters
    def _engines(self) -> List[Any]:
        shards = getattr(self.engine, "_shards", None)
        return list(shards) if shards is not None else [self.engine]

    def _target_engine(self, sid: Hashable) -> Any:
        shards = getattr(self.engine, "_shards", None)
        if shards is None:
            return self.engine
        return shards[self.engine.shard_of(sid)]

    def _fleet_watermark(self, producer: str) -> int:
        return max((eng.serve_watermark(producer) for eng in self._engines()), default=0)

    # ---------------------------------------------------------------- connections
    def adopt(self, sock: socket.socket) -> None:
        """Register an already-connected socket (socketpair tests, chaos)."""
        sock.setblocking(False)
        conn = _Conn(sock, "adopted", self.max_frame_bytes)
        self._conns[sock] = conn
        self._sel.register(sock, selectors.EVENT_READ, conn)

    def _accept(self) -> None:
        assert self._lsock is not None
        while True:
            try:
                sock, peer = self._lsock.accept()
            except (BlockingIOError, InterruptedError):
                return
            sock.setblocking(False)
            conn = _Conn(sock, peer, self.max_frame_bytes)
            self._conns[sock] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _drop(self, conn: _Conn, reason: str) -> None:
        if conn.sock not in self._conns:
            return
        del self._conns[conn.sock]
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self.disconnects += 1
        if conn.producer is not None:
            _observe.note_serve_disconnect(conn.producer, reason)

    def _read(self, conn: _Conn) -> None:
        budget = self.read_budget_bytes
        while budget > 0:
            if len(conn.pending) >= self.pending_cap:
                # a peer far past its advertised credit window: stop reading
                # and let TCP backpressure hold the rest in its send buffer
                return
            try:
                chunk = conn.sock.recv(min(65536, budget))
            except (BlockingIOError, InterruptedError):
                return
            except (ConnectionResetError, OSError):
                self._drop(conn, "reset")
                return
            if not chunk:
                # peer went away; whatever partial frame it left behind never
                # decoded, so the engine saw only whole records
                self._drop(conn, "eof")
                return
            budget -= len(chunk)
            self.bytes_in_total += len(chunk)
            conn.bytes_unmetered += len(chunk)
            _observe.note_serve_bytes(len(chunk))
            try:
                conn.pending.extend(conn.decoder.feed(chunk))
            except ProtocolError as exc:
                # intact records decoded before the damage still count; the
                # framing itself can no longer be trusted past it. They face
                # the same admission signals and durability point as a normal
                # poll batch (acks queued here die with the drop, so fsyncing
                # before it keeps the ack-implies-durable contract vacuously
                # true and the journal consistent with what was applied).
                conn.pending.extend(getattr(exc, "records", []))
                self.protocol_errors += 1
                _observe.note_serve_protocol_error(str(exc))
                self._signals = self.admission.signals(self.engine)
                self._process(conn)
                self._sync_wals()
                self._drop(conn, "protocol_error")
                return

    # ---------------------------------------------------------------- record processing
    def _respond(self, conn: _Conn, kind: str, pseq: int, sid: Any, payload: Dict[str, Any]) -> None:
        conn.out += encode_frame(kind, pseq, sid, payload)

    def _materialize_metric(self, payload: Any) -> Metric:
        # the nested blob is full pickle by design — it reconstructs arbitrary
        # Metric subclasses — and is only ever loaded here, after the session
        # key authenticated the producer and admission accepted the arrival;
        # pre-auth bytes never reach pickle machinery beyond the restricted
        # frame decoder (protocol.SAFE_PICKLE_GLOBALS)
        if isinstance(payload, tuple) and len(payload) == 2 and payload[0] == "__metric__":
            payload = pickle.loads(payload[1])
        if not isinstance(payload, Metric):
            raise ProtocolError(f"add payload is not a Metric ({type(payload).__name__})")
        return payload

    def _process(self, conn: _Conn) -> None:
        """Apply this connection's decoded records in order; queue responses."""
        pending, conn.pending = conn.pending, []
        n_data = 0
        dedup_before = self.dedup_skipped
        try:
            for rec in pending:
                kind, pseq, sid, payload = rec
                self.frames_total += 1
                _observe.note_serve_frame(kind)
                if conn.producer is None:
                    if kind != "hello":
                        self.protocol_errors += 1
                        _observe.note_serve_protocol_error("data before hello")
                        conn.closing = True
                        return
                    hello = payload if isinstance(payload, dict) else {}
                    key = str(hello.get("key", "")).encode("utf-8", "replace")
                    producer = str(hello.get("producer", sid))
                    if not hmac.compare_digest(key, self._key_bytes):
                        _observe.note_serve_admission("reject", "auth")
                        self._respond(conn, "ack", 0, None, {"status": "reject", "reason": "auth"})
                        conn.closing = True
                        return
                    conn.producer = producer
                    wm = self._fleet_watermark(producer)
                    if wm > self._resolved.get(producer, 0):
                        self._resolved[producer] = wm
                    _observe.note_serve_connect(producer)
                    self._respond(conn, "welcome", 0, producer, {
                        "watermark": wm,
                        "credits": self.window,
                        "proto": PROTO_VERSION,
                    })
                    continue
                if kind == "ping":
                    self._respond(conn, "pong", pseq, None, {})
                    continue
                if kind == "bye":
                    conn.closing = True
                    continue
                if kind not in DATA_KINDS:
                    self.protocol_errors += 1
                    _observe.note_serve_protocol_error(f"unknown kind {kind!r}")
                    conn.closing = True
                    return
                if not isinstance(pseq, int) or isinstance(pseq, bool) or pseq < 1:
                    self.protocol_errors += 1
                    _observe.note_serve_protocol_error(f"bad pseq for {kind!r} record")
                    conn.closing = True
                    return
                n_data += 1
                self._apply(conn, kind, pseq, sid, payload)
        except Exception as exc:  # noqa: BLE001 — a malformed CRC-valid record
            # must cost only its own connection, never the reactor: anything
            # escaping per-record handling would otherwise propagate out of
            # poll() and kill service for every connected producer
            self.protocol_errors += 1
            _observe.note_serve_protocol_error(f"malformed record: {type(exc).__name__}")
            conn.closing = True
        finally:
            # per-producer ingest attribution (observe/metering.py): one meter
            # call per processed batch, covering early exits too
            mt = installed_meter()
            if mt is not None and conn.producer is not None and (n_data or conn.bytes_unmetered):
                mt.note_ingest(
                    conn.producer, n_data, conn.bytes_unmetered,
                    self.dedup_skipped - dedup_before,
                )
                conn.bytes_unmetered = 0

    def _apply(self, conn: _Conn, kind: str, pseq: int, sid: Any, payload: Any) -> None:
        producer = conn.producer
        target = self._target_engine(sid) if sid is not None else self._engines()[0]
        resolved = self._resolved.get(producer, 0)
        if pseq <= target.serve_watermark(producer):
            # a resend of something this shard already durably resolved
            self.dedup_skipped += 1
            _observe.note_serve_dedup(producer)
            self._respond(conn, "ack", pseq, sid, {"status": "dup"})
            if pseq > resolved:
                self._resolved[producer] = pseq
            return
        if pseq > resolved + 1:
            # in-order resolution: an earlier record from this producer is
            # still unresolved (deferred). Applying or watermarking this one
            # would advance the shard watermark over the gap and the deferred
            # record's retry would be falsely acked "dup" — applied never.
            self.ordering_defers += 1
            _observe.note_serve_admission("defer", "ordering")
            self._respond(conn, "ack", pseq, sid, {
                "status": "defer", "rule": "ordering", "retry_after_s": 0.05,
            })
            return
        decision = self.admission.decide(kind, self._signals)
        _observe.note_serve_admission(decision.verdict, decision.rule)
        if decision.verdict == "defer":
            self._respond(conn, "ack", pseq, sid, {
                "status": "defer", "rule": decision.rule,
                "retry_after_s": decision.retry_after_s if decision.retry_after_s is not None else 0.25,
            })
            return  # unresolved: the ordering gate holds later pseqs back until the retry
        if decision.verdict == "reject":
            target.serve_mark(producer, pseq)  # refusals are final: dedup resends
            self._resolved[producer] = max(resolved, pseq)
            self._respond(conn, "ack", pseq, sid, {"status": "reject", "reason": decision.rule})
            return
        if decision.verdict == "shed" and self.autonomic is not None:
            self.autonomic.shed(1, reason=f"admission:{decision.rule}")
        status: Dict[str, Any] = {"status": "ok"}
        try:
            if kind == "add":
                self.engine.add_session(self._materialize_metric(payload), session_id=sid)
            elif kind == "submit":
                args, kwargs = payload
                self.engine.submit(sid, *args, **kwargs)
            elif kind == "expire":
                self.engine.expire(sid)
            else:  # reset
                self.engine.reset(sid)
        except Exception as exc:  # noqa: BLE001 — per-record failure, connection survives
            status = {"status": "err", "reason": f"{type(exc).__name__}: {str(exc)[:200]}"}
        target.serve_mark(producer, pseq)
        self._resolved[producer] = max(resolved, pseq)
        self._respond(conn, "ack", pseq, sid, status)

    # ---------------------------------------------------------------- IO pump
    def _sync_wals(self) -> None:
        """Durability point for this poll batch: every ack queued above is
        backed by a journal record; fsync them before any ack leaves."""
        for eng in self._engines():
            if eng._wal is not None:
                eng._wal.sync()

    def _flush_writes(self) -> None:
        for conn in list(self._conns.values()):
            if conn.out:
                try:
                    sent = conn.sock.send(conn.out)
                    del conn.out[:sent]
                except (BlockingIOError, InterruptedError):
                    pass
                except OSError:
                    self._drop(conn, "reset")
                    continue
            if conn.closing and not conn.out:
                self._drop(conn, "bye")

    def poll(self, timeout: float = 0.0) -> int:
        """One reactor pass: read sockets, admit/apply whole records, fsync
        touched journals, then release the batch's acks. Returns the number
        of records processed."""
        for key, _mask in self._sel.select(timeout):
            if key.data is None:
                self._accept()
            else:
                self._read(key.data)
        backlog = sum(len(c.pending) for c in self._conns.values())
        self.queue_high_water = max(self.queue_high_water, backlog)
        processed = 0
        if backlog:
            self._signals = self.admission.signals(self.engine)
            for conn in list(self._conns.values()):
                processed += len(conn.pending)
                self._process(conn)
            self._sync_wals()
        if self.autonomic is not None:
            self.autonomic.step()
        self._flush_writes()
        if _observe.ENABLED:
            producers = sum(1 for c in self._conns.values() if c.producer is not None)
            _observe.set_serve_gauges(producers, sum(len(c.pending) for c in self._conns.values()))
        return processed

    def tick(self) -> int:
        """Convenience cadence: one poll, one engine tick."""
        self.poll(0.0)
        return self.engine.tick()

    # ---------------------------------------------------------------- lifecycle
    def serve_in_thread(self, poll_interval_s: float = 0.01, tick_every: int = 5) -> threading.Thread:
        """Run the reactor on a daemon thread, ticking the engine every
        ``tick_every`` polls; ``stop()`` joins it."""

        def _loop() -> None:
            polls = 0
            while not self._stop.is_set():
                self.poll(poll_interval_s)
                polls += 1
                if polls % max(1, int(tick_every)) == 0:
                    self.engine.tick()

        self._stop.clear()
        self._thread = threading.Thread(target=_loop, name=f"{self._name}-reactor", daemon=True)
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def close(self) -> None:
        self.stop()
        for conn in list(self._conns.values()):
            self._drop(conn, "server_close")
        if self._lsock is not None:
            try:
                self._sel.unregister(self._lsock)
            except (KeyError, ValueError):
                pass
            self._lsock.close()
            self._lsock = None
        self._sel.close()

    # ---------------------------------------------------------------- telemetry
    def stats(self) -> Dict[str, Any]:
        return {
            "name": self._name,
            "address": self.address,
            "connections": len(self._conns),
            "producers": sorted(
                c.producer for c in self._conns.values() if c.producer is not None
            ),
            "frames_total": self.frames_total,
            "bytes_in_total": self.bytes_in_total,
            "dedup_skipped": self.dedup_skipped,
            "protocol_errors": self.protocol_errors,
            "ordering_defers": self.ordering_defers,
            "disconnects": self.disconnects,
            "queue_high_water": self.queue_high_water,
            "admission": dict(self.admission.counts),
            "autonomic": dict(self.autonomic.counts) if self.autonomic is not None else None,
            "window": self.window,
        }
