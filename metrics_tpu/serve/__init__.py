"""serve/ — the fleet's front door (DESIGN §26).

Everything below the waterline — bucketed dispatch (``engine/stream.py``),
session sharding (``engine/sharded.py``), WAL durability
(``engine/durability.py``), per-session metering with a demotion handshake
(``observe/metering.py``) and the SLO watchdog (``observe/watchdog.py``) —
already exists; this package is how remote producers reach it and how the
fleet's own signals become reflexes:

* :mod:`metrics_tpu.serve.protocol` — the MTWAL001 CRC-framed record format
  lifted onto the wire: a producer's socket stream *is* the journal format,
  with per-producer sequence watermarks for exactly-once application over
  at-least-once delivery and a credit-based backpressure window.
* :mod:`metrics_tpu.serve.server` — stdlib ``selectors`` socket server:
  authenticates a session key, routes by the stable crc32 shard hash,
  journals every applied record (write-ahead) before acking, and coalesces
  remote submissions into the normal per-bucket waves via ``submit()``.
* :mod:`metrics_tpu.serve.admission` — the explicit admission-control table:
  accept / defer-with-retry-after / shed-loose-first / reject, driven by live
  occupancy, quota, watchdog and WAL-lag signals.
* :mod:`metrics_tpu.serve.autonomic` — the observe→act controller: occupancy
  pressure → pre-emptive capacity doubling; sustained quota breaches → the
  existing demotion handshake; shard imbalance → rendezvous-free elastic
  resize; overload → shed loose sessions first. Every action rate-limited,
  logged as structured observe events, and dry-runnable.
"""

from metrics_tpu.serve.admission import (
    ADMISSION_VERDICTS,
    AdmissionController,
    AdmissionDecision,
    AdmissionRule,
    DEFAULT_ADMISSION_TABLE,
)
from metrics_tpu.serve.autonomic import (
    AUTONOMIC_ACTIONS,
    AutonomicAction,
    AutonomicController,
    shed_loose,
)
from metrics_tpu.serve.protocol import (
    DATA_KINDS,
    DEFAULT_MAX_FRAME_BYTES,
    DEFAULT_WINDOW,
    PROTO_VERSION,
    FrameDecoder,
    Producer,
    ProtocolError,
    decode_blob,
    encode_frame,
)
from metrics_tpu.serve.server import MetricsServer

__all__ = [
    "ADMISSION_VERDICTS",
    "AUTONOMIC_ACTIONS",
    "AdmissionController",
    "AdmissionDecision",
    "AdmissionRule",
    "AutonomicAction",
    "AutonomicController",
    "DATA_KINDS",
    "DEFAULT_ADMISSION_TABLE",
    "DEFAULT_MAX_FRAME_BYTES",
    "DEFAULT_WINDOW",
    "FrameDecoder",
    "MetricsServer",
    "PROTO_VERSION",
    "Producer",
    "ProtocolError",
    "decode_blob",
    "encode_frame",
    "shed_loose",
]
