"""Admission control for the serve front door: an explicit verdict table.

Every remote arrival (and, for the hard-reject rows, every remote record)
is judged against a small, ordered table of rules over *live* fleet signals
— bucket occupancy, durability (WAL) lag, the installed watchdog's verdict,
and the meter's quota-pressure gauge. The table is data, not code: each row
names the signal, the comparison, the threshold, and the verdict, so an
operator can read the whole policy in one screen and tests can pin it.

Verdicts, gentlest-first:

* ``accept`` — apply the record normally (the default when no row trips);
* ``defer`` — do not apply; ack ``status="defer"`` with a ``retry_after_s``
  hint, and the producer's credit-window buffer retries it;
* ``shed`` — admit the arrival, but shed loose sessions first to make room
  (the autonomic ladder's cheapest eviction: loose rows cost no bucket
  state change and no recompile);
* ``reject`` — refuse permanently; the producer records the refusal and
  does not retry.

Signal reads are batched: the server refreshes one signal snapshot per
poll pass, so per-record admission is a few dict lookups.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

from metrics_tpu.observe import recorder as _observe
from metrics_tpu.observe.watchdog import installed_watchdog

__all__ = [
    "ADMISSION_VERDICTS",
    "AdmissionController",
    "AdmissionDecision",
    "AdmissionRule",
    "DEFAULT_ADMISSION_TABLE",
]

ADMISSION_VERDICTS = ("accept", "defer", "shed", "reject")


class AdmissionRule(NamedTuple):
    """One table row: ``verdict`` when ``signal op threshold`` holds."""

    name: str
    signal: str
    op: str  # ">=" or "<="
    threshold: float
    verdict: str
    retry_after_s: Optional[float] = None  # only meaningful for "defer"
    arrivals_only: bool = True  # False: the row also judges submit/expire/reset

    def tripped(self, signals: Dict[str, float]) -> bool:
        value = signals.get(self.signal)
        if value is None:
            return False
        return value >= self.threshold if self.op == ">=" else value <= self.threshold


class AdmissionDecision(NamedTuple):
    verdict: str
    rule: Optional[str]  # the table row that tripped, None for default-accept
    retry_after_s: Optional[float]


_ACCEPT = AdmissionDecision("accept", None, None)

# Ordered: the first tripped row wins. Hard protection (journal backlog)
# outranks health-based deferral, which outranks occupancy-based responses —
# and the shed row sits *below* reject so a fleet drowning in replay debt
# refuses work outright instead of thrashing its loose sessions.
DEFAULT_ADMISSION_TABLE: Tuple[AdmissionRule, ...] = (
    AdmissionRule("wal_backlog", "wal_lag_records", ">=", 100_000.0, "reject", None, False),
    AdmissionRule("watchdog_degraded", "watchdog_degraded", ">=", 1.0, "defer", 1.0),
    AdmissionRule("occupancy_full", "occupancy_pct", ">=", 97.0, "shed", None),
    AdmissionRule("quota_pressure", "quota_sessions_over", ">=", 1.0, "defer", 0.5),
    AdmissionRule("occupancy_high", "occupancy_pct", ">=", 90.0, "defer", 0.25),
)


class AdmissionController:
    """Evaluate the admission table; keep per-verdict counts for telemetry."""

    def __init__(self, table: Sequence[AdmissionRule] = DEFAULT_ADMISSION_TABLE) -> None:
        for rule in table:
            if rule.verdict not in ADMISSION_VERDICTS:
                raise ValueError(f"admission rule {rule.name!r} has unknown verdict {rule.verdict!r}")
            if rule.op not in (">=", "<="):
                raise ValueError(f"admission rule {rule.name!r} has unknown op {rule.op!r}")
        self.table: Tuple[AdmissionRule, ...] = tuple(table)
        self.counts: Dict[str, int] = {v: 0 for v in ADMISSION_VERDICTS}

    def signals(self, engine: Any) -> Dict[str, float]:
        """One snapshot of the live signals the table reads.

        ``occupancy_pct`` and ``wal_lag_records`` come from the engine's own
        ``stats()``; ``watchdog_degraded`` is 1.0 when an installed watchdog's
        ``health()`` verdict is degraded; ``quota_sessions_over`` reads the
        meter-maintained recorder gauge (0 when no meter or telemetry off).
        """
        stats = engine.stats()
        occupancy = stats.get("occupancy_pct")
        signals: Dict[str, float] = {
            "occupancy_pct": float(occupancy) if occupancy is not None else 0.0,
            "wal_lag_records": float(stats.get("wal_lag_records", 0)),
            "sessions": float(stats.get("sessions", 0)),
            "watchdog_degraded": 0.0,
            "quota_sessions_over": 0.0,
        }
        wd = installed_watchdog()
        if wd is not None and wd.health()["verdict"] == "degraded":
            signals["watchdog_degraded"] = 1.0
        if _observe.ENABLED:
            signals["quota_sessions_over"] = float(
                _observe.RECORDER.gauges.get(("quota_sessions_over", "meter"), 0.0)
            )
        return signals

    def decide(self, kind: str, signals: Dict[str, float]) -> AdmissionDecision:
        """First tripped row wins; records for live sessions (submit/expire/
        reset) are only subject to rows marked ``arrivals_only=False`` — an
        admitted session keeps flowing under pressure that merely defers new
        arrivals."""
        arrival = kind == "add"
        for rule in self.table:
            if not arrival and rule.arrivals_only:
                continue
            if rule.tripped(signals):
                decision = AdmissionDecision(rule.verdict, rule.name, rule.retry_after_s)
                break
        else:
            decision = _ACCEPT
        self.counts[decision.verdict] += 1
        return decision
