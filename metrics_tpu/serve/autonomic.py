"""The autonomic observe→act loop: fleet signals become rate-limited reflexes.

Every rung already exists below this module — it only closes the loop:

* **double** — occupancy pressure (the watchdog's ``occupancy_psi`` signal
  when one is installed, raw occupancy otherwise) triggers pre-emptive
  capacity doubling via ``preexpand()``: exactly one compile per grown
  bucket, already pinned by the padded-capacity program-cache key, paid
  *before* the arrival burst empties a free-list mid-wave.
* **demote** — sustained ``quota_exceeded`` breaches drive the meter's
  existing ``pending_demotions()`` / ``confirm_demotion()`` handshake, so
  quota offenders walk down the gentlest blast-radius rung (loose, never
  failed) even when the owning engine is idle between ticks.
* **resize** — sharded fleets whose session populations skew past
  ``imbalance_ratio`` get a rendezvous-free elastic resize (every session
  re-enters through the normal arrival path; journals rebuilt).
* **shed** — overload (occupancy at the shed threshold, or the server's
  admission table saying so) expires loose sessions first: zero bucket
  state change, zero recompiles, smallest possible blast radius.

Every action is rate-limited per type, logged as a structured
``autonomic_action`` observe event + counter, and dry-runnable: a
``dry_run=True`` controller decides, logs and counts, but never mutates
the fleet — the operator reads exactly what the reflexes *would* do.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Deque, Dict, Hashable, List, NamedTuple, Optional

from metrics_tpu.observe import recorder as _observe
from metrics_tpu.observe.metering import installed_meter
from metrics_tpu.observe.watchdog import installed_watchdog

__all__ = [
    "AUTONOMIC_ACTIONS",
    "AUTONOMIC_ENGINE_ALLOWLIST",
    "AutonomicAction",
    "AutonomicController",
    "shed_loose",
]

AUTONOMIC_ACTIONS = ("double", "demote", "resize", "shed")

# The declared action surface: the ONLY engine entry points a reflex may
# mutate through. racelint RC004 reads this literal from the AST and fails the
# build on any engine-mutating call not named here, so widening the autonomic
# blast radius is always an explicit, reviewable diff on this line.
AUTONOMIC_ENGINE_ALLOWLIST = ("preexpand", "resize", "expire", "_demote_by_meter")


class AutonomicAction(NamedTuple):
    action: str
    reason: str
    detail: Dict[str, Any]
    dry_run: bool
    executed: bool


def shed_loose(engine: Any, n: int = 1, reason: str = "overload") -> List[Hashable]:
    """Expire up to ``n`` loose/quarantined sessions — the shed ladder's first
    rung. Returns the session ids shed (possibly empty: an all-bucketed fleet
    has nothing cheap to shed, and this helper never escalates on its own)."""
    shed: List[Hashable] = []
    for sid in engine.loose_session_ids():
        if len(shed) >= n:
            break
        engine.expire(sid)
        shed.append(sid)
        _observe.note_serve_shed(str(sid), reason)
    return shed


class AutonomicController:
    """Observe fleet signals, act through existing primitives, rate-limited.

    ``step()`` is cheap enough to call every server poll / engine tick: each
    reflex first checks its own rate limit (one clock read), then its trip
    condition, and only then pays for the action. ``history`` keeps the last
    256 actions for the operator; ``counts`` feeds ``fleet_top``.
    """

    def __init__(
        self,
        engine: Any,
        *,
        dry_run: bool = False,
        psi_high: float = 0.25,
        occupancy_high_pct: float = 85.0,
        shed_occupancy_pct: float = 97.0,
        max_shed_per_step: int = 4,
        imbalance_ratio: float = 4.0,
        max_shards: Optional[int] = None,
        min_interval_s: Optional[Dict[str, float]] = None,
    ) -> None:
        self.engine = engine
        self.dry_run = bool(dry_run)
        self.psi_high = float(psi_high)
        self.occupancy_high_pct = float(occupancy_high_pct)
        self.shed_occupancy_pct = float(shed_occupancy_pct)
        self.max_shed_per_step = int(max_shed_per_step)
        self.imbalance_ratio = float(imbalance_ratio)
        self.max_shards = max_shards
        intervals = {"double": 2.0, "demote": 0.25, "resize": 30.0, "shed": 0.5}
        if min_interval_s:
            intervals.update(min_interval_s)
        self.min_interval_s = intervals
        self._last: Dict[str, float] = {}
        self.counts: Dict[str, int] = {a: 0 for a in AUTONOMIC_ACTIONS}
        self.history: Deque[AutonomicAction] = deque(maxlen=256)

    # ---------------------------------------------------------------- observe
    def observe(self) -> Dict[str, Any]:
        """One snapshot of every signal the reflexes read."""
        stats = self.engine.stats()
        signals: Dict[str, Any] = {
            "occupancy_pct": stats.get("occupancy_pct"),
            "sessions": stats.get("sessions", 0),
            "occupancy_psi": None,
            "quota_pending": 0,
            "shard_sessions": None,
        }
        wd = installed_watchdog()
        if wd is not None:
            signals["occupancy_psi"] = wd.health()["signals"].get("occupancy_psi")
        mt = installed_meter()
        if mt is not None and mt.policy is not None:
            mt.poll_quota()
            signals["quota_pending"] = len(mt.pending_demotions())
        shards = stats.get("shards")
        if shards is not None:
            signals["shard_sessions"] = [s["sessions"] for s in shards]
        return signals

    # ---------------------------------------------------------------- act
    def _allowed(self, action: str, now: float) -> bool:
        last = self._last.get(action)
        return last is None or now - last >= self.min_interval_s[action]

    def _record(
        self, action: str, reason: str, detail: Dict[str, Any], executed: bool, now: float
    ) -> AutonomicAction:
        self._last[action] = now
        self.counts[action] += 1
        act = AutonomicAction(action, reason, detail, self.dry_run, executed)
        self.history.append(act)
        _observe.note_autonomic_action(action, self.dry_run)
        _observe.record_event(
            "autonomic_action", action=action, reason=reason,
            dry_run=self.dry_run, executed=executed, **detail,
        )
        return act

    def step(self, now: Optional[float] = None) -> List[AutonomicAction]:
        """One observe→decide→act pass; returns the actions taken (or, under
        ``dry_run``, the actions that would have been taken)."""
        t = _observe.clock() if now is None else now
        signals = self.observe()
        actions: List[AutonomicAction] = []

        # double: occupancy pressure → pre-emptive capacity growth
        if self._allowed("double", t):
            psi = signals["occupancy_psi"]
            occ = signals["occupancy_pct"]
            psi_hot = psi is not None and psi >= self.psi_high
            occ_hot = occ is not None and occ >= self.occupancy_high_pct
            if psi_hot or occ_hot:
                reason = "occupancy_psi" if psi_hot else "occupancy"
                if self.dry_run:
                    actions.append(self._record("double", reason, {"occupancy_pct": occ, "psi": psi}, False, t))
                else:
                    grown = self.engine.preexpand(self.occupancy_high_pct)
                    if grown:
                        actions.append(self._record("double", reason, {"buckets": grown}, True, t))

        # demote: sustained quota breaches → the existing meter handshake
        if signals["quota_pending"] and self._allowed("demote", t):
            mt = installed_meter()
            if self.dry_run:
                actions.append(self._record(
                    "demote", "quota_exceeded", {"pending": list(mt.pending_demotions())}, False, t,
                ))
            else:
                demoted = self._drive_demotions(mt)
                if demoted:
                    actions.append(self._record("demote", "quota_exceeded", {"sessions": demoted}, True, t))

        # resize: shard population skew → rendezvous-free elastic resize
        shard_sessions = signals["shard_sessions"]
        if shard_sessions and len(shard_sessions) > 1 and self._allowed("resize", t):
            hi, lo = max(shard_sessions), min(shard_sessions)
            n = len(shard_sessions)
            room = self.max_shards is None or n < int(self.max_shards)
            if hi >= self.imbalance_ratio * max(1, lo) and room:
                detail = {"shard_sessions": shard_sessions, "to_shards": n + 1}
                if self.dry_run:
                    actions.append(self._record("resize", "shard_imbalance", detail, False, t))
                else:
                    self.engine.resize(n + 1)
                    actions.append(self._record("resize", "shard_imbalance", detail, True, t))

        # shed: overload → loose sessions first
        occ = signals["occupancy_pct"]
        if occ is not None and occ >= self.shed_occupancy_pct and self._allowed("shed", t):
            if self.dry_run:
                candidates = self.engine.loose_session_ids()[: self.max_shed_per_step]
                actions.append(self._record(
                    "shed", "occupancy", {"candidates": [str(s) for s in candidates]}, False, t,
                ))
            else:
                shed = shed_loose(self.engine, self.max_shed_per_step, "occupancy")
                if shed:
                    actions.append(self._record(
                        "shed", "occupancy", {"sessions": [str(s) for s in shed]}, True, t,
                    ))
        return actions

    def shed(self, n: int = 1, reason: str = "admission") -> List[Hashable]:
        """Shed on demand (the server's shed-loose-first admission verdict).

        Rate-limited like the autonomous shed reflex; a dry-run controller
        refuses (returns []) so admission under dry-run stays observe-only.
        """
        t = _observe.clock()
        if not self._allowed("shed", t):
            return []
        if self.dry_run:
            candidates = self.engine.loose_session_ids()[:n]
            self._record("shed", reason, {"candidates": [str(s) for s in candidates]}, False, t)
            return []
        shed = shed_loose(self.engine, n, reason)
        if shed:
            self._record("shed", reason, {"sessions": [str(s) for s in shed]}, True, t)
        return shed

    def _drive_demotions(self, mt: Any) -> List[str]:
        """Walk the meter's pending-demotion queue through the owning engines."""
        engines = getattr(self.engine, "_shards", None) or [self.engine]
        demoted: List[str] = []
        for skey in mt.pending_demotions():
            for eng in engines:
                before = skey in (str(s) for s in eng._sessions)
                if before:
                    eng._demote_by_meter(mt, skey)
                    demoted.append(skey)
                    break
            else:
                # the offender expired between breach and reflex: close the
                # handshake so the queue cannot wedge on a ghost
                mt.confirm_demotion(skey)
        return demoted
