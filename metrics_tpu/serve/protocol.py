"""The MTWAL001 wire protocol: a producer's socket stream IS the journal format.

``engine/durability.py`` frames every ingest-WAL record as::

    u32 record_len | u32 crc32(record) | pickle((kind, seq, sid, payload))

preceded, once per file, by the 8-byte magic ``b"MTWAL001"``. This module
lifts exactly that grammar onto a socket: each direction of a connection
starts with the same magic and then carries nothing but CRC-framed records,
so a captured client stream written to disk byte-for-byte *is* a readable
WAL file, and the decoder here accepts/rejects frames under the same rules
as :meth:`IngestWAL.read_records_detailed` (pinned by the protocol fuzz
test). Two deliberate divergences, both because a socket peer is untrusted
where a local journal file is not: the streaming decoder rejects any
declared length above ``max_frame_bytes`` before buffering the body (on a
finite file the same bytes simply read as a torn tail), and it unpickles
record bodies under the :data:`SAFE_PICKLE_GLOBALS` allowlist — a CRC only
proves integrity, not trust, and frames arrive *before* any
authentication, so a frame whose pickle references any global outside the
allowlist reads as damage (``ProtocolError``), never as code execution.

**Record kinds.** Client→server data records reuse the WAL kinds verbatim —
``add`` / ``submit`` / ``expire`` / ``reset`` — with ``seq`` drawn from the
producer's own monotonically increasing sequence (``pseq``). Control records
ride the same framing: the client opens with ``hello`` (payload carries the
session key, producer name, protocol version) and may send ``ping`` /
``bye``; the server answers ``welcome`` (payload: the producer's recovered
seq watermark + granted credit window), one ``ack`` per data record (payload
``status``: ``ok`` / ``dup`` / ``err`` / ``defer`` / ``reject``), and
``pong``.

**At-least-once + dedup.** A producer retains every data record until its
ack arrives; the server journals each applied record (and the producer's
``pseq``, as a ``serve_mark`` journal record) into the target shard's WAL
and fsyncs *before* acking — so an acked record is durable, a crash loses at
most unacked records, and after reconnecting the producer simply resends its
unacked buffer. Routing is a stable hash of the session id, so a resent
record lands on the same shard; the shard's recovered per-producer watermark
makes the duplicate detectable (``status="dup"``) and application
exactly-once. The server resolves a producer's records strictly in ``pseq``
order: while a record sits deferred, every later ``pseq`` is answered
``defer`` (rule ``ordering``) instead of applied, so the watermark always
describes a contiguous resolved prefix and a deferred record's retry can
never be mistaken for a duplicate.

**Backpressure.** The ``welcome`` grants a credit window: the producer keeps
at most ``window`` data records in flight (sent, unacked); each ack returns
one credit. Deferred records (``status="defer"``) drop back into the resend
buffer and are retried after ``retry_after_s``.
"""

from __future__ import annotations

import io
import pickle
import select
import socket
import time
import zlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

from metrics_tpu.engine.durability import _FRAME, _PICKLE, WAL_MAGIC
from metrics_tpu.metric import Metric

__all__ = [
    "CONTROL_KINDS",
    "DATA_KINDS",
    "DEFAULT_MAX_FRAME_BYTES",
    "DEFAULT_WINDOW",
    "FrameDecoder",
    "PROTO_VERSION",
    "Producer",
    "ProtocolError",
    "SAFE_PICKLE_GLOBALS",
    "WAL_MAGIC",
    "decode_blob",
    "encode_frame",
    "restricted_loads",
]

PROTO_VERSION = 1
DEFAULT_WINDOW = 64  # data records in flight per producer before pausing sends
DEFAULT_MAX_FRAME_BYTES = 64 << 20  # streaming-only guard; files have no cap
DATA_KINDS = ("add", "submit", "expire", "reset")
CONTROL_KINDS = ("hello", "welcome", "ack", "ping", "pong", "bye")


class ProtocolError(RuntimeError):
    """Framing or handshake violation; the connection cannot be trusted past it."""


# The outer (kind, seq, sid, payload) record is pure data — containers,
# scalars, strings, bytes — plus the reconstruction callables numpy and jax
# array payloads pickle through. Anything else (the classic ``os.system``
# reduce gadget included) raises, and the frame reads as damage. Metric
# objects are unaffected: they travel as tagged ``("__metric__", bytes)``
# blobs that the server unpickles only after the session key authenticated
# the producer.
SAFE_PICKLE_GLOBALS = frozenset({
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy._core.numeric", "_frombuffer"),
    ("numpy.core.multiarray", "_reconstruct"),  # frames from pre-numpy-2 writers
    ("numpy.core.multiarray", "scalar"),
    ("numpy.core.numeric", "_frombuffer"),
    ("jax._src.array", "_reconstruct_array"),
})


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str) -> Any:
        if (module, name) in SAFE_PICKLE_GLOBALS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(f"disallowed global {module}.{name}")


def restricted_loads(blob: bytes) -> Any:
    """``pickle.loads`` confined to :data:`SAFE_PICKLE_GLOBALS`.

    Safe for bytes from an unauthenticated socket peer: a pickle that names
    any other global — i.e. anything that could execute code — raises
    ``UnpicklingError`` instead of importing it. Used by :class:`FrameDecoder`
    for every frame, on both the server and the client side.
    """
    return _RestrictedUnpickler(io.BytesIO(blob)).load()


def encode_frame(kind: str, seq: int, sid: Any, payload: Any = None) -> bytes:
    """Frame one record exactly as ``IngestWAL.append`` writes it.

    Metric payloads get the same ``("__metric__", bytes)`` tagging the WAL
    uses (``Metric.__getstate__`` moves device arrays to host, so frames are
    process- and host-portable).
    """
    if isinstance(payload, Metric):
        payload = ("__metric__", pickle.dumps(payload, protocol=_PICKLE))
    rec = pickle.dumps((kind, seq, sid, payload), protocol=_PICKLE)
    return _FRAME.pack(len(rec), zlib.crc32(rec) & 0xFFFFFFFF) + rec


class FrameDecoder:
    """Incremental MTWAL001 reader over a byte stream.

    ``feed`` returns every complete record the buffered bytes hold, in order;
    partial frames simply wait for more bytes. Damage — bad magic, CRC
    mismatch, an unpicklable or non-4-tuple record, or a declared length
    above ``max_frame_bytes`` — raises :class:`ProtocolError`; the records
    decoded before the damage ride on the exception's ``records`` attribute
    so a caller draining a dying connection loses nothing intact.
    """

    def __init__(
        self, expect_magic: bool = True, max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES
    ) -> None:
        self._buf = bytearray()
        self._magic_ok = not expect_magic
        self.max_frame_bytes = int(max_frame_bytes)
        self.frames_decoded = 0  # intact records handed out so far
        self.bytes_consumed = 0  # magic + full frames consumed so far

    def pending_bytes(self) -> int:
        """Buffered bytes not yet part of a complete record (a partial frame)."""
        return len(self._buf)

    def _damage(self, msg: str, records: List[Tuple[Any, ...]]) -> ProtocolError:
        err = ProtocolError(msg)
        err.records = records  # type: ignore[attr-defined]
        err.byte_offset = self.bytes_consumed  # type: ignore[attr-defined]
        return err

    def feed(self, data: bytes) -> List[Tuple[Any, ...]]:
        self._buf += data
        out: List[Tuple[Any, ...]] = []
        if not self._magic_ok:
            if len(self._buf) < len(WAL_MAGIC):
                if WAL_MAGIC.startswith(bytes(self._buf)):
                    return out  # a magic prefix: wait for the rest
                raise self._damage("bad stream magic", out)
            if bytes(self._buf[: len(WAL_MAGIC)]) != WAL_MAGIC:
                raise self._damage("bad stream magic", out)
            del self._buf[: len(WAL_MAGIC)]
            self.bytes_consumed += len(WAL_MAGIC)
            self._magic_ok = True
        while len(self._buf) >= _FRAME.size:
            length, crc = _FRAME.unpack_from(self._buf, 0)
            if length > self.max_frame_bytes:
                raise self._damage(f"oversized frame: {length} bytes declared", out)
            if len(self._buf) < _FRAME.size + length:
                break  # partial body: wait for more
            body = bytes(self._buf[_FRAME.size : _FRAME.size + length])
            if zlib.crc32(body) & 0xFFFFFFFF != crc:
                raise self._damage("frame crc mismatch", out)
            try:
                rec = restricted_loads(body)
            except Exception as exc:  # noqa: BLE001 — CRC passed but the record is garbage or hostile
                detail = str(exc) if isinstance(exc, pickle.UnpicklingError) else type(exc).__name__
                raise self._damage(f"frame does not unpickle: {detail}", out) from exc
            if not (isinstance(rec, tuple) and len(rec) == 4):
                raise self._damage("frame is not a (kind, seq, sid, payload) record", out)
            del self._buf[: _FRAME.size + length]
            self.bytes_consumed += _FRAME.size + length
            self.frames_decoded += 1
            out.append(rec)
        return out


def decode_blob(blob: bytes) -> Tuple[List[Tuple[Any, ...]], Optional[Dict[str, int]]]:
    """Decode one finite byte blob under the streaming acceptance rules.

    Returns ``(records, torn)`` shaped exactly like
    ``IngestWAL.read_records_detailed``: ``torn`` is ``None`` for a clean
    decode or ``{"frame_index", "byte_offset"}`` locating the first damaged
    or incomplete frame. The protocol fuzz test pins this byte-for-byte
    against the file reader over truncations, bit-flips, oversized lengths
    and alien magic.
    """
    dec = FrameDecoder()
    try:
        records = dec.feed(blob)
    except ProtocolError as exc:
        return (
            list(getattr(exc, "records", [])),
            {"frame_index": dec.frames_decoded, "byte_offset": dec.bytes_consumed},
        )
    if dec.pending_bytes():
        return records, {"frame_index": dec.frames_decoded, "byte_offset": dec.bytes_consumed}
    return records, None


# ------------------------------------------------------------------ producer
class Producer:
    """Reference client: journal-framed metric ops over a socket, at-least-once.

    Every data op is buffered until its ack arrives; ``flush`` drives the
    window until the buffer drains. ``drive`` (optional) is called while
    waiting — an in-process test passes ``lambda: server.poll(0)`` so one
    thread can play both ends of the loopback. After a server crash,
    ``reconnect()`` re-handshakes and resends the whole unacked buffer; the
    server's per-shard watermarks turn duplicates into ``dup`` acks.
    """

    def __init__(
        self,
        address: Optional[Tuple[str, int]],
        session_key: str,
        name: str,
        *,
        window: int = DEFAULT_WINDOW,
        timeout: float = 10.0,
        drive: Optional[Callable[[], Any]] = None,
        sock: Optional[socket.socket] = None,
    ) -> None:
        self.name = str(name)
        self._key = str(session_key)
        self.window = int(window)
        self._timeout = float(timeout)
        self._drive = drive
        self._address = address
        self._seq = 0  # last data pseq assigned
        # pseq -> (frame bytes, kind, sid); insertion order == send order
        self._unacked: "OrderedDict[int, Tuple[bytes, str, Any]]" = OrderedDict()
        self._inflight: set = set()  # pseqs sent and awaiting a response
        self._deferred_until: Dict[int, float] = {}  # pseq -> earliest resend time
        self.errors: List[Tuple[int, str, Any, str]] = []  # (pseq, kind, sid, reason)
        self.acked = 0  # highest pseq ever acked ok/dup (informational)
        self.deferred = 0
        self.rejected = 0
        self.server_watermark = 0  # from the last welcome
        self._sock: Optional[socket.socket] = None
        self._connect(sock)

    # ---------------------------------------------------------------- wiring
    def _connect(self, sock: Optional[socket.socket] = None) -> None:
        self._dec = FrameDecoder()
        if sock is not None:
            self._sock = sock
        else:
            if self._address is None:
                raise ProtocolError("producer has no address to connect to")
            self._sock = socket.create_connection(self._address, timeout=self._timeout)
        self._sock.setblocking(False)
        hello = encode_frame(
            "hello", 0, self.name,
            {"key": self._key, "producer": self.name, "proto": PROTO_VERSION},
        )
        self._send_raw(WAL_MAGIC + hello)
        rec = self._await_control(("welcome",))
        self.server_watermark = int(rec[3].get("watermark", 0))
        self.window = int(rec[3].get("credits", self.window))

    def _send_raw(self, data: bytes) -> None:
        assert self._sock is not None
        deadline = time.monotonic() + self._timeout
        view = memoryview(data)
        while view:
            try:
                n = self._sock.send(view)
            except (BlockingIOError, InterruptedError):
                n = 0
            if n:
                view = view[n:]
                continue
            if time.monotonic() > deadline:
                raise ProtocolError("send timed out (window stalled?)")
            if self._drive is not None:
                self._drive()
            select.select([], [self._sock], [], 0.05)

    def _recv_available(self) -> List[Tuple[Any, ...]]:
        assert self._sock is not None
        out: List[Tuple[Any, ...]] = []
        while True:
            try:
                chunk = self._sock.recv(65536)
            except (BlockingIOError, InterruptedError):
                break
            if not chunk:
                raise ProtocolError("server closed the connection")
            out.extend(self._dec.feed(chunk))
        return out

    def _await_control(self, kinds: Tuple[str, ...]) -> Tuple[Any, ...]:
        deadline = time.monotonic() + self._timeout
        while True:
            for rec in self._recv_available():
                if rec[0] in kinds:
                    return rec
                self._handle(rec)
            if time.monotonic() > deadline:
                raise ProtocolError(f"timed out waiting for {'/'.join(kinds)}")
            if self._drive is not None:
                self._drive()
            else:
                select.select([self._sock], [], [], 0.05)

    # ---------------------------------------------------------------- acks
    def _handle(self, rec: Tuple[Any, ...]) -> None:
        kind, pseq, sid, payload = rec
        if kind != "ack":
            return  # welcome/pong outside a wait: informational
        pseq = int(pseq)
        status = (payload or {}).get("status", "ok")
        self._inflight.discard(pseq)
        if status in ("ok", "dup"):
            self._unacked.pop(pseq, None)
            self._deferred_until.pop(pseq, None)
            self.acked = max(self.acked, pseq)
        elif status == "defer":
            self.deferred += 1
            retry = float((payload or {}).get("retry_after_s", 0.05))
            self._deferred_until[pseq] = time.monotonic() + retry
        elif status == "reject":
            self.rejected += 1
            entry = self._unacked.pop(pseq, None)
            self._deferred_until.pop(pseq, None)
            if entry is not None:
                self.errors.append((pseq, entry[1], entry[2], (payload or {}).get("reason", "rejected")))
        else:  # "err": applied-side failure; the record will not be retried
            entry = self._unacked.pop(pseq, None)
            self._deferred_until.pop(pseq, None)
            if entry is not None:
                self.errors.append((pseq, entry[1], entry[2], (payload or {}).get("reason", "error")))

    def pump(self) -> None:
        """One non-blocking round: drain acks, then fill the credit window."""
        for rec in self._recv_available():
            self._handle(rec)
        now = time.monotonic()
        for pseq, (frame, _kind, _sid) in list(self._unacked.items()):
            if len(self._inflight) >= self.window:
                break
            if pseq in self._inflight:
                continue
            if self._deferred_until.get(pseq, 0.0) > now:
                continue
            self._send_raw(frame)
            self._inflight.add(pseq)

    # ---------------------------------------------------------------- data ops
    def _data(self, kind: str, sid: Any, payload: Any = None) -> int:
        self._seq += 1
        frame = encode_frame(kind, self._seq, sid, payload)
        self._unacked[self._seq] = (frame, kind, sid)
        self.pump()
        return self._seq

    def add_session(self, metric: Metric, session_id: Hashable) -> int:
        """Arrive one session (explicit id: the producer owns its namespace)."""
        return self._data("add", session_id, metric)

    def submit(self, session_id: Hashable, *args: Any, **kwargs: Any) -> int:
        return self._data("submit", session_id, (tuple(args), dict(kwargs)))

    def expire(self, session_id: Hashable) -> int:
        return self._data("expire", session_id)

    def reset(self, session_id: Optional[Hashable] = None) -> int:
        return self._data("reset", session_id)

    @property
    def outstanding(self) -> int:
        """Unacked data records (buffered + in flight)."""
        return len(self._unacked)

    def flush(self, timeout: Optional[float] = None) -> None:
        """Pump until every data record is acked (ok/dup/err/reject)."""
        deadline = time.monotonic() + (self._timeout if timeout is None else float(timeout))
        while self._unacked:
            self.pump()
            if not self._unacked:
                break
            if time.monotonic() > deadline:
                raise ProtocolError(f"flush timed out with {len(self._unacked)} records unacked")
            if self._drive is not None:
                self._drive()
            else:
                select.select([self._sock], [], [], 0.05)

    def resume_from_watermark(self) -> int:
        """Skip pseq numbering past the server's recovered watermark.

        For a *fresh* producer process that reuses a durable name but has NEW
        data to send (no replay): without this, its numbering restarts at 1
        and every new record is silently squelched as a ``dup`` of the
        recovered prefix. Never call it when replaying old records for
        idempotence — replay relies on reusing the original numbering.
        Returns the pseq the next record will follow.
        """
        if self._unacked:
            raise ProtocolError("resume_from_watermark with records unacked: replay them instead")
        self._seq = max(self._seq, self.server_watermark)
        return self._seq

    def reconnect(self, sock: Optional[socket.socket] = None) -> None:
        """Re-handshake after a drop and resend the whole unacked buffer.

        The welcome watermark is informational only: after a crash, shards
        may have durably applied *different* prefixes of the producer's
        stream, so the only safe recovery is resending everything unacked
        and letting per-shard watermarks squelch the duplicates. (A fresh
        process with new data under a recovered name is the opposite case:
        see :meth:`resume_from_watermark`.)
        """
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._inflight.clear()
        self._deferred_until.clear()
        self._connect(sock)
        self.pump()

    def close(self) -> None:
        if self._sock is None:
            return
        try:
            self._send_raw(encode_frame("bye", 0, self.name))
        except (ProtocolError, OSError):
            pass
        try:
            self._sock.close()
        finally:
            self._sock = None
