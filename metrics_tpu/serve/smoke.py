"""Serve front-door CI smoke: loopback producer, 100 sessions, one forced
overload→shed→recover cycle — the ≤30s slice of ``bench.py serve_soak`` that
``tools/ci_check.sh --tier1`` runs on every invocation.

One real TCP loopback connection drives the whole MTWAL001 story end to end:
handshake + auth, credit-window pumping, per-record acks, write-ahead
journaling with fsync-before-ack, and watermark dedup on an intentional
resend. The overload leg swaps in an admission table whose shed row trips at
occupancy 0%, proves the loose-first shed actually evicted loose sessions
(``status="ok"`` still — shed admits the arrival after making room), then
restores the default table and proves a fresh arrival is plainly accepted.

Exit code 0 with a one-line JSON verdict on stdout; 1 with the failing
checks named. Runs under a private telemetry probe, so the process-wide
recorder is untouched.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import tempfile
from typing import Any, Dict, List

import numpy as np

from metrics_tpu.engine.core import _FLEET_JIT_CACHE
from metrics_tpu.engine.stream import StreamEngine
from metrics_tpu.observe import recorder as rec_mod
from metrics_tpu.serve.admission import AdmissionController, AdmissionRule, DEFAULT_ADMISSION_TABLE
from metrics_tpu.serve.autonomic import AutonomicController
from metrics_tpu.serve.protocol import Producer, encode_frame
from metrics_tpu.serve.server import MetricsServer

__all__ = ["run_serve_smoke"]

_SHED_TABLE = (AdmissionRule("forced_overload", "occupancy_pct", ">=", 0.0, "shed", None),)


def run_serve_smoke(n_sessions: int = 100, n_loose: int = 4, seed: int = 0) -> Dict[str, Any]:
    """Drive the loopback smoke; returns observed numbers plus failed checks."""
    from metrics_tpu.classification.accuracy import MulticlassAccuracy

    rng = np.random.default_rng(seed)
    failures: List[str] = []
    saved_enabled, saved_recorder = rec_mod.ENABLED, rec_mod.RECORDER
    probe = rec_mod.Recorder()
    rec_mod.RECORDER, rec_mod.ENABLED = probe, True
    _FLEET_JIT_CACHE.clear()
    try:
        with tempfile.TemporaryDirectory() as td:
            engine = StreamEngine(
                initial_capacity=max(8, n_sessions), wal_path=os.path.join(td, "serve.wal")
            )
            autonomic = AutonomicController(engine, min_interval_s={"shed": 0.0})
            server = MetricsServer(engine, "smoke-key", host="127.0.0.1", autonomic=autonomic)
            prod = Producer(
                server.address, "smoke-key", name="smoke-producer",
                drive=lambda _t=None: server.poll(0.0),
            )
            prod.pump()

            # steady intake: n_sessions arrivals, two submit waves, two ticks
            for i in range(n_sessions):
                prod.add_session(MulticlassAccuracy(num_classes=8), session_id=f"s{i}")
            prod.flush(20.0)
            for _ in range(2):
                for i in range(n_sessions):
                    n = int(rng.integers(4, 16))
                    prod.submit(f"s{i}", rng.integers(0, 8, n), rng.integers(0, 8, n))
                prod.flush(20.0)
                server.tick()
            if prod.errors:
                failures.append(f"steady-state errors: {prod.errors[:3]}")
            if len(engine) != n_sessions:
                failures.append(f"engine holds {len(engine)} sessions, expected {n_sessions}")

            # watermark dedup: replay an already-acked pseq (dedup consults the
            # watermark before admission or apply, so the payload is irrelevant)
            prod._send_raw(encode_frame("submit", 1, "s0", ((), {})))
            server.poll(0.0)
            prod.pump()
            if server.dedup_skipped < 1:
                failures.append("resent record was not watermark-deduped")

            # forced overload: demote a few sessions to loose, then swap in a
            # table whose shed row trips on every arrival
            for i in range(n_loose):
                engine._demote_session(engine._sessions[f"s{i}"])
            server.admission = AdmissionController(_SHED_TABLE)
            shed_before = sum(
                v for (name, _l), v in probe.counters.items() if name == "serve_shed_sessions"
            )
            prod.add_session(MulticlassAccuracy(num_classes=8), session_id="overload-arrival")
            prod.flush(20.0)
            shed_after = sum(
                v for (name, _l), v in probe.counters.items() if name == "serve_shed_sessions"
            )
            if shed_after <= shed_before:
                failures.append("forced overload shed no loose sessions")
            if "overload-arrival" not in engine._sessions:
                failures.append("shed verdict failed to admit the arrival after making room")

            # recover: default table back, a fresh arrival is plainly accepted
            server.admission = AdmissionController(DEFAULT_ADMISSION_TABLE)
            prod.add_session(MulticlassAccuracy(num_classes=8), session_id="recovered-arrival")
            prod.flush(20.0)
            server.tick()
            if server.admission.counts["accept"] < 1:
                failures.append("post-recovery arrival was not accepted")
            if prod.outstanding:
                failures.append(f"{prod.outstanding} records never acked")

            result = {
                "sessions": len(engine),
                "frames_total": server.frames_total,
                "bytes_in_total": server.bytes_in_total,
                "dedup_skipped": server.dedup_skipped,
                "protocol_errors": server.protocol_errors,
                "shed_sessions": int(shed_after),
                "acked": prod.acked,
                "wal_lag_records": engine.stats()["wal_lag_records"],
                "failures": failures,
                "ok": not failures,
            }
            prod.close()
            server.close()
            return result
    finally:
        rec_mod.RECORDER, rec_mod.ENABLED = saved_recorder, saved_enabled
        _FLEET_JIT_CACHE.clear()


def main() -> int:
    result = run_serve_smoke()
    print(json.dumps(result, sort_keys=True))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
